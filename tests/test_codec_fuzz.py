"""Wire-codec fuzz/property tests (VERDICT r5 ask #7).

Three codecs carry training data across processes and deployments; each
gets seeded property coverage rather than single golden cases:

- reference-CSV ↔ schema: randomized Download / NetworkTopologyRecord
  instances (random scalars, list lengths up to the reference's fixed
  caps, strings with CSV metacharacters) roundtrip to full dataclass
  equality;
- DFC1 container: truncation at EVERY header boundary and seeded data
  offsets either raises ValueError or yields exactly the complete-row
  prefix — never an exception of another type, never garbage rows;
  bit-flips in the magic/header fail loudly, bit-flips in the data
  region never break framing;
- StreamingRowDecoder: arbitrary seeded chunkings of the same byte
  stream (including 1-byte chunks) decode to identical rows.
"""

import os
import string

import numpy as np
import pytest

from dragonfly2_tpu.records.columnar import (
    ColumnarReader,
    ColumnarWriter,
    StreamingRowDecoder,
    read_header,
)
from dragonfly2_tpu.records.csv_compat import (
    download_from_row,
    download_to_row,
    read_download_csv,
    read_topology_csv,
    topology_from_row,
    topology_to_row,
    write_download_csv,
    write_topology_csv,
)
from dragonfly2_tpu.records.schema import (
    Download,
    DownloadError,
    HostRecord,
    NetworkTopologyRecord,
    Parent,
    Piece,
    ProbeStats,
    TaskRecord,
    TopoHost,
)

# Deliberately includes CSV metacharacters: commas, quotes, spaces —
# the codec must quote its way through them like gocsv does.
_CHARS = string.ascii_letters + string.digits + ' ,"-_.:/'


def _s(rng) -> str:
    n = int(rng.integers(0, 24))
    return "".join(_CHARS[int(i)] for i in rng.integers(0, len(_CHARS), n))


def _i(rng) -> int:
    return int(rng.integers(0, 1 << 48))


def _f(rng) -> float:
    # round() keeps the values inside the codec's %g-style formatting
    # precision; full 17-digit doubles are covered by the dedicated
    # precision test in test_csv_compat.
    return round(float(rng.uniform(0, 1e9)), 6)


def _host(rng) -> HostRecord:
    h = HostRecord(
        id=_s(rng), hostname=_s(rng), ip=_s(rng), port=_i(rng),
        download_port=_i(rng), concurrent_upload_limit=_i(rng),
    )
    h.cpu.logical_count = _i(rng)
    h.cpu.percent = _f(rng)
    h.cpu.times.user = _f(rng)
    h.cpu.times.iowait = _f(rng)
    h.memory.total = _i(rng)
    h.memory.used_percent = _f(rng)
    h.network.idc = _s(rng)
    h.network.location = _s(rng)
    h.disk.total = _i(rng)
    h.build.git_version = _s(rng)
    return h


def random_download(rng) -> Download:
    parents = []
    for p in range(int(rng.integers(0, 21))):  # reference cap: 20
        pieces = [
            Piece(length=_i(rng), cost=_i(rng), created_at=_i(rng))
            for _ in range(int(rng.integers(0, 11)))  # cap: 10
        ]
        parents.append(Parent(
            id=_s(rng), state=_s(rng), cost=_i(rng),
            upload_piece_count=_i(rng), finished_piece_count=_i(rng),
            host=_host(rng), pieces=pieces,
            created_at=_i(rng), updated_at=_i(rng),
        ))
    return Download(
        id=_s(rng), tag=_s(rng), application=_s(rng), state=_s(rng),
        error=DownloadError(code=_s(rng), message=_s(rng)),
        cost=_i(rng), finished_piece_count=_i(rng),
        task=TaskRecord(
            id=_s(rng), url=_s(rng), type=_s(rng), content_length=_i(rng),
            total_piece_count=_i(rng), state=_s(rng),
            created_at=_i(rng), updated_at=_i(rng),
        ),
        host=_host(rng), parents=parents,
        created_at=_i(rng), updated_at=_i(rng),
    )


def random_topology(rng) -> NetworkTopologyRecord:
    src = TopoHost(id=_s(rng), type=_s(rng), hostname=_s(rng), ip=_s(rng),
                   port=_i(rng))
    src.network.idc = _s(rng)
    dests = [
        TopoHost(
            id=_s(rng), type=_s(rng), hostname=_s(rng), ip=_s(rng),
            port=_i(rng),
            probes=ProbeStats(average_rtt=_i(rng), created_at=_i(rng),
                              updated_at=_i(rng)),
        )
        for _ in range(int(rng.integers(0, 6)))  # reference cap: 5
    ]
    return NetworkTopologyRecord(id=_s(rng), host=src, dest_hosts=dests,
                                 created_at=_i(rng))


class TestReferenceCSVProperty:
    def test_download_roundtrip_randomized(self, tmp_path):
        rng = np.random.default_rng(1234)
        records = [random_download(rng) for _ in range(12)] + [Download()]
        path = str(tmp_path / "dl.csv")
        assert write_download_csv(records, path) == len(records)
        assert read_download_csv(path) == records

    def test_download_row_roundtrip_per_record(self):
        rng = np.random.default_rng(99)
        for _ in range(25):
            rec = random_download(rng)
            assert download_from_row(download_to_row(rec)) == rec

    def test_topology_roundtrip_randomized(self, tmp_path):
        rng = np.random.default_rng(4321)
        records = [random_topology(rng) for _ in range(12)]
        records.append(NetworkTopologyRecord())
        path = str(tmp_path / "nt.csv")
        assert write_topology_csv(records, path) == len(records)
        assert read_topology_csv(path) == records

    def test_topology_row_roundtrip_per_record(self):
        rng = np.random.default_rng(7)
        for _ in range(25):
            rec = random_topology(rng)
            assert topology_from_row(topology_to_row(rec)) == rec


def _write_dfc(path: str, rows: np.ndarray) -> bytes:
    with ColumnarWriter(path, [f"c{i}" for i in range(rows.shape[1])]) as w:
        w.append(rows)
    with open(path, "rb") as f:
        return f.read()


class TestDFC1Truncation:
    N_ROWS, N_COLS = 16, 5

    @pytest.fixture()
    def dfc(self, tmp_path):
        rng = np.random.default_rng(0)
        rows = rng.normal(size=(self.N_ROWS, self.N_COLS)).astype(np.float32)
        path = str(tmp_path / "full.dfc")
        blob = _write_dfc(path, rows)
        data_off = read_header(path)[1]
        return path, rows, blob, data_off

    def test_every_header_truncation_raises_valueerror(self, dfc, tmp_path):
        _, _, blob, data_off = dfc
        for cut in range(0, data_off):
            p = str(tmp_path / "cut.dfc")
            with open(p, "wb") as f:
                f.write(blob[:cut])
            # ValueError EXACTLY — no struct.error / JSONDecodeError /
            # silent empty-file success escapes the header parser.
            with pytest.raises(ValueError):
                read_header(p)

    def test_data_truncation_yields_complete_row_prefix(self, dfc, tmp_path):
        _, rows, blob, data_off = dfc
        row_nbytes = 4 * self.N_COLS
        rng = np.random.default_rng(5)
        cuts = set(rng.integers(data_off, len(blob), 20).tolist())
        cuts |= {data_off, data_off + 1, data_off + row_nbytes, len(blob)}
        for cut in cuts:
            p = str(tmp_path / "cut.dfc")
            with open(p, "wb") as f:
                f.write(blob[:cut])
            r = ColumnarReader(p)
            n_complete = (cut - data_off) // row_nbytes
            assert r.num_rows == n_complete
            np.testing.assert_array_equal(r.to_array(), rows[:n_complete])

    def test_bit_flips_in_prefix_fail_loudly(self, dfc, tmp_path):
        _, _, blob, data_off = dfc
        rng = np.random.default_rng(11)
        for _ in range(30):
            pos = int(rng.integers(0, data_off))
            bit = 1 << int(rng.integers(0, 8))
            flipped = bytearray(blob)
            flipped[pos] ^= bit
            p = str(tmp_path / "flip.dfc")
            with open(p, "wb") as f:
                f.write(bytes(flipped))
            try:
                header, off = read_header(p)
                # A flip that survives parsing must not have corrupted
                # framing: either the header still describes the same
                # layout, or construction fails loudly below.
                reader = ColumnarReader(p)
                assert reader.num_rows * header.row_nbytes <= len(blob) - off
            except (ValueError, TypeError):
                pass  # loud failure is the accepted outcome

    def test_bit_flips_in_data_never_break_framing(self, dfc, tmp_path):
        _, _, blob, data_off = dfc
        rng = np.random.default_rng(13)
        for _ in range(30):
            pos = int(rng.integers(data_off, len(blob)))
            flipped = bytearray(blob)
            flipped[pos] ^= 1 << int(rng.integers(0, 8))
            p = str(tmp_path / "flip.dfc")
            with open(p, "wb") as f:
                f.write(bytes(flipped))
            r = ColumnarReader(p)
            assert r.num_rows == self.N_ROWS
            assert r.to_array().shape == (self.N_ROWS, self.N_COLS)


class TestStreamingDecoderChunking:
    def _encoded(self):
        rng = np.random.default_rng(21)
        rows = rng.normal(size=(64, 7)).astype(np.float32)
        import io
        import json as _json
        import struct as _struct

        payload = _json.dumps(
            {"columns": [f"c{i}" for i in range(7)], "dtype": "float32",
             "created_at_ns": 0}
        ).encode()
        buf = io.BytesIO()
        buf.write(b"DFC1" + _struct.pack("<I", len(payload)) + payload)
        buf.write(rows.tobytes())
        return rows, buf.getvalue()

    def _chunks(self, blob, rng):
        out, pos = [], 0
        while pos < len(blob):
            n = int(rng.integers(1, 97))
            out.append(blob[pos : pos + n])
            pos += n
        return out

    def test_arbitrary_chunk_boundaries_decode_identically(self):
        rows, blob = self._encoded()
        for seed in range(8):
            rng = np.random.default_rng(seed)
            dec = StreamingRowDecoder()
            got = [dec.feed(c) for c in self._chunks(blob, rng)]
            got = np.concatenate([g for g in got if len(g)], axis=0)
            np.testing.assert_array_equal(got, rows)
            assert dec.rows_decoded == len(rows)

    def test_one_byte_chunks(self):
        rows, blob = self._encoded()
        dec = StreamingRowDecoder()
        got = [dec.feed(blob[i : i + 1]) for i in range(len(blob))]
        got = np.concatenate([g for g in got if len(g)], axis=0)
        np.testing.assert_array_equal(got, rows)

    def test_truncated_stream_yields_only_complete_rows(self):
        rows, blob = self._encoded()
        dec = StreamingRowDecoder()
        cut = len(blob) - 11  # mid-row
        out = dec.feed(blob[:cut])
        n_complete = len(out)
        np.testing.assert_array_equal(out, rows[:n_complete])
        assert n_complete < len(rows)
        # The tail stays buffered; completing the stream completes rows.
        rest = dec.feed(blob[cut:])
        np.testing.assert_array_equal(
            np.concatenate([out, rest], axis=0), rows
        )

    def test_bad_magic_raises(self):
        dec = StreamingRowDecoder()
        with pytest.raises(ValueError):
            dec.feed(b"NOPE" + os.urandom(32))
