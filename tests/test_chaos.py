"""Chaos e2e drills + fault-injection layer contract (ISSUE 1).

The reference proves resilience with e2e drills (test/e2e inside kind);
these are the failure-mode analogs against REAL processes and the real
wire, all driven by the deterministic fault layer (utils/faultinject +
sim/chaos):

- determinism: same scenario seed ⇒ byte-identical fault sequence;
- retry hardening: full jitter, per-attempt deadline propagation,
  circuit breaker give-up/half-open recovery;
- drill 1 — scheduler SIGKILLed mid-download: the late peer finishes
  through pex gossip fallback, digest verified;
- drill 2 — manager SIGKILLed: dynconfig's disk cache keeps the
  scheduler scheduling with the manager's cluster limits;
- drill 3 — daemon SIGKILLed mid-upload: its children reschedule onto
  the surviving parent, digest verified;
- drill 4 — trainer SIGKILLed mid-online-ingest (self-inflicted at a
  deterministic dispatch): orbax resume continues exactly-once — no
  duplicate, no lost records;
- truncation: injected torn piece bodies NEVER commit (length guard →
  refetch), digest verified;
- satellites: bench backend-init failure JSON, OAuth refresh race +
  HTTPError classification, job results that don't serialize.
"""

import json
import re
import threading
import time
import urllib.error
import urllib.request

import pytest

from dragonfly2_tpu.rpc.retry import (
    CircuitBreaker,
    CircuitOpenError,
    RetryBudgetExceeded,
    retry_call,
)
from dragonfly2_tpu.sim.chaos import (
    ChaosProcess,
    ChaosScenario,
    crash_at,
    drop_storm,
    free_port,
    replay_history,
    sha256_hex,
    wait_until,
)
from dragonfly2_tpu.utils import faultinject
from dragonfly2_tpu.utils.faultinject import FaultInjected, FaultSpec

PIECE = 64 * 1024


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    yield
    faultinject.uninstall()


# ---------------------------------------------------------------------------
# Fault layer contract
# ---------------------------------------------------------------------------


class TestFaultLayerDeterminism:
    def _drive(self, inj):
        for _ in range(60):
            for site in ("rpc.client.register_peer", "piece.fetch",
                         "state.put.jobs"):
                try:
                    inj.fire(site)
                except Exception:  # noqa: BLE001 — injected
                    pass

    def test_same_seed_same_fault_sequence(self):
        sc = ChaosScenario(seed=7, faults=[
            FaultSpec(site="rpc.client.*", kind="drop", probability=0.3),
            FaultSpec(site="piece.*", kind="dferror", probability=0.2),
            FaultSpec(site="state.put.*", kind="drop", probability=0.1),
        ])
        h1 = replay_history(sc, self._drive)
        h2 = replay_history(sc, self._drive)
        assert h1 and h1 == h2
        h3 = replay_history(
            ChaosScenario(seed=8, faults=list(sc.faults)), self._drive
        )
        assert h3 != h1

    def test_explicit_indices_modulus_and_caps(self):
        inj = ChaosScenario(faults=[
            FaultSpec(site="a", kind="drop", at=(1, 3)),
            FaultSpec(site="b", kind="drop", every=2, max_fires=2),
        ]).injector()
        outcomes = []
        for _ in range(5):
            try:
                inj.fire("a")
                outcomes.append("ok")
            except FaultInjected:
                outcomes.append("drop")
        assert outcomes == ["ok", "drop", "ok", "drop", "ok"]
        dropped = 0
        for _ in range(8):
            try:
                inj.fire("b")
            except FaultInjected:
                dropped += 1
        assert dropped == 2  # every=2 would fire 4×; max_fires caps at 2

    def test_typed_dferror_and_truncate_and_env(self):
        from dragonfly2_tpu.utils.dferrors import Code, DfError, UnavailableError

        sc = ChaosScenario(seed=3, faults=[
            FaultSpec(site="rpc.*", kind="dferror", at=(0,), code=14),
            FaultSpec(site="rpc.*", kind="dferror", at=(1,),
                      code=int(Code.NOT_FOUND)),
            FaultSpec(site="*.body", kind="truncate", at=(0,), keep_bytes=2),
        ])
        inj = faultinject.install_from_env({faultinject.ENV_VAR: sc.to_json()})
        try:
            with pytest.raises(UnavailableError):
                inj.fire("rpc.client.x")
            with pytest.raises(DfError) as ei:
                inj.fire("rpc.client.x")
            assert ei.value.code is Code.NOT_FOUND
            assert inj.fire("piece.fetch.body", b"abcdef") == b"ab"
            assert inj.fire("piece.fetch.body", b"abcdef") == b"abcdef"
        finally:
            faultinject.uninstall()

    def test_crash_kind_uses_kill_hook(self):
        killed = []
        inj = faultinject.FaultInjector(
            [FaultSpec(site="trainer.dispatch", kind="crash", at=(2,))],
            kill=lambda: killed.append(True),
        )
        for _ in range(4):
            inj.fire("trainer.dispatch")
        assert killed == [True]
        assert [k[:3] for k in inj.history_keys()] == [
            ("trainer.dispatch", 2, "crash")
        ]

    def test_delay_uses_sleep_hook_and_uninstalled_is_noop(self):
        slept = []
        inj = faultinject.FaultInjector(
            [FaultSpec(site="s", kind="delay", at=(0,), delay_s=1.5)],
            sleep=slept.append,
        )
        inj.fire("s")
        assert slept == [1.5]
        # No injector installed: fire is a passthrough.
        assert faultinject.fire("anything", b"xy") == b"xy"


# ---------------------------------------------------------------------------
# Retry hardening (ISSUE acceptance: give-up, half-open, deadlines)
# ---------------------------------------------------------------------------


class TestRetryHardening:
    def test_gives_up_after_attempts_with_last_error(self):
        calls = []

        def dead():
            calls.append(1)
            raise ConnectionError("down")

        with pytest.raises(ConnectionError):
            retry_call(dead, attempts=4, sleep=lambda s: None)
        assert len(calls) == 4

    def test_full_jitter_bounded_by_exponential_envelope(self):
        import random

        delays = []

        def flaky():
            raise TimeoutError("t")

        with pytest.raises(TimeoutError):
            retry_call(
                flaky, attempts=5, base_delay=0.1, max_delay=0.6,
                sleep=delays.append, rng=random.Random(0),
            )
        assert len(delays) == 4
        for i, d in enumerate(delays):
            assert 0.0 <= d <= min(0.1 * 2**i, 0.6)
        # Deterministic with a seeded rng: replay gives the same schedule.
        delays2 = []
        with pytest.raises(TimeoutError):
            retry_call(
                flaky, attempts=5, base_delay=0.1, max_delay=0.6,
                sleep=delays2.append, rng=random.Random(0),
            )
        assert delays == delays2

    def test_budget_exceeded_raises_chained(self):
        clock = [0.0]

        def tick_sleep(s):
            clock[0] += s

        def dead():
            clock[0] += 0.4
            raise ConnectionError("down")

        with pytest.raises(RetryBudgetExceeded) as ei:
            retry_call(
                dead, attempts=50, base_delay=0.4, max_delay=0.4,
                deadline_s=1.0, sleep=tick_sleep, clock=lambda: clock[0],
            )
        assert isinstance(ei.value.__cause__, ConnectionError)

    def test_deadline_propagates_remaining_budget(self):
        clock = [0.0]
        seen = []

        def fn(deadline_s=None):
            seen.append(round(deadline_s, 6))
            clock[0] += 0.25
            raise TimeoutError("t")

        with pytest.raises((TimeoutError, RetryBudgetExceeded)):
            retry_call(
                fn, attempts=10, base_delay=0.0, deadline_s=1.0,
                sleep=lambda s: None, clock=lambda: clock[0],
            )
        # Each attempt saw the SHRINKING remainder, never the full budget
        # again — the transport can clamp its socket timeout to it.
        assert seen[0] == 1.0
        assert all(seen[i] > seen[i + 1] for i in range(len(seen) - 1))
        assert all(0 <= s <= 1.0 for s in seen)

    def test_breaker_opens_then_half_open_recovers(self):
        clock = [0.0]
        b = CircuitBreaker(
            failure_threshold=3, reset_timeout_s=5.0, clock=lambda: clock[0]
        )
        for _ in range(3):
            assert b.allow()
            b.record_failure()
        assert b.state == "open"
        # Open: fail fast, no call attempted.
        calls = []

        def fn():
            calls.append(1)
            return "ok"

        with pytest.raises(CircuitOpenError):
            retry_call(fn, attempts=3, sleep=lambda s: None, breaker=b)
        assert calls == []
        # Reset window passes → HALF-OPEN probe; success closes.
        clock[0] += 5.0
        assert retry_call(fn, attempts=1, breaker=b) == "ok"
        assert b.state == "closed" and calls == [1]

    def test_half_open_failure_reopens(self):
        clock = [0.0]
        b = CircuitBreaker(
            failure_threshold=1, reset_timeout_s=5.0, clock=lambda: clock[0]
        )
        b.record_failure()
        assert b.state == "open"
        clock[0] += 5.0
        assert b.allow()  # the probe
        b.record_failure()
        assert b.state == "open"  # single probe failure re-trips
        assert not b.allow()


# ---------------------------------------------------------------------------
# Truncation: no silent corruption (in-process swarm, injected torn body)
# ---------------------------------------------------------------------------


class TestTruncationNoSilentCorruption:
    def test_torn_piece_body_refetched_digest_intact(self, tmp_path):
        from dragonfly2_tpu.daemon import Daemon
        from dragonfly2_tpu.daemon.pex import GossipBus
        from dragonfly2_tpu.scheduler import (
            Evaluator,
            NetworkTopology,
            Resource,
            SchedulerService,
            Scheduling,
            SchedulingConfig,
        )
        from dragonfly2_tpu.scheduler.resource import Host

        resource = Resource()
        service = SchedulerService(
            resource,
            Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)),
            None,
            NetworkTopology(resource.host_manager),
        )

        class Origin:
            def fetch(self, url, number, piece_size):
                return bytes((number + i) % 251 for i in range(PIECE))

        registry, bus = {}, GossipBus()
        daemons = []
        for i in range(3):
            h = Host(id=f"tr-host-{i}", hostname=f"tr{i}", ip=f"10.9.0.{i}",
                     port=8002, download_port=8001)
            h.stats.network.idc = "idc-a"
            resource.store_host(h)
            daemons.append(Daemon(
                h, service, storage_root=str(tmp_path / f"d{i}"),
                daemon_registry=registry, gossip_bus=bus,
                # The child (d2) has NO origin: it can only finish P2P.
                source_fetcher=Origin() if i < 2 else None,
                prefer_native=False,
            ))
        url = "https://origin/torn-blob"
        r0 = daemons[0].download(url, piece_size=PIECE, content_length=4 * PIECE)
        r1 = daemons[1].download(url, piece_size=PIECE, content_length=4 * PIECE)
        assert r0.ok and r1.ok
        want = sha256_hex(daemons[0].read_task_bytes(r0.task_id))

        # Child downloads P2P with the serving parent's upload body TORN
        # once on the first serve: the length guard must detect it, count
        # a failure, and refetch/reschedule — never commit a short body.
        scenario = ChaosScenario(faults=[
            FaultSpec(site="daemon.upload.body", kind="truncate",
                      at=(0,), keep_bytes=100),
        ])
        with faultinject.installed(scenario.injector()):
            r2 = daemons[2].download(
                url, piece_size=PIECE, content_length=4 * PIECE
            )
        assert r2.ok and not r2.back_to_source
        assert sha256_hex(daemons[2].read_task_bytes(r2.task_id)) == want
        assert r2.failed_pieces >= 1  # the torn body surfaced as a failure


# ---------------------------------------------------------------------------
# Drill 1 — scheduler SIGKILL mid-download → pex fallback, digest verified
# ---------------------------------------------------------------------------


class TestSchedulerKillDrill:
    def test_peer_finishes_via_pex_after_scheduler_sigkill(self, tmp_path):
        from dragonfly2_tpu.daemon import Daemon
        from dragonfly2_tpu.daemon.pex import GossipBus
        from dragonfly2_tpu.rpc import RemoteScheduler
        from dragonfly2_tpu.scheduler.resource import Host
        from dragonfly2_tpu.utils import idgen

        cfg = tmp_path / "sched.yaml"
        cfg.write_text(
            "server: {host: 127.0.0.1, port: 0, grpc_port: -1}\n"
            "scheduling: {retry_interval_s: 0.0}\n"
            f"storage: {{dir: {tmp_path / 'records'}, buffer_size: 1}}\n"
        )
        sched = ChaosProcess(
            ["-m", "dragonfly2_tpu.cli.scheduler", "--config", str(cfg)],
            ready_prefixes=["scheduler: serving"],
        ).start()
        try:
            line = sched.wait_ready(60)["scheduler: serving"]
            sched_url = re.search(r"rpc on (\S+)", line).group(1)

            class Origin:
                def fetch(self, url, number, piece_size):
                    return bytes((number * 7 + i) % 251 for i in range(PIECE))

            registry, bus = {}, GossipBus()

            def make_daemon(i, source):
                h = Host(id=f"ck-host-{i}", hostname=f"ck{i}",
                         ip=f"10.8.0.{i}", port=8002, download_port=8001)
                h.stats.network.idc = "idc-a"
                return Daemon(
                    h, RemoteScheduler(sched_url, timeout=2.0),
                    storage_root=str(tmp_path / f"ck{i}"),
                    daemon_registry=registry, gossip_bus=bus,
                    source_fetcher=source, prefer_native=False,
                )

            a = make_daemon(0, Origin())
            b = make_daemon(1, None)  # no origin: pex is its ONLY fallback

            url = "https://origin/chaos-blob"
            tid = idgen.task_id(url)
            r0 = a.download(url, piece_size=PIECE, content_length=4 * PIECE)
            assert r0.ok
            want = sha256_hex(a.read_task_bytes(tid))

            # B's download starts CONCURRENTLY; its first scheduler RPC
            # (announce, site index 1 — A consumed index 0) is delayed by
            # the injector, and the scheduler is SIGKILLed inside that
            # window: a mid-download control-plane death, deterministic.
            scenario = ChaosScenario(faults=[
                FaultSpec(site="rpc.client.announce_host", kind="delay",
                          at=(1,), delay_s=0.6),
            ])
            result = {}

            def download_b():
                result["r"] = b.download(
                    url, piece_size=PIECE, content_length=4 * PIECE
                )

            with faultinject.installed(scenario.injector()):
                t = threading.Thread(target=download_b)
                t.start()
                time.sleep(0.1)  # inside B's injected delay window
                sched.sigkill()
                assert sched.proc.returncode == -9
                t.join(timeout=60)
            assert not t.is_alive(), "download hung after scheduler kill"
            r1 = result["r"]
            # Control plane dead → gossip-discovered holder served it.
            assert r1.ok, r1
            assert sha256_hex(b.read_task_bytes(tid)) == want
        finally:
            sched.stop()


# ---------------------------------------------------------------------------
# Drill 2 — manager SIGKILL → dynconfig disk fallback keeps scheduling
# ---------------------------------------------------------------------------


class TestManagerKillDrill:
    def test_dynconfig_disk_fallback_keeps_scheduling(self, tmp_path):
        from dragonfly2_tpu.manager.dynconfig import Dynconfig
        from dragonfly2_tpu.records.storage import Storage
        from dragonfly2_tpu.sim import SwarmConfig, SwarmSimulator

        port = free_port()
        cfg = tmp_path / "manager.yaml"
        cfg.write_text(
            f"server: {{host: 127.0.0.1, port: {port}, grpc_port: -1}}\n"
            f"registry: {{blob_dir: {tmp_path / 'mgr'}}}\n"
        )
        mgr = ChaosProcess(
            ["-m", "dragonfly2_tpu.cli.manager", "--config", str(cfg)],
            ready_prefixes=["manager: serving"],
        ).start()
        url = f"http://127.0.0.1:{port}"
        cache_path = str(tmp_path / "dynconfig-cache.json")

        def fetch():
            with urllib.request.urlopen(
                url + "/api/v1/clusters/c1:config", timeout=5
            ) as r:
                return json.loads(r.read())

        try:
            mgr.wait_ready(60)
            body = json.dumps({
                "id": "c1", "name": "c1",
                "scheduler_cluster_config": {"candidate_parent_limit": 2,
                                             "filter_parent_limit": 10},
            }).encode()
            req = urllib.request.Request(
                url + "/api/v1/clusters", data=body,
                headers={"Content-Type": "application/json"}, method="POST",
            )
            with urllib.request.urlopen(req, timeout=10):
                pass
            # A running client fetched once (writing the disk cache)...
            dyn0 = Dynconfig(fetch, cache_path=cache_path)
            assert dyn0.refresh() is True

            # ...then the manager dies.
            mgr.sigkill()
            with pytest.raises((urllib.error.URLError, ConnectionError)):
                fetch()

            # A RESTARTED scheduler's dynconfig (fresh instance, no
            # memory) has only the disk cache — which must still apply
            # the manager's cluster limits to live scheduling.
            sim = SwarmSimulator(
                Storage(str(tmp_path / "rec"), buffer_size=4),
                config=SwarmConfig(num_hosts=12, seed=3),
            )
            assert sim.scheduling.config.candidate_parent_limit == 4

            applied = []

            def observer(data):
                limit = data["scheduler_cluster_config"]["candidate_parent_limit"]
                sim.scheduling.config.candidate_parent_limit = limit
                applied.append(limit)

            dyn1 = Dynconfig(fetch, cache_path=cache_path)
            dyn1.register(observer)
            assert dyn1.refresh() is False  # fetch failed — disk fallback
            assert applied == [2]
            assert dyn1.get()["scheduler_cluster_config"][
                "candidate_parent_limit"] == 2

            # Scheduling CONTINUES under the cached config: a fresh child
            # gets parents, capped at the manager-set limit.
            url_task = "https://origin.example.com/mgr-drill"
            sim.seed_task(url_task, n_seeds=5)
            reg = sim.service.register_peer(host=sim.hosts[7], url=url_task)
            assert reg.schedule is not None and reg.schedule.parents
            assert 1 <= len(reg.schedule.parents) <= 2
        finally:
            mgr.stop()


# ---------------------------------------------------------------------------
# Drill 2b — manager leader dies WITH a standby attached → dynconfig
# fails over to the replica and never touches the disk fallback
# (Manager HA, DESIGN.md §20; the pin/fallback is the ALL-replicas-down
# last resort only)
# ---------------------------------------------------------------------------


class TestManagerFailoverDrill:
    def test_dynconfig_fails_over_to_standby_without_disk_fallback(
        self, tmp_path
    ):
        from dragonfly2_tpu.manager.cluster import ClusterManager
        from dragonfly2_tpu.manager.crud import CrudStore
        from dragonfly2_tpu.manager.dynconfig import Dynconfig
        from dragonfly2_tpu.manager.registry import ModelRegistry
        from dragonfly2_tpu.manager.replication import (
            LogFollower, ReplicatedStateBackend,
        )
        from dragonfly2_tpu.manager.rest import ManagerRESTServer
        from dragonfly2_tpu.manager.state import MemoryBackend
        from dragonfly2_tpu.rpc.resolver import ManagerEndpoints

        leader = ReplicatedStateBackend(
            MemoryBackend(), node_id="L", lease_ttl_s=60.0
        )
        crud = CrudStore(backend=leader)
        rest = ManagerRESTServer(
            ModelRegistry(backend=leader), ClusterManager(), crud=crud,
            state_backend=leader, ha=leader,
        )
        rest.serve()
        crud.create("cluster", id="c1", name="c1", scheduler_cluster_config={
            "candidate_parent_limit": 2, "filter_parent_limit": 10,
        })

        standby_backend = ReplicatedStateBackend(
            MemoryBackend(), node_id="F", role="standby", lease_ttl_s=60.0
        )
        follower = LogFollower(standby_backend, rest.url)
        follower.poll_once()
        standby_rest = ManagerRESTServer(
            ModelRegistry(backend=standby_backend), ClusterManager(),
            crud=CrudStore(backend=standby_backend),
            state_backend=standby_backend, ha=standby_backend,
        )
        standby_rest.serve()

        endpoints = ManagerEndpoints(f"{rest.url},{standby_rest.url}")
        cache_path = str(tmp_path / "dyn-cache.json")

        def fetch():
            def one(base):
                with urllib.request.urlopen(
                    base + "/api/v1/clusters/c1:config", timeout=5
                ) as r:
                    return json.loads(r.read())

            return endpoints.call(one)

        try:
            dyn = Dynconfig(fetch, cache_path=cache_path)
            assert dyn.refresh() is True
            # The leader dies; the standby replica holds the same rows.
            rest.stop()
            dyn2 = Dynconfig(fetch, cache_path=str(tmp_path / "absent.json"))
            assert dyn2.refresh() is True, (
                "fetch did not fail over to the standby"
            )
            assert dyn2.last_refresh_ok is True  # live fetch, NOT fallback
            assert dyn2.get()["scheduler_cluster_config"][
                "candidate_parent_limit"] == 2
            assert endpoints.current() == standby_rest.url
        finally:
            rest.stop()
            standby_rest.stop()


# ---------------------------------------------------------------------------
# Drill 3 — daemon SIGKILL mid-upload → children reschedule, digest verified
# ---------------------------------------------------------------------------


class TestDaemonKillMidUploadDrill:
    def test_children_reschedule_onto_surviving_parent(self, tmp_path):
        from dragonfly2_tpu.daemon import DaemonStorage
        from dragonfly2_tpu.daemon.conductor import Conductor
        from dragonfly2_tpu.records.storage import Storage
        from dragonfly2_tpu.rpc import HTTPPieceFetcher, RemoteScheduler
        from dragonfly2_tpu.rpc.daemon_control import (
            download_via_daemon,
            read_state,
        )
        from dragonfly2_tpu.rpc.scheduler_server import SchedulerHTTPServer
        from dragonfly2_tpu.scheduler import (
            Evaluator,
            NetworkTopology,
            Resource,
            SchedulerService,
            Scheduling,
            SchedulingConfig,
        )
        from dragonfly2_tpu.scheduler.resource import Host
        from dragonfly2_tpu.utils import idgen

        # Control plane IN-PROCESS (it must survive the daemon kill and
        # is where we watch rescheduling happen); parents are REAL
        # dfdaemon processes serving the piece plane over HTTP.
        resource = Resource()
        service = SchedulerService(
            resource,
            Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)),
            Storage(str(tmp_path / "records"), buffer_size=4),
            NetworkTopology(resource.host_manager),
        )
        server = SchedulerHTTPServer(service)
        server.serve()

        blob = bytes(i % 249 for i in range(8 * PIECE))
        blob_path = tmp_path / "blob.bin"
        blob_path.write_bytes(blob)
        url = f"file://{blob_path}"
        tid = idgen.task_id(url)

        daemons = []
        try:
            for i in range(2):
                dcfg = tmp_path / f"daemon{i}.yaml"
                dcfg.write_text(
                    "server: {host: 127.0.0.1, port: 0, "
                    "advertise_ip: 127.0.0.1}\n"
                    f"storage: {{dir: {tmp_path / f'dstore{i}'}}}\n"
                    f"piece_size: {PIECE}\n"
                )
                d = ChaosProcess(
                    ["-m", "dragonfly2_tpu.cli.dfdaemon",
                     "--scheduler", server.url, "--config", str(dcfg)],
                    ready_prefixes=["dfdaemon: serving"],
                    env={**__import__("os").environ,
                         "DF_DAEMON_STATE": str(tmp_path / f"d{i}.json")},
                ).start()
                daemons.append(d)
            for i, d in enumerate(daemons):
                d.wait_ready(90)
                control = read_state(str(tmp_path / f"d{i}.json"))["url"]
                r = download_via_daemon(url, control)
                assert r["ok"], r

            # The child: in-process conductor on the wire, no source
            # fetcher — it can ONLY finish from surviving parents.
            child_host = Host(id="chaos-child", hostname="cc",
                              ip="127.0.0.1", port=8002, download_port=1)
            child_host.stats.network.idc = "idc-a"
            client = RemoteScheduler(server.url, timeout=3.0)
            storage = DaemonStorage(
                str(tmp_path / "childstore"), prefer_native=False
            )
            conductor = Conductor(
                child_host, storage, client,
                piece_fetcher=HTTPPieceFetcher(
                    client.resolve_host, timeout=3.0
                ),
                source_fetcher=None,
                max_piece_retries=8,
                piece_wait_timeout_s=20.0,
            )

            # Pace the child's fetches so the kill lands mid-download.
            scenario = ChaosScenario(faults=[
                FaultSpec(site="piece.fetch", kind="delay", every=1,
                          delay_s=0.15),
            ])
            result = {}

            def run_child():
                result["r"] = conductor.download(
                    url, piece_size=PIECE, content_length=len(blob)
                )

            with faultinject.installed(scenario.injector()):
                t = threading.Thread(target=run_child)
                t.start()
                # Mid-upload: the child has committed ≥1 piece and the
                # swarm is still serving it when parent 0 dies.
                wait_until(
                    lambda: storage.held_pieces(tid) >= 1,
                    timeout=60, desc="first piece committed",
                )
                daemons[0].sigkill()
                assert daemons[0].proc.returncode == -9
                t.join(timeout=120)
            assert not t.is_alive(), "child hung after parent kill"
            r = result["r"]
            assert r.ok, r
            assert not r.back_to_source  # finished from the swarm
            assert sha256_hex(storage.read_task_bytes(tid)) == sha256_hex(blob)
            # The dead parent was actually in play: failures were
            # reported and rescheduling happened around them.
            assert r.failed_pieces >= 1
        finally:
            for d in daemons:
                d.stop()
            server.stop()


# ---------------------------------------------------------------------------
# Drill 3b — piece data plane (PR 11): hedged straggler fetch + pooled
# connection eviction on parent death
# ---------------------------------------------------------------------------


class _PlaneOrigin:
    def content(self, url, number):
        seed = (hash(url) ^ number) & 0xFF
        return bytes((seed + i) % 251 for i in range(PIECE))

    def fetch(self, url, number, piece_size):
        return self.content(url, number)


class _PlaneNode:
    """In-process wire node for the data-plane drills: piece server +
    remote scheduler client + conductor (test_rpc.WireNode shape)."""

    def __init__(self, name, scheduler_url, tmp_path, origin=None, **conductor_kw):
        from dragonfly2_tpu.daemon import DaemonStorage, UploadManager
        from dragonfly2_tpu.daemon.conductor import Conductor
        from dragonfly2_tpu.rpc import HTTPPieceFetcher, RemoteScheduler
        from dragonfly2_tpu.rpc.piece_transport import PieceHTTPServer
        from dragonfly2_tpu.scheduler.resource import Host

        self.storage = DaemonStorage(str(tmp_path / name), prefer_native=False)
        self.upload = UploadManager(self.storage)
        self.server = PieceHTTPServer(self.upload)
        self.server.serve()
        self.host = Host(
            id=name, hostname=name, ip="127.0.0.1",
            download_port=self.server.port,
        )
        self.host.stats.network.idc = "idc-a"
        self.client = RemoteScheduler(scheduler_url)
        self.fetcher = HTTPPieceFetcher(self.client.resolve_host, timeout=5.0)
        self.conductor = Conductor(
            self.host, self.storage, self.client,
            piece_fetcher=self.fetcher, source_fetcher=origin,
            **conductor_kw,
        )

    def stop(self):
        self.server.stop()
        self.fetcher.close()


def _plane_swarm(tmp_path):
    from dragonfly2_tpu.records.storage import Storage
    from dragonfly2_tpu.rpc.scheduler_server import SchedulerHTTPServer
    from dragonfly2_tpu.scheduler import (
        Evaluator,
        NetworkTopology,
        Resource,
        SchedulerService,
        Scheduling,
        SchedulingConfig,
    )

    resource = Resource()
    service = SchedulerService(
        resource,
        Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)),
        Storage(str(tmp_path / "records"), buffer_size=1),
        NetworkTopology(resource.host_manager),
    )
    server = SchedulerHTTPServer(service)
    server.serve()
    return server


class _CountingStore:
    """DaemonStorage wrapper counting write_piece calls per number — the
    exactly-one-commit-per-piece witness for the hedge drill."""

    def __init__(self, inner):
        self._inner = inner
        self.writes = {}
        self._mu = threading.Lock()

    def write_piece(self, task_id, number, data):
        with self._mu:
            self.writes[number] = self.writes.get(number, 0) + 1
        return self._inner.write_piece(task_id, number, data)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class TestHedgedStragglerDrill:
    N_PIECES = 8

    def test_slow_parent_hedge_wins_exactly_one_commit(self, tmp_path):
        from dragonfly2_tpu.daemon.piece_pipeline import PIECE_HEDGE_TOTAL

        server = _plane_swarm(tmp_path)
        origin = _PlaneOrigin()
        url = "https://origin/hedge-blob"
        blob = b"".join(origin.content(url, n) for n in range(self.N_PIECES))
        parents = [
            _PlaneNode(f"hparent-{i}", server.url, tmp_path, origin)
            for i in range(2)
        ]
        child = _PlaneNode(
            "hchild", server.url, tmp_path, None,
            # Aggressive hedging so the drill derives its threshold from
            # the first couple of fetches: baseline ~ms, floor 0.15 s.
            hedge_min_samples=2, hedge_floor_s=0.15, hedge_multiplier=3.0,
            max_piece_retries=4,
        )
        try:
            for p in parents:
                r = p.conductor.download(
                    url, piece_size=PIECE, content_length=len(blob)
                )
                assert r.ok  # first seeds from origin, second via p2p
            counting = _CountingStore(child.storage)
            child.conductor.storage = counting
            fired0 = PIECE_HEDGE_TOTAL.value(outcome="fired")
            # ONE straggler: piece.fetch call #5 stalls 2 s — far past
            # the hedge threshold, far under the piece timeout.  The
            # hedge (a later piece.fetch index) races the other parent.
            scenario = ChaosScenario(faults=[
                FaultSpec(site="piece.fetch", kind="delay", at=(5,),
                          delay_s=2.0),
            ])
            with faultinject.installed(scenario.injector()):
                result = child.conductor.download(url, piece_size=PIECE)
            assert result.ok and not result.back_to_source, result
            # Zero digest failures: crc checked at every read, whole
            # content byte-identical to the origin.
            assert sha256_hex(
                child.storage.read_task_bytes(result.task_id)
            ) == sha256_hex(blob)
            # The hedge actually fired...
            assert PIECE_HEDGE_TOTAL.value(outcome="fired") > fired0
            # ...and NEVER double-committed: exactly one write per piece.
            assert counting.writes == {
                n: 1 for n in range(self.N_PIECES)
            }, counting.writes
        finally:
            child.stop()
            for p in parents:
                p.stop()
            server.stop()


class TestParentDeathPoolEvictionDrill:
    N_PIECES = 8

    def test_dead_parent_evicted_from_pool_and_rescheduled(self, tmp_path):
        server = _plane_swarm(tmp_path)
        origin = _PlaneOrigin()
        url = "https://origin/pool-evict-blob"
        blob = b"".join(origin.content(url, n) for n in range(self.N_PIECES))
        parents = [
            _PlaneNode(f"kparent-{i}", server.url, tmp_path, origin)
            for i in range(2)
        ]
        child = _PlaneNode(
            "kchild", server.url, tmp_path, None,
            hedge_enabled=False, max_piece_retries=8,
            piece_wait_timeout_s=20.0, piece_parallelism=2,
        )
        try:
            for p in parents:
                r = p.conductor.download(
                    url, piece_size=PIECE, content_length=len(blob)
                )
                assert r.ok
            # Pace fetches so the kill lands mid-download (2 workers ×
            # 0.25 s/fetch ≈ 1 s of download against a ~0.3 s kill).
            scenario = ChaosScenario(faults=[
                FaultSpec(site="piece.fetch", kind="delay", every=1,
                          delay_s=0.25),
            ])
            result = {}

            def run_child():
                result["r"] = child.conductor.download(url, piece_size=PIECE)

            victim = parents[0]
            with faultinject.installed(scenario.injector()):
                t = threading.Thread(target=run_child, daemon=True)
                t.start()
                wait_until(
                    lambda: child.storage.held_pieces(
                        child.conductor._task_id(url, None)
                    ) >= 1,
                    timeout=30, desc="first piece committed",
                )
                # Parent death: the listener closes AND its established
                # keep-alive sockets sever (a SIGKILLed process's RSTs —
                # stop() alone lets handler threads drain gracefully).
                victim.server.stop()
                for conn in list(
                    child.fetcher.pool._idle.get(victim.host.id, [])
                ):
                    conn.sock.close()
                t.join(timeout=60)
            assert not t.is_alive(), "child hung after parent kill"
            r = result["r"]
            assert r.ok and not r.back_to_source, r
            assert sha256_hex(
                child.storage.read_task_bytes(r.task_id)
            ) == sha256_hex(blob)
            # The reschedule path ran: failures were reported against the
            # dead parent and the pool holds NO connection to it.
            assert r.failed_pieces >= 1
            assert child.fetcher.pool.idle_count(victim.host.id) == 0
            # The surviving parent's connection(s) are still pooled.
            assert child.fetcher.pool.idle_count(parents[1].host.id) >= 1
        finally:
            child.stop()
            for p in parents:
                p.stop()
            server.stop()


# ---------------------------------------------------------------------------
# Drill 4 — trainer crash mid-online-ingest → orbax resume, exactly-once
# ---------------------------------------------------------------------------


class TestTrainerCrashDrill:
    TOTAL_DISPATCHES = 6
    CRASH_AT = 3

    def test_orbax_resume_no_duplicate_no_lost_records(self, tmp_path):
        import os
        import sys

        child = os.path.join(os.path.dirname(__file__), "_chaos_child.py")
        ckpt = str(tmp_path / "ckpt")

        # Phase 1: the trainer SIGKILLs ITSELF at dispatch index 3 (the
        # crash fault on the trainer.dispatch seam) — dispatches 0..2
        # trained and checkpointed, the stream position mid-flight.
        p1 = ChaosProcess(
            [child, "fresh", ckpt, str(self.TOTAL_DISPATCHES)],
            scenario=crash_at("trainer.dispatch", self.CRASH_AT),
            ready_prefixes=["chaos-child: ready"],
        ).start()
        p1.wait_ready(120)
        assert p1.wait_dead(300) == -9, p1.lines[-5:]
        assert os.path.isdir(os.path.join(ckpt, "online_graph"))

        # Phase 2: a fresh process resumes from the checkpoint and
        # finishes the stream, skipping exactly what was already trained.
        p2 = ChaosProcess(
            [child, "resume", ckpt, str(self.TOTAL_DISPATCHES)],
        ).start()
        assert p2.wait_dead(300) == 0, p2.lines[-8:]
        out = json.loads([l for l in p2.lines if l.startswith("{")][-1])
        resumed = [l for l in p2.lines if "resumed at dispatch" in l]
        assert resumed and resumed[0].endswith(str(self.CRASH_AT))

        # Exactly-once accounting: every record trained once, none lost.
        import _chaos_child as cc

        assert out["dispatch"] == self.TOTAL_DISPATCHES
        assert out["records_seen"] == self.TOTAL_DISPATCHES * cc.PER_DISPATCH

        # Byte-identity against an UNINTERRUPTED run of the same stream
        # (in-process — same platform config as the children).
        ref = cc.run("fresh", str(tmp_path / "ref_ckpt"), self.TOTAL_DISPATCHES)
        assert ref["records_seen"] == out["records_seen"]
        assert ref["state_hash"] == out["state_hash"]


# ---------------------------------------------------------------------------
# Drill 5 — scheduler SIGKILLed mid-announce → columnar rebuild, no torn rows
# ---------------------------------------------------------------------------


class TestColumnarRebuildDrill:
    """ISSUE 7: the columnar host store is the source of truth for host
    serving state, and it is IN-MEMORY — a scheduler killed mid-announce
    loses it.  The restart contract is rebuild-from-announces: a fresh
    process replaying the announce stream must end with zero torn slot
    rows (every bound row byte-matches a recompute off the column-backed
    accessors, write stamps agree with the hosts' mutation counters) and
    with columnar rule scores still bit-equal to the scalar oracle."""

    def test_kill_mid_announce_then_rebuild_has_no_torn_rows(self):
        import os

        child = os.path.join(os.path.dirname(__file__), "_columnar_child.py")

        # Phase 1: announce storm against the live columnar store; the
        # SIGKILL lands while announcer threads are mid-write.
        p1 = ChaosProcess(
            [child, "hammer"], ready_prefixes=["columnar-child: ready"],
        ).start()
        p1.wait_ready(120)
        time.sleep(0.5)  # the storm is genuinely mid-announce
        p1.sigkill()
        assert p1.wait_dead(60) == -9

        # Phase 2: the "restarted" scheduler — a fresh process — rebuilds
        # columnar state from the (deterministic) announce stream and
        # self-validates.
        p2 = ChaosProcess([child, "rebuild"]).start()
        assert p2.wait_dead(300) == 0, p2.lines[-8:]
        verdict = json.loads([l for l in p2.lines if l.startswith("{")][-1])
        assert verdict["torn"] == []
        assert verdict["rows_checked"] > 0
        assert verdict["row_mismatch"] == 0
        assert verdict["scores_bit_equal"] is True


# ---------------------------------------------------------------------------
# Satellites
# ---------------------------------------------------------------------------


class TestBenchInitFailure:
    def test_persistent_unavailable_emits_one_json_line(self, capsys):
        import bench

        calls = []

        def busy():
            calls.append(1)
            raise RuntimeError("UNAVAILABLE: TPU runtime busy")

        rc = bench.main(
            acquire=lambda: bench.acquire_backend(
                busy, attempts=3, sleep=lambda s: None
            )
        )
        out_lines = capsys.readouterr().out.strip().splitlines()
        # Unavailable hardware is a structured SKIP, not a failure exit:
        # rc stays 0 so a busy TPU runtime can never cost the perf
        # trajectory a round the way BENCH_r05 was lost (ISSUE 6).
        assert rc == 0 and len(out_lines) == 1
        line = json.loads(out_lines[0])
        assert line["ok"] is False
        assert line["failure"] == "backend_unavailable"
        assert line["skipped"] == "backend_unavailable"
        assert len(calls) == 3  # bounded backoff actually retried

    def test_transient_unavailable_recovers(self):
        import bench

        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise RuntimeError("UNAVAILABLE: borrowed")
            return "backend"

        assert bench.acquire_backend(
            flaky, attempts=5, sleep=lambda s: None
        ) == "backend"
        assert len(calls) == 3

    def test_post_acquire_backend_failure_still_one_json_line(self, capsys):
        # Acquisition succeeds but the benchmark body dies on a backend
        # touch (the round-5 failure shape: jax.devices() after acquire):
        # still rc=1 with ONE parseable ok:false line, never a traceback.
        import bench

        class ExplodesOnTouch:
            def __getattr__(self, name):
                raise RuntimeError("UNAVAILABLE: TPU runtime went away")

        rc = bench.main(acquire=lambda: ExplodesOnTouch())
        out_lines = capsys.readouterr().out.strip().splitlines()
        assert rc == 0 and len(out_lines) == 1
        line = json.loads(out_lines[0])
        assert line["ok"] is False
        assert line["failure"] == "backend_unavailable"
        assert line["skipped"] == "backend_unavailable"

    def test_headline_regression_guard(self, tmp_path):
        # ISSUE 7 satellite: a fresh round is compared against the last
        # GOOD recorded round — >20% below it flags loudly in the JSON;
        # skipped/value-less rounds (r05) and CPU-fallback rounds never
        # become the bar.
        import bench

        def _round(n, parsed):
            (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps(
                {"n": n, "rc": 0, "parsed": parsed}
            ))

        _round(3, {"value": 4.9e6})
        _round(4, {"value": 4.78e6})
        _round(5, None)                                  # the lost round
        _round(6, {"value": 2600.0, "backend": "cpu"})   # smoke fallback
        good = bench.last_good_headline(str(tmp_path))
        assert good == {"round": 4, "value": 4.78e6, "file": "BENCH_r04.json"}

        ok = bench.apply_regression_guard({"value": 4.6e6}, good)
        assert "regression_warning" not in ok
        assert ok["last_good"]["round"] == 4

        bad = bench.apply_regression_guard({"value": 3.0e6}, good)
        assert bad["regression_warning"]["vs_round"] == 4
        assert bad["regression_warning"]["dropped_to"] < 0.8

        # No good rounds at all → the guard stays silent, never crashes.
        empty = bench.apply_regression_guard({"value": 1.0}, {})
        assert "last_good" not in empty

    def test_non_backend_failure_is_still_rc_1(self, capsys):
        # A genuine code/config error must NOT masquerade as a hardware
        # skip: one parseable line, no "skipped" key, nonzero exit.
        import bench

        def broken():
            raise ValueError("bad benchmark config")

        rc = bench.main(acquire=broken)
        out_lines = capsys.readouterr().out.strip().splitlines()
        assert rc == 1 and len(out_lines) == 1
        line = json.loads(out_lines[0])
        assert line["ok"] is False
        assert "skipped" not in line
        assert line["failure"] == "ValueError"


class TestSchedulerBatcherFaultSeam:
    """ISSUE 3 satellite: a dropped/delayed coalesced scorer batch
    degrades to per-request scoring — announces never stall on the
    batcher (seam ``scheduler.eval.batch``, DF004 inventory)."""

    def _swarm(self):
        import numpy as np

        from dragonfly2_tpu.scheduler import (
            HostFeatureCache,
            MLEvaluator,
            ScorerBatcher,
        )
        from dragonfly2_tpu.sim.swarm import build_announce_swarm

        task, peers = build_announce_swarm(48, seed=11)

        class MLP:
            def __init__(self):
                rng = np.random.default_rng(0)
                self.w = rng.standard_normal((32, 1)).astype(np.float32)

            def score(self, features, **_buckets):
                return (np.asarray(features, np.float32) @ self.w)[..., 0]

        batcher = ScorerBatcher(linger_s=0.005)
        ml = MLEvaluator(
            MLP(), feature_cache=HostFeatureCache(max_hosts=256),
            batcher=batcher,
        )
        return task, peers, ml, batcher

    def _announce_storm(self, task, peers, ml, n_threads=8, per_thread=12):
        import numpy as np

        results, errs = [], []

        def worker(tid):
            rng = np.random.default_rng(tid)
            try:
                for _ in range(per_thread):
                    child_i = int(rng.integers(0, len(peers)))
                    cand = rng.choice(len(peers) - 1, size=9, replace=False)
                    cand = [c if c < child_i else c + 1 for c in cand]
                    ranked = ml.evaluate_parents(
                        [peers[c] for c in cand], peers[child_i],
                        task.total_piece_count,
                    )
                    results.append((child_i, tuple(cand),
                                    tuple(p.id for p in ranked)))
            except Exception as exc:  # noqa: BLE001
                errs.append(exc)

        threads = [
            threading.Thread(target=worker, args=(i,), daemon=True)
            for i in range(n_threads)
        ]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return results, errs, time.monotonic() - t0

    def test_dropped_batch_degrades_to_per_request(self):
        task, peers, ml, batcher = self._swarm()
        scenario = ChaosScenario(faults=[
            FaultSpec(site="scheduler.eval.batch", kind="drop", every=2),
        ])
        with faultinject.installed(scenario.injector()) as inj:
            results, errs, _ = self._announce_storm(task, peers, ml)
        assert errs == []
        assert len(results) == 8 * 12          # every announce completed
        assert batcher.fallbacks >= 1          # the degrade path actually ran
        assert any(k[0] == "scheduler.eval.batch" for k in inj.history_keys())
        # Degraded (per-request) rankings are the SAME rankings the intact
        # coalesced path produces — the fault changes latency, not order.
        for child_i, cand, ranked in results:
            ref = ml._evaluate_parents_reference(
                [peers[c] for c in cand], peers[child_i],
                task.total_piece_count,
            )
            assert tuple(p.id for p in ref) == ranked

    def test_delayed_batch_does_not_stall_announces(self):
        task, peers, ml, batcher = self._swarm()
        scenario = ChaosScenario(faults=[
            FaultSpec(site="scheduler.eval.batch", kind="delay",
                      every=3, delay_s=0.05),
        ])
        with faultinject.installed(scenario.injector()):
            results, errs, wall = self._announce_storm(task, peers, ml)
        assert errs == []
        assert len(results) == 8 * 12
        # Delays pushed through the coalesced path, bounded, not a stall.
        assert wall < 30.0


class _FakeIdPTransport:
    """OAuth transport double: token endpoint + profile endpoint with
    scriptable outcomes."""

    def __init__(self):
        self.token_hits = 0
        self.profile_hits = 0
        self.token_delay_s = 0.0
        self.profile_error = None  # HTTP status to raise, or None
        self.rotate_to = None      # refresh_token rotation

    def __call__(self, req, timeout):
        url = req.full_url
        if "token" in url:
            self.token_hits += 1
            if self.token_delay_s:
                time.sleep(self.token_delay_s)
            body = {"access_token": "at-1"}
            if self.rotate_to:
                body["refresh_token"] = self.rotate_to
            return _Resp(body)
        self.profile_hits += 1
        if self.profile_error is not None:
            import io

            raise urllib.error.HTTPError(
                url, self.profile_error, "err", None, io.BytesIO(b"")
            )
        return _Resp({"email": "u@x", "login": "u"})


class _Resp:
    def __init__(self, body):
        self._body = json.dumps(body).encode()

    def read(self):
        return self._body

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def _oauth(transport):
    from dragonfly2_tpu.manager.oauth import OAuthProvider, OAuthSignin
    from dragonfly2_tpu.manager.users import UserStore

    users = UserStore(db_path=None)
    oauth = OAuthSignin(users, transport=transport)
    oauth.register(OAuthProvider(
        name="prov", client_id="c", client_secret="s",
        auth_url="https://idp/auth", token_url="https://idp/token",
        profile_url="https://idp/profile",
    ))
    return oauth


class TestOAuthRefreshHardening:
    def test_handle_single_use_one_idp_redemption_under_race(self):
        tr = _FakeIdPTransport()
        tr.token_delay_s = 0.3
        oauth = _oauth(tr)
        rid = oauth._store_grant("prov", "uid-1", "rt-0")
        outcomes = []

        def go():
            try:
                outcomes.append(("ok", oauth.refresh(rid)[1]))
            except PermissionError as exc:
                outcomes.append(("denied", str(exc)))

        threads = [threading.Thread(target=go) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        # Exactly ONE redemption reached the IdP: a rotation-strict
        # provider sees one use of the refresh token, not token theft.
        assert tr.token_hits == 1
        assert sorted(o[0] for o in outcomes) == ["denied", "ok"]

    def test_profile_401_destroys_grant(self):
        tr = _FakeIdPTransport()
        tr.profile_error = 401
        oauth = _oauth(tr)
        rid = oauth._store_grant("prov", "uid-1", "rt-0")
        with pytest.raises(PermissionError):
            oauth.refresh(rid)
        assert rid not in oauth._grants  # destroyed → re-authenticate
        with pytest.raises(PermissionError):
            oauth.refresh(rid)  # unknown handle now

    def test_profile_5xx_is_transient_and_keeps_rotated_token(self):
        from dragonfly2_tpu.manager.oauth import OAuthUnavailable

        tr = _FakeIdPTransport()
        tr.profile_error = 503
        tr.rotate_to = "rt-1"
        oauth = _oauth(tr)
        rid = oauth._store_grant("prov", "uid-1", "rt-0")
        with pytest.raises(OAuthUnavailable):
            oauth.refresh(rid)
        # Grant survived AND carries the ROTATED token (rt-0 is dead at
        # the IdP after the redemption above).
        assert oauth._grants[rid][2] == "rt-1"
        # IdP recovers → the same handle refreshes fine.
        tr.profile_error = None
        user, new_rid = oauth.refresh(rid)
        assert user.name == "prov:u" and new_rid
        assert rid not in oauth._grants  # rotated handle

    def test_token_endpoint_outage_restores_grant(self):
        from dragonfly2_tpu.manager.oauth import OAuthUnavailable

        calls = []

        def down(req, timeout):
            calls.append(req.full_url)
            raise urllib.error.URLError("connection refused")

        oauth = _oauth(down)
        rid = oauth._store_grant("prov", "uid-1", "rt-0")
        with pytest.raises(OAuthUnavailable):
            oauth.refresh(rid)
        assert oauth._grants[rid][2] == "rt-0"  # intact, caller retries


class TestJobResultPersistence:
    def test_unserializable_result_persists_completion(self):
        from dragonfly2_tpu.jobs.queue import JobQueue, JobState
        from dragonfly2_tpu.manager.state import MemoryBackend

        backend = MemoryBackend()
        q = JobQueue(backend=backend)
        job = q.enqueue("preheat", {"urls": ["u"]}, queue_name="q-s")
        popped = q.poll("q-s", timeout=1.0)
        assert popped.id == job.id

        q.set_result(job.id, JobState.SUCCESS, result=object())  # not JSON

        # A restarted manager reloads the broker from the same backend:
        # the job is SUCCESS with result=None — NOT a STARTED row that
        # the stale-visibility requeue would guarantee-redeliver.
        q2 = JobQueue(backend=backend)
        reloaded = q2.jobs[job.id]
        assert reloaded.state is JobState.SUCCESS
        assert reloaded.result is None
        assert q2.poll("q-s", timeout=0.2, requeue_started_after_s=0.01) is None
