"""Native (C++) wire-ingest engine vs the Python spec adapter
(native/src/native.cpp oi_* vs trainer/online_graph.WireIngestAdapter).

The Python adapter is the SPEC: mapping, lifecycle, accumulation and
edge ordering must match byte-for-byte for the same arrival order (the
engine allocates ids per-chunk sorted-unique over both endpoint columns,
exactly like the spec).  These tests drive both implementations with
identical streams and injected clocks and diff every observable.
"""

import numpy as np
import pytest

from dragonfly2_tpu.models.hop import HopConfig
from dragonfly2_tpu.records.features import DOWNLOAD_COLUMNS
from dragonfly2_tpu.records.synthetic import SyntheticCluster
from dragonfly2_tpu.trainer.online_graph import OnlineGraphConfig, OnlineGraphTrainer
from dragonfly2_tpu.trainer.train import TrainConfig

pytestmark = pytest.mark.skipif(
    not __import__("dragonfly2_tpu.native", fromlist=["available"]).available(),
    reason="native library unavailable",
)

N = 64


def _mk(native: bool, ttl: float = 0.0, **kw):
    cluster = SyntheticCluster(num_hosts=N, seed=0)
    rng = np.random.default_rng(1)
    src = rng.integers(0, N, N * 4)
    dst = (src + 1 + rng.integers(0, N - 1, N * 4)) % N
    defaults = dict(
        num_nodes=N,
        max_neighbors=8,
        batch_size=128,
        super_steps=2,
        queue_capacity=16,
        node_ttl=ttl,
        native_ingest=native,
        model=HopConfig(hidden=16, out_dim=8, node_embed_dim=4, dropout=0.0),
        train=TrainConfig(warmup_steps=2),
        total_steps_hint=500,
    )
    ckpt = kw.pop("checkpoint_dir", None)
    defaults.update(kw)
    tr = OnlineGraphTrainer(
        OnlineGraphConfig(**defaults),
        node_feats=cluster._host_feature_matrix(),
        topo_src=src, topo_dst=dst,
        topo_rtt=(cluster._rtt_vec(src, dst, noise=False) / 1e9).astype(
            np.float32
        ),
        checkpoint_dir=ckpt,
    )
    ad = tr.make_wire_adapter()
    t = {"now": 1000.0}
    ad.clock = lambda: t["now"]
    return tr, ad, t


def _lookup(ad, buckets):
    b = np.asarray(buckets)
    if ad._native is not None:
        return ad._native.lookup(b.astype(np.float32))
    return ad._id_table[b.astype(np.int64)].copy()


def _rows(src_b, dst_b, rng):
    n = len(src_b)
    rows = rng.random((n, len(DOWNLOAD_COLUMNS))).astype(np.float32)
    rows[:, 0] = src_b
    rows[:, 1] = dst_b
    rows[:, -1] = np.log1p(rng.random(n).astype(np.float32) * 50.0)
    return rows


class TestParityWithSpec:
    def test_mapping_edges_features_match_python_spec(self):
        """Same stream → identical id mapping, identical dispatch
        blocks, identical feature means, identical counters."""
        tr_py, ad_py, t_py = _mk(False)
        tr_nat, ad_nat, t_nat = _mk(True)
        assert ad_nat._native is not None, "native path did not engage"
        rng = np.random.default_rng(7)
        chunks = []
        for i in range(4):
            sb = rng.integers(0, 50_000, 96)
            db = rng.integers(0, 50_000, 96)
            keep = sb != db
            chunks.append(_rows(sb[keep], db[keep], rng))
        for c in chunks:
            ad_py.feed_download_rows(c.copy())
            ad_nat.feed_download_rows(c.copy())

        all_buckets = np.unique(
            np.concatenate([c[:, :2].ravel() for c in chunks])
        ).astype(np.int64)
        np.testing.assert_array_equal(
            _lookup(ad_py, all_buckets), _lookup(ad_nat, all_buckets)
        )
        assert ad_py.overflow_edges == ad_nat.overflow_edges
        np.testing.assert_allclose(
            ad_py.node_features(), ad_nat.node_features(), rtol=1e-6
        )
        # Dispatch blocks come out identical (queue path vs edge ring).
        b_py = tr_py._next_dispatch_block(timeout=1.0)
        b_nat = tr_nat._next_dispatch_block(timeout=1.0)
        assert (b_py is None) == (b_nat is None)
        if b_py is not None:
            for a, b in zip(b_py, b_nat):
                np.testing.assert_array_equal(a, b)

    def test_churn_parity_with_injected_clocks(self):
        """TTL eviction: same clocks → same evictions, same recycled id
        sets, same post-churn mapping on both engines."""
        tr_py, ad_py, t_py = _mk(False, ttl=10.0)
        tr_nat, ad_nat, t_nat = _mk(True, ttl=10.0)
        rng1, rng2 = (np.random.default_rng(3) for _ in range(2))
        for phase in range(3):
            b = np.arange(N, dtype=np.int64) + 10_000 * (phase + 1)
            for ad, t, rng in ((ad_py, t_py, rng1), (ad_nat, t_nat, rng2)):
                t["now"] = 1000.0 + phase * 40.0
                ad.feed_download_rows(_rows(b, np.roll(b, 1), rng))
            assert ad_py.evicted_nodes == ad_nat.evicted_nodes == phase * N
            np.testing.assert_array_equal(
                _lookup(ad_py, b), _lookup(ad_nat, b)
            )
        assert ad_py.overflow_edges == ad_nat.overflow_edges == 0
        # Same recycle queues reach the trainers.
        n_py = tr_py.apply_pending_recycles()
        n_nat = tr_nat.apply_pending_recycles()
        assert n_py == n_nat == N
        assert tr_py.nodes_recycled == tr_nat.nodes_recycled


class TestNativeTraining:
    def test_block_source_trains_and_counts(self):
        """Dispatch blocks come straight from the C++ ring: the trainer
        runs, records count, loss is finite, EOF ends the run."""
        tr, ad, t = _mk(True)
        rng = np.random.default_rng(5)
        need = 2 * 128  # super_steps * batch
        b = np.arange(N, dtype=np.int64) + 10_000
        fed = 0
        while fed < 3 * need:
            sb = rng.choice(b, 256)
            db = rng.choice(b, 256)
            keep = sb != db
            fed += int(keep.sum())
            ad.feed_download_rows(_rows(sb[keep], db[keep], rng))
        assert tr.run(max_dispatches=3, idle_timeout=2.0) == 3
        assert tr.records_seen == 3 * need
        tr.end_of_stream()
        assert tr.run(max_dispatches=1, idle_timeout=0.5) == 0  # EOF drains
        v = tr.eval_mae(
            rng.integers(0, N, 128), rng.integers(0, N, 128),
            rng.random(128).astype(np.float32),
        )
        assert np.isfinite(v)

    def test_feed_downloads_rejected_with_native_adapter(self):
        tr, ad, _ = _mk(True)
        with pytest.raises(RuntimeError, match="wire adapter"):
            tr.feed_downloads(
                np.zeros(4, np.int32), np.ones(4, np.int32),
                np.zeros(4, np.float32),
            )

    def test_backpressure_blocks_until_taken(self):
        """A full edge ring blocks the feeder (wire backpressure) until
        the trainer takes a block."""
        import threading

        tr, ad, t = _mk(True, queue_capacity=1, super_steps=1, batch_size=64)
        rng = np.random.default_rng(9)
        b = np.arange(N, dtype=np.int64) + 10_000
        ring_cap = 2 * 64  # max(queue_capacity, 2) * super * batch
        done = threading.Event()

        def feeder():
            fed = 0
            while fed < ring_cap + 64:  # one block beyond capacity
                sb, db = rng.choice(b, 64), rng.choice(b, 64)
                keep = sb != db
                fed += int(keep.sum())
                ad.feed_download_rows(_rows(sb[keep], db[keep], rng))
            done.set()

        th = threading.Thread(target=feeder, daemon=True)
        th.start()
        assert not done.wait(0.5), "feeder never blocked on the full ring"
        assert tr.run(max_dispatches=2, idle_timeout=5.0) == 2
        assert done.wait(5.0), "feeder did not resume after space freed"
        th.join(5.0)


class TestCheckpointInterop:
    def test_native_checkpoint_restores_into_python_and_back(self, tmp_path):
        """The adapter state format is engine-agnostic: a mapping built
        natively restores into the python adapter (and back) with ids,
        free pool and feature accumulators intact."""
        rng = np.random.default_rng(11)
        b = np.arange(N, dtype=np.int64) + 10_000

        tr1, ad1, t1 = _mk(True, ttl=10.0, checkpoint_dir=str(tmp_path))
        tr1.checkpoint_dir = str(tmp_path)
        ad1.feed_download_rows(_rows(b, np.roll(b, 1), rng))
        mapping = _lookup(ad1, b)
        feats = ad1.node_features()
        tr1.checkpoint()

        tr2, ad2, t2 = _mk(False, ttl=10.0)
        tr2.checkpoint_dir = str(tmp_path)
        assert tr2.resume()
        np.testing.assert_array_equal(_lookup(ad2, b), mapping)
        np.testing.assert_allclose(ad2.node_features(), feats, rtol=1e-6)

        tr3, ad3, t3 = _mk(True, ttl=10.0)
        tr3.checkpoint_dir = str(tmp_path)
        assert tr3.resume()
        np.testing.assert_array_equal(_lookup(ad3, b), mapping)
        assert ad3._native.stats()["next_id"] == N
