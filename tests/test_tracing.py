"""Flight recorder (DESIGN.md §21): durable trace log framing, head
sampling, the tracing toggle, traceparent fuzzing through the parser and
both transports, and cross-process trace assembly with critical-path
analysis (tools/trace_assemble.py).
"""

from __future__ import annotations

import json
import os
import sys
import zlib
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from dragonfly2_tpu.utils import tracing  # noqa: E402


@pytest.fixture()
def tracer(tmp_path):
    """A scoped tracer with a durable log; default tracer untouched."""
    path = str(tmp_path / "proc.dftrace")
    exporter = tracing.DurableSpanExporter(path, service="test")
    t = tracing.Tracer("test", exporter)
    yield t, path, exporter
    exporter.close()


class TestDurableTraceLog:
    def test_roundtrip_and_schema(self, tracer):
        import jsonschema

        t, path, _ = tracer
        with t.span("a", x=1, big=2**40, f=0.5, flag=True):
            with t.span("b"):
                pass
        requests, stats = tracing.replay_trace_log(path)
        assert stats == {"frames": 2, "corrupt": 0, "torn_tail": False}
        spans = list(tracing.log_spans(requests))
        assert {s["name"] for s in spans} == {"a", "b"}
        assert all(s["service"] == "test" for s in spans)
        # Every durable batch validates against the vendored OTLP schema.
        validator = jsonschema.Draft202012Validator(
            tracing.otlp_trace_schema()
        )
        for req in requests:
            validator.validate(req)

    def test_torn_tail_tolerated(self, tracer):
        t, path, _ = tracer
        with t.span("a"):
            pass
        with open(path, "ab") as f:
            f.write(b"DFTL1 9999 00000000\n{\"resourceSpans")  # SIGKILL mid-append
        requests, stats = tracing.replay_trace_log(path)
        assert stats["frames"] == 1
        assert stats["torn_tail"] is True
        assert stats["corrupt"] == 0

    def test_digest_bad_frame_never_admitted(self, tracer):
        t, path, _ = tracer
        with t.span("a"):
            pass
        with t.span("b"):
            pass
        data = open(path, "rb").read()
        # Flip one payload byte of the FIRST frame: its crc fails, the
        # second frame must still be admitted (resync on magic).
        idx = data.find(b'"name": "a"')
        assert idx > 0
        mutated = data[:idx + 10] + b"X" + data[idx + 11:]
        open(path, "wb").write(mutated)
        requests, stats = tracing.replay_trace_log(path)
        assert stats["corrupt"] == 1
        names = [s["name"] for s in tracing.log_spans(requests)]
        assert names == ["b"]

    def test_truncated_frame_mid_file_resyncs(self, tracer):
        t, path, _ = tracer
        with t.span("a"):
            pass
        with open(path, "ab") as f:
            f.write(b"DFTL1 500 deadbeef\n{\"partial")
        with t.span("c"):
            pass
        requests, stats = tracing.replay_trace_log(path)
        assert stats == {"frames": 2, "corrupt": 1, "torn_tail": False}

    def test_frame_digest_matches_payload(self, tracer):
        t, path, _ = tracer
        with t.span("a"):
            pass
        raw = open(path, "rb").read()
        header, rest = raw.split(b"\n", 1)
        magic, length, crc = header.split(b" ")
        assert magic == b"DFTL1"
        payload = rest[: int(length)]
        assert int(crc, 16) == (zlib.crc32(payload) & 0xFFFFFFFF)
        json.loads(payload)  # the payload is one OTLP/JSON request

    def test_missing_log_replays_empty(self, tmp_path):
        requests, stats = tracing.replay_trace_log(str(tmp_path / "nope"))
        assert requests == [] and stats["frames"] == 0


class TestHeadSampling:
    def test_deterministic_and_proportional(self):
        import random

        rng = random.Random(7)
        ids = ["%032x" % rng.getrandbits(128) for _ in range(4000)]
        kept = [t for t in ids if tracing.trace_sampled(t, 0.1)]
        # Deterministic: the same decision on every "process".
        assert kept == [t for t in ids if tracing.trace_sampled(t, 0.1)]
        assert 0.05 < len(kept) / len(ids) < 0.2
        assert all(tracing.trace_sampled(t, 1.0) for t in ids[:10])
        assert not any(tracing.trace_sampled(t, 0.0) for t in ids[:10])

    def test_sampling_keeps_whole_traces(self, tmp_path):
        """Child spans share the root's trace id, so one decision keeps
        or drops the whole per-process shard of a trace."""
        path = str(tmp_path / "s.dftrace")
        exporter = tracing.DurableSpanExporter(
            path, service="t", sample_rate=0.5
        )
        t = tracing.Tracer("t", exporter)
        for _ in range(50):
            with t.span("root"):
                with t.span("child"):
                    pass
        requests, _ = tracing.replay_trace_log(path)
        spans = list(tracing.log_spans(requests))
        by_trace = {}
        for s in spans:
            by_trace.setdefault(s["traceId"], set()).add(s["name"])
        # Every kept trace kept BOTH spans.
        assert by_trace and all(v == {"root", "child"} for v in by_trace.values())
        assert exporter.sampled_out > 0


class TestTracingToggle:
    def test_disabled_spans_are_noops(self, tmp_path):
        path = str(tmp_path / "t.dftrace")
        exporter = tracing.DurableSpanExporter(path, service="t")
        t = tracing.Tracer("t", exporter)
        tracing.set_enabled(False)
        try:
            with t.span("invisible") as s:
                s.set(x=1)
                assert t.inject() == {}
                assert t.current_trace_id() is None
        finally:
            tracing.set_enabled(True)
        with t.span("visible"):
            pass
        names = [
            s["name"]
            for s in tracing.log_spans(tracing.replay_trace_log(path)[0])
        ]
        assert names == ["visible"]


class TestCompositeExporter:
    def test_ring_plus_durable_and_debug_dump(self, tmp_path):
        path = str(tmp_path / "c.dftrace")
        ring = tracing.InMemoryExporter(max_spans=8)
        durable = tracing.DurableSpanExporter(path, service="svc")
        t = tracing.Tracer("svc", tracing.CompositeExporter([ring, durable]))
        with t.span("x"):
            pass
        assert len(ring.find("x")) == 1
        assert tracing.replay_trace_log(path)[1]["frames"] == 1
        dump = tracing.recent_spans_otlp(t)
        names = [s["name"] for s in tracing.log_spans([dump])]
        assert names == ["x"]
        import jsonschema

        jsonschema.Draft202012Validator(tracing.otlp_trace_schema()).validate(dump)


HOSTILE_TRACEPARENTS = [
    "",
    "garbage",
    "00",
    "00-" + "g" * 32 + "-" + "a" * 16 + "-01",          # non-hex trace id
    "00-" + "a" * 31 + "-" + "b" * 16 + "-01",          # short trace id
    "00-" + "a" * 32 + "-" + "b" * 15 + "-01",          # short span id
    "00-" + "a" * 32 + "-" + "b" * 16,                   # missing flags
    "00-" + "a" * 33 + "-" + "b" * 17 + "-01-extra-extra",
    "00--" + "b" * 16 + "-01",
    "\x00\x01\x02",
    "00-" + "a" * 32 + "-" + "b" * 16 + "-01" + "\n" * 50,
    "トレース-ペアレント-ヘッダ-01",
    "00-" + "A" * 32 + "-" + "B" * 16 + "-01",          # uppercase hex is valid
    "a" * 10_000,
    "-".join(["00"] * 200),
]


class TestTraceparentFuzz:
    @pytest.mark.parametrize("value", HOSTILE_TRACEPARENTS)
    def test_parse_never_raises(self, value):
        parsed = tracing.parse_traceparent(value)
        if parsed is not None:
            trace_id, span_id = parsed
            assert len(trace_id) == 32 and len(span_id) == 16
            int(trace_id, 16), int(span_id, 16)

    @pytest.mark.parametrize("value", HOSTILE_TRACEPARENTS)
    def test_remote_span_falls_back_to_local_root(self, value):
        t = tracing.Tracer("t", tracing.InMemoryExporter())
        with t.remote_span("handler", value) as span:
            assert len(span.trace_id) == 32
            parsed = tracing.parse_traceparent(value)
            if parsed is None:
                assert span.parent_id is None  # clean local root
            else:
                assert span.trace_id == parsed[0]
                assert span.parent_id == parsed[1]

    def test_http_transport_survives_hostile_headers(self, tmp_path):
        """Malformed traceparent on the wire: 200s, handler runs, local
        root span — never a 500."""
        from dragonfly2_tpu.records.storage import Storage
        from dragonfly2_tpu.rpc import SchedulerHTTPServer
        from dragonfly2_tpu.scheduler import (
            Evaluator,
            NetworkTopology,
            Resource,
            SchedulerService,
            Scheduling,
            SchedulingConfig,
        )

        resource = Resource()
        service = SchedulerService(
            resource,
            Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)),
            Storage(str(tmp_path / "records"), buffer_size=1),
            NetworkTopology(resource.host_manager),
        )
        server = SchedulerHTTPServer(service)
        server.serve()
        try:
            import urllib.request

            for value in HOSTILE_TRACEPARENTS:
                body = json.dumps(
                    {"host": {"id": "h-fuzz", "hostname": "h", "ip": "1.1.1.1"}}
                ).encode()
                headers = {"Content-Type": "application/json"}
                # urllib forbids control chars in header values; that
                # rejection IS the clean client-side fallback.
                try:
                    req = urllib.request.Request(
                        server.url + "/rpc/announce_host",
                        data=body,
                        headers={**headers, "traceparent": value},
                        method="POST",
                    )
                except ValueError:
                    continue
                try:
                    with urllib.request.urlopen(req, timeout=5) as resp:
                        assert resp.status == 200
                except ValueError:
                    continue
        finally:
            server.stop()


class TestTraceAssembly:
    def _two_process_logs(self, tmp_path, *, kill_parent_export=False):
        """A daemon-side and scheduler-side log for ONE download trace.
        With ``kill_parent_export`` the daemon's root span never exports
        (the SIGKILL signature) and its log gets a torn tail."""
        dlog = str(tmp_path / "daemon.dftrace")
        slog = str(tmp_path / "sched.dftrace")
        d_exp = tracing.DurableSpanExporter(dlog, service="dfdaemon")
        s_exp = tracing.DurableSpanExporter(slog, service="scheduler")
        daemon = tracing.Tracer("dfdaemon", d_exp)
        sched = tracing.Tracer("scheduler", s_exp)
        root_cm = daemon.span("daemon/download", task_id="t1")
        root = root_cm.__enter__()
        tp = root.traceparent
        with sched.remote_span("rpc/register_peer", tp):
            pass
        for n in range(3):
            with daemon.span("daemon/piece", number=n) as ps:
                ps.set(bytes=4096, parent="p0", retries=0)
                with sched.remote_span("rpc/report_piece_finished", daemon.inject()["traceparent"]):
                    pass
        # The PR-11 batched-report window: one flush span carrying the
        # download's context, with the batched RPC's handler span inside.
        with daemon.remote_span("daemon/report.flush", tp, reports=3):
            with sched.remote_span(
                "rpc/report_pieces_finished", daemon.inject()["traceparent"]
            ):
                pass
        if kill_parent_export:
            # Root never exports; the log ends in a torn frame.  Sever
            # the exporter too — otherwise the root contextmanager's GC
            # finalization would "export after death", which no SIGKILLed
            # process gets to do.
            with open(dlog, "ab") as f:
                f.write(b"DFTL1 4096 0badf00d\n{\"resourceSp")
            d_exp.export = lambda span: None
        else:
            root_cm.__exit__(None, None, None)
            with sched.remote_span("rpc/report_peer_finished", tp):
                pass
        return dlog, slog, root.trace_id

    def test_critical_path_and_phases(self, tmp_path):
        from tools.trace_assemble import build_report

        dlog, slog, trace_id = self._two_process_logs(tmp_path)
        report = build_report([dlog, slog], validate=True)
        trace = report["trace"]
        assert trace["trace_id"] == trace_id
        assert set(trace["services"]) == {"dfdaemon", "scheduler"}
        assert trace["critical_path"][0]["name"] == "daemon/download"
        assert {"schedule", "piece", "commit", "download"} <= set(trace["phases"])
        assert trace["anomalies"] == []

    def test_data_plane_phase_breakdown(self, tmp_path):
        """The per-download table splits the PR-11 data plane: piece
        FETCH (daemon/piece), COMMIT acknowledgment (the scheduler's
        report handlers, batched RPC included), and the REPORT-FLUSH
        window (daemon/report.flush) each get their own phase row."""
        from tools.trace_assemble import build_report, phase_of, render_report

        assert phase_of("daemon/piece") == "piece"
        assert phase_of("daemon/report.flush") == "report_flush"
        assert phase_of("rpc/report_piece_finished") == "commit"
        assert phase_of("rpc/report_pieces_finished") == "commit"
        dlog, slog, _ = self._two_process_logs(tmp_path)
        report = build_report([dlog, slog], validate=True)
        phases = report["trace"]["phases"]
        assert phases["piece"]["count"] == 3
        assert phases["report_flush"]["count"] == 1
        # Per-piece reports AND the batched flush RPC both land in commit.
        assert phases["commit"]["count"] == 5
        rendered = render_report(report)
        assert "| report_flush | 1 |" in rendered

    def test_torn_log_still_assembles_with_anomalies(self, tmp_path):
        from tools.trace_assemble import build_report

        dlog, slog, trace_id = self._two_process_logs(
            tmp_path, kill_parent_export=True
        )
        report = build_report([dlog, slog], validate=True)
        daemon_log = next(
            log for log in report["logs"] if "daemon" in log["path"]
        )
        assert daemon_log["torn_tail"] is True
        trace = report["trace"]
        assert trace["trace_id"] == trace_id
        # Orphans (the unexported download root) are flagged, and the
        # critical path still renders from the surviving spans.
        assert any("orphan" in a for a in trace["anomalies"])
        assert trace["critical_path"]

    def test_markdown_render_and_marker_update(self, tmp_path):
        from tools.trace_assemble import (
            ASSEMBLY_BEGIN,
            ASSEMBLY_END,
            build_report,
            render_report,
            update_file,
        )

        dlog, slog, _ = self._two_process_logs(tmp_path)
        rendered = render_report(build_report([dlog, slog]))
        assert rendered.startswith(ASSEMBLY_BEGIN)
        assert rendered.endswith(ASSEMBLY_END)
        assert "Critical path:" in rendered
        doc = tmp_path / "OBS.md"
        doc.write_text(f"# head\n{ASSEMBLY_BEGIN}\nstale\n{ASSEMBLY_END}\ntail\n")
        assert update_file(doc, rendered) is True
        assert update_file(doc, rendered) is False  # idempotent
        text = doc.read_text()
        assert "stale" not in text and "# head" in text and "tail" in text

    def test_gap_detection(self, tmp_path):
        from tools.trace_assemble import build_report

        path = str(tmp_path / "gap.dftrace")
        exp = tracing.DurableSpanExporter(path, service="svc")
        t = tracing.Tracer("svc", exp)
        import time as _time

        with t.span("daemon/download"):
            with t.span("daemon/piece", number=0):
                pass
            _time.sleep(0.08)  # nobody doing attributable work
            with t.span("daemon/piece", number=1):
                pass
        report = build_report([path], gap_ms=50.0)
        gaps = report["trace"]["gaps"]
        assert gaps and gaps[0]["duration_ms"] >= 50.0

    def test_cli_json_mode(self, tmp_path, capsys):
        from tools.trace_assemble import main

        dlog, slog, trace_id = self._two_process_logs(tmp_path)
        assert main([dlog, slog, "--json", "--validate"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["trace"]["trace_id"] == trace_id
        assert out["traces"] >= 1
