"""Multi-tenant QoS chaos drills (DESIGN.md §26, ISSUE 15).

1. SIGKILL-mid-burst: a shard serving a two-tenant overload storm (rate
   caps + band sheds firing) is SIGKILLed at a deterministic
   ``scheduler.qos.shed`` fire via a crash FaultSpec.  The replacement
   process rebuilds shed state and tenant accounting from traffic alone
   — two independent rebuilds over the same deterministic stream must
   agree (nothing about the kill leaks into a fresh process), and the
   accounting invariants must hold (every request accounted exactly
   once, caps ⊆ sheds, the noisy tenant identified).

2. Isolation (small-scale in-tree twin of tools/bench_qos.py): the
   shaped arm's interference on tenant A must be far below the
   unshaped arm's, the flood must actually be shed/capped, and tenant
   A's downloads must all complete under the shaped burst.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from dragonfly2_tpu.utils.faultinject import FaultSpec  # noqa: E402

CHILD = REPO / "tests" / "_qos_child.py"


def _run_child(mode: str, *, scenario=None, timeout=120):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "DF_LOCK_WITNESS": "0",
        "DF_SPAN_WITNESS": "0",
        "DF_CRASH_WITNESS": "0",
    }
    if scenario is not None:
        env["DF_FAULTINJECT"] = json.dumps(scenario)
    else:
        env.pop("DF_FAULTINJECT", None)
    proc = subprocess.Popen(
        [sys.executable, str(CHILD), mode],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        cwd=str(REPO),
    )
    return proc


class TestQoSKillDrill:
    def test_sigkill_mid_burst_and_clean_rebuild(self):
        # The storm dies at its 400th QoS shed — deep enough that caps
        # and band sheds have both fired, mid-burst by construction.
        scenario = {
            "seed": 11,
            "faults": [
                FaultSpec(
                    site="scheduler.qos.shed", kind="crash", at=(400,),
                ).to_dict(),
            ],
        }
        proc = _run_child("hammer", scenario=scenario)
        try:
            out, err = proc.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, err = proc.communicate()
            pytest.fail(f"hammer child hung: {out!r} {err!r}")
        assert proc.returncode == -signal.SIGKILL, (
            proc.returncode, out, err,
        )
        assert b"qos-child: ready" in out

        # The replacement shard rebuilds accounting from traffic alone;
        # two independent rebuilds must agree.
        verdicts = []
        for _ in range(2):
            proc = _run_child("rebuild")
            out, err = proc.communicate(timeout=120)
            assert proc.returncode == 0, (out, err)
            verdicts.append(json.loads(out.strip().splitlines()[-1]))
        v1, v2 = verdicts
        for v in verdicts:
            assert all(v["invariants"].values()), v["invariants"]
            assert v["snapshot"]["t-b"]["sheds"] > 0, (
                "rebuild never shed the noisy tenant"
            )
        # Deterministic structure: request totals are exact; the
        # rate-capped counts ride a real-time token bucket, so they get
        # a small tolerance (the bucket refills in wall time).
        for t in ("t-a", "t-b"):
            assert v1["snapshot"][t]["requests"] == v2["snapshot"][t]["requests"]
            assert v1["outcomes"][t] == pytest.approx(
                v2["outcomes"][t], rel=0.2
            ) or v1["outcomes"][t] == v2["outcomes"][t]
        s1, s2 = v1["snapshot"]["t-b"], v2["snapshot"]["t-b"]
        assert s1["sheds"] == pytest.approx(s2["sheds"], rel=0.2)
        assert s1["over_quota"] == pytest.approx(s2["over_quota"], rel=0.1)


class TestQoSIsolationDrill:
    def test_shaped_burst_isolates_tenant_a(self):
        from dragonfly2_tpu.sim.qos import QoSDrillConfig, run_isolation_drill

        out = run_isolation_drill(QoSDrillConfig(
            a_announces=300, a_downloads=4, pieces_per_task=4,
            piece_size=32 * 1024, b_threads=2,
        ))
        shaped, unshaped = out["shaped"], out["unshaped"]
        # The flood really ran unshaped and was really shed/capped
        # shaped.
        assert unshaped["b_offered"] > 100
        assert shaped["b_sheds"] + shaped["b_throttled"] > 0
        # Tenant A's downloads all complete under the shaped burst.
        assert shaped["a_downloads_ok"] == 4
        # Directional isolation (robust to 1-CPU noise; the <10%
        # absolute bar is the bench's regression-guarded headline over
        # interleaved rounds): the shaped TTLB interference is a small
        # fraction of the unshaped interference.
        move = out["movement"]
        assert move["unshaped_ttlb_pct"] > 50.0, move
        assert (
            max(move["shaped_ttlb_pct"], 0.0)
            < move["unshaped_ttlb_pct"] / 2.0
        ), move
        # The seed's bandwidth accounting attributes the flood to B.
        assert shaped["seed_tenant_bytes"].get("t-b", 0) < (
            unshaped["seed_tenant_bytes"].get("t-b", 0)
        )

    def test_drill_is_wired_through_real_admission(self):
        """The shaped arm's accounting snapshot names both tenants with
        the bounded classes — proof the drill exercises the real plane,
        not a mock."""
        from dragonfly2_tpu.sim.qos import QoSDrillConfig, run_isolation_drill

        out = run_isolation_drill(QoSDrillConfig(
            a_announces=120, a_downloads=2, pieces_per_task=2,
            piece_size=16 * 1024, b_threads=1,
        ))
        acct = out["shaped"]["tenant_accounting"]
        assert acct["t-a"]["tenant_class"] == "gold"
        assert acct["t-b"]["tenant_class"] == "background"
        assert acct["t-b"]["requests"] > 0
