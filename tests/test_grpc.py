"""gRPC transport: the same adapters as the HTTP/JSON wire, over binary
protobuf — scheduler unary RPCs driving a real P2P swarm, trainer Train
client-streaming ingest, error-code mapping."""

import glob
import os

import pytest

from dragonfly2_tpu.daemon import DaemonStorage, UploadManager
from dragonfly2_tpu.daemon.conductor import Conductor
from dragonfly2_tpu.records.storage import Storage
from dragonfly2_tpu.rpc import HTTPPieceFetcher, PieceHTTPServer
from dragonfly2_tpu.rpc.grpc_transport import (
    GRPCRemoteScheduler,
    GRPCTrainerClient,
    SchedulerGRPCServer,
    TrainerGRPCServer,
)
from dragonfly2_tpu.rpc.scheduler_client import RPCError
from dragonfly2_tpu.scheduler import (
    Evaluator,
    NetworkTopology,
    Resource,
    SchedulerService,
    Scheduling,
    SchedulingConfig,
)
from dragonfly2_tpu.scheduler.resource import Host

PIECE = 32 * 1024


class WireOrigin:
    def __init__(self):
        self.fetches = 0

    def content(self, url, number):
        seed = (hash(url) ^ number) & 0xFF
        return bytes((seed + i) % 256 for i in range(PIECE))

    def fetch(self, url, number, piece_size):
        self.fetches += 1
        return self.content(url, number)


class GRPCNode:
    def __init__(self, i, target, tmp_path, origin):
        self.storage = DaemonStorage(str(tmp_path / f"gnode{i}"), prefer_native=False)
        self.upload = UploadManager(self.storage)
        self.piece_server = PieceHTTPServer(self.upload)
        self.piece_server.serve()
        self.host = Host(
            id=f"gnode-{i}",
            hostname=f"gnode-{i}",
            ip="127.0.0.1",
            download_port=self.piece_server.port,
        )
        self.host.stats.network.idc = "idc-a"
        self.client = GRPCRemoteScheduler(target)
        self.conductor = Conductor(
            self.host,
            self.storage,
            self.client,
            piece_fetcher=HTTPPieceFetcher(self.client.resolve_host),
            source_fetcher=origin,
        )

    def stop(self):
        self.piece_server.stop()
        self.client.close()


@pytest.fixture()
def grpc_swarm(tmp_path):
    resource = Resource()
    service = SchedulerService(
        resource,
        Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)),
        Storage(str(tmp_path / "records"), buffer_size=1),
        NetworkTopology(resource.host_manager),
    )
    server = SchedulerGRPCServer(service)
    server.serve()
    origin = WireOrigin()
    nodes = [GRPCNode(i, server.target, tmp_path, origin) for i in range(3)]
    yield {"server": server, "service": service, "nodes": nodes, "origin": origin}
    for n in nodes:
        n.stop()
    server.stop()


class TestSchedulerGRPC:
    def test_p2p_over_grpc(self, grpc_swarm):
        """Whole control plane over binary protobuf: seed back-to-source,
        second node gets the first as parent, records written."""
        nodes, origin = grpc_swarm["nodes"], grpc_swarm["origin"]
        url = "https://origin/grpc-blob"
        r0 = nodes[0].conductor.download(
            url, piece_size=PIECE, content_length=4 * PIECE
        )
        assert r0.ok and r0.back_to_source and r0.pieces == 4
        fetches = origin.fetches

        r1 = nodes[1].conductor.download(url, piece_size=PIECE)
        assert r1.ok and not r1.back_to_source
        assert origin.fetches == fetches
        assert nodes[0].upload.upload_count == 4
        for n in range(4):
            assert nodes[1].storage.read_piece(r1.task_id, n) == \
                origin.content(url, n)

        service = grpc_swarm["service"]
        service.storage.flush()
        downloads = service.storage.list_download()
        assert len(downloads) == 2
        assert [d for d in downloads if d.parents]

    def test_tiny_direct_piece_inline(self, grpc_swarm):
        """TINY tasks ride back inside RegisterPeerResponse.direct_piece."""
        nodes = grpc_swarm["nodes"]
        url = "https://origin/grpc-tiny"

        class TinyOrigin:
            def content_length(self, u):
                return 64

            def fetch(self, u, n, ps):
                return bytes(range(64))

        nodes[0].conductor.source_fetcher = TinyOrigin()
        r0 = nodes[0].conductor.download(url, piece_size=PIECE, content_length=64)
        assert r0.ok
        r1 = nodes[1].conductor.download(url, piece_size=PIECE)
        assert r1.ok and r1.pieces == 1
        assert nodes[1].storage.read_piece(r1.task_id, 0)[:64] == bytes(range(64))

    def test_probe_roundtrip_over_grpc(self, grpc_swarm):
        nodes = grpc_swarm["nodes"]
        for n in nodes:
            n.client.announce_host(n.host)
        targets = nodes[0].client.sync_probes_start(nodes[0].host)
        assert targets  # other announced hosts offered for probing
        results = [(t.id, 5_000_000) for t in targets]
        nodes[0].client.sync_probes_finished(nodes[0].host, results)
        topo = grpc_swarm["service"].networktopology
        edges = topo.neighbours(nodes[0].host.id)
        assert edges

    def test_scheduler_restart_recovery(self, tmp_path):
        """NOT_FOUND carries the typed dfcode over gRPC, so the client's
        re-announce-and-retry branch works after a scheduler restart."""
        def make_server(port=0):
            resource = Resource()
            service = SchedulerService(
                resource,
                Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)),
                None,
                NetworkTopology(resource.host_manager),
            )
            srv = SchedulerGRPCServer(service, port=port)
            srv.serve()
            return srv

        srv = make_server()
        port = srv.address[1]
        client = GRPCRemoteScheduler(srv.target)
        host = Host(id="r-host", hostname="r", ip="127.0.0.1", download_port=1)
        client.announce_host(host)
        # Restart on the SAME port with empty state: the announce is gone.
        # The rebind can transiently fail under suite load (the kernel
        # may briefly hold the port, or another process can race the
        # ephemeral) — retry; the RESTART semantics under test need the
        # same port, not a first-try bind.
        srv.stop()
        srv2 = None
        for attempt in range(20):
            try:
                cand = make_server(port=port)
            except (OSError, RuntimeError):
                cand = None
            # grpc reports a failed bind as port 0, not an exception.
            if cand is not None and cand.address[1] == port:
                srv2 = cand
                break
            if cand is not None:
                cand.stop()
            import time as _time

            _time.sleep(0.25)
        assert srv2 is not None, f"port {port} never rebound"
        try:
            reg = client.register_peer(host=host, url="https://o/restart-blob")
            assert reg.peer.id  # recovered via re-announce, not an error
        finally:
            srv2.stop()
            client.close()

    def test_unknown_peer_maps_to_rpc_error(self, grpc_swarm):
        node = grpc_swarm["nodes"][0]
        import dragonfly2_tpu.rpc.grpc_transport as gt

        with pytest.raises(RPCError) as exc:
            node.client._call("report_peer_finished", {"peer_id": "ghost"})
        assert "NOT_FOUND" in str(exc.value)
        # And the proto round-trip preserves int64 semantics.
        d = gt.proto_to_dict(
            gt.dict_to_proto(
                {"peer_id": "p", "content_length": 5 << 40},
                gt.pb.SetTaskInfoRequest,
            )
        )
        assert d["content_length"] == 5 << 40 and isinstance(
            d["content_length"], int
        )


class TestWireMetrics:
    def test_grpc_and_ratelimit_counters(self):
        from dragonfly2_tpu.rpc.metrics import (
            GRPC_REQUESTS_TOTAL,
            RATE_LIMITED_TOTAL,
        )
        from dragonfly2_tpu.rpc.ratelimit import TokenBucket

        resource = Resource()
        service = SchedulerService(
            resource,
            Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)),
            None,
            NetworkTopology(resource.host_manager),
        )
        server = SchedulerGRPCServer(
            service, rate_limit=TokenBucket(qps=0.001, burst=2)
        )
        server.serve()
        try:
            ok_before = GRPC_REQUESTS_TOTAL.value(
                service="scheduler", method="announce_host", code="OK"
            )
            rl_before = RATE_LIMITED_TOTAL.value(transport="grpc")
            client = GRPCRemoteScheduler(server.target)
            h = Host(id="m1", hostname="m1", ip="127.0.0.1", download_port=1)
            client.announce_host(h)
            client.announce_host(
                Host(id="m2", hostname="m2", ip="127.0.0.1", download_port=1)
            )
            with pytest.raises(RPCError):
                client.announce_host(
                    Host(id="m3", hostname="m3", ip="127.0.0.1", download_port=1)
                )
            assert GRPC_REQUESTS_TOTAL.value(
                service="scheduler", method="announce_host", code="OK"
            ) == ok_before + 2
            assert RATE_LIMITED_TOTAL.value(transport="grpc") == rl_before + 1
            client.close()
        finally:
            server.stop()


class TestTrainerCLIServe:
    def test_serve_mode_starts_both_transports(self, tmp_path):
        import os
        import subprocess
        import sys
        import time
        import urllib.request
        import json as _json

        cfgp = tmp_path / "trainer.yaml"
        cfgp.write_text(
            f"data_dir: {tmp_path}/staging\n"
            "server:\n  host: 127.0.0.1\n  port: 0\n  grpc_port: 0\n"
        )
        env = {**os.environ, "PYTHONPATH": os.getcwd()}
        p = subprocess.Popen(
            [sys.executable, "-m", "dragonfly2_tpu.cli.trainer",
             "--config", str(cfgp), "--console"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        )
        try:
            import select

            line = ""
            deadline = time.time() + 30
            while time.time() < deadline:
                ready, _, _ = select.select(
                    [p.stdout], [], [], max(deadline - time.time(), 0.1)
                )
                if not ready:
                    break
                line = p.stdout.readline()
                if "ingest on" in line:
                    break
            assert "ingest on" in line and "grpc on" in line, line
            http_url = line.split("ingest on ")[1].split()[0]
            grpc_target = line.split("grpc on ")[1].split(",")[0]
            # HTTP ingest answers; gRPC Train stream accepts a session.
            req = urllib.request.Request(
                http_url + "/train/open",
                data=_json.dumps({"ip": "1.2.3.4", "hostname": "s",
                                  "scheduler_id": "s"}).encode(),
                headers={"Content-Type": "application/json"}, method="POST",
            )
            with urllib.request.urlopen(req, timeout=5) as r:
                assert _json.loads(r.read())["session"]
            from dragonfly2_tpu.rpc.grpc_transport import GRPCTrainerClient

            client = GRPCTrainerClient(grpc_target)
            with pytest.raises(Exception):
                client.run_status("nonexistent")  # NOT_FOUND, but reachable
            client.close()
        finally:
            p.kill()


class TestRateLimit:
    def test_token_bucket_refills(self):
        import time

        from dragonfly2_tpu.rpc.ratelimit import TokenBucket, maybe_bucket

        b = TokenBucket(qps=100.0, burst=3)
        assert all(b.take() for _ in range(3))
        assert not b.take()  # drained
        time.sleep(0.05)     # ~5 tokens refill at 100 qps
        assert b.take()
        assert maybe_bucket(0, 0) is None
        assert maybe_bucket(5.0, None) is not None

    def test_grpc_server_rejects_when_drained(self):
        from dragonfly2_tpu.rpc.ratelimit import TokenBucket

        resource = Resource()
        service = SchedulerService(
            resource,
            Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)),
            None,
            NetworkTopology(resource.host_manager),
        )
        server = SchedulerGRPCServer(
            service, rate_limit=TokenBucket(qps=0.001, burst=2)
        )
        server.serve()
        try:
            client = GRPCRemoteScheduler(server.target)
            host = Host(id="rl", hostname="rl", ip="127.0.0.1", download_port=1)
            client.announce_host(host)  # token 1
            client.register_peer(host=host, url="https://o/rl-blob")  # token 2
            with pytest.raises(RPCError) as exc:
                client.announce_host(
                    Host(id="rl2", hostname="rl2", ip="127.0.0.1", download_port=1)
                )
            assert "RESOURCE_EXHAUSTED" in str(exc.value)
            client.close()
        finally:
            server.stop()

    def test_http_server_answers_429(self):
        import json as _json
        import urllib.error
        import urllib.request

        from dragonfly2_tpu.rpc import SchedulerHTTPServer
        from dragonfly2_tpu.rpc.ratelimit import TokenBucket

        resource = Resource()
        service = SchedulerService(
            resource,
            Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)),
            None,
            NetworkTopology(resource.host_manager),
        )
        server = SchedulerHTTPServer(
            service, rate_limit=TokenBucket(qps=0.001, burst=1)
        )
        server.serve()
        try:
            req = urllib.request.Request(
                server.url + "/rpc/announce_host",
                data=_json.dumps({"host": {"id": "h"}}).encode(),
                headers={"Content-Type": "application/json"}, method="POST",
            )
            urllib.request.urlopen(req, timeout=5).read()  # token 1
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=5)
            assert exc.value.code == 429
        finally:
            server.stop()


class TestManagerGRPC:
    def test_model_lifecycle_over_grpc(self):
        from dragonfly2_tpu.manager import ClusterManager, ModelRegistry
        from dragonfly2_tpu.rpc.grpc_transport import (
            GRPCRemoteRegistry,
            ManagerGRPCServer,
        )

        registry = ModelRegistry()
        clusters = ClusterManager()
        server = ManagerGRPCServer(registry, clusters)
        server.serve()
        try:
            client = GRPCRemoteRegistry(server.target)
            m = client.create_model(
                name="gnn", type="gnn", scheduler_id="s1",
                artifact=b"npz-bytes", evaluation={"mae": 0.5},
            )
            assert m.version == 1 and m.state.value == "inactive"
            m2 = client.create_model(
                name="gnn", type="gnn", scheduler_id="s1", artifact=b"v2"
            )
            assert m2.version == 2
            # Single-active activation flips transactionally.
            client.activate(m.id)
            active = client.active_model("s1", "gnn")
            assert active.id == m.id
            client.activate(m2.id)
            assert client.active_model("s1", "gnn").id == m2.id
            assert client.get(m.id).state.value == "inactive"
            assert client.load_artifact(m2) == b"v2"
            assert len(client.list(scheduler_id="s1")) == 2
            assert client.get("ghost") is None
            assert client.active_model("s1", "nope") is None
            client.close()
        finally:
            server.stop()

    def test_rbac_enforced_on_grpc_port(self):
        from dragonfly2_tpu.manager import ClusterManager, ModelRegistry
        from dragonfly2_tpu.rpc.grpc_transport import (
            GRPCRemoteRegistry,
            ManagerGRPCServer,
        )
        from dragonfly2_tpu.security.tokens import Role, TokenIssuer, TokenVerifier

        secret = b"grpc-rbac-secret-0123456789"
        issuer = TokenIssuer(secret)
        server = ManagerGRPCServer(
            ModelRegistry(), ClusterManager(),
            token_verifier=TokenVerifier(secret),
        )
        server.serve()
        try:
            anon = GRPCRemoteRegistry(server.target)
            with pytest.raises(RPCError) as exc:
                anon.create_model(name="m", type="mlp", scheduler_id="s")
            assert "PERMISSION_DENIED" in str(exc.value)
            assert anon.list() == []  # reads stay open
            peer = GRPCRemoteRegistry(
                server.target, token=issuer.issue("trainer", Role.PEER)
            )
            m = peer.create_model(name="m", type="mlp", scheduler_id="s")
            with pytest.raises(RPCError):  # PEER cannot activate
                peer.activate(m.id)
            ops = GRPCRemoteRegistry(
                server.target, token=issuer.issue("ops", Role.OPERATOR)
            )
            assert ops.activate(m.id).state.value == "active"
            # Typed errors match the local registry contract.
            with pytest.raises(KeyError):
                ops.activate("ghost")
            with pytest.raises(ValueError):
                peer.create_model(name="x", type="xgb", scheduler_id="s")
            for c in (anon, peer, ops):
                c.close()
        finally:
            server.stop()

    def test_disable_bites_grpc_sessions_immediately(self):
        """The shared credential resolver: disabling a user kills their
        outstanding session token on the gRPC port too, not at expiry."""
        from dragonfly2_tpu.manager import ClusterManager, ModelRegistry, UserStore
        from dragonfly2_tpu.rpc.grpc_transport import (
            GRPCRemoteRegistry,
            ManagerGRPCServer,
        )
        from dragonfly2_tpu.security.tokens import Role, TokenIssuer, TokenVerifier

        secret = b"grpc-disable-secret-0123456789"
        users = UserStore()
        u = users.create_user("victim", "password123", role=Role.ADMIN)
        session = TokenIssuer(secret).issue(u.id, u.role)
        server = ManagerGRPCServer(
            ModelRegistry(), ClusterManager(),
            token_verifier=TokenVerifier(secret), users=users,
        )
        server.serve()
        try:
            client = GRPCRemoteRegistry(server.target, token=session)
            client.create_model(name="m", type="mlp", scheduler_id="s")
            users.set_state(u.id, "disabled")
            with pytest.raises(RPCError) as exc:
                client.create_model(name="m2", type="mlp", scheduler_id="s")
            assert "PERMISSION_DENIED" in str(exc.value)
            client.close()
        finally:
            server.stop()

    def test_pats_authenticate_on_grpc_port(self):
        """Both ports accept the same credentials: a PAT works over gRPC
        with its capped role, exactly like REST."""
        from dragonfly2_tpu.manager import ClusterManager, ModelRegistry, UserStore
        from dragonfly2_tpu.rpc.grpc_transport import (
            GRPCRemoteRegistry,
            ManagerGRPCServer,
        )
        from dragonfly2_tpu.security.tokens import Role, TokenVerifier

        users = UserStore()
        admin = users.create_user("boss", "password123", role=Role.ADMIN)
        _, peer_pat = users.create_pat(admin.id, "trainer", role=Role.PEER)
        server = ManagerGRPCServer(
            ModelRegistry(), ClusterManager(),
            token_verifier=TokenVerifier(b"grpc-pat-secret-0123456789"),
            users=users,
        )
        server.serve()
        try:
            client = GRPCRemoteRegistry(server.target, token=peer_pat)
            m = client.create_model(name="m", type="mlp", scheduler_id="s")
            with pytest.raises(RPCError):  # PEER-capped: no activation
                client.activate(m.id)
            users.revoke_pat(users.list_pats(admin.id)[0].id)
            with pytest.raises(RPCError):  # revocation applies here too
                client.create_model(name="m2", type="mlp", scheduler_id="s")
            client.close()
        finally:
            server.stop()

    def test_keepalive_and_scheduler_listing(self):
        from dragonfly2_tpu.manager import ClusterManager, ModelRegistry
        from dragonfly2_tpu.rpc.grpc_transport import (
            GRPCRemoteRegistry,
            ManagerGRPCServer,
        )

        clusters = ClusterManager(keepalive_ttl=0.3)
        server = ManagerGRPCServer(ModelRegistry(), clusters)
        server.serve()
        try:
            client = GRPCRemoteRegistry(server.target)
            client.register_scheduler(
                id="sched-g", cluster_id="c1", ip="10.0.0.1", port=8002
            )
            assert [s["id"] for s in client.list_schedulers()] == ["sched-g"]
            assert client.keepalive("sched-g") is True
            assert client.keepalive("ghost") is False
            import time

            time.sleep(0.4)  # TTL expiry without keepalive
            assert client.list_schedulers() == []
            client.close()
        finally:
            server.stop()


class TestFullGRPCLoop:
    def test_four_process_architecture_over_grpc(self, tmp_path, cluster):
        """The complete records → train → registry → activation →
        evaluator loop with EVERY control-plane arrow on binary gRPC:
        manager, scheduler, and trainer in their own OS processes."""
        import os
        import subprocess
        import sys
        import time

        env = {**os.environ, "PYTHONPATH": os.getcwd()}
        procs = []

        def spawn(code, *argv):
            proc = subprocess.Popen(
                [sys.executable, "-c", code, *argv],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, env=env,
            )
            procs.append(proc)
            import select

            ready, _, _ = select.select([proc.stdout], [], [], 30)
            assert ready, "child did not print READY within 30s"
            line = proc.stdout.readline().strip()
            assert line.startswith("READY"), (
                line,
                proc.stderr.read()[:500] if proc.poll() is not None else "",
            )
            return proc, line.split()[1]

        manager_code = (
            "import sys, time\n"
            "from dragonfly2_tpu.manager import ClusterManager, ModelRegistry\n"
            "from dragonfly2_tpu.manager.registry import BlobStore\n"
            "from dragonfly2_tpu.rpc.grpc_transport import ManagerGRPCServer\n"
            "reg = ModelRegistry(BlobStore(sys.argv[1]), db_path=sys.argv[1]+'/m.db')\n"
            "srv = ManagerGRPCServer(reg, ClusterManager())\n"
            "srv.serve(); print('READY', srv.target, flush=True); time.sleep(180)\n"
        )
        scheduler_code = (
            "import sys, time\n"
            "from dragonfly2_tpu.records.storage import Storage\n"
            "from dragonfly2_tpu.rpc.grpc_transport import SchedulerGRPCServer\n"
            "from dragonfly2_tpu.scheduler import Evaluator, Resource, SchedulerService, Scheduling, SchedulingConfig\n"
            "res = Resource()\n"
            "svc = SchedulerService(res, Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)), Storage(sys.argv[1], buffer_size=1))\n"
            "srv = SchedulerGRPCServer(svc)\n"
            "srv.serve(); print('READY', srv.target, flush=True); time.sleep(180)\n"
        )
        trainer_code = (
            "import sys, time\n"
            "from dragonfly2_tpu.rpc.grpc_transport import GRPCRemoteRegistry, TrainerGRPCServer\n"
            "from dragonfly2_tpu.trainer.service import TrainerService\n"
            "from dragonfly2_tpu.trainer.train import TrainConfig\n"
            "svc = TrainerService(GRPCRemoteRegistry(sys.argv[1]), data_dir=sys.argv[2],\n"
            "    train_config=TrainConfig(epochs=6, learning_rate=3e-3, warmup_steps=10))\n"
            "srv = TrainerGRPCServer(svc)\n"
            "srv.serve(); print('READY', srv.target, flush=True); time.sleep(300)\n"
        )

        try:
            mproc, mtarget = spawn(manager_code, str(tmp_path / "manager"))
            sproc, starget = spawn(scheduler_code, str(tmp_path / "records"))
            tproc, ttarget = spawn(trainer_code, mtarget, str(tmp_path / "staged"))

            # Daemons in this process: control plane over gRPC, pieces HTTP.
            origin = WireOrigin()
            nodes = [GRPCNode(i, starget, tmp_path, origin) for i in range(3)]
            url_a = "https://origin/grpc-wire-a"
            r0 = nodes[0].conductor.download(
                url_a, piece_size=PIECE, content_length=4 * PIECE
            )
            assert r0.ok
            for i in (1, 2):
                r = nodes[i].conductor.download(url_a, piece_size=PIECE)
                assert r.ok and not r.back_to_source

            # Dataset → trainer over the gRPC Train stream.
            from dragonfly2_tpu.records.columnar import ColumnarWriter
            from dragonfly2_tpu.records.features import DOWNLOAD_COLUMNS

            shard = tmp_path / "synth.dfc"
            with ColumnarWriter(str(shard), DOWNLOAD_COLUMNS) as w:
                w.append(cluster.generate_feature_rows(2000, seed=11))
            tclient = GRPCTrainerClient(ttarget, timeout=300)
            key = tclient.train(
                ip="10.0.0.1", hostname="sched", scheduler_id="sched-grpc",
                download_shards=[str(shard)],
            )
            for _ in range(900):
                status = tclient.run_status(key)
                if status["done"]:
                    break
                time.sleep(0.1)
            assert status["done"] and not status["error"], status

            # Models live in the MANAGER process; activate + pull over gRPC.
            from dragonfly2_tpu.rpc.grpc_transport import GRPCRemoteRegistry
            from dragonfly2_tpu.scheduler import MLEvaluator, ModelSubscriber

            registry = GRPCRemoteRegistry(mtarget)
            models = registry.list(
                scheduler_id="sched-grpc", name="parent-bandwidth-mlp"
            )
            assert len(models) == 1
            registry.activate(models[0].id)
            ev = MLEvaluator()
            sub = ModelSubscriber(registry, ev, scheduler_id="sched-grpc")
            assert sub.refresh() is True
            assert ev.has_model
            for n in nodes:
                n.stop()
            tclient.close()
            registry.close()
        finally:
            for p in procs:
                p.terminate()


class TestTrainerGRPC:
    def test_train_stream_end_to_end(self, tmp_path, cluster):
        """Announcer-shaped upload over a real gRPC client stream: train
        server-side, model lands in the registry, run status readable."""
        from dragonfly2_tpu.manager import ModelRegistry
        from dragonfly2_tpu.records.columnar import ColumnarWriter
        from dragonfly2_tpu.records.features import DOWNLOAD_COLUMNS
        from dragonfly2_tpu.trainer.service import MLP_MODEL_NAME, TrainerService
        from dragonfly2_tpu.trainer.train import TrainConfig

        registry = ModelRegistry()
        service = TrainerService(
            registry,
            data_dir=str(tmp_path / "staged"),
            train_config=TrainConfig(epochs=3, warmup_steps=5),
        )
        server = TrainerGRPCServer(service)
        server.serve()
        try:
            shard = tmp_path / "download.dfc"
            with ColumnarWriter(str(shard), DOWNLOAD_COLUMNS) as w:
                w.append(cluster.generate_feature_rows(1500, seed=3))
            client = GRPCTrainerClient(server.target)
            key = client.train(
                ip="10.0.0.9", hostname="sched-9", scheduler_id="sched-9",
                download_shards=[str(shard)],
            )
            # Async training (the goroutine analog): poll run status.
            import time

            for _ in range(600):
                status = client.run_status(key)
                if status["done"]:
                    break
                time.sleep(0.1)
            assert status["done"] and not status["error"], status
            assert status["download_rows"] == 1500
            assert status["models"]
            assert registry.list(scheduler_id="sched-9", name=MLP_MODEL_NAME)
            client.close()
        finally:
            server.stop()

    def test_chunked_stream_reassembles(self, tmp_path, cluster):
        from dragonfly2_tpu.records.columnar import ColumnarWriter
        from dragonfly2_tpu.records.features import DOWNLOAD_COLUMNS
        from dragonfly2_tpu.trainer.service import TrainerService
        from dragonfly2_tpu.trainer.train import TrainConfig

        # Tiny config + run-completion wait below: the async training
        # thread must NOT outlive this test (it would mutate the global
        # trainer metrics under later tests).
        service = TrainerService(
            data_dir=str(tmp_path / "staged"),
            train_config=TrainConfig(epochs=1, warmup_steps=1),
        )
        server = TrainerGRPCServer(service)
        server.serve()
        try:
            shard = tmp_path / "big.dfc"
            with ColumnarWriter(str(shard), DOWNLOAD_COLUMNS) as w:
                w.append(cluster.generate_feature_rows(4000, seed=4))
            client = GRPCTrainerClient(server.target)
            client.CHUNK_BYTES = 64 * 1024  # force many chunks
            key = None
            try:
                key = client.train(
                    ip="1.2.3.4", hostname="s", scheduler_id="s",
                    download_shards=[str(shard)],
                )
            except RPCError:
                pass  # no registry configured: training may no-op/fail;
                # the assertion below is about BYTES, not training.
            if key is not None:
                import time

                for _ in range(600):
                    if client.run_status(key)["done"]:
                        break
                    time.sleep(0.1)
            staged = glob.glob(
                str(tmp_path / "staged" / "*" / "download_big.dfc")
            )[0]
            assert os.path.getsize(staged) == os.path.getsize(shard)
            with open(staged, "rb") as a, open(shard, "rb") as b:
                assert a.read() == b.read()
            client.close()
        finally:
            server.stop()


class TestAnnouncePeerStream:
    """The v2 bidi wire (announce_peer stream): per-peer calls ride one
    stream; the scheduler pushes reschedules down mid-download
    (service_v2.go:89-207 semantics)."""

    def _swarm(self, tmp_path, **sched_kw):
        from dragonfly2_tpu.rpc.grpc_transport import GRPCStreamingScheduler

        resource = Resource()
        service = SchedulerService(
            resource,
            Scheduling(Evaluator(), SchedulingConfig(retry_interval=0, **sched_kw)),
            Storage(str(tmp_path / "records"), buffer_size=1),
            NetworkTopology(resource.host_manager),
        )
        server = SchedulerGRPCServer(service)
        server.serve()
        origin = WireOrigin()

        class StreamNode(GRPCNode):
            def __init__(self, i, target, tmp_path, origin):
                super().__init__(i, target, tmp_path, origin)
                self.client.close()
                self.client = GRPCStreamingScheduler(target)
                self.conductor.scheduler = self.client
                self.conductor.piece_fetcher = HTTPPieceFetcher(
                    self.client.resolve_host
                )

        nodes = [StreamNode(i, server.target, tmp_path, origin) for i in range(3)]
        return server, service, nodes, origin

    def test_p2p_over_stream(self, tmp_path):
        """The whole download control flow over ONE bidi stream per node."""
        server, service, nodes, origin = self._swarm(tmp_path)
        try:
            url = "https://origin/stream-blob"
            r0 = nodes[0].conductor.download(
                url, piece_size=PIECE, content_length=4 * PIECE
            )
            assert r0.ok and r0.back_to_source
            r1 = nodes[1].conductor.download(url, piece_size=PIECE)
            assert r1.ok and not r1.back_to_source
            service.storage.flush()
            downloads = service.storage.list_download()
            assert [d for d in downloads if d.parents]
            # The per-peer traffic really rode the stream, not unary stubs.
            from dragonfly2_tpu.rpc.metrics import GRPC_REQUESTS_TOTAL

            assert GRPC_REQUESTS_TOTAL.value(
                service="scheduler", method="stream/register_peer", code="OK"
            ) >= 2
        finally:
            for n in nodes:
                n.stop()
            server.stop()

    def test_slow_parent_triggers_server_push(self, tmp_path):
        """A stalled-but-not-failing parent: the scheduler's stall sweep
        pushes fresh parents mid-download; the child switches WITHOUT ever
        reporting a piece failure (VERDICT r1 missing-#1 done-condition)."""
        import threading
        import time as _time

        server, service, nodes, origin = self._swarm(
            tmp_path, candidate_parent_limit=1
        )
        service.hub.push_cooldown_s = 0.2
        try:
            url = "https://origin/stall-blob"
            n_pieces = 6
            # 1. Node A seeds the task from the origin.
            rA = nodes[0].conductor.download(
                url, piece_size=PIECE, content_length=n_pieces * PIECE
            )
            assert rA.ok
            slow_host = nodes[0].host.id

            # 2. Child C fetches from A at 0.45 s/piece (slow, not failing).
            fetches = {}
            inner = nodes[2].conductor.piece_fetcher

            class SlowFetcher:
                def fetch(self, host_id, task_id, number):
                    fetches[host_id] = fetches.get(host_id, 0) + 1
                    if host_id == slow_host:
                        _time.sleep(0.45)
                    return inner.fetch(host_id, task_id, number)

                def piece_bitmap(self, host_id, task_id):
                    return inner.piece_bitmap(host_id, task_id)

            nodes[2].conductor.piece_fetcher = SlowFetcher()
            result = {}

            def run_child():
                result["r"] = nodes[2].conductor.download(url, piece_size=PIECE)

            t = threading.Thread(target=run_child)
            t.start()

            # 3. B completes the task meanwhile (a second serveable parent).
            rB = nodes[1].conductor.download(url, piece_size=PIECE)
            assert rB.ok

            # 4. Server-side stall sweeps until a push lands.
            pushed = 0
            deadline = _time.time() + 5.0
            while not pushed and _time.time() < deadline:
                pushed = service.reschedule_stalled(max_idle_s=0.25)
                _time.sleep(0.05)
            t.join(timeout=15)
            r = result["r"]
            assert pushed >= 1, "stall sweep never pushed"
            assert r.ok and not r.back_to_source
            # The child NEVER failed a piece — the push, not the failure
            # path, moved it off the slow parent...
            assert r.failed_pieces == 0
            # ...and the fast parent (B) actually served pieces.
            assert fetches.get(nodes[1].host.id, 0) >= 1
            assert fetches.get(slow_host, 0) < n_pieces
        finally:
            for n in nodes:
                n.stop()
            server.stop()

    def test_stream_reconnect_resumes_push_registration(self, tmp_path):
        """After a mid-download stream break, the NEXT stream re-attaches
        the server hub's push channel via the `resume` payload — pushes
        keep flowing (ADVICE r2: they were silently lost until the next
        register_peer)."""
        import time as _time

        from dragonfly2_tpu.scheduler.scheduling import (
            ScheduleResult,
            ScheduleResultKind,
        )

        server, service, nodes, origin = self._swarm(tmp_path)
        try:
            url = "https://origin/resume-blob"
            rA = nodes[0].conductor.download(
                url, piece_size=PIECE, content_length=2 * PIECE
            )
            assert rA.ok
            client = nodes[1].client
            reg = client.register_peer(host=nodes[1].host, url=url)
            peer = reg.peer
            assert service.hub.subscribed(peer.id)

            # Break the stream: half-close the request iterator; the
            # server-side teardown unregisters the push channel.
            with client._stream_mu:
                sendq = client._sendq
            sendq.put(None)
            deadline = _time.time() + 5
            while (
                service.hub.subscribed(peer.id) or client._sendq is not None
            ) and _time.time() < deadline:
                _time.sleep(0.02)
            assert not service.hub.subscribed(peer.id)

            # Any next stream traffic reconnects + resumes the peer...
            client.report_piece_finished(
                peer, 0, parent_id="", length=PIECE, cost_ns=1
            )
            deadline = _time.time() + 5
            while not service.hub.subscribed(peer.id) and _time.time() < deadline:
                _time.sleep(0.02)
            assert service.hub.subscribed(peer.id)

            # ...and a server push actually reaches the client again.
            assert service.hub.push(
                peer.id,
                ScheduleResult(kind=ScheduleResultKind.NEED_BACK_TO_SOURCE),
            )
            got = None
            deadline = _time.time() + 5
            while got is None and _time.time() < deadline:
                got = client.take_pushed_schedule(peer)
                _time.sleep(0.02)
            assert got is not None
            assert got.kind is ScheduleResultKind.NEED_BACK_TO_SOURCE
        finally:
            for n in nodes:
                n.stop()
            server.stop()

    def test_stream_falls_back_to_unary(self, tmp_path):
        """A broken stream degrades to the unary stubs instead of failing
        the download."""
        server, service, nodes, origin = self._swarm(tmp_path)
        try:
            client = nodes[0].client
            # Sabotage the stream path entirely.
            client._stream_call = lambda *a, **k: (_ for _ in ()).throw(
                ConnectionError("stream down")
            )
            r = nodes[0].conductor.download(
                "https://origin/fallback-blob", piece_size=PIECE,
                content_length=2 * PIECE,
            )
            assert r.ok
        finally:
            for n in nodes:
                n.stop()
            server.stop()


class TestTenantOnWire:
    """Tenant identity over the binary dialect (DESIGN.md §26).

    The JSON wire has carried ``tenant`` since the QoS plane landed, but
    the checked-in pb2 predates the field and ``dict_to_proto`` parses
    with ``ignore_unknown_fields`` — so gRPC deployments silently dropped
    the stamp and degraded to the default tenant.  The runtime-assembled
    messages in protos/tenantext.py close that gap; these tests pin both
    the JSON parity and the wire compatibility story."""

    REGISTER = {
        "host_id": "h-1", "url": "https://origin/t", "peer_id": "p-1",
        "task_id": "task-1", "tag": "", "application": "", "priority": 2,
        "tenant": "t-gold",
    }

    def test_register_dict_round_trips_tenant(self):
        from dragonfly2_tpu.rpc.grpc_transport import (
            dict_to_proto,
            proto_to_dict,
        )
        from dragonfly2_tpu.rpc.protos import tenantext as pbx

        out = proto_to_dict(dict_to_proto(self.REGISTER, pbx.RegisterPeerRequest))
        assert out["tenant"] == "t-gold"
        assert out["host_id"] == "h-1"
        assert out["priority"] == 2

    def test_announce_dict_round_trips_tenant(self):
        from dragonfly2_tpu.rpc.grpc_transport import (
            dict_to_proto,
            proto_to_dict,
        )
        from dragonfly2_tpu.rpc.protos import tenantext as pbx

        req = {
            "host": {"id": "h-1", "hostname": "h-1", "ip": "127.0.0.1"},
            "protocol_version": 2,
            "tenant": "t-gold",
        }
        out = proto_to_dict(dict_to_proto(req, pbx.AnnounceHostRequest))
        assert out["tenant"] == "t-gold"
        assert out["host"]["id"] == "h-1"
        assert out["protocol_version"] == 2

    def test_wire_compat_with_pre_tenant_binaries(self):
        """Field addition is compatible both ways: old bytes parse with
        tenant empty; new bytes parse on the old message with the unknown
        field skipped (the documented degradation)."""
        from dragonfly2_tpu.rpc.grpc_transport import dict_to_proto
        from dragonfly2_tpu.rpc.protos import dragonfly_pb2 as pb
        from dragonfly2_tpu.rpc.protos import tenantext as pbx

        base = {k: v for k, v in self.REGISTER.items() if k != "tenant"}
        old_bytes = dict_to_proto(base, pb.RegisterPeerRequest).SerializeToString()
        new_msg = pbx.RegisterPeerRequest.FromString(old_bytes)
        assert new_msg.tenant == ""
        assert new_msg.host_id == "h-1"

        new_bytes = dict_to_proto(
            self.REGISTER, pbx.RegisterPeerRequest
        ).SerializeToString()
        old_msg = pb.RegisterPeerRequest.FromString(new_bytes)
        assert old_msg.host_id == "h-1"
        assert old_msg.priority == 2
        assert "tenant" not in type(old_msg).DESCRIPTOR.fields_by_name

    def test_stream_envelope_register_arm_compat(self):
        """The bidi envelope's extended register arm still decodes on a
        pre-tenant AnnouncePeerRequest (tail field skipped)."""
        from dragonfly2_tpu.rpc.grpc_transport import dict_to_proto_into
        from dragonfly2_tpu.rpc.protos import dragonfly_pb2 as pb
        from dragonfly2_tpu.rpc.protos import tenantext as pbx

        env = pbx.AnnouncePeerRequest(seq=7)
        dict_to_proto_into(self.REGISTER, env.register)
        assert env.register.tenant == "t-gold"
        old_env = pb.AnnouncePeerRequest.FromString(env.SerializeToString())
        assert old_env.seq == 7
        assert old_env.WhichOneof("payload") == "register"
        assert old_env.register.host_id == "h-1"

    def test_register_over_grpc_carries_tenant(self, grpc_swarm):
        """End to end: the daemon's tenant stamp survives the binary wire
        and lands on the server-side Peer (it used to arrive as ""), so
        §26 accounting attributes gRPC traffic to the real tenant."""
        node = grpc_swarm["nodes"][0]
        node.client.tenant = "t-gold"
        res = node.client.register_peer(
            host=node.host, url="https://origin/tenant-blob"
        )
        service = grpc_swarm["service"]
        peer = service.resource.peer_manager.load(res.peer.id)
        assert peer is not None
        assert peer.tenant == "t-gold"

    def test_announce_over_grpc_carries_tenant(self, grpc_swarm, monkeypatch):
        service = grpc_swarm["service"]
        seen = {}
        orig = service.announce_host

        def spy(host, *, tenant=""):
            seen["tenant"] = tenant
            return orig(host, tenant=tenant)

        monkeypatch.setattr(service, "announce_host", spy)
        node = grpc_swarm["nodes"][1]
        node.client.tenant = "t-silver"
        node.client.announce_host(node.host)
        assert seen["tenant"] == "t-silver"

    def test_register_over_stream_carries_tenant(self, tmp_path):
        """Same guarantee on the bidi stream dialect: register rides the
        extended envelope arm."""
        from dragonfly2_tpu.rpc.grpc_transport import GRPCStreamingScheduler

        resource = Resource()
        service = SchedulerService(
            resource,
            Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)),
            Storage(str(tmp_path / "records"), buffer_size=1),
            NetworkTopology(resource.host_manager),
        )
        server = SchedulerGRPCServer(service)
        server.serve()
        try:
            client = GRPCStreamingScheduler(server.target)
            client.tenant = "t-stream"
            host = Host(
                id="stream-h", hostname="stream-h", ip="127.0.0.1",
                download_port=1,
            )
            res = client.register_peer(
                host=host, url="https://origin/stream-tenant"
            )
            peer = service.resource.peer_manager.load(res.peer.id)
            assert peer is not None
            assert peer.tenant == "t-stream"
            client.close()
        finally:
            server.stop()
