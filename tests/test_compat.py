"""Wire-version negotiation + N-1 compatibility (VERDICT r4 #6).

Reference: the scheduler serves gRPC v1 AND v2 concurrently and CI runs
old client images against new servers (DRAGONFLY_COMPATIBILITY_E2E_TEST
_MODE, SURVEY §4).  Here: rpc/version.py defines the handshake; the N-1
shim is ``RemoteScheduler(protocol_version=1)`` — its requests carry NO
version field, byte-identical to every client built before the
handshake existed — and the headline test downloads through that shim
against the current scheduler: the old-protocol daemon completing a
download against a new scheduler, every CI run.
"""

import pytest

from dragonfly2_tpu.daemon import DaemonStorage, UploadManager
from dragonfly2_tpu.daemon.conductor import Conductor
from dragonfly2_tpu.rpc import (
    HTTPPieceFetcher,
    PieceHTTPServer,
    RemoteScheduler,
    SchedulerHTTPServer,
)
from dragonfly2_tpu.rpc.scheduler_client import RPCError
from dragonfly2_tpu.rpc.version import MIN_SUPPORTED, PROTOCOL_VERSION
from dragonfly2_tpu.scheduler.evaluator import Evaluator
from dragonfly2_tpu.scheduler.networktopology import NetworkTopology
from dragonfly2_tpu.scheduler.resource import Host, Resource
from dragonfly2_tpu.scheduler.scheduling import Scheduling, SchedulingConfig
from dragonfly2_tpu.scheduler.service import SchedulerService
from dragonfly2_tpu.records.storage import Storage

PIECE = 16 * 1024


class _Origin:
    def __init__(self):
        self.fetches = 0

    def content(self, url, i):
        return bytes((len(url) + i + j) % 256 for j in range(PIECE))

    def fetch(self, url, number, piece_size):
        self.fetches += 1
        return self.content(url, number)


class _Node:
    def __init__(self, i, scheduler_url, tmp_path, origin, *, protocol_version):
        self.storage = DaemonStorage(
            str(tmp_path / f"compat{i}"), prefer_native=False
        )
        self.upload = UploadManager(self.storage)
        self.piece_server = PieceHTTPServer(self.upload)
        self.piece_server.serve()
        self.host = Host(
            id=f"compat-{i}", hostname=f"compat-{i}", ip="127.0.0.1",
            download_port=self.piece_server.port,
        )
        self.client = RemoteScheduler(
            scheduler_url, protocol_version=protocol_version
        )
        self.conductor = Conductor(
            self.host, self.storage, self.client,
            piece_fetcher=HTTPPieceFetcher(self.client.resolve_host),
            source_fetcher=origin,
        )

    def stop(self):
        self.piece_server.stop()


@pytest.fixture()
def scheduler(tmp_path):
    resource = Resource()
    service = SchedulerService(
        resource,
        Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)),
        Storage(str(tmp_path / "records"), buffer_size=1),
        NetworkTopology(resource.host_manager),
    )
    server = SchedulerHTTPServer(service)
    server.serve()
    yield server, service
    server.stop()


class TestCompatE2E:
    def test_v1_daemon_completes_download_against_current_scheduler(
        self, scheduler, tmp_path
    ):
        """THE compat e2e: two N-1 (pre-handshake dialect) daemons run
        the full flow — announce, register, back-to-source, then a P2P
        re-download with parent attribution — against today's
        scheduler."""
        server, service = scheduler
        origin = _Origin()
        nodes = [
            _Node(i, server.url, tmp_path, origin, protocol_version=1)
            for i in range(2)
        ]
        try:
            url = "https://origin/compat-blob"
            r0 = nodes[0].conductor.download(
                url, piece_size=PIECE, content_length=3 * PIECE
            )
            assert r0.ok and r0.back_to_source and r0.pieces == 3
            fetches = origin.fetches
            r1 = nodes[1].conductor.download(url, piece_size=PIECE)
            assert r1.ok and not r1.back_to_source
            assert origin.fetches == fetches  # bytes moved P2P
            for n in range(3):
                assert (
                    nodes[1].storage.read_piece(r1.task_id, n)
                    == origin.content(url, n)
                )
            # The server recorded both hosts at the legacy dialect.
            for i in range(2):
                host = service.resource.host_manager.load(f"compat-{i}")
                assert host.protocol_version == 1
        finally:
            for n in nodes:
                n.stop()

    def test_mixed_dialect_swarm(self, scheduler, tmp_path):
        """v1 and v2 daemons share one swarm: a v2 child downloads from
        a v1 parent — skew inside a rolling upgrade."""
        server, service = scheduler
        origin = _Origin()
        old = _Node(0, server.url, tmp_path, origin, protocol_version=1)
        new = _Node(1, server.url, tmp_path, origin,
                    protocol_version=PROTOCOL_VERSION)
        try:
            url = "https://origin/mixed-blob"
            assert old.conductor.download(
                url, piece_size=PIECE, content_length=2 * PIECE
            ).ok
            r = new.conductor.download(url, piece_size=PIECE)
            assert r.ok and not r.back_to_source
            assert new.client.negotiated_version == PROTOCOL_VERSION
            # HTTP transport: no push stream, so no push capability —
            # discovery is per-transport, not a static list.
            assert "steering" in new.client.server_capabilities
            assert "push-reschedule" not in new.client.server_capabilities
            assert service.resource.host_manager.load(
                "compat-0"
            ).protocol_version == 1
            assert service.resource.host_manager.load(
                "compat-1"
            ).protocol_version == PROTOCOL_VERSION
        finally:
            old.stop()
            new.stop()


class TestHandshake:
    def _announce(self, server, *, protocol_version):
        client = RemoteScheduler(
            server.url, protocol_version=protocol_version
        )
        host = Host(id=f"hs-{protocol_version}", hostname="h", ip="127.0.0.1")
        client.announce_host(host)
        return client

    def test_v2_negotiates_and_discovers_capabilities(self, scheduler):
        server, service = scheduler
        client = self._announce(server, protocol_version=PROTOCOL_VERSION)
        assert client.negotiated_version == PROTOCOL_VERSION
        assert set(client.server_capabilities) >= {"steering", "probe-sync"}

    def test_future_client_downgrades_to_server_version(self, scheduler):
        """A client one release AHEAD speaks the server's dialect after
        the handshake (the symmetric half of the skew policy)."""
        server, service = scheduler
        client = self._announce(
            server, protocol_version=PROTOCOL_VERSION + 1
        )
        assert client.negotiated_version == PROTOCOL_VERSION

    def test_too_old_dialect_gets_typed_refusal(self, scheduler):
        """When MIN_SUPPORTED moves past 1 (the deprecation policy,
        DESIGN.md §10d), legacy clients get INVALID_ARGUMENT with an
        actionable message — not a silent misbehavior."""
        from unittest import mock

        from dragonfly2_tpu.rpc import version as v
        from dragonfly2_tpu.utils.dferrors import Code

        server, service = scheduler
        with mock.patch.object(v, "MIN_SUPPORTED", 2):
            client = RemoteScheduler(server.url, protocol_version=1)
            host = Host(id="old", hostname="h", ip="127.0.0.1")
            with pytest.raises(RPCError) as exc:
                client.announce_host(host)
            assert exc.value.code == int(Code.INVALID_ARGUMENT)
            assert "upgrade the client" in str(exc.value)

    def test_grpc_transport_carries_the_handshake(self, tmp_path):
        """Same negotiation over the gRPC binding (the proto gained
        AnnounceHostRequest.protocol_version / AnnounceHostResponse)."""
        from dragonfly2_tpu.rpc.grpc_transport import (
            GRPCRemoteScheduler,
            SchedulerGRPCServer,
        )

        resource = Resource()
        service = SchedulerService(
            resource,
            Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)),
            Storage(str(tmp_path / "records"), buffer_size=1),
            NetworkTopology(resource.host_manager),
        )
        srv = SchedulerGRPCServer(service)
        srv.serve()
        try:
            client = GRPCRemoteScheduler(srv.target)
            host = Host(id="grpc-hs", hostname="h", ip="127.0.0.1")
            client.announce_host(host)
            assert client.negotiated_version == PROTOCOL_VERSION
            assert "push-reschedule" in client.server_capabilities
            assert resource.host_manager.load(
                "grpc-hs"
            ).protocol_version == PROTOCOL_VERSION
            # The v1 shim over gRPC: unset proto field = legacy dialect.
            shim = GRPCRemoteScheduler(srv.target, protocol_version=1)
            host2 = Host(id="grpc-old", hostname="h", ip="127.0.0.1")
            shim.announce_host(host2)
            assert resource.host_manager.load(
                "grpc-old"
            ).protocol_version == 1
        finally:
            srv.stop()

    def test_min_supported_window_is_n_minus_1(self):
        assert MIN_SUPPORTED == PROTOCOL_VERSION - 1
