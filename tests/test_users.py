"""Manager users / PATs / oauth: store semantics, persistence, and the
REST surface with mixed session-token + PAT auth."""

import io
import json
import time
import urllib.error
import urllib.parse
import urllib.request

import pytest

from dragonfly2_tpu.manager import (
    ClusterManager,
    ModelRegistry,
    OAuthProvider,
    OAuthSignin,
    UserStore,
)
from dragonfly2_tpu.manager.rest import ManagerRESTServer
from dragonfly2_tpu.security.tokens import Role, TokenIssuer, TokenVerifier

SECRET = b"manager-secret-0123456789abcd"


class TestUserStore:
    def test_create_and_signin(self):
        store = UserStore()
        u = store.create_user("alice", "password123", email="a@x", role=Role.OPERATOR)
        assert store.verify_password("alice", "password123").id == u.id
        assert store.verify_password("alice", "wrong") is None
        assert store.verify_password("nobody", "password123") is None

    def test_duplicate_and_weak_password(self):
        store = UserStore()
        store.create_user("bob", "password123")
        with pytest.raises(ValueError):
            store.create_user("bob", "password456")
        with pytest.raises(ValueError):
            store.create_user("carl", "short")

    def test_disabled_user_cannot_signin_or_use_pat(self):
        store = UserStore()
        u = store.create_user("dave", "password123", role=Role.ADMIN)
        _, raw = store.create_pat(u.id, "ci")
        assert store.authenticate_pat(raw) is not None
        store.set_state(u.id, "disabled")
        assert store.verify_password("dave", "password123") is None
        assert store.authenticate_pat(raw) is None

    def test_ensure_root_idempotent(self):
        store = UserStore()
        r1 = store.ensure_root("rootpassword")
        r2 = store.ensure_root("otherpassword")
        assert r1.id == r2.id and r1.role == Role.ADMIN
        assert store.verify_password("root", "rootpassword") is not None

    def test_sqlite_persistence_roundtrip(self, tmp_path):
        db = str(tmp_path / "users.db")
        store = UserStore(db)
        u = store.create_user("eve", "password123", role=Role.OPERATOR)
        pat, raw = store.create_pat(u.id, "laptop")
        store2 = UserStore(db)  # restart
        assert store2.verify_password("eve", "password123").role == Role.OPERATOR
        again = store2.authenticate_pat(raw)
        assert again is not None and again.id == u.id
        store2.revoke_pat(pat.id)
        store3 = UserStore(db)
        assert store3.authenticate_pat(raw) is None  # revocation persisted


class TestPATs:
    def test_role_capped_at_owner(self):
        store = UserStore()
        u = store.create_user("peer", "password123", role=Role.PEER)
        pat, raw = store.create_pat(u.id, "t", role=Role.ADMIN)
        assert pat.role == Role.PEER  # no escalation
        assert store.authenticate_pat(raw).role == Role.PEER

    def test_expiry_and_revocation(self):
        store = UserStore()
        u = store.create_user("frank", "password123", role=Role.OPERATOR)
        pat, raw = store.create_pat(u.id, "gone", ttl_s=0.05)
        assert store.authenticate_pat(raw) is not None
        time.sleep(0.1)
        assert store.authenticate_pat(raw) is None
        pat2, raw2 = store.create_pat(u.id, "kept")
        store.revoke_pat(pat2.id)
        assert store.authenticate_pat(raw2) is None

    def test_bad_tokens_rejected(self):
        store = UserStore()
        assert store.authenticate_pat("dfp_deadbeef") is None
        assert store.authenticate_pat("not-a-pat") is None


def _post(url, payload, token=None):
    headers = {"Content-Type": "application/json"}
    if token:
        headers["Authorization"] = f"Bearer {token}"
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), headers=headers, method="POST"
    )
    with urllib.request.urlopen(req, timeout=5) as resp:
        return json.loads(resp.read())


def _get(url, token=None):
    headers = {"Authorization": f"Bearer {token}"} if token else {}
    req = urllib.request.Request(url, headers=headers)
    with urllib.request.urlopen(req, timeout=5) as resp:
        return json.loads(resp.read())


@pytest.fixture
def rest_server():
    users = UserStore()
    users.ensure_root("rootpassword")
    server = ManagerRESTServer(
        ModelRegistry(),
        ClusterManager(),
        token_verifier=TokenVerifier(SECRET),
        token_issuer=TokenIssuer(SECRET),
        users=users,
        oauth=None,
    )
    server.serve()
    yield server
    server.stop()


class TestUserREST:
    def test_signup_signin_and_admin_flow(self, rest_server):
        base = rest_server.url
        # Open signup → READONLY.
        u = _post(base + "/api/v1/users:signup",
                  {"name": "grace", "password": "password123"})
        assert u["role"] == "readonly"
        # Signin → session token.
        sess = _post(base + "/api/v1/users:signin",
                     {"name": "grace", "password": "password123"})
        assert sess["role"] == "readonly"
        # Listing users needs ADMIN.
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(base + "/api/v1/users", token=sess["token"])
        assert exc.value.code == 403
        # root promotes grace to operator.
        root = _post(base + "/api/v1/users:signin",
                     {"name": "root", "password": "rootpassword"})
        promoted = _post(base + f"/api/v1/users/{u['id']}:role",
                         {"role": "operator"}, token=root["token"])
        assert promoted["role"] == "operator"
        listing = _get(base + "/api/v1/users", token=root["token"])
        assert {x["name"] for x in listing} >= {"root", "grace"}

    def test_bad_signin_rejected(self, rest_server):
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(rest_server.url + "/api/v1/users:signin",
                  {"name": "root", "password": "nope"})
        assert exc.value.code == 401

    def test_pat_lifecycle_and_model_auth(self, rest_server):
        base = rest_server.url
        root = _post(base + "/api/v1/users:signin",
                     {"name": "root", "password": "rootpassword"})
        # Create a PEER-scoped PAT; the raw token appears exactly once.
        pat = _post(base + "/api/v1/pats",
                    {"name": "trainer-ci", "role": "peer"}, token=root["token"])
        raw = pat["token"]
        assert raw.startswith("dfp_") and pat["role"] == "peer"
        # The PAT authenticates model creation (Role.PEER route)...
        created = _post(base + "/api/v1/models",
                        {"name": "m", "type": "mlp", "scheduler_id": "s"},
                        token=raw)
        assert created["name"] == "m"
        # ...but not activation (OPERATOR).
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(base + f"/api/v1/models/{created['id']}:activate", {},
                  token=raw)
        assert exc.value.code == 401
        # Listing my PATs works with the session token.
        pats = _get(base + "/api/v1/pats", token=root["token"])
        assert [p["id"] for p in pats] == [pat["id"]]
        # Revoke → the raw token dies.
        _post(base + f"/api/v1/pats/{pat['id']}:revoke", {}, token=root["token"])
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(base + "/api/v1/models",
                  {"name": "m2", "type": "mlp", "scheduler_id": "s"}, token=raw)
        assert exc.value.code == 401

    def test_capped_pat_cannot_escalate(self, rest_server):
        """A READONLY-capped PAT of an admin must not mint admin PATs or
        rotate the admin's password."""
        base = rest_server.url
        root = _post(base + "/api/v1/users:signin",
                     {"name": "root", "password": "rootpassword"})
        limited = _post(base + "/api/v1/pats",
                        {"name": "ci", "role": "readonly"}, token=root["token"])
        # Minting a new PAT through the capped PAT: role stays READONLY.
        minted = _post(base + "/api/v1/pats",
                       {"name": "evil", "role": "admin"}, token=limited["token"])
        assert minted["role"] == "readonly"
        # Password rotation through a PAT is refused outright.
        root_id = None
        listing = _get(base + "/api/v1/users", token=root["token"])
        root_id = next(u["id"] for u in listing if u["name"] == "root")
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(base + f"/api/v1/users/{root_id}:reset-password",
                  {"password": "ownedpassword1"}, token=limited["token"])
        assert exc.value.code == 403

    def test_disable_kills_live_session(self, rest_server):
        base = rest_server.url
        u = _post(base + "/api/v1/users:signup",
                  {"name": "mallory", "password": "password123"})
        sess = _post(base + "/api/v1/users:signin",
                     {"name": "mallory", "password": "password123"})
        # Session works now.
        assert _get(base + "/api/v1/pats", token=sess["token"]) == []
        root = _post(base + "/api/v1/users:signin",
                     {"name": "root", "password": "rootpassword"})
        _post(base + f"/api/v1/users/{u['id']}:state",
              {"state": "disabled"}, token=root["token"])
        # The outstanding 24h session token dies immediately.
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(base + "/api/v1/pats", token=sess["token"])
        assert exc.value.code == 401

    def test_reset_password_self_only(self, rest_server):
        base = rest_server.url
        u = _post(base + "/api/v1/users:signup",
                  {"name": "henry", "password": "password123"})
        sess = _post(base + "/api/v1/users:signin",
                     {"name": "henry", "password": "password123"})
        other = _post(base + "/api/v1/users:signup",
                      {"name": "iris", "password": "password123"})
        # henry cannot reset iris's password.
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(base + f"/api/v1/users/{other['id']}:reset-password",
                  {"password": "hacked12345"}, token=sess["token"])
        assert exc.value.code == 403
        # but can reset his own.
        _post(base + f"/api/v1/users/{u['id']}:reset-password",
              {"password": "newpassword1"}, token=sess["token"])
        assert _post(base + "/api/v1/users:signin",
                     {"name": "henry", "password": "newpassword1"})["token"]


class TestConsole:
    def test_console_served_at_root(self, rest_server):
        with urllib.request.urlopen(rest_server.url + "/", timeout=5) as r:
            body = r.read().decode()
            assert r.headers.get_content_type() == "text/html"
        assert "manager console" in body and "/api/v1" in body
        with urllib.request.urlopen(rest_server.url + "/console", timeout=5) as r:
            assert r.status == 200


class TestManagerAuthConfig:
    def test_short_token_secret_is_config_error(self):
        from dragonfly2_tpu.config import ConfigError
        from dragonfly2_tpu.config.schema import ManagerConfig

        cfg = ManagerConfig(token_secret="abc")
        with pytest.raises(ConfigError):
            cfg.validate()
        ManagerConfig(token_secret="long-enough-secret-123").validate()

    def test_oauth_provider_needs_name(self):
        from dragonfly2_tpu.config import ConfigError
        from dragonfly2_tpu.config.schema import ManagerConfig

        cfg = ManagerConfig(oauth_providers=[{"client_id": "x"}])
        with pytest.raises(ConfigError):
            cfg.validate()


class _FakeOAuthTransport:
    """Answers the provider's token + profile endpoints in-process."""

    def __init__(self):
        self.seen = []

    def __call__(self, req, timeout):
        self.seen.append(req.full_url)
        if "token" in req.full_url:
            body = json.dumps({"access_token": "at-123"}).encode()
        else:
            assert req.headers.get("Authorization") == "Bearer at-123"
            body = json.dumps(
                {"login": "octocat", "email": "octo@cat"}
            ).encode()

        class _Resp(io.BytesIO):
            def __enter__(self):
                return self

            def __exit__(self, *a):
                return False

        return _Resp(body)


class TestOAuth:
    def test_full_signin_flow(self):
        users = UserStore()
        oauth = OAuthSignin(users, transport=_FakeOAuthTransport())
        oauth.register(OAuthProvider(
            name="hub", client_id="cid", client_secret="cs",
            auth_url="https://hub/oauth/authorize",
            token_url="https://hub/oauth/token",
            profile_url="https://hub/api/user",
        ))
        url = oauth.authorize_url("hub", "https://manager/cb")
        state = dict(urllib.parse.parse_qsl(urllib.parse.urlsplit(url).query))["state"]
        user = oauth.signin("hub", "code-1", state, "https://manager/cb")
        assert user.name == "hub:octocat" and user.role == Role.READONLY
        # Second signin with the SAME identity maps to the same user.
        url2 = oauth.authorize_url("hub", "https://manager/cb")
        state2 = dict(urllib.parse.parse_qsl(urllib.parse.urlsplit(url2).query))["state"]
        again = oauth.signin("hub", "code-2", state2, "https://manager/cb")
        assert again.id == user.id

    def test_disabled_user_blocked_at_oauth_door(self):
        users = UserStore()
        oauth = OAuthSignin(users, transport=_FakeOAuthTransport())
        oauth.register(OAuthProvider(
            name="hub", client_id="c", client_secret="s",
            auth_url="https://h/a", token_url="https://h/token",
            profile_url="https://h/profile",
        ))
        url = oauth.authorize_url("hub", "https://m/cb")
        state = dict(urllib.parse.parse_qsl(urllib.parse.urlsplit(url).query))["state"]
        user = oauth.signin("hub", "c1", state, "https://m/cb")
        users.set_state(user.id, "disabled")
        url2 = oauth.authorize_url("hub", "https://m/cb")
        state2 = dict(urllib.parse.parse_qsl(urllib.parse.urlsplit(url2).query))["state"]
        with pytest.raises(PermissionError):
            oauth.signin("hub", "c2", state2, "https://m/cb")

    def test_stale_states_pruned(self):
        users = UserStore()
        oauth = OAuthSignin(users, transport=_FakeOAuthTransport())
        oauth.register(OAuthProvider(
            name="hub", client_id="c", client_secret="s",
            auth_url="https://h/a", token_url="https://h/t",
            profile_url="https://h/p",
        ))
        oauth.state_ttl_s = 0.05
        for _ in range(50):
            oauth.authorize_url("hub", "https://m/cb")
        time.sleep(0.1)
        oauth.authorize_url("hub", "https://m/cb")
        assert len(oauth._states) == 1  # the fresh one; the 50 are gone

    def test_state_mismatch_rejected(self):
        users = UserStore()
        oauth = OAuthSignin(users, transport=_FakeOAuthTransport())
        oauth.register(OAuthProvider(
            name="hub", client_id="c", client_secret="s",
            auth_url="https://h/a", token_url="https://h/t",
            profile_url="https://h/p",
        ))
        with pytest.raises(PermissionError):
            oauth.signin("hub", "code", "forged-state", "https://m/cb")

    def test_rest_oauth_routes(self):
        users = UserStore()
        oauth = OAuthSignin(users, transport=_FakeOAuthTransport())
        oauth.register(OAuthProvider(
            name="hub", client_id="cid", client_secret="cs",
            auth_url="https://hub/oauth/authorize",
            token_url="https://hub/oauth/token",
            profile_url="https://hub/api/user",
        ))
        server = ManagerRESTServer(
            ModelRegistry(), ClusterManager(),
            token_verifier=TokenVerifier(SECRET),
            token_issuer=TokenIssuer(SECRET),
            users=users, oauth=oauth,
        )
        server.serve()
        try:
            base = server.url
            assert _get(base + "/api/v1/oauth:providers") == ["hub"]
            out = _get(
                base + "/api/v1/oauth/hub:authorize-url?"
                + urllib.parse.urlencode({"redirect_uri": "https://m/cb"})
            )
            state = dict(
                urllib.parse.parse_qsl(urllib.parse.urlsplit(out["url"]).query)
            )["state"]
            sess = _post(base + "/api/v1/oauth/hub:signin",
                         {"code": "c1", "state": state,
                          "redirect_uri": "https://m/cb"})
            assert sess["role"] == "readonly" and sess["token"]
        finally:
            server.stop()
