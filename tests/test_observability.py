"""Observability satellites (ISSUE 10): Prometheus exposition
correctness under hostile label values, histogram exemplars, and the
uniform diagnostics endpoints (/metrics + /debug/spans +
/debug/exemplars) on every plane.
"""

from __future__ import annotations

import json
import re
import sys
import urllib.request
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from dragonfly2_tpu.utils import tracing  # noqa: E402
from dragonfly2_tpu.utils.metrics import Registry  # noqa: E402

HOSTILE_VALUES = [
    'quote"inside',
    "back\\slash",
    "new\nline",
    'all\\of"them\ntogether',
    "trailing\\",
    '"""',
    "\n\n",
    "ünïcode-ok",
]

_SAMPLE = re.compile(r'^(\w+)\{(.*)\} ([-0-9.e+]+)$')
_LABEL = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    out, i = [], 0
    while i < len(v):
        if v[i] == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            out.append({"\\": "\\", '"': '"', "n": "\n"}.get(nxt, "\\" + nxt))
            i += 2
        else:
            out.append(v[i])
            i += 1
    return "".join(out)


def parse_exposition(text: str):
    """Minimal Prometheus text-format consumer: {metric: {labels-tuple:
    value}}.  Raises on any line that is neither a comment nor a
    well-formed sample — a split line (unescaped newline in a label)
    fails here, which is the point."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        if "{" not in line:
            name, value = line.rsplit(" ", 1)
            out.setdefault(name, {})[()] = float(value)
            continue
        m = _SAMPLE.match(line)
        assert m, f"malformed sample line: {line!r}"
        labels = tuple(
            (k, _unescape(v)) for k, v in _LABEL.findall(m.group(2))
        )
        out.setdefault(m.group(1), {})[labels] = float(m.group(3))
    return out


class TestPromExposition:
    @pytest.mark.parametrize("value", HOSTILE_VALUES)
    def test_hostile_label_values_round_trip(self, value):
        reg = Registry()
        c = reg.counter("evil_total", "count", ["url"])
        c.inc(url=value)
        parsed = parse_exposition(reg.expose_text())
        assert parsed["evil_total"][(("url", value),)] == 1.0

    def test_hostile_values_do_not_split_following_series(self):
        reg = Registry()
        c = reg.counter("first_total", "a", ["v"])
        g = reg.gauge("second_gauge", "b")
        for v in HOSTILE_VALUES:
            c.inc(v=v)
        g.set(42.0)
        parsed = parse_exposition(reg.expose_text())
        assert len(parsed["first_total"]) == len(HOSTILE_VALUES)
        assert parsed["second_gauge"][()] == 42.0

    def test_help_and_type_lines_emitted_and_escaped(self):
        reg = Registry()
        reg.counter("c_total", "multi\nline \\help", ["x"])
        reg.gauge("g", "gh")
        reg.histogram("h_seconds", "hh")
        text = reg.expose_text()
        assert "# HELP c_total multi\\nline \\\\help\n" in text
        for line in (
            "# TYPE c_total counter",
            "# HELP g gh", "# TYPE g gauge",
            "# HELP h_seconds hh", "# TYPE h_seconds histogram",
        ):
            assert line in text
        # The escaped HELP stays ONE line.
        assert sum(1 for ln in text.splitlines() if ln.startswith("# HELP c_total")) == 1

    def test_histogram_exposition_with_hostile_labels(self):
        reg = Registry()
        h = reg.histogram("lat_seconds", "lat", ["op"], buckets=(0.1, 1.0))
        h.observe(0.05, op='a"b\nc\\d')
        text = reg.expose_text()
        parsed = parse_exposition(text)
        key = (("op", 'a"b\nc\\d'), ("le", "0.1"))
        assert parsed["lat_seconds_bucket"][key] == 1.0


class TestHistogramExemplars:
    def test_last_trace_id_per_bucket(self):
        reg = Registry()
        h = reg.histogram("x_seconds", "x", ["op"], buckets=(0.1, 1.0))
        # Exemplars join to the PROCESS tracer's active span — the same
        # context the service planes run under.
        t = tracing.default_tracer
        with t.span("slow-op") as s1:
            h.observe(0.05, op="k")
        with t.span("slower-op") as s2:
            h.observe(0.5, op="k")
            h.labels(op="k").observe(5.0)  # +Inf bucket, child path
        ex = reg.exemplars()["x_seconds"]['{op="k"}']
        assert ex["0.1"] == s1.trace_id
        assert ex["1.0"] == s2.trace_id
        assert ex["+Inf"] == s2.trace_id

    def test_no_active_span_records_nothing(self):
        reg = Registry()
        h = reg.histogram("y_seconds", "y")
        h.observe(0.05)
        assert reg.exemplars() == {}

    def test_last_write_wins_per_bucket(self):
        reg = Registry()
        h = reg.histogram("z_seconds", "z", buckets=(1.0,))
        t = tracing.default_tracer
        with t.span("a") as s1:
            h.observe(0.1)
        with t.span("b") as s2:
            h.observe(0.2)
        assert reg.exemplars()["z_seconds"]["{}"]["1.0"] == s2.trace_id
        assert s1.trace_id != s2.trace_id


def _get(url: str):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, resp.headers.get("Content-Type", ""), resp.read()


def _get_slow(url: str):
    with urllib.request.urlopen(url, timeout=60) as resp:
        return resp.status, resp.headers.get("Content-Type", ""), resp.read()


class TestDiagnosticsServer:
    @pytest.fixture()
    def server(self):
        from dragonfly2_tpu.utils.diagnostics import DiagnosticsServer

        srv = DiagnosticsServer(port=0)
        srv.serve()
        yield srv
        srv.stop()

    def test_metrics_endpoint_serves_default_registry(self, server):
        from dragonfly2_tpu.utils.metrics import default_registry

        default_registry.counter("diag_probe_total", "probe").inc()
        status, ctype, body = _get(server.url + "/metrics")
        assert status == 200 and "text/plain" in ctype
        assert b"diag_probe_total" in body
        assert b"# HELP" in body and b"# TYPE" in body

    def test_debug_spans_returns_otlp_request(self, server):
        import jsonschema

        from dragonfly2_tpu.utils.tracing import (
            CompositeExporter,
            InMemoryExporter,
            default_tracer,
            otlp_trace_schema,
        )

        prev = default_tracer.exporter
        default_tracer.exporter = CompositeExporter(
            [InMemoryExporter(max_spans=16), prev]
        )
        try:
            with default_tracer.span("diag-probe"):
                pass
            status, ctype, body = _get(server.url + "/debug/spans")
        finally:
            default_tracer.exporter = prev
        assert status == 200 and "json" in ctype
        req = json.loads(body)
        jsonschema.Draft202012Validator(otlp_trace_schema()).validate(req)
        names = [
            s["name"] for s in tracing.log_spans([req])
        ]
        assert "diag-probe" in names

    def test_debug_exemplars_json(self, server):
        from dragonfly2_tpu.utils.metrics import default_registry
        from dragonfly2_tpu.utils.tracing import default_tracer

        h = default_registry.histogram("diag_lat_seconds", "lat")
        with default_tracer.span("diag-exemplar") as s:
            h.observe(0.02)
        status, _ctype, body = _get(server.url + "/debug/exemplars")
        assert status == 200
        payload = json.loads(body)
        assert any(
            s.trace_id in per_bucket.values()
            for metric in payload.values()
            for per_bucket in metric.values()
        )

    def test_unknown_route_404(self, server):
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as e:
            _get(server.url + "/nope")
        assert e.value.code == 404


class TestConcurrentScrape:
    """ISSUE 12 satellite: hammer ``/metrics`` while 8 threads mutate
    counters/histograms/sketches — every scrape must parse through the
    real text-format parser with monotonic counters and no torn lines."""

    def test_scrapes_parse_and_counters_monotonic(self):
        import threading

        from dragonfly2_tpu.utils.diagnostics import DiagnosticsServer
        from dragonfly2_tpu.utils.metrics import default_registry

        c = default_registry.counter(
            "scrape_storm_total", "storm", ["worker", "result"]
        )
        h = default_registry.histogram(
            "scrape_storm_seconds", "storm", ["worker"]
        )
        s = default_registry.sketch(
            "scrape_storm_lat_seconds", "storm", ["worker"]
        )
        srv = DiagnosticsServer(port=0)
        srv.serve()
        stop = threading.Event()
        errors = []

        def mutate(wid: int) -> None:
            # Hostile label values included: escaping must hold under
            # concurrency, not just in the single-threaded tests above.
            label = f'w{wid}"evil\n' if wid % 2 else f"w{wid}"
            child_h = h.labels(worker=label)
            child_s = s.labels(worker=label)
            i = 0
            try:
                while not stop.is_set():
                    c.inc(worker=label, result="ok")
                    child_h.observe(0.001 * (i % 50))
                    child_s.observe(0.001 * (i % 50) + 1e-6)
                    i += 1
                    if i % 20 == 0:
                        # Yield: 8 hot loops on a 1-CPU box would starve
                        # the scrape thread via the GIL — the test is
                        # about torn lines, not about out-scheduling it.
                        stop.wait(0.001)
            except Exception as exc:  # noqa: BLE001 — surfaced below
                errors.append(exc)

        threads = [
            threading.Thread(target=mutate, args=(i,), daemon=True)
            for i in range(8)
        ]
        for t in threads:
            t.start()
        try:
            last_totals = {}
            parsed_rounds = 0
            for _ in range(15):
                # Generous timeout: late in the suite the default
                # registry is large and the box has one CPU.
                status, ctype, body = _get_slow(srv.url + "/metrics")
                assert status == 200 and "text/plain" in ctype
                parsed = parse_exposition(body.decode())
                parsed_rounds += 1
                # Counters never go backwards between scrapes.
                for key, value in parsed.get("scrape_storm_total", {}).items():
                    prev = last_totals.get(key, 0.0)
                    assert value >= prev, (key, prev, value)
                    last_totals[key] = value
                # Histogram internal consistency per scrape: +Inf bucket
                # equals _count (a torn line would break the pairing).
                buckets = parsed.get("scrape_storm_seconds_bucket", {})
                counts = parsed.get("scrape_storm_seconds_count", {})
                for key, total in counts.items():
                    inf_key = tuple(list(key) + [("le", "+Inf")])
                    assert buckets.get(inf_key) == total
                # Sketch summary lines parse with their quantile label.
                for key in parsed.get("scrape_storm_lat_seconds", {}):
                    assert any(k == "quantile" for k, _v in key)
        finally:
            stop.set()
            for t in threads:
                t.join(5.0)
            srv.stop()
        assert errors == []
        assert parsed_rounds == 15
        assert sum(last_totals.values()) > 0


class TestManagerDiagnosticsRoutes:
    """The manager serves the SAME surface on its REST port."""

    def test_metrics_and_debug_spans(self, tmp_path):
        from dragonfly2_tpu.manager.cluster import ClusterManager
        from dragonfly2_tpu.manager.registry import ModelRegistry
        from dragonfly2_tpu.manager.rest import ManagerRESTServer

        server = ManagerRESTServer(ModelRegistry(), ClusterManager())
        server.serve()
        try:
            status, ctype, body = _get(server.url + "/metrics")
            assert status == 200 and "text/plain" in ctype
            assert b"# TYPE" in body
            status, _, body = _get(server.url + "/debug/spans")
            assert status == 200
            json.loads(body)["resourceSpans"]
            status, _, body = _get(server.url + "/debug/exemplars")
            assert status == 200
            json.loads(body)
        finally:
            server.stop()
