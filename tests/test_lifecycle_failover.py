"""Lifecycle HA chaos drill (ISSUE 19 satellite): kill the manager
leader mid-promotion and let the promoted standby's reconciler resume.

The tear under test is the worst one the promotion path can take: the
registry's CANARY flip is committed (and replicated) but the injected
fault drops the rollout-row persist — the leader dies with the two
tables disagreeing.  The promoted standby must:

- repair the rollout row to the registry's phase (``_reconcile``);
- hand the resumed daemon its watermark and in-flight candidate back
  from the replicated ``lifecycle`` namespace (no retrain);
- finish the walk to exactly one ACTIVE per (region, name) — the
  arbitration-retired regional arm stays retired — with the artifact
  still digest-verified.

Built on the in-process HA idioms of tests/test_replication.py: a
shared fake clock, a leader ``ReplicatedStateBackend`` tailed over REST
by a ``LogFollower``, and lease-expiry promotion.
"""

from __future__ import annotations

import numpy as np

from tests.test_replication import _Clock, _leader, _rest_for, _standby

from dragonfly2_tpu.lifecycle import (
    GLOBAL_KEY,
    LifecycleConfig,
    LifecycleDaemon,
    LifecycleStore,
    regional_model_name,
)
from dragonfly2_tpu.manager.registry import KVBlobStore, ModelRegistry
from dragonfly2_tpu.manager.replication import LogFollower
from dragonfly2_tpu.manager.state import MemoryBackend
from dragonfly2_tpu.manager import ModelState
from dragonfly2_tpu.rollout import (
    LocalRolloutClient,
    RolloutController,
    RolloutGuardrails,
)
from dragonfly2_tpu.sim.lifecycle import LifecycleDrillConfig, _World
from dragonfly2_tpu.trainer.export import load_scorer
from dragonfly2_tpu.trainer.streaming import StreamingConfig, StreamingTrainer
from dragonfly2_tpu.utils import faultinject

MODEL_NAME = "parent-bandwidth-mlp"
SID = "scheduler-ha"
REGION = "idc-a"


def _drill_world():
    return _World(LifecycleDrillConfig(
        seed=11, scheduler_id=SID, epoch_records=128, batch_size=32,
        announces=24, parents=4,
    ))


def _trainer(_key):
    return StreamingTrainer(
        StreamingConfig(batch_size=32, warmup_steps=4, learning_rate=3e-3,
                        snapshot_rows=512, seed=11)
    )


def _replay_source(registry, world):
    """Honest read side (same shape as sim/lifecycle.py): score the REAL
    registry blobs, accumulate per candidate version so joined counts
    grow across pumps."""
    acc = {}

    def source(key):
        name = regional_model_name(MODEL_NAME, key)
        cand = registry.candidate_model(SID, name)
        if cand is None:
            return None
        active = registry.active_model(SID, name)
        shadow, dl, _ = world.shadow_batch(
            load_scorer(registry.load_artifact(cand)), cand.version,
            load_scorer(registry.load_artifact(active)) if active else None,
            active.version if active else 0,
        )
        slot = acc.get(key)
        if slot is None or slot["version"] != cand.version:
            slot = {"version": cand.version, "shadow": [], "dl": []}
            acc[key] = slot
        slot["shadow"].append(shadow)
        slot["dl"].append(dl)
        return (np.concatenate(slot["shadow"]), np.concatenate(slot["dl"]))

    return source


def _plane(backend, world):
    """One manager+daemon composition over ``backend`` (the standby
    builds a second one after promotion — the 'manager process')."""
    registry = ModelRegistry(KVBlobStore(backend), backend=backend)
    controller = RolloutController(
        registry, backend=backend,
        guardrails=RolloutGuardrails(
            min_shadow_samples=150, min_canary_samples=150, canary_percent=25,
        ),
    )
    daemon = LifecycleDaemon(
        registry, LocalRolloutClient(controller),
        config=LifecycleConfig(
            scheduler_id=SID, regions=(REGION,), epoch_records=128,
            max_steps_per_epoch=20, min_joined=10, arbitration_margin=0.25,
            canary_percent=25,
        ),
        backend=backend, trainer_factory=_trainer,
        replay_source=_replay_source(registry, world),
    )
    return registry, controller, daemon


class TestLeaderKillMidPromotion:
    def test_promoted_standby_resumes_to_exactly_one_active(self):
        clock = _Clock()
        leader = _leader(clock)
        world = _drill_world()
        registry, controller, daemon = _plane(leader, world)
        rest = _rest_for(leader, registry)
        follower_backend = _standby(clock)
        follower = LogFollower(
            follower_backend, rest.url, clock=clock, poll_interval_s=0.05
        )
        regional_name = regional_model_name(MODEL_NAME, REGION)
        try:
            # Epoch 1 on BOTH arms: candidates registered, SHADOW begun.
            # The same pump crosses the arbitration evidence floor
            # (min_joined=10 < 96 joined) and retires the regional arm —
            # same data → identical quality cannot beat global by the
            # margin — while the global report HOLDS below the
            # controller's 150-sample floor.
            daemon.feed(world.record_rows(160), region=REGION)
            daemon.step()
            cand = registry.candidate_model(SID, MODEL_NAME)
            assert cand is not None and cand.state is ModelState.SHADOW
            assert registry.candidate_model(SID, regional_name) is None
            assert daemon.store.candidate(GLOBAL_KEY) == cand.id

            # The kill step: the global candidate's evidence crosses the
            # floor and it advances — and the injected fault drops the
            # rollout-row persist AFTER the registry's CANARY flip
            # committed.  The daemon survives the failed report
            # (retry-next-cycle), but we kill the leader before any
            # retry.
            inj = faultinject.FaultInjector([
                faultinject.FaultSpec(site="state.put.rollouts", kind="drop",
                                      at=(0,)),
            ])
            with faultinject.installed(inj):
                daemon.step()
            assert registry.get(cand.id).state is ModelState.CANARY
            torn = leader.table("rollouts").load_all()[f"{SID}:{MODEL_NAME}"]
            assert torn["phase"] == "shadow", (
                "the drill needs the tear: registry CANARY, row SHADOW"
            )
            assert registry.candidate_model(SID, regional_name) is None

            follower.poll_once()  # the standby tails everything committed
        finally:
            rest.stop()  # SIGKILL stand-in: the leader process is gone

        # Lease ages out with the leader dark → the standby promotes.
        clock.t = 30.0
        follower.poll_once()
        assert follower.promoted and follower_backend.role == "leader"

        # The promoted manager boots a fresh plane over the replicated
        # state.  The controller's reconciler repairs the torn row to
        # the registry's phase; the daemon resumes from the lifecycle
        # namespace instead of retraining.
        registry2, controller2, daemon2 = _plane(follower_backend, world)
        repaired = controller2.get(SID, MODEL_NAME)
        assert repaired is not None and repaired.phase == "canary"
        assert "reconciled" in repaired.reason
        assert daemon2.store.candidate(GLOBAL_KEY) == cand.id
        assert daemon2.store.row(GLOBAL_KEY)["watermark"] == 160
        pre_models = len(registry2.list(scheduler_id=SID))

        for _ in range(8):
            daemon2.step()
            if registry2.active_model(SID, MODEL_NAME) is not None:
                break

        # Exactly one ACTIVE per (region, name): the resumed candidate
        # holds the global key, the retired specialization stays retired.
        actives = registry2.list(
            scheduler_id=SID, name=MODEL_NAME, state=ModelState.ACTIVE
        )
        assert [m.id for m in actives] == [cand.id]
        assert registry2.list(
            scheduler_id=SID, name=regional_name, state=ModelState.ACTIVE
        ) == []
        assert registry2.candidate_model(SID, regional_name) is None
        # Digest-checked artifact: load_artifact verifies the sha256
        # recorded at create_model against the replicated blob.
        assert load_scorer(registry2.load_artifact(actives[0])) is not None
        # Resume, not restart: same epoch counter, no re-registered
        # models, candidate slot cleared, promotion in the lineage.
        assert daemon2.store.row(GLOBAL_KEY)["epoch"] == 1
        assert len(registry2.list(scheduler_id=SID)) == pre_models
        assert daemon2.store.candidate(GLOBAL_KEY) is None
        events = [h["event"] for h in daemon2.store.row(GLOBAL_KEY)["history"]]
        assert events[0] == "registered" and events[-1] == "promote"


class TestLifecycleRowsRideTheWAL:
    def test_store_rows_replicate_and_reload_on_the_standby(self):
        clock = _Clock()
        leader = _leader(clock)
        store = LifecycleStore(leader)
        store.update(GLOBAL_KEY, epoch=2, watermark=2048, candidate_id="m-9",
                     candidate_version=9)
        store.append_history(GLOBAL_KEY, {"epoch": 2, "event": "registered"})
        follower = _standby(clock)
        follower.apply_ops(leader.log.entries_since(0))
        resumed = LifecycleStore(follower)
        row = resumed.row(GLOBAL_KEY)
        assert row["epoch"] == 2 and row["watermark"] == 2048
        assert resumed.candidate(GLOBAL_KEY) == "m-9"
        assert row["history"] == [{"epoch": 2, "event": "registered"}]
