"""Cross-process job fan-out (jobs/remote.py + the manager's jobs API):
the machinery-over-Redis analog — manager hosts the broker, remote
scheduler workers poll their queues over the wire
(reference: manager/job/preheat.go:126-167, internal/job/job.go:48-147).
"""

import json
import os
import re
import select
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dragonfly2_tpu.jobs.queue import JobQueue, JobState
from dragonfly2_tpu.jobs.remote import RemoteJobClient, RemoteJobWorker
from dragonfly2_tpu.manager import ClusterManager, ModelRegistry
from dragonfly2_tpu.manager.rest import ManagerRESTServer

PIECE = 32 * 1024


@pytest.fixture()
def broker_server():
    jq = JobQueue()
    server = ManagerRESTServer(
        ModelRegistry(), ClusterManager(), jobqueue=jq
    )
    server.serve()
    yield server, jq
    server.stop()


class TestJobsAPI:
    def test_group_create_poll_result_roundtrip(self, broker_server):
        server, jq = broker_server
        client = RemoteJobClient(server.url)
        group = client.create_group(
            "preheat", {"urls": ["https://o/a"]}, ["q-1", "q-2"]
        )
        assert group["state"] == "PENDING" and len(group["jobs"]) == 2

        worker = RemoteJobWorker(server.url, "q-1", poll_timeout_s=0.2)
        worker.register("preheat", lambda args: {"ok": args["urls"]})
        assert worker.poll_once() is True
        assert worker.poll_once() is False  # queue drained

        st = client.group_state(group["group_id"])
        states = {j["queue"]: j["state"] for j in st["jobs"]}
        assert states["q-1"] == "SUCCESS" and states["q-2"] == "PENDING"

        worker2 = RemoteJobWorker(server.url, "q-2", poll_timeout_s=0.2)
        worker2.register("preheat", lambda args: "done")
        worker2.poll_once()
        assert client.group_state(group["group_id"])["state"] == "SUCCESS"

    def test_list_groups_feeds_console(self, broker_server):
        """GET /api/v1/jobs: recent group snapshots, newest first — the
        console's jobs panel view."""
        server, jq = broker_server
        client = RemoteJobClient(server.url)
        g1 = client.create_group("preheat", {"urls": ["u"]}, ["q-1"])
        g2 = client.create_group("sync_peers", {}, ["q-1", "q-2"])
        with urllib.request.urlopen(server.url + "/api/v1/jobs", timeout=5) as r:
            groups = json.loads(r.read())
        assert [g["group_id"] for g in groups[:2]] == [
            g2["group_id"], g1["group_id"]
        ]
        assert len(groups[0]["jobs"]) == 2
        # The console SPA ships the panel that drives these routes.
        from dragonfly2_tpu.manager.console import CONSOLE_HTML

        assert 'api("/jobs"' in CONSOLE_HTML and "createJob" in CONSOLE_HTML

    def test_handler_failure_reported(self, broker_server):
        server, jq = broker_server
        client = RemoteJobClient(server.url)
        group = client.create_group("preheat", {"urls": []}, ["qf"])
        worker = RemoteJobWorker(server.url, "qf", poll_timeout_s=0.2)

        def boom(args):
            raise RuntimeError("origin 403")

        worker.register("preheat", boom)
        worker.poll_once()
        st = client.group_state(group["group_id"])
        assert st["state"] == "FAILURE"
        assert "origin 403" in st["jobs"][0]["error"]

    def test_unknown_type_fails_job(self, broker_server):
        server, jq = broker_server
        client = RemoteJobClient(server.url)
        group = client.create_group("mystery", {}, ["qm"])
        worker = RemoteJobWorker(server.url, "qm", poll_timeout_s=0.2)
        worker.poll_once()
        assert client.group_state(group["group_id"])["state"] == "FAILURE"

    def test_worker_survives_manager_outage(self, broker_server):
        server, jq = broker_server
        worker = RemoteJobWorker(server.url, "qo", poll_timeout_s=0.2,
                                 error_backoff_s=0.05)
        done = []
        worker.register("t", lambda a: done.append(a) or "ok")
        # Point at a dead port first: poll_once must raise ConnectionError
        # (the serve loop backs off), not crash.
        dead = RemoteJobWorker("http://127.0.0.1:1", "qo", poll_timeout_s=0.2)
        with pytest.raises(ConnectionError):
            dead.poll_once()
        # Live path still works afterwards.
        jq.enqueue("t", {"n": 1}, queue_name="qo")
        assert worker.poll_once() is True and done


class _RangeOrigin(BaseHTTPRequestHandler):
    BLOB = bytes(i % 251 for i in range(4 * PIECE))
    hits = []

    def log_message(self, *args):
        pass

    def do_HEAD(self):
        self.send_response(200)
        self.send_header("Content-Length", str(len(self.BLOB)))
        self.send_header("Accept-Ranges", "bytes")
        self.end_headers()

    def do_GET(self):
        type(self).hits.append(self.path)
        rng = self.headers.get("Range")
        body, code = self.BLOB, 200
        if rng:
            s, e = rng.split("=", 1)[1].split("-")
            body = self.BLOB[int(s): (int(e) if e else len(self.BLOB) - 1) + 1]
            code = 206
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class TestCrossProcessPreheat:
    """VERDICT r1 weak-#5 done-condition: REST preheat request → remote
    scheduler queue → seed daemon downloads layers, with manager,
    scheduler, and seed daemon in their own OS processes."""

    def test_rest_preheat_reaches_seed_daemon(self, tmp_path):
        procs = []

        def spawn(argv, prefixes, extra_env=None):
            env = {**os.environ, "PYTHONPATH": os.getcwd(), **(extra_env or {})}
            proc = subprocess.Popen(
                [sys.executable, *argv], stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True, env=env,
            )
            procs.append(proc)
            found = {}
            deadline = time.time() + 30
            while time.time() < deadline and len(found) < len(prefixes):
                ready, _, _ = select.select([proc.stdout], [], [], 30)
                assert ready, f"{argv}: silent"
                line = proc.stdout.readline().strip()
                for p in prefixes:
                    if line.startswith(p):
                        found[p] = line
            assert len(found) == len(prefixes), found
            return proc, found

        origin_srv = ThreadingHTTPServer(("127.0.0.1", 0), _RangeOrigin)
        threading.Thread(target=origin_srv.serve_forever, daemon=True).start()
        layer_urls = [
            f"http://127.0.0.1:{origin_srv.server_address[1]}/layer-{i}"
            for i in range(2)
        ]
        _RangeOrigin.hits.clear()

        (tmp_path / "m.yaml").write_text(
            "server: {host: 127.0.0.1, port: 0, grpc_port: -1}\n"
            f"registry: {{blob_dir: {tmp_path / 'blobs'}}}\n"
        )
        try:
            _, mout = spawn(
                ["-m", "dragonfly2_tpu.cli.manager", "--config",
                 str(tmp_path / "m.yaml")],
                ["manager: serving"],
            )
            manager_url = re.search(
                r"REST on (\S+)", mout["manager: serving"]
            ).group(1)

            (tmp_path / "s.yaml").write_text(
                "server: {host: 127.0.0.1, port: 0, grpc_port: -1}\n"
                "scheduling: {retry_interval_s: 0.0}\n"
                f"storage: {{dir: {tmp_path / 'records'}, buffer_size: 1}}\n"
                f"manager_addr: {manager_url}\n"
            )
            _, sout = spawn(
                ["-m", "dragonfly2_tpu.cli.scheduler", "--config",
                 str(tmp_path / "s.yaml")],
                ["scheduler: serving"],
            )
            sline = sout["scheduler: serving"]
            sched_url = re.search(r"rpc on (\S+?),", sline + ",").group(1)
            queue = re.search(r"job queue (\S+) on", sline).group(1)

            (tmp_path / "d.yaml").write_text(
                "server: {host: 127.0.0.1, port: 0, advertise_ip: 127.0.0.1}\n"
                f"storage: {{dir: {tmp_path / 'seedstore'}}}\n"
                f"piece_size: {PIECE}\n"
            )
            _, dout = spawn(
                ["-m", "dragonfly2_tpu.cli.dfdaemon", "--scheduler", sched_url,
                 "--config", str(tmp_path / "d.yaml"), "--seed-peer"],
                ["dfdaemon: serving"],
                {"DF_DAEMON_STATE": str(tmp_path / "d.json")},
            )
            piece_port = int(
                re.search(r"pieces on :(\d+)", dout["dfdaemon: serving"]).group(1)
            )

            # THE flow: REST preheat → scheduler queue → seed daemon.
            client = RemoteJobClient(manager_url)
            group = client.create_group(
                "preheat", {"urls": layer_urls, "piece_size": PIECE}, [queue]
            )
            deadline = time.time() + 30
            state = "PENDING"
            while time.time() < deadline:
                st = client.group_state(group["group_id"])
                state = st["state"]
                if state in ("SUCCESS", "FAILURE"):
                    break
                time.sleep(0.2)
            assert state == "SUCCESS", st
            # The seed daemon REALLY holds the layers: bitmap over its
            # piece port says all pieces present.
            from dragonfly2_tpu.utils import idgen

            for url in layer_urls:
                task_id = idgen.task_id(url)
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{piece_port}/tasks/{task_id}/pieces",
                    timeout=5,
                ) as resp:
                    bm = resp.read()
                assert bm == b"\x01" * 4, (url, bm)
            assert _RangeOrigin.hits, "origin never fetched"
        finally:
            for proc in procs:
                proc.terminate()
            origin_srv.shutdown()


class TestBrokerWireSemantics:
    def test_poll_skips_expired_jobs(self, broker_server):
        server, jq = broker_server
        jq.enqueue("t", {"n": 1}, queue_name="qe",
                   expires_at=time.time() - 1)
        live = jq.enqueue("t", {"n": 2}, queue_name="qe")
        worker = RemoteJobWorker(server.url, "qe", poll_timeout_s=0.2)
        got = []
        worker.register("t", lambda a: got.append(a["n"]))
        assert worker.poll_once() is True
        assert got == [2]  # expired job failed server-side, never delivered
        expired = [j for j in jq.jobs.values() if j.id != live.id][0]
        assert expired.state is JobState.FAILURE
        assert "expired" in expired.error

    def test_stale_started_requeued(self, broker_server):
        server, jq = broker_server
        job = jq.enqueue("t", {"n": 1}, queue_name="qs")
        # A worker popped it and died: STARTED long ago, never reported.
        polled = jq.poll("qs", timeout=0.1)
        assert polled is not None and polled.state is JobState.STARTED
        polled.started_at = time.time() - 600
        # Next poll requeues and redelivers it.
        again = jq.poll("qs", timeout=0.1, requeue_started_after_s=120)
        assert again is not None and again.id == job.id
        assert again.state is JobState.STARTED


class TestSchedulerRegistration:
    """The REST registration wire (ADVICE r2 medium): sync_peers fan-out
    targets f"scheduler:{sched.id}" for REGISTERED schedulers only — the
    CLI must register under the same id its job worker polls."""

    def test_register_keepalive_and_sync_peers_fanout(self, broker_server):
        from dragonfly2_tpu.jobs.sync_peers import SYNC_PEERS, SyncPeers
        from dragonfly2_tpu.rpc.cluster_client import RemoteClusterClient

        server, jq = broker_server
        link = RemoteClusterClient(server.url)
        assert link.register_scheduler(
            id="sched-t", cluster_id="default", hostname="h",
            ip="1.2.3.4", port=8002,
        )
        assert [s.id for s in server.clusters.active_schedulers()] == ["sched-t"]
        assert link.keepalive("sched-t") is True
        assert link.keepalive("ghost") is False

        worker = RemoteJobWorker(server.url, "scheduler:sched-t",
                                 poll_timeout_s=0.2)
        worker.register(SYNC_PEERS, lambda args: [{
            "id": "host-1", "hostname": "h1", "ip": "", "port": 0,
            "download_port": 0, "type": 0, "peer_count": 0,
        }])
        sp = SyncPeers(jq, server.clusters, job_timeout_s=5.0)
        answered = []
        th = threading.Thread(target=lambda: answered.append(sp.run_once()))
        th.start()
        deadline = time.time() + 4
        while time.time() < deadline and not worker.jobs_done:
            worker.poll_once()
        th.join(timeout=5)
        assert answered == [1]
        assert [r.id for r in sp.list_peers(active_only=True)] == ["host-1"]

    def test_keepalive_loop_reregisters_after_manager_restart(
        self, broker_server
    ):
        from dragonfly2_tpu.rpc.cluster_client import RemoteClusterClient

        server, jq = broker_server
        link = RemoteClusterClient(server.url, keepalive_interval_s=0.05)
        assert link.register_scheduler(id="sched-r")
        # Manager "restart": the in-memory cluster table is lost.  The
        # next keepalive self-heals (known=False → re-register) — same
        # behavior whichever loop ticks it (Announcer or serve()).
        server.clusters._schedulers.clear()
        assert link.keepalive("sched-r") is True
        assert [s.id for s in server.clusters.active_schedulers()] == ["sched-r"]
        # The standalone loop keeps it alive too.
        server.clusters._schedulers.clear()
        link.serve()
        try:
            deadline = time.time() + 3
            while not server.clusters.active_schedulers() and time.time() < deadline:
                time.sleep(0.02)
            assert [s.id for s in server.clusters.active_schedulers()] == ["sched-r"]
        finally:
            link.stop()

    def test_unauthorized_poll_and_register_log_warnings(self, caplog):
        """RBAC-enabled manager + tokenless worker: the 401 must surface
        at WARNING (jobs stuck PENDING with only debug logs was the
        ADVICE r2 failure mode)."""
        import logging

        from dragonfly2_tpu.rpc.cluster_client import RemoteClusterClient
        from dragonfly2_tpu.security.tokens import TokenIssuer, TokenVerifier

        issuer = TokenIssuer(b"k" * 32)
        server = ManagerRESTServer(
            ModelRegistry(), ClusterManager(), jobqueue=JobQueue(),
            token_verifier=TokenVerifier(b"k" * 32),
        )
        server.serve()
        try:
            worker = RemoteJobWorker(server.url, "scheduler:x",
                                     poll_timeout_s=0.2)
            with caplog.at_level(logging.WARNING):
                with pytest.raises(ConnectionError):
                    worker.poll_once()
            assert any("unauthorized" in r.message for r in caplog.records)
            caplog.clear()
            link = RemoteClusterClient(server.url)
            with caplog.at_level(logging.WARNING):
                assert link.register_scheduler(id="sched-x") is False
            assert any("unauthorized" in r.message.lower()
                       for r in caplog.records)
        finally:
            server.stop()

    def test_announcer_drives_remote_cluster_link(self, broker_server):
        """The Announcer's in-process register/keepalive loop works
        unchanged against the REST wire (one liveness implementation)."""
        from dragonfly2_tpu.records.storage import Storage
        from dragonfly2_tpu.rpc.cluster_client import RemoteClusterClient
        from dragonfly2_tpu.scheduler.announcer import Announcer
        import tempfile

        server, jq = broker_server
        link = RemoteClusterClient(server.url)
        with tempfile.TemporaryDirectory() as d:
            ann = Announcer(
                scheduler_id="sched-a", storage=Storage(d),
                trainer=None, cluster_manager=link, cluster_id="c9",
                hostname="hh", ip="9.9.9.9",
            )
            ann.announce_to_manager()
            got = server.clusters.active_schedulers()
            assert [(s.id, s.cluster_id) for s in got] == [("sched-a", "c9")]
            ann.keepalive()  # ticks through the same wire


class TestLiveClusterConfig:
    """VERDICT r2 next-#4 done-condition: PATCH cluster config on the
    manager → the NEXT scheduling pass on a live scheduler PROCESS uses
    the new limits (REST → dynconfig → SchedulingConfig, config tier c)."""

    def test_patch_changes_live_scheduler_limits(self, tmp_path):
        import select as _select

        from tests.test_rpc import PIECE as WPIECE, WireNode, WireOrigin

        procs = []

        def spawn(argv, prefixes, extra_env=None):
            env = {**os.environ, "PYTHONPATH": os.getcwd(), **(extra_env or {})}
            proc = subprocess.Popen(
                [sys.executable, *argv], stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True, env=env,
            )
            procs.append(proc)
            found = {}
            deadline = time.time() + 30
            while time.time() < deadline and len(found) < len(prefixes):
                ready, _, _ = _select.select([proc.stdout], [], [], 30)
                assert ready, f"{argv}: silent"
                line = proc.stdout.readline().strip()
                for p in prefixes:
                    if line.startswith(p):
                        found[p] = line
            assert len(found) == len(prefixes), found
            threading.Thread(
                target=lambda: [None for _ in proc.stdout], daemon=True
            ).start()
            return proc, found

        def call(base, method, path, body=None):
            data = json.dumps(body).encode() if body is not None else None
            req = urllib.request.Request(
                base + path, data=data,
                headers={"Content-Type": "application/json"}, method=method,
            )
            with urllib.request.urlopen(req, timeout=5) as resp:
                return json.loads(resp.read() or b"{}")

        (tmp_path / "m.yaml").write_text(
            "server: {host: 127.0.0.1, port: 0, grpc_port: -1}\n"
            f"registry: {{blob_dir: {tmp_path / 'blobs'}}}\n"
        )
        try:
            _, mout = spawn(
                ["-m", "dragonfly2_tpu.cli.manager", "--config",
                 str(tmp_path / "m.yaml")],
                ["manager: serving"],
            )
            manager_url = re.search(
                r"REST on (\S+)", mout["manager: serving"]
            ).group(1)

            (tmp_path / "s.yaml").write_text(
                "server: {host: 127.0.0.1, port: 0, grpc_port: -1}\n"
                "scheduling: {retry_interval_s: 0.0}\n"
                f"storage: {{dir: {tmp_path / 'records'}, buffer_size: 1}}\n"
                f"manager_addr: {manager_url}\n"
                "dynconfig_refresh_s: 0.2\n"
            )
            _, sout = spawn(
                ["-m", "dragonfly2_tpu.cli.scheduler", "--config",
                 str(tmp_path / "s.yaml")],
                ["scheduler: serving"],
            )
            sched_url = re.search(
                r"rpc on (\S+?),", sout["scheduler: serving"] + ","
            ).group(1)

            origin = WireOrigin()
            url = "https://origin/live-config-blob"
            nodes = [WireNode(i, sched_url, tmp_path, origin) for i in range(5)]
            try:
                # Seed 3 completed parents.
                assert nodes[0].conductor.download(
                    url, piece_size=WPIECE, content_length=2 * WPIECE
                ).ok
                for i in (1, 2):
                    assert nodes[i].conductor.download(url, piece_size=WPIECE).ok
                # Default cluster config: candidate_parent_limit 4 → the
                # child is offered multiple parents.
                reg = nodes[3].client.register_peer(host=nodes[3].host, url=url)
                assert reg.schedule is not None
                assert len(reg.schedule.parents) >= 2
                nodes[3].client.report_peer_failed(reg.peer)

                # PATCH → the live process's next pass caps at 1.
                call(manager_url, "POST", "/api/v1/clusters/default:update",
                     {"scheduler_cluster_config": {
                         "candidate_parent_limit": 1,
                         "filter_parent_limit": 15}})
                deadline = time.time() + 10
                n_parents = 99
                while time.time() < deadline:
                    reg = nodes[4].client.register_peer(
                        host=nodes[4].host, url=url
                    )
                    n_parents = len(reg.schedule.parents) if reg.schedule else 0
                    nodes[4].client.report_peer_failed(reg.peer)
                    if n_parents == 1:
                        break
                    time.sleep(0.3)
                assert n_parents == 1, (
                    f"live scheduler still hands out {n_parents} parents"
                )
            finally:
                for n in nodes:
                    n.stop()
        finally:
            for proc in procs:
                proc.terminate()
