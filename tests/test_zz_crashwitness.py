"""Dynamic crash-witness cross-check (persistence inventory, enforced).

``tests/conftest.py`` installs ``dragonfly2_tpu.utils.dfcrash`` before
any test import, so every KVTable write issued from project code during
this pytest session records (namespace, caller site, method, rows).
This module (named ``zz`` so it collects last and sees the whole
session's writes) drives the durable surfaces, then asserts:

- every observed write site maps into DF014's static persistence
  inventory (``tools/dflint/staterules.py``) with the same namespace —
  a stale inventory is a test failure, not silent rot;
- the declared multi-row sites (the registry's single-ACTIVE flip) are
  only ever observed as ONE ``put_many``;
- a crash injected at each declared multi-row site — through the
  existing ``state.put.*`` fault seams — leaves the namespace's
  declared invariant intact after the consumer reloads;
- the acceptance mutation (splitting the ACTIVE-flip ``put_many`` into
  sequential ``put``s) fails BOTH halves: statically by DF014 rule
  name, and dynamically as a witness gap naming the multi-row site —
  and the crash drill against the mutant really does tear the
  exactly-one-ACTIVE invariant on disk.

A gap here means the static resolver (or the contract registry) has a
blind spot — fix ``tools/dflint/staterules.py`` /
``records/state_contracts.py``, never this test.
"""

from __future__ import annotations

import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from dragonfly2_tpu.utils import dfcrash, faultinject  # noqa: E402

REGISTRY_RELPATH = "dragonfly2_tpu/manager/registry.py"
# Single-line-for-single-line replacement: the split puts land on the
# SAME line the real put_many occupies, so the mutant's writes map to
# the real _persist span in the static inventory — the witness then
# fails it on METHOD, which is the claim under test.
PUT_MANY_NEEDLE = (
    "            self._table.put_many({m.id: _model_to_doc(m) for m in models})"
)
PUT_SPLIT_REPL = (
    "            [self._table.put(m.id, _model_to_doc(m)) for m in models]"
)


def _witness():
    w = dfcrash.witness()
    if w is None:
        pytest.skip("crash witness disabled (DF_CRASH_WITNESS=0)")
    return w


@pytest.fixture(scope="module")
def analysis():
    from tests.test_dflint import _df_tree_program
    from tools.dflint.staterules import StateAnalysis

    return StateAnalysis(_df_tree_program(), REPO)


def _drive_workloads(tmp_path=None):
    """Writes across the durable surfaces the inventory declares:
    registry create/activate (the multi-row flip), jobs + groups
    (declared write order), rollout rows."""
    from dragonfly2_tpu.jobs.queue import JobQueue
    from dragonfly2_tpu.manager.registry import ModelRegistry
    from dragonfly2_tpu.manager.state import MemoryBackend
    from dragonfly2_tpu.rollout.controller import RolloutController

    backend = MemoryBackend()
    registry = ModelRegistry(backend=backend)
    m1 = registry.create_model(
        name="parent-bandwidth-mlp", type="mlp", scheduler_id="cw-sched",
        artifact=b"\x01" * 8,
    )
    m2 = registry.create_model(
        name="parent-bandwidth-mlp", type="mlp", scheduler_id="cw-sched",
        artifact=b"\x02" * 8,
    )
    registry.activate(m1.id)
    registry.activate(m2.id)          # two-row flip: ONE put_many, 2 rows

    controller = RolloutController(registry, backend=backend)
    m3 = registry.create_model(
        name="parent-bandwidth-mlp", type="mlp", scheduler_id="cw-sched",
        artifact=b"\x03" * 8,
    )
    controller.begin(m3.id)
    controller.delete_model(m3.id)

    q = JobQueue(backend=backend)
    q.enqueue("preheat", {"url": "http://x/1"}, group_id="cw-group")
    q.enqueue("preheat", {"url": "http://x/2"}, group_id="cw-group")
    return backend


class TestCrashWitness:
    def test_witness_is_installed_and_recording(self):
        w = _witness()
        _drive_workloads()
        assert w.snapshot(), "no KVTable writes recorded all session"

    def test_every_observed_write_is_in_the_static_inventory(self, analysis):
        from tools.dflint.staterules import crash_witness_gaps

        w = _witness()
        _drive_workloads()
        gaps = crash_witness_gaps(analysis, w.snapshot())
        assert not gaps, (
            "static persistence-inventory gaps (fix "
            "tools/dflint/staterules.py / records/state_contracts.py, "
            "not this test):\n  " + "\n  ".join(gaps)
        )

    def test_multi_row_flip_observed_as_one_put_many(self, analysis):
        """The ACTIVE swap must be OBSERVED as a single two-row
        put_many (if the workload stops exercising it, the cross-check
        goes vacuous)."""
        w = _witness()
        _drive_workloads()
        multi = analysis.multi_row_sites()
        assert multi, "no declared multi-row sites in the contract registry"
        fi = analysis.program.funcs.get(
            f"{REGISTRY_RELPATH}:ModelRegistry._persist"
        )
        assert fi is not None
        span = range(fi.node.lineno, (fi.node.end_lineno or fi.node.lineno) + 1)
        seen = [
            r
            for (relpath, line), records in w.snapshot().items()
            if relpath == REGISTRY_RELPATH and line in span
            for r in records
        ]
        assert seen, "registry._persist writes not observed"
        assert all(r["method"] == "put_many" for r in seen), seen
        assert any(r["max_rows"] >= 2 for r in seen), (
            "the two-row ACTIVE flip was never observed", seen,
        )

    def test_unknown_write_site_is_a_gap(self, analysis):
        from tools.dflint.staterules import crash_witness_gaps

        _witness()
        fake = {
            ("dragonfly2_tpu/daemon/nowhere.py", 7): [
                {"namespace": "models", "method": "put", "writes": 1,
                 "max_rows": 1},
            ],
        }
        gaps = crash_witness_gaps(analysis, fake)
        assert len(gaps) == 1 and "unknown to the static" in gaps[0]

    # -- crash drills against the declared invariants -------------------

    def test_crash_at_active_flip_keeps_exactly_one_active(self, tmp_path):
        """Drop the state.put.models seam mid-activate: the transaction
        never commits, and a reloaded registry still shows exactly one
        ACTIVE (the declared 'single_active' invariant)."""
        from dragonfly2_tpu.manager.registry import ModelRegistry, ModelState
        from dragonfly2_tpu.manager.state import SQLiteBackend

        db = str(tmp_path / "state.db")
        backend = SQLiteBackend(db)
        registry = ModelRegistry(backend=backend)
        m1 = registry.create_model(
            name="m", type="mlp", scheduler_id="s", artifact=b"\x01" * 4,
        )
        m2 = registry.create_model(
            name="m", type="mlp", scheduler_id="s", artifact=b"\x02" * 4,
        )
        registry.activate(m1.id)
        backend.close()

        backend = SQLiteBackend(db)
        registry = ModelRegistry(backend=backend)
        inj = faultinject.FaultInjector([
            faultinject.FaultSpec(site="state.put.models", kind="drop", at=(0,)),
        ])
        with faultinject.installed(inj):
            with pytest.raises(ConnectionError):
                registry.activate(m2.id)
        backend.close()

        backend = SQLiteBackend(db)
        reloaded = ModelRegistry(backend=backend)
        active = [
            m for m in reloaded.list(scheduler_id="s", name="m")
            if m.state is ModelState.ACTIVE
        ]
        assert [m.id for m in active] == [m1.id], (
            "exactly-one-ACTIVE torn by a crash at the flip", active,
        )
        backend.close()

    def test_crash_between_job_and_group_rows_reconciles(self, tmp_path):
        """Drop the group-row put after the job row committed: the
        reloaded queue re-adopts the job into its group from the job
        row's group_id (the declared 'jobs_absent_or_complete'
        invariant — no group may reference a missing job)."""
        from dragonfly2_tpu.jobs.queue import JobQueue
        from dragonfly2_tpu.manager.state import SQLiteBackend

        db = str(tmp_path / "state.db")
        backend = SQLiteBackend(db)
        q = JobQueue(backend=backend)
        inj = faultinject.FaultInjector([
            faultinject.FaultSpec(
                site="state.put.job_groups", kind="drop", at=(0,)
            ),
        ])
        with faultinject.installed(inj):
            with pytest.raises(ConnectionError):
                q.enqueue("preheat", {"url": "u"}, group_id="g1")
        backend.close()

        backend = SQLiteBackend(db)
        q2 = JobQueue(backend=backend)
        jobs = [j for j in q2.jobs.values() if j.group_id == "g1"]
        assert len(jobs) == 1, "job row must have committed before the tear"
        group = q2.groups.get("g1")
        assert group is not None and group.job_ids == [jobs[0].id], (
            "group not reconciled from the committed job row",
            group and group.job_ids,
        )
        assert all(i in q2.jobs for i in group.job_ids)
        backend.close()

    # -- acceptance mutation: the split-put registry ---------------------

    def _mutant_registry_module(self):
        src = (REPO / REGISTRY_RELPATH).read_text(encoding="utf-8")
        assert PUT_MANY_NEEDLE in src
        mutated = src.replace(PUT_MANY_NEEDLE, PUT_SPLIT_REPL)
        code = compile(mutated, str(REPO / REGISTRY_RELPATH), "exec")
        import types

        mod = types.ModuleType("dragonfly2_tpu.manager._registry_split_mutant")
        mod.__package__ = "dragonfly2_tpu.manager"
        mod.__file__ = str(REPO / REGISTRY_RELPATH)
        # dataclass string-annotation resolution reads
        # sys.modules[cls.__module__] at exec time.
        sys.modules[mod.__name__] = mod
        exec(code, mod.__dict__)  # noqa: S102 — controlled project-source mutant
        return mod.__dict__

    def test_put_many_split_fails_static_df014_by_name(self):
        from tests.test_dflint import _df_tree_program_with
        from tools.dflint.staterules import StateAnalysis

        mutated = (REPO / REGISTRY_RELPATH).read_text(encoding="utf-8").replace(
            PUT_MANY_NEEDLE, PUT_SPLIT_REPL
        )
        a = StateAnalysis(
            _df_tree_program_with(REGISTRY_RELPATH, mutated), REPO
        )
        hits = [
            f for f in a.findings()
            if f.rule == "DF014" and "multi-row site ModelRegistry._persist"
            in f.message and "models" in f.message
        ]
        assert hits, [f.render() for f in a.findings()]

    def test_put_many_split_fails_the_witness_by_site(self, analysis):
        """Dynamic half: drive the torn registry through the LIVE
        witness (records isolated from the session inventory) — the
        observed put() at the declared multi-row site is a gap."""
        from tools.dflint.staterules import crash_witness_gaps

        _witness()
        from dragonfly2_tpu.manager.state import MemoryBackend

        ns = self._mutant_registry_module()
        with dfcrash.isolated() as w:
            registry = ns["ModelRegistry"](backend=MemoryBackend())
            m1 = registry.create_model(
                name="m", type="mlp", scheduler_id="s", artifact=b"\x01" * 4,
            )
            m2 = registry.create_model(
                name="m", type="mlp", scheduler_id="s", artifact=b"\x02" * 4,
            )
            registry.activate(m1.id)
            registry.activate(m2.id)
            snap = w.snapshot()
        gaps = crash_witness_gaps(analysis, snap)
        assert any(
            "multi-row site" in g and "ModelRegistry._persist" in g
            and "put()" in g
            for g in gaps
        ), gaps

    def test_put_many_split_tears_the_invariant_on_crash(self, tmp_path):
        """The drill that motivates the rule: with the split mutant, a
        drop on the SECOND row's put leaves TWO ACTIVE versions on disk
        — the exact corruption the one-transaction contract prevents."""
        from dragonfly2_tpu.manager.registry import ModelRegistry, ModelState
        from dragonfly2_tpu.manager.state import SQLiteBackend

        ns = self._mutant_registry_module()
        db = str(tmp_path / "state.db")
        backend = SQLiteBackend(db)
        registry = ns["ModelRegistry"](backend=backend)
        m1 = registry.create_model(
            name="m", type="mlp", scheduler_id="s", artifact=b"\x01" * 4,
        )
        m2 = registry.create_model(
            name="m", type="mlp", scheduler_id="s", artifact=b"\x02" * 4,
        )
        registry.activate(m1.id)
        inj = faultinject.FaultInjector([
            faultinject.FaultSpec(site="state.put.models", kind="drop", at=(1,)),
        ])
        with faultinject.installed(inj):
            with pytest.raises(ConnectionError):
                registry.activate(m2.id)
        backend.close()

        backend = SQLiteBackend(db)
        reloaded = ModelRegistry(backend=backend)
        active = [
            m for m in reloaded.list(scheduler_id="s", name="m")
            if m.state is ModelState.ACTIVE
        ]
        assert len(active) == 2, (
            "the mutant was supposed to tear exactly-one-ACTIVE; the "
            "drill lost its sensitivity", [m.id for m in active],
        )
        backend.close()
