"""Dual-run determinism child (DESIGN.md §27).

Runs OUTSIDE conftest (no witnesses): the parent test
(``tests/test_zz_detwitness.py``) launches this script twice over the
SAME on-disk inputs with different ``PYTHONHASHSEED`` values and asserts
the stdout bytes are identical.  Every declared replay root
(``dragonfly2_tpu/records/determinism_contracts.py``) is exercised and
its decision output folded into one canonical JSON document.

Modes:

``roots <workdir>``
    ``workdir`` holds ``*.dfmj`` metric journals (written once by the
    parent via ``encode_frame``), ``slos.json`` and ``spans.json``.
    Prints ``json.dumps(results, sort_keys=True)`` for all roots.

``drill <metric_journal_source.py>``
    Loads the given metric_journal SOURCE (real or mutated copy) as a
    synthetic module and encodes one frame whose metrics dict is built
    by iterating a **set** of metric names — the canonical-bytes
    stressor.  With ``sort_keys=True`` intact the frame bytes are
    hash-seed-independent; the sort_keys-dropped mutant diverges
    across PYTHONHASHSEED values.  Prints the frame as hex.
"""

from __future__ import annotations

import glob
import json
import os
import sys
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def run_roots(workdir: str) -> None:
    import numpy as np

    import tools.fleet_assemble as fa
    import tools.trace_assemble as ta
    from dragonfly2_tpu.qos.accounting import TenantAccounting
    from dragonfly2_tpu.qos.autopilot import SLOAutopilot
    from dragonfly2_tpu.rollout import evaluation as ev
    from dragonfly2_tpu.rollout.controller import (
        RolloutController,
        RolloutGuardrails,
    )
    from dragonfly2_tpu.rollout.shadow import SHADOW_COLUMNS
    from dragonfly2_tpu.scheduler.sharding import ShardRing
    from dragonfly2_tpu.utils.metric_journal import replay_metric_journal
    from dragonfly2_tpu.utils.slo import SLOEngine, replay_fleet

    with open(os.path.join(workdir, "slos.json"), encoding="utf-8") as f:
        slos = json.load(f)
    with open(os.path.join(workdir, "spans.json"), encoding="utf-8") as f:
        spans = json.load(f)
    journals = sorted(glob.glob(os.path.join(workdir, "*.dfmj")))

    results = {}

    # -- slo.* roots: journal bytes -> snapshots -> engine verdicts ----------
    snapshots = []
    for path in journals:
        snaps, _stats = replay_metric_journal(path)
        snapshots.extend(snaps)
    snapshots.sort(key=lambda s: (s["run_id"], s["seq"]))
    eng = replay_fleet(snapshots, slos)  # ingest_snapshot + evaluate inside
    results["slo.replay_fleet"] = eng.state()
    eng2 = SLOEngine(slos)
    for snap in snapshots:
        eng2.ingest_snapshot(snap)
    last_ts = max(float(s["ts"]) for s in snapshots)
    # Mid-stream verdict: distinguishes ingest_snapshot's sample history
    # from the final evaluate below.
    results["slo.ingest_snapshot"] = eng2.evaluate(last_ts - 50.0)
    results["slo.evaluate"] = eng2.evaluate(last_ts)

    # -- autopilot.* ---------------------------------------------------------
    ap = SLOAutopilot.replay(snapshots, slos)
    results["autopilot.replay"] = {
        "decisions": [list(d) for d in ap.decisions],
        "levels": ap.levels(),
    }
    ap2 = SLOAutopilot(slos)
    results["autopilot.ingest"] = [ap2.ingest(s) for s in snapshots]

    # -- accounting.* --------------------------------------------------------
    acct = TenantAccounting(now=0.0)
    tenants = ["tenant-%02d" % i for i in range(8)]
    verdicts = []
    t = 0.0
    for step in range(240):
        t += 0.05
        verdicts.append(acct.note_at(tenants[step % len(tenants)], t))
    results["accounting.note_at"] = verdicts
    results["accounting.snapshot"] = acct.snapshot()

    # -- rollout.breach ------------------------------------------------------
    ctl = RolloutController.__new__(RolloutController)
    ctl.guardrails = RolloutGuardrails()
    reports = [
        {
            "psi_max": 0.01,
            "regret_at_k": {"candidate": 0.1, "active": 0.12, "k": 4},
            "inversion_rate": {"candidate": 0.2, "active": 0.25},
        },
        {
            "psi_max": 9.0,
            "regret_at_k": {"candidate": 0.1, "active": 0.12, "k": 4},
            "inversion_rate": {"candidate": 0.2, "active": 0.25},
        },
        {
            "psi_max": 0.01,
            "regret_at_k": {"candidate": 0.9, "active": 0.1, "k": 4},
            "inversion_rate": {"candidate": 0.9, "active": 0.1},
        },
    ]
    results["rollout.breach"] = [ctl._breach(r) for r in reports]

    # -- rollout evaluation roots (seeded synthetic log) ---------------------
    rng = np.random.default_rng(7)
    n = 400
    col = {name: i for i, name in enumerate(SHADOW_COLUMNS)}
    shadow = np.zeros((n, len(SHADOW_COLUMNS)), dtype=np.float32)
    shadow[:, col["announce_seq"]] = np.arange(n) // 8
    shadow[:, col["candidate_version"]] = 3
    shadow[:, col["active_version"]] = 2
    shadow[:, col["src_bucket"]] = rng.integers(0, 48, n)
    shadow[:, col["dst_bucket"]] = rng.integers(0, 48, n)
    shadow[:, col["active_score"]] = rng.random(n)
    shadow[:, col["candidate_score"]] = rng.random(n)
    shadow[:, col["active_rank"]] = rng.integers(0, 8, n)
    shadow[:, col["candidate_rank"]] = rng.integers(0, 8, n)
    dl = np.zeros((n // 2, 3), dtype=np.float32)
    dl[:, 0] = rng.integers(0, 48, n // 2)
    dl[:, 1] = rng.integers(0, 48, n // 2)
    dl[:, 2] = rng.random(n // 2) * 10.0
    realized = ev.join_outcomes(shadow, dl)
    results["rollout.regret_at_k"] = ev.regret_at_k(shadow, realized, k=3)
    results["rollout.inversion_rate"] = ev.pairwise_inversion_rate(
        shadow, realized
    )
    results["rollout.evaluate_shadow"] = ev.evaluate_shadow(
        shadow, dl, k=3, psi_max=0.12
    )

    # -- sharding.* ----------------------------------------------------------
    ring = ShardRing(
        {"shard-%02d" % i: "http://s%d" % i for i in range(16)}, version=3
    )
    keys = ["host-%04d" % i for i in range(256)]
    results["sharding.owner"] = [ring.owner(k) for k in keys]
    loads = {"shard-%02d" % i: float((i * 37) % 11) for i in range(16)}
    results["sharding.pick"] = [
        ring.pick(k, load_of=lambda sid: loads[sid]) for k in keys
    ]

    # -- fleet_assemble.* ----------------------------------------------------
    report = fa.build_report(journals, slo_config=slos)
    # Journal paths live under the parent's tmpdir; identical for both
    # child invocations but not across pytest runs — keep the decision
    # payload, drop the path echo.
    report.pop("journals", None)
    results["fleet_assemble.build_report"] = report
    results["fleet_assemble.merge_runs"] = fa.merge_runs(snapshots)

    # -- lifecycle.* ---------------------------------------------------------
    from dragonfly2_tpu.lifecycle import arbitrate_candidates, plan_epoch

    results["lifecycle.epoch_plan"] = [
        plan_epoch(
            records_seen=seen,
            watermark=mark,
            epoch_records=256,
            candidate_in_flight=busy,
        )
        for seen, mark, busy in [
            (100, 0, False),
            (300, 0, False),
            (300, 0, True),
            (900, 512, False),
        ]
    ]
    # Reports built by iterating a SET of keys — arbitration output must
    # not depend on dict-insertion/hash order.
    lc_reports = {}
    for key in {"global", "idc-a", "idc-b", "idc-c"}:
        rk = {
            "global": 0.30, "idc-a": 0.21, "idc-b": 0.35, "idc-c": 0.29,
        }[key]
        lc_reports[key] = {
            "joined_edges": 10 if key == "idc-c" else 120,
            "regret_at_k": {"candidate": rk, "active": 0.33, "k": 4},
        }
    results["lifecycle.arbitrate"] = arbitrate_candidates(
        lc_reports, min_joined=50, margin=0.02
    )

    # -- trace_assemble.* ----------------------------------------------------
    traces = ta.assemble(spans)
    results["trace_assemble.critical_path"] = {
        tid: ta.critical_path(tspans) for tid, tspans in sorted(traces.items())
    }
    results["trace_assemble.summarize_trace"] = [
        ta.summarize_trace(tid, traces[tid]) for tid in sorted(traces)
    ]

    sys.stdout.write(json.dumps(results, sort_keys=True))


def run_drill(source_path: str) -> None:
    with open(source_path, encoding="utf-8") as f:
        src = f.read()
    code = compile(src, source_path, "exec")
    mod = types.ModuleType("dragonfly2_tpu.utils._mj_drill")
    mod.__package__ = "dragonfly2_tpu.utils"
    mod.__file__ = source_path
    sys.modules[mod.__name__] = mod
    exec(code, mod.__dict__)

    names = {
        "announce_total", "rpc_tx_bytes", "sched_decisions", "qos_sheds",
        "journal_frames", "trace_spans", "slo_breaches", "cache_hits",
        "piece_bytes", "peer_churn", "probe_edges", "model_flips",
    }
    metrics = {}
    for name in names:  # SET iteration: order depends on PYTHONHASHSEED
        metrics[name] = {
            "type": "counter",
            "series": [[name, float(len(name))]],
        }
    snapshot = {
        "v": 1,
        "service": "drill",
        "run_id": "run-fixed",
        "pid": 1,
        "seq": 1,
        "ts": 0.0,
        "metrics": metrics,
    }
    sys.stdout.write(mod.encode_frame(snapshot).hex())


def main() -> int:
    mode = sys.argv[1]
    if mode == "roots":
        run_roots(sys.argv[2])
    elif mode == "drill":
        run_drill(sys.argv[2])
    else:
        raise SystemExit(f"unknown mode {mode!r}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
