"""Shared-kernel unit tests (idgen, digest, DAG, TTL cache, FSM, GC)."""

import threading
import time

import pytest

from dragonfly2_tpu.utils import cache, dag, digest, fsm, gc as gcmod, idgen
from dragonfly2_tpu.utils.types import HostType, SizeScope


class TestDigest:
    def test_sha256_from_strings_deterministic(self):
        a = digest.sha256_from_strings("10.0.0.1", "host-a")
        b = digest.sha256_from_strings("10.0.0.1", "host-a")
        assert a == b and len(a) == 64

    def test_separator_matters(self):
        # ("ab", "c") must differ from ("a", "bc")
        assert digest.sha256_from_strings("ab", "c") != digest.sha256_from_strings("a", "bc")

    def test_parse_roundtrip(self):
        d = digest.new("sha256", digest.sha256_from_bytes(b"hello"))
        algo, enc = digest.parse(d)
        assert algo == "sha256" and len(enc) == 64

    def test_parse_rejects_bad(self):
        with pytest.raises(ValueError):
            digest.parse("sha256:short")
        with pytest.raises(ValueError):
            digest.parse("nope:aa")


class TestIDGen:
    def test_host_id_v2_stable(self):
        assert idgen.host_id_v2("1.2.3.4", "h") == idgen.host_id_v2("1.2.3.4", "h")
        assert idgen.host_id_v2("1.2.3.4", "h") != idgen.host_id_v2("1.2.3.4", "h", seed_peer=True)

    def test_task_id_filters_query_params(self):
        meta = idgen.URLMeta(filtered_query_params=("token",))
        a = idgen.task_id("https://x.com/f?token=1&v=2", meta)
        b = idgen.task_id("https://x.com/f?token=9&v=2", meta)
        assert a == b

    def test_task_id_no_filter_is_raw(self):
        # Empty filter list ⇒ raw URL hashed: task_id(url) == task_id(url, URLMeta())
        # (reference pkg/net/url/url.go:24-27 no-ops on an empty filter).
        url = "https://x.com/f?b=2&a=1"
        assert idgen.task_id(url) == idgen.task_id(url, idgen.URLMeta())
        a = idgen.task_id("https://x.com/f?a=1&b=2", idgen.URLMeta())
        b = idgen.task_id(url, idgen.URLMeta())
        assert a != b  # param order matters when nothing is filtered

    def test_task_id_canonical_param_order_when_filtering(self):
        meta = idgen.URLMeta(filtered_query_params=("sig",))
        a = idgen.task_id("https://x.com/f?a=1&b=2&sig=XYZ", meta)
        b = idgen.task_id("https://x.com/f?b=2&sig=ABC&a=1", meta)
        assert a == b

    def test_task_id_range_vs_parent(self):
        meta = idgen.URLMeta(range="0-100")
        assert idgen.task_id("https://x.com/f", meta) != idgen.parent_task_id("https://x.com/f", meta)
        assert idgen.parent_task_id("https://x.com/f", meta) == idgen.task_id("https://x.com/f", idgen.URLMeta())

    def test_peer_id_unique(self):
        assert idgen.peer_id("1.2.3.4", "h") != idgen.peer_id("1.2.3.4", "h")
        assert idgen.peer_id("1.2.3.4", "h", seed=True).endswith("-seed")


class TestDAG:
    def test_add_edge_and_cycle_rejection(self):
        g = dag.DAG()
        for v in "abc":
            g.add_vertex(v, v)
        g.add_edge("a", "b")
        g.add_edge("b", "c")
        assert not g.can_add_edge("c", "a")
        with pytest.raises(dag.CycleError):
            g.add_edge("c", "a")
        assert g.can_add_edge("a", "c")

    def test_degrees_and_delete(self):
        g = dag.DAG()
        for v in "abc":
            g.add_vertex(v, v)
        g.add_edge("a", "b")
        g.add_edge("a", "c")
        assert g.get_vertex("a").out_degree() == 2
        assert g.get_vertex("b").in_degree() == 1
        g.delete_vertex("a")
        assert g.get_vertex("b").in_degree() == 0
        assert len(g) == 2

    def test_delete_in_edges(self):
        g = dag.DAG()
        for v in "abc":
            g.add_vertex(v, v)
        g.add_edge("a", "c")
        g.add_edge("b", "c")
        g.delete_vertex_in_edges("c")
        assert g.get_vertex("c").in_degree() == 0
        assert g.get_vertex("a").out_degree() == 0

    def test_topo_order(self):
        g = dag.DAG()
        for v in "abcd":
            g.add_vertex(v, v)
        g.add_edge("a", "b")
        g.add_edge("b", "c")
        g.add_edge("a", "d")
        order = [v.id for v in g.topo_order()]
        assert order.index("a") < order.index("b") < order.index("c")

    def test_concurrent_mutation(self):
        g = dag.DAG()
        for i in range(100):
            g.add_vertex(str(i), i)

        errors = []

        def worker(base):
            try:
                for i in range(base, 99):
                    if g.can_add_edge(str(i), str(i + 1)):
                        try:
                            g.add_edge(str(i), str(i + 1))
                        except dag.DAGError:
                            pass
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        # still acyclic
        list(g.topo_order())


class TestTTLCache:
    def test_set_get_expire(self):
        t = [0.0]
        c = cache.TTLCache(default_ttl=10.0, clock=lambda: t[0])
        c.set("k", "v")
        assert c.get("k") == "v"
        t[0] = 11.0
        assert c.get("k") is None

    def test_add_only_if_absent(self):
        c = cache.TTLCache()
        assert c.add("k", 1)
        assert not c.add("k", 2)
        assert c.get("k") == 1

    def test_scan(self):
        c = cache.TTLCache()
        c.set("networktopology:a:b", 1)
        c.set("networktopology:a:c", 2)
        c.set("other", 3)
        found = dict(c.scan(r"^networktopology:a:"))
        assert set(found.values()) == {1, 2}

    def test_purge(self):
        t = [0.0]
        c = cache.TTLCache(default_ttl=5.0, clock=lambda: t[0])
        for i in range(10):
            c.set(str(i), i)
        t[0] = 6.0
        assert c.purge_expired() == 10
        assert len(c) == 0


class TestFSM:
    def make(self):
        return fsm.FSM(
            initial="pending",
            events=[
                fsm.EventDesc("register", ["pending"], "running"),
                fsm.EventDesc("succeed", ["running"], "succeeded"),
                fsm.EventDesc("fail", ["pending", "running"], "failed"),
            ],
        )

    def test_transitions(self):
        m = self.make()
        assert m.current == "pending"
        m.event("register")
        assert m.is_("running")
        m.event("succeed")
        assert m.is_("succeeded")

    def test_illegal_event_raises(self):
        m = self.make()
        with pytest.raises(fsm.InvalidEventError):
            m.event("succeed")
        assert m.current == "pending"

    def test_can(self):
        m = self.make()
        assert m.can("register") and m.can("fail") and not m.can("succeed")

    def test_callbacks(self):
        calls = []
        m = fsm.FSM(
            "a",
            [fsm.EventDesc("go", ["a"], "b")],
            callbacks={"enter_b": lambda f, e, s, d: calls.append((e, s, d))},
        )
        m.event("go")
        assert calls == [("go", "a", "b")]


class TestGC:
    def test_interval_and_manual_run(self):
        runs = []
        g = gcmod.GC()
        g.add(gcmod.Task(id="t", interval=0.05, timeout=0.05, runner=lambda: runs.append(1)))
        g.run("t")
        time.sleep(0.02)
        assert len(runs) == 1
        g.start()
        time.sleep(0.18)
        g.stop()
        assert len(runs) >= 3

    def test_bad_task_rejected(self):
        with pytest.raises(ValueError):
            gcmod.Task(id="x", interval=1.0, timeout=2.0, runner=lambda: None)


class TestTypes:
    def test_host_type(self):
        assert not HostType.NORMAL.is_seed
        assert HostType.SUPER_SEED.is_seed

    def test_size_scope_enum(self):
        assert SizeScope.TINY.value == 2


class TestICMPPing:
    def test_icmp_echo_loopback(self):
        from dragonfly2_tpu.utils.ping import icmp_available, icmp_ping

        if not icmp_available():
            pytest.skip("no ICMP socket capability in this environment")
        rtt = icmp_ping("127.0.0.1", timeout=2.0)
        assert rtt is not None and 0 < rtt < 2_000_000_000

    def test_icmp_timeout_returns_none(self):
        from dragonfly2_tpu.utils.ping import icmp_available, icmp_ping

        if not icmp_available():
            pytest.skip("no ICMP socket capability in this environment")
        import time

        t0 = time.monotonic()
        assert icmp_ping("10.255.255.1", timeout=0.2) is None
        assert time.monotonic() - t0 < 2.0

    def test_host_pinger_prefers_icmp_with_tcp_fallback(self):
        import socket
        import threading

        from dragonfly2_tpu.utils.ping import icmp_available, make_host_pinger

        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        accepted = threading.Thread(
            target=lambda: [srv.accept() for _ in range(2)], daemon=True
        )
        accepted.start()

        class H:
            ip = "127.0.0.1"
            port = srv.getsockname()[1]
            download_port = srv.getsockname()[1]

        icmp = make_host_pinger(prefer_icmp=True)
        tcp_only = make_host_pinger(prefer_icmp=False)
        assert tcp_only(H()) is not None
        if icmp_available():
            assert icmp(H()) is not None
        srv.close()


class TestTraceParent:
    def test_inject_and_remote_span_link(self):
        from dragonfly2_tpu.utils.tracing import (
            InMemoryExporter,
            Tracer,
            parse_traceparent,
        )

        exp = InMemoryExporter()
        tracer = Tracer(exporter=exp)
        assert tracer.inject() == {}  # no active span
        with tracer.span("client/op") as client_span:
            header = tracer.inject()["traceparent"]
            assert parse_traceparent(header) == (
                client_span.trace_id, client_span.span_id
            )
        # "Server side": link a handler span from the wire header.
        with tracer.remote_span("server/handler", header) as server_span:
            assert server_span.trace_id == client_span.trace_id
            assert server_span.parent_id == client_span.span_id
        # Malformed headers degrade to a fresh root span, never raise.
        with tracer.remote_span("server/handler", "garbage") as s:
            assert s.parent_id is None
        assert parse_traceparent(None) is None
        assert parse_traceparent("00-zz-yy-01") is None


class TestOTLPExport:
    """OTLP/JSON exporter (VERDICT r3 next-#7): standard-collector trace
    export — the --jaeger analog, cmd/dependency/dependency.go:263-297."""

    def _traced(self, exporter):
        from dragonfly2_tpu.utils.tracing import Tracer

        tracer = Tracer(service="test-svc", exporter=exporter)
        with tracer.span("download", task_id="t-1", pieces=12) as root:
            header = tracer.inject()["traceparent"]
            with tracer.span("piece/fetch", number=0, cost_s=0.5):
                pass
        # Cross-process hop: the handler span joins the SAME trace.
        with tracer.remote_span("scheduler/handle", header, ok=True):
            pass
        return root

    def test_otlp_json_file_shape(self, tmp_path):
        """Golden-shape assertions on the emitted ExportTraceServiceRequest:
        hex ids, parent linkage across a remote hop, proto3-JSON value
        encodings — what Jaeger's :4318/v1/traces endpoint ingests."""
        import json

        from dragonfly2_tpu.utils.tracing import OTLPJSONExporter

        path = str(tmp_path / "spans.otlp.json")
        exp = OTLPJSONExporter(path, service="test-svc")
        root = self._traced(exp)
        exp.flush()

        lines = [json.loads(l) for l in open(path)]
        spans = []
        for req in lines:
            rs = req["resourceSpans"][0]
            attrs = {
                a["key"]: a["value"] for a in rs["resource"]["attributes"]
            }
            assert attrs["service.name"] == {"stringValue": "test-svc"}
            spans += rs["scopeSpans"][0]["spans"]
        by_name = {s["name"]: s for s in spans}
        assert set(by_name) == {"download", "piece/fetch", "scheduler/handle"}
        # ONE trace across all three, incl. the remote hop.
        assert {s["traceId"] for s in spans} == {root.trace_id}
        assert by_name["piece/fetch"]["parentSpanId"] == root.span_id
        assert by_name["scheduler/handle"]["parentSpanId"] == root.span_id
        assert "parentSpanId" not in by_name["download"]
        # OTLP/JSON encodings: hex ids, int64 as string, typed values.
        int(by_name["download"]["traceId"], 16)
        assert isinstance(by_name["download"]["startTimeUnixNano"], str)
        piece_attrs = {
            a["key"]: a["value"] for a in by_name["piece/fetch"]["attributes"]
        }
        assert piece_attrs["number"] == {"intValue": "0"}
        assert piece_attrs["cost_s"] == {"doubleValue": 0.5}
        assert all(s["status"]["code"] == 1 for s in spans)

    def test_otlp_requests_validate_against_vendored_schema(self, tmp_path):
        """Every emitted ExportTraceServiceRequest validates against the
        vendored opentelemetry-proto JSON Schema (VERDICT r4 #9) — and
        the schema has TEETH: each known rot class fails it."""
        import copy
        import json

        import jsonschema

        from dragonfly2_tpu.utils.tracing import (
            OTLPJSONExporter,
            otlp_trace_schema,
        )

        validator = jsonschema.Draft202012Validator(otlp_trace_schema())

        path = str(tmp_path / "spans.otlp.json")
        exp = OTLPJSONExporter(path, service="test-svc", batch_size=2)
        self._traced(exp)
        exp.flush()
        reqs = [json.loads(l) for l in open(path)]
        assert reqs
        for req in reqs:
            validator.validate(req)  # raises on any violation

        # Teeth: mutate one valid request per rot class — all must fail.
        def fails(mutate):
            bad = copy.deepcopy(reqs[0])
            mutate(bad)
            return list(validator.iter_errors(bad))

        span = lambda r: r["resourceSpans"][0]["scopeSpans"][0]["spans"][0]
        assert fails(lambda r: span(r).__setitem__("traceid",
                     span(r).pop("traceId")))      # misspelled field
        assert fails(lambda r: span(r).__setitem__("traceId", "xyz"))
        assert fails(lambda r: span(r).__setitem__(
            "startTimeUnixNano", 123456))          # int64 must be a string
        assert fails(lambda r: span(r).__setitem__("status", {"code": 3}))
        assert fails(lambda r: span(r)["attributes"][0]["value"].update(
            {"stringValue": "x", "intValue": "1"}))  # AnyValue is a oneof
        assert fails(lambda r: span(r).__setitem__("kind", 9))
        assert fails(lambda r: r["resourceSpans"][0].__setitem__(
            "resource", {"attrs": []}))            # misplaced resource field

    def test_otlp_http_endpoint_and_error_status(self, tmp_path):
        import json
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from dragonfly2_tpu.utils.tracing import OTLPJSONExporter, Tracer

        received = []

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                received.append(json.loads(self.rfile.read(n)))
                self.send_response(200)
                self.send_header("Content-Length", "0")
                self.end_headers()

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            url = f"http://127.0.0.1:{httpd.server_address[1]}/v1/traces"
            exp = OTLPJSONExporter(url, service="svc")
            tracer = Tracer(exporter=exp)
            import pytest

            with pytest.raises(RuntimeError):
                with tracer.span("boom"):
                    raise RuntimeError("nope")
            exp.flush()
            spans = received[0]["resourceSpans"][0]["scopeSpans"][0]["spans"]
            assert spans[0]["status"]["code"] == 2
            assert "RuntimeError" in spans[0]["status"]["message"]
            assert exp.dropped == 0
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_export_failure_never_raises(self):
        from dragonfly2_tpu.utils.tracing import OTLPJSONExporter, Tracer

        exp = OTLPJSONExporter(
            "http://127.0.0.1:1/v1/traces", batch_size=1
        )  # nothing listens
        tracer = Tracer(exporter=exp)
        with tracer.span("lonely"):
            pass  # export happens on span end — must not raise
        exp.flush()  # joins the background sender
        assert exp.dropped == 1
