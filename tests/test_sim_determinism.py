"""Seed-sweep reproducibility gates for the simulators (DESIGN.md §27).

Same seed ⇒ byte-identical deterministic summary, across subprocesses
with DIFFERENT ``PYTHONHASHSEED`` values.  The simulators are the
repo's evidence generators (bench_swarm, bench_qos headline numbers);
if their *behavioral* outputs drift with interpreter hash salting, a
"regression" in a bench arm can be pure hash noise.  Wall-time
measurements are excluded by design — ``deterministic_summary`` in each
sim module is the declared projection.

The known regression this gate was built for: ``sim/qos.py``'s origin
content used builtin ``hash(url)`` (salted per process), so two
identically-seeded drills served different bytes.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]


def _run_child(mode: str, hashseed: int) -> bytes:
    env = dict(os.environ)
    env["PYTHONHASHSEED"] = str(hashseed)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, str(REPO / "tests" / "_sim_child.py"), mode],
        capture_output=True, timeout=300, cwd=str(REPO), env=env,
    )
    assert proc.returncode == 0, (
        f"sim child {mode!r} failed (seed {hashseed}):\n"
        f"{proc.stderr.decode()}"
    )
    return proc.stdout


class TestFleetSeedSweep:
    def test_fleet_summary_byte_identical_across_hashseeds(self):
        out0 = _run_child("fleet", 0)
        out42 = _run_child("fleet", 42)
        summary = json.loads(out0)
        # The run really simulated something (the gate isn't vacuous)...
        assert summary["announces"] > 0
        assert summary["online"] > 0
        # ...and the wall-time keys really are projected out.
        for key in ("wall_s", "announce_wall_s", "announces_per_sec"):
            assert key not in summary
        assert out0 == out42, (
            "fleet sim summary diverged across PYTHONHASHSEED"
        )

    def test_timing_keys_are_the_only_drops(self):
        from dragonfly2_tpu.sim.fleet import TIMING_KEYS, deterministic_summary

        report = {"joins": 3, "wall_s": 1.5, "announce_wall_s": 0.2,
                  "announces_per_sec": 10.0, "sheds": 0}
        out = deterministic_summary(report)
        assert out == {"joins": 3, "sheds": 0}
        assert set(TIMING_KEYS) == {
            "wall_s", "announce_wall_s", "announces_per_sec"
        }


class TestQoSSeedSweep:
    def test_qos_baseline_byte_identical_across_hashseeds(self):
        out0 = _run_child("qos", 0)
        out42 = _run_child("qos", 42)
        doc = json.loads(out0)
        assert doc["baseline"]["a_announces"] > 0
        assert doc["baseline"]["a_downloads_ok"] > 0
        assert out0 == out42, (
            "qos drill baseline diverged across PYTHONHASHSEED "
            "(origin content or accounting is hash-salted again)"
        )

    def test_origin_content_is_not_hash_salted(self):
        """In-process guard (cheap, no subprocess): origin bytes derive
        from crc32, never builtin hash()."""
        import zlib

        from dragonfly2_tpu.sim.qos import _Origin

        url = "https://origin.qos/a-0"
        origin = _Origin(64)
        seed = (zlib.crc32(url.encode()) ^ 3) & 0xFF
        expect = bytes((seed + i) % 256 for i in range(64))
        assert origin.fetch(url, 3, 64) == expect
