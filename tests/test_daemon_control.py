"""dfget→daemon contract: control API, state-file discovery, auto-spawn,
and the debug endpoint."""

import json
import os
import subprocess
import sys
import tempfile
import urllib.request

import pytest

from dragonfly2_tpu.rpc.daemon_control import (
    DaemonControlServer,
    daemon_healthy,
    download_via_daemon,
    read_state,
    write_state,
)

from tests.test_daemon import PIECE, _Swarm


class TestControlServer:
    def test_healthy_and_download(self, tmp_path):
        swarm = _Swarm(tmp_path, n_hosts=2)
        swarm.origin.content_length = lambda u: 3 * PIECE
        d = swarm.daemons[0]
        srv = DaemonControlServer(d.conductor, piece_size=PIECE)
        srv.serve()
        try:
            assert daemon_healthy(srv.url)
            out_file = str(tmp_path / "via-daemon.bin")
            result = download_via_daemon(
                "https://origin/ctl-blob", srv.url, output=out_file,
                piece_size=PIECE,
            )
            assert result["ok"] and result["pieces"] == 3
            expected = b"".join(
                swarm.origin.content("https://origin/ctl-blob", n)
                for n in range(3)
            )
            with open(out_file, "rb") as f:
                assert f.read() == expected
        finally:
            srv.stop()

    def test_state_file_roundtrip(self, tmp_path, monkeypatch):
        path = str(tmp_path / "daemon.json")
        monkeypatch.setenv("DF_DAEMON_STATE", path)
        write_state("http://127.0.0.1:1234")
        state = read_state()
        assert state["url"] == "http://127.0.0.1:1234"
        assert state["pid"] == os.getpid()
        assert not daemon_healthy(state["url"])  # nothing listening

    def test_failed_download_returns_dict_not_traceback(self, tmp_path):
        """Error statuses carry the JSON result back to the caller — the
        dfget ok-check path must be reachable."""
        swarm = _Swarm(tmp_path, n_hosts=1)
        d = swarm.daemons[0]
        d.conductor.source_fetcher = None  # downloads will fail
        srv = DaemonControlServer(d.conductor, piece_size=PIECE)
        srv.serve()
        try:
            result = download_via_daemon(
                "https://origin/doomed", srv.url, piece_size=PIECE
            )
            assert result["ok"] is False
        finally:
            srv.stop()

    def test_bad_request_rejected(self, tmp_path):
        swarm = _Swarm(tmp_path, n_hosts=1)
        d = swarm.daemons[0]
        srv = DaemonControlServer(d.conductor)
        srv.serve()
        try:
            req = urllib.request.Request(
                srv.url + "/download", data=b"{}",
                headers={"Content-Type": "application/json"}, method="POST",
            )
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=5)
            assert exc.value.code == 400
        finally:
            srv.stop()


class TestDfgetDaemonSpawn:
    def test_spawn_download_reuse(self, tmp_path):
        """dfget --daemon: spawns a dfdaemon against a real scheduler
        process, downloads through it, and a second dfget reuses the SAME
        daemon (no second spawn)."""
        env = {
            **os.environ,
            "PYTHONPATH": "/root/repo",
            "DF_DAEMON_STATE": str(tmp_path / "daemon.json"),
        }
        sched_cfg = tmp_path / "sched.yaml"
        sched_cfg.write_text(
            f"storage:\n  dir: {tmp_path}/records\n"
            "server:\n  host: 127.0.0.1\n  port: 0\n"
        )
        launcher = (
            "import sys\n"
            "from dragonfly2_tpu.cli.scheduler import build\n"
            "from dragonfly2_tpu.config import SchedulerConfigFile, load_config\n"
            "from dragonfly2_tpu.rpc import SchedulerHTTPServer\n"
            "cfg = load_config(SchedulerConfigFile, sys.argv[1])\n"
            "service, storage, runner = build(cfg)\n"
            "srv = SchedulerHTTPServer(service, port=0)\nsrv.serve()\n"
            "print('READY', srv.url, flush=True)\n"
            "import time; time.sleep(120)\n"
        )
        sched = subprocess.Popen(
            [sys.executable, "-c", launcher, str(sched_cfg)],
            stdout=subprocess.PIPE, text=True, env=env,
        )
        daemon_pid = None
        try:
            sched_url = sched.stdout.readline().split()[1]
            daemon_cfg = tmp_path / "daemon.yaml"
            daemon_cfg.write_text(
                f"storage:\n  dir: {tmp_path}/dstore\n"
                "probe_interval_s: 3600\n"
            )
            blob = tmp_path / "origin.bin"
            blob.write_bytes(os.urandom(300_000))
            out1 = str(tmp_path / "out1.bin")
            r = subprocess.run(
                [sys.executable, "-m", "dragonfly2_tpu.cli.dfget",
                 f"file://{blob}", "-O", out1, "--daemon",
                 "--scheduler", sched_url, "--config", str(daemon_cfg),
                 "--piece-size", str(64 * 1024)],
                capture_output=True, text=True, env=env, timeout=60,
            )
            assert r.returncode == 0, r.stderr
            assert "through daemon" in r.stdout
            with open(out1, "rb") as f:
                assert f.read() == blob.read_bytes()
            state = json.loads((tmp_path / "daemon.json").read_text())
            daemon_pid = state["pid"]
            # Second dfget: reuses the running daemon (same pid in state).
            out2 = str(tmp_path / "out2.bin")
            r2 = subprocess.run(
                [sys.executable, "-m", "dragonfly2_tpu.cli.dfget",
                 f"file://{blob}", "-O", out2, "--daemon",
                 "--piece-size", str(64 * 1024)],
                capture_output=True, text=True, env=env, timeout=60,
            )
            assert r2.returncode == 0, r2.stderr
            assert json.loads(
                (tmp_path / "daemon.json").read_text()
            )["pid"] == daemon_pid
            with open(out2, "rb") as f:
                assert f.read() == blob.read_bytes()
        finally:
            sched.kill()
            if daemon_pid:
                try:
                    os.kill(daemon_pid, 9)
                except OSError:
                    pass


class TestDebugEndpoint:
    def test_stacks_stats_profile(self):
        from dragonfly2_tpu.utils.debug import DebugServer

        srv = DebugServer()
        srv.serve()
        try:
            with urllib.request.urlopen(srv.url + "/debug/stacks", timeout=5) as r:
                body = r.read().decode()
            assert "MainThread" in body and "---" in body
            with urllib.request.urlopen(srv.url + "/debug/stats", timeout=5) as r:
                stats = json.loads(r.read())
            assert stats["threads"] >= 1 and "gc_counts" in stats
            with urllib.request.urlopen(
                srv.url + "/debug/profile?seconds=0.2", timeout=10
            ) as r:
                assert b"cumulative" in r.read()
        finally:
            srv.stop()


class TestDfgetDaemonRecursive:
    def test_recursive_through_daemon(self, tmp_path):
        """VERDICT r2 next-#10: --daemon --recursive routes a directory
        tree through the daemon control API instead of refusing."""
        env = {
            **os.environ,
            "PYTHONPATH": "/root/repo",
            "DF_DAEMON_STATE": str(tmp_path / "daemon.json"),
        }
        sched_cfg = tmp_path / "sched.yaml"
        sched_cfg.write_text(
            f"storage:\n  dir: {tmp_path}/records\n"
            "server:\n  host: 127.0.0.1\n  port: 0\n"
        )
        launcher = (
            "import sys\n"
            "from dragonfly2_tpu.cli.scheduler import build\n"
            "from dragonfly2_tpu.config import SchedulerConfigFile, load_config\n"
            "from dragonfly2_tpu.rpc import SchedulerHTTPServer\n"
            "cfg = load_config(SchedulerConfigFile, sys.argv[1])\n"
            "service, storage, runner = build(cfg)\n"
            "srv = SchedulerHTTPServer(service, port=0)\nsrv.serve()\n"
            "print('READY', srv.url, flush=True)\n"
            "import time; time.sleep(120)\n"
        )
        sched = subprocess.Popen(
            [sys.executable, "-c", launcher, str(sched_cfg)],
            stdout=subprocess.PIPE, text=True, env=env,
        )
        daemon_pid = None
        try:
            sched_url = sched.stdout.readline().split()[1]
            daemon_cfg = tmp_path / "daemon.yaml"
            daemon_cfg.write_text(
                f"storage:\n  dir: {tmp_path}/dstore\n"
                "probe_interval_s: 3600\n"
            )
            # A small tree with a nested dir, an empty dir, and an odd name.
            src = tmp_path / "tree"
            (src / "sub").mkdir(parents=True)
            (src / "empty").mkdir()
            (src / "a.bin").write_bytes(os.urandom(150_000))
            (src / "sub" / "b#x.bin").write_bytes(os.urandom(70_000))
            out = str(tmp_path / "restored")
            r = subprocess.run(
                [sys.executable, "-m", "dragonfly2_tpu.cli.dfget",
                 f"file://{src}", "-O", out, "--daemon", "--recursive",
                 "--scheduler", sched_url, "--config", str(daemon_cfg),
                 "--piece-size", str(64 * 1024)],
                capture_output=True, text=True, env=env, timeout=90,
            )
            assert r.returncode == 0, r.stderr + r.stdout
            assert "downloaded 2 files through daemon" in r.stdout
            assert (src / "a.bin").read_bytes() == \
                (tmp_path / "restored" / "a.bin").read_bytes()
            assert (src / "sub" / "b#x.bin").read_bytes() == \
                (tmp_path / "restored" / "sub" / "b#x.bin").read_bytes()
            assert (tmp_path / "restored" / "empty").is_dir()
        finally:
            sched.kill()
            # Read the pid HERE: a failed assertion above must still kill
            # the daemon dfget spawned (it registers the state file as
            # soon as it boots).
            try:
                daemon_pid = json.loads(
                    (tmp_path / "daemon.json").read_text()
                )["pid"]
                os.kill(daemon_pid, 15)
            except (OSError, ValueError, KeyError):
                pass
