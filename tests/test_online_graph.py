"""Online graph trainer (BASELINE configs[5]): two-stream ingest,
mid-training snapshot refresh, byte-identical resume across a refresh
boundary (trainer/online_graph.py; reference stream demux
trainer/service/service_v1.go:128-143)."""

import numpy as np
import pytest

from dragonfly2_tpu.models.hop import HopConfig
from dragonfly2_tpu.records.synthetic import SyntheticCluster
from dragonfly2_tpu.trainer.online_graph import (
    OnlineGraphConfig,
    OnlineGraphTrainer,
    state_hash,
)
from dragonfly2_tpu.trainer.train import TrainConfig

N_NODES = 128


def _mk_cluster(seed=0):
    return SyntheticCluster(num_hosts=N_NODES, seed=seed)


def _topo(cluster, seed):
    rng = np.random.default_rng(seed)
    n = N_NODES * 8
    src = rng.integers(0, N_NODES, n)
    dst = rng.integers(0, N_NODES, n)
    keep = src != dst
    src, dst = src[keep], dst[keep]
    # Deterministic rtt (no shared-rng draw) for replayable streams.
    return src, dst, (cluster._rtt_vec(src, dst, noise=False) / 1e9).astype(
        np.float32
    )


def _downloads(cluster, seed, n):
    rng = np.random.default_rng(seed)
    es = rng.integers(0, N_NODES, n).astype(np.int32)
    ed = (es + rng.integers(1, N_NODES, n).astype(np.int32)) % N_NODES
    y = np.log1p(cluster._bandwidth_vec(es, ed, rng=rng)).astype(np.float32)
    return es, ed, y


def _mk_trainer(cluster, tmp_path=None, **cfg_kw):
    defaults = dict(
        num_nodes=N_NODES,
        max_neighbors=8,
        batch_size=256,
        super_steps=4,
        queue_capacity=16,  # tests feed the whole stream before run()
        model=HopConfig(hidden=16, out_dim=8, node_embed_dim=4, dropout=0.1),
        train=TrainConfig(warmup_steps=2),
        total_steps_hint=1000,
    )
    defaults.update(cfg_kw)
    cfg = OnlineGraphConfig(**defaults)
    src, dst, rtt = _topo(cluster, seed=1)
    return OnlineGraphTrainer(
        cfg,
        node_feats=cluster._host_feature_matrix(),
        topo_src=src, topo_dst=dst, topo_rtt=rtt,
        checkpoint_dir=str(tmp_path) if tmp_path else None,
    )


def _state_hash(trainer) -> str:
    return state_hash(trainer.state)


class TestSnapshotRefresh:
    def test_swap_changes_graph_not_optimizer(self):
        import jax

        cluster = _mk_cluster()
        tr = _mk_trainer(cluster)
        es, ed, y = _downloads(cluster, 2, 4 * 256 * 2)
        tr.feed_downloads(es, ed, y)
        assert tr.run(max_dispatches=2, idle_timeout=0.1) == 2
        compiles_before = tr._dispatch_fn._cache_size()
        step_before = int(tr.state.step)
        params_before = jax.tree_util.tree_map(np.asarray, tr.state.params)
        digest_before = tr.snapshot_digest()

        # New topology (drifted load) → refresh swaps the hop tables only.
        cluster.drift(np.random.default_rng(7))
        tr.set_node_features(cluster._host_feature_matrix())
        src, dst, rtt = _topo(cluster, seed=9)
        tr.feed_topology(src, dst, rtt)
        assert tr.refresh_snapshot() is not None
        assert tr.snapshot_digest() != digest_before
        assert tr.snapshot_idx == 1
        assert int(tr.state.step) == step_before  # optimizer untouched
        for a, b in zip(
            jax.tree_util.tree_leaves(params_before),
            jax.tree_util.tree_leaves(tr.state.params),
        ):
            np.testing.assert_array_equal(a, np.asarray(b))

        # Training continues on the new snapshot with the SAME compiled
        # program (hop tables are arguments, shapes static).
        tr.feed_downloads(*_downloads(cluster, 3, 4 * 256))
        assert tr.run(max_dispatches=1, idle_timeout=0.1) == 1
        assert int(tr.state.step) == step_before + 4
        assert compiles_before == 1, "steady-state dispatch recompiled"
        assert tr._dispatch_fn._cache_size() == compiles_before, (
            "snapshot swap recompiled"
        )

    def test_refresh_with_no_new_topology_keeps_old_graph(self):
        """The bootstrap feed belongs to snapshot 0 — with no probes since,
        a refresh keeps serving the old graph instead of paying a rebuild
        for an identical one."""
        cluster = _mk_cluster()
        tr = _mk_trainer(cluster, topo_window=100)
        digest = tr.snapshot_digest()
        assert tr.refresh_snapshot() is None
        assert tr.snapshot_digest() == digest
        assert tr.snapshot_idx == 0
        # New probes arrive → the next refresh swaps.
        tr.feed_topology(*_topo(cluster, seed=77))
        assert tr.refresh_snapshot() is not None
        assert tr.snapshot_idx == 1

    def test_topology_window_trims_oldest(self):
        cluster = _mk_cluster()
        tr = _mk_trainer(cluster, topo_window=500)
        for seed in range(5):
            src, dst, rtt = _topo(cluster, seed=seed)
            tr.feed_topology(src, dst, rtt)
        src, dst, rtt = tr._drain_window()
        assert len(src) <= 500
        # The window holds the MOST RECENT edges (tail of the last feed).
        last_src, _, _ = _topo(cluster, seed=4)
        np.testing.assert_array_equal(src[-len(last_src):], last_src[-len(src):])


class TestResumeAcrossRefresh:
    def test_byte_identical_resume_across_refresh_boundary(self, tmp_path):
        """Kill after a swap, resume, continue → same bytes as the
        uninterrupted run (the r3 soak's proof, now with a mid-stream
        graph swap in the window)."""
        def feed_all(tr, cluster):
            # Deterministic two-stream schedule: topology for snapshot 1
            # arrives before dispatch 2's refresh.
            src, dst, rtt = _topo(cluster, seed=100)
            tr.feed_topology(src, dst, rtt)
            for d in range(4):
                tr.feed_downloads(*_downloads(cluster, 50 + d, 4 * 256))

        # Run A: uninterrupted, refresh every 2 dispatches.
        ca = _mk_cluster()
        a = _mk_trainer(ca, tmp_path / "a", refresh_every=2)
        feed_all(a, ca)
        assert a.run(max_dispatches=4, idle_timeout=0.1) == 4
        assert a.snapshot_idx >= 1

        # Run B: same stream, checkpoint at dispatch 3 (PAST the refresh
        # at 2), then a fresh process resumes and finishes.
        cb = _mk_cluster()
        b = _mk_trainer(cb, tmp_path / "b", refresh_every=2)
        feed_all(b, cb)
        assert b.run(max_dispatches=3, idle_timeout=0.1) == 3
        assert b.snapshot_idx >= 1  # the boundary is behind the checkpoint
        b.checkpoint()
        del b

        cc = _mk_cluster()
        c = _mk_trainer(cc, tmp_path / "b", refresh_every=2)
        assert c.resume()
        assert c.dispatch == 3 and c.snapshot_idx >= 1
        # Rebuilt snapshot must equal run A's post-refresh snapshot.
        assert c.snapshot_digest() == a.snapshot_digest()
        c.feed_downloads(*_downloads(cc, 53, 4 * 256))
        assert c.run(max_dispatches=1, idle_timeout=0.1) == 1
        assert _state_hash(c) == _state_hash(a)

    def test_resume_without_checkpoint_returns_false(self, tmp_path):
        cluster = _mk_cluster()
        tr = _mk_trainer(cluster, tmp_path / "none")
        assert not tr.resume()


class TestWireIngest:
    """The Train stream feeds the online trainer DIRECTLY (VERDICT r3's
    configs[5] wire story): chunks decode incrementally mid-stream and
    rows reach the train loop before EOF."""

    def test_streaming_decoder_matches_reader_at_awkward_splits(self, tmp_path):
        from dragonfly2_tpu.records.columnar import (
            ColumnarReader,
            ColumnarWriter,
            StreamingRowDecoder,
        )

        path = str(tmp_path / "s.dfc")
        rng = np.random.default_rng(0)
        want = rng.random((257, 7)).astype(np.float32)
        with ColumnarWriter(path, tuple(f"c{i}" for i in range(7))) as w:
            w.append(want)
        blob = open(path, "rb").read()
        # Splits that straddle the magic, the header, and row boundaries.
        dec = StreamingRowDecoder()
        pos = 0
        parts = []
        for cut in (2, 5, 11, 64, 300, 301):
            parts.append(blob[pos:cut])
            pos = cut
        parts.append(blob[pos:])
        chunks = [dec.feed(p) for p in parts]
        rows = np.concatenate([c for c in chunks if c.size], axis=0)
        np.testing.assert_array_equal(rows, ColumnarReader(path).to_array())
        assert dec.rows_decoded == 257

        # Fixed-size chunker whose boundary NEVER aligns with rows (the
        # gRPC framing shape): every split row reassembles exactly once.
        dec2 = StreamingRowDecoder()
        got2 = [
            dec2.feed(blob[i : i + 1000]) for i in range(0, len(blob), 1000)
        ]
        rows2 = np.concatenate([c for c in got2 if c.size], axis=0)
        np.testing.assert_array_equal(rows2, ColumnarReader(path).to_array())

    def test_train_stream_feeds_online_trainer(self, tmp_path):
        """Wire e2e: shards stream over the real Train HTTP transport;
        the online trainer consumes edges and refreshes its graph from
        the WIRE-fed topology."""
        from dragonfly2_tpu.records.columnar import ColumnarWriter
        from dragonfly2_tpu.records.features import DOWNLOAD_COLUMNS, TOPO_COLUMNS
        from dragonfly2_tpu.rpc.trainer_transport import (
            RemoteTrainer,
            TrainerHTTPServer,
        )
        from dragonfly2_tpu.trainer.service import TrainerService

        cluster = _mk_cluster()
        tr = _mk_trainer(cluster)
        adapter = tr.make_wire_adapter()
        service = TrainerService(
            data_dir=str(tmp_path / "stage"), online_sink=adapter
        )
        # Ingest-only here: EOF batch retraining has its own tests.
        service._run_training = lambda run, session: run.done.set()

        # Download shard: bucket-space rows from the synthetic swarm.
        dl = cluster.generate_feature_rows(4 * 256 * 3, seed=5)
        dl_path = str(tmp_path / "dl.dfc")
        with ColumnarWriter(dl_path, DOWNLOAD_COLUMNS) as w:
            w.append(dl)
        # Topology shard: probe edges in the SAME bucket space.
        buckets = cluster._bucket_table()
        src, dst, rtt = _topo(cluster, seed=8)
        topo = np.zeros((len(src), len(TOPO_COLUMNS)), np.float32)
        topo[:, 0] = buckets[src]
        topo[:, 1] = buckets[dst]
        topo[:, 2] = rtt
        topo_path = str(tmp_path / "topo.dfc")
        with ColumnarWriter(topo_path, TOPO_COLUMNS) as w:
            w.append(topo)

        server = TrainerHTTPServer(service)
        server.serve()
        try:
            client = RemoteTrainer(server.url)
            session = client.open_train_stream(
                ip="10.0.0.7", hostname="wire-online", scheduler_id="s"
            )
            session.send_download_shard(dl_path)
            session.send_network_topology_shard(topo_path)
        finally:
            server.stop()

        assert adapter.overflow_edges == 0
        # Edges reached the trainer off the WIRE: a dispatch runs...
        assert tr.run(max_dispatches=2, idle_timeout=0.5) == 2
        assert tr.records_seen == 2 * 4 * 256
        # ...and the wire-fed topology builds the NEXT snapshot.
        digest = tr.snapshot_digest()
        assert tr.refresh_snapshot() is not None
        assert tr.snapshot_digest() != digest


    def test_reconnect_resend_feeds_rows_once(self, tmp_path):
        """A client that reconnects and resends a shard (fresh session,
        empty chunk_seq) must not double-feed the sink — the service
        dedupes on a per-dataset row high-water mark."""
        from dragonfly2_tpu.records.columnar import ColumnarWriter
        from dragonfly2_tpu.records.features import DOWNLOAD_COLUMNS
        from dragonfly2_tpu.trainer.service import TrainerService

        class Sink:
            def __init__(self):
                self.download_rows = 0
                self.topology_rows = 0

            def feed_download_rows(self, rows):
                self.download_rows += len(rows)

            def feed_topology_rows(self, rows):
                self.topology_rows += len(rows)

        sink = Sink()
        service = TrainerService(
            data_dir=str(tmp_path / "stage"), online_sink=sink
        )
        path = str(tmp_path / "d.dfc")
        with ColumnarWriter(path, DOWNLOAD_COLUMNS) as w:
            w.append(np.random.default_rng(0).random(
                (100, len(DOWNLOAD_COLUMNS))).astype(np.float32))
        blob = open(path, "rb").read()

        s1 = service.open_train_stream(ip="1.2.3.4", hostname="h", scheduler_id="s")
        service.receive_shard_bytes(s1, "download", "d.dfc", blob, seq=0)
        assert sink.download_rows == 100
        # Reconnect: fresh session, SAME shard resent from scratch.
        s2 = service.open_train_stream(ip="1.2.3.4", hostname="h", scheduler_id="s")
        service.receive_shard_bytes(s2, "download", "d.dfc", blob, seq=0)
        assert sink.download_rows == 100  # not 200
        # A LONGER resend (shard grew) feeds only the new tail.
        with ColumnarWriter(str(tmp_path / "d2.dfc"), DOWNLOAD_COLUMNS) as w:
            w.append(np.random.default_rng(0).random(
                (130, len(DOWNLOAD_COLUMNS))).astype(np.float32))
        blob2 = open(str(tmp_path / "d2.dfc"), "rb").read()
        s3 = service.open_train_stream(ip="1.2.3.4", hostname="h", scheduler_id="s")
        service.receive_shard_bytes(s3, "download", "d.dfc", blob2, seq=0)
        assert sink.download_rows == 130

    def test_online_mode_tolerates_reference_csv(self, tmp_path):
        """A legacy CSV shard on the wire (the compat path) must not
        crash online mode — it skips online decode and stages normally."""
        from dragonfly2_tpu.trainer.service import TrainerService

        class Sink:
            def feed_download_rows(self, rows):
                raise AssertionError("CSV must not online-decode")

            feed_topology_rows = feed_download_rows

        service = TrainerService(
            data_dir=str(tmp_path / "stage"), online_sink=Sink()
        )
        s = service.open_train_stream(ip="1.2.3.4", hostname="h", scheduler_id="s")
        service.receive_shard_bytes(
            s, "download", "legacy.csv", b"a,b,c\n1,2,3\n", seq=0
        )
        assert len(s.download_shards) == 1  # staged for batch conversion


class TestOnlineMeshMode:
    """config[4]×[5]: the ONLINE trainer on a (data, model) mesh — node
    tables AND the snapshot precompute sharded over the model axis."""

    def _mk(self, cluster, tmp_path=None, **kw):
        from dragonfly2_tpu.parallel.mesh import MeshSpec, create_mesh

        mesh = create_mesh(MeshSpec(data=4, model=2))
        return _mk_trainer(
            cluster, tmp_path, mesh=mesh, node_sharding="model", **kw
        )

    def test_matches_replicated_and_swaps_without_recompile(self, tmp_path):
        import jax

        cluster_a = _mk_cluster()
        repl = _mk_trainer(cluster_a)
        cluster_b = _mk_cluster()
        mp = self._mk(cluster_b)

        for tr, cl in ((repl, cluster_a), (mp, cluster_b)):
            tr.feed_downloads(*_downloads(cl, 7, 4 * 256 * 2))
            assert tr.run(max_dispatches=2, idle_timeout=0.1) == 2
        # Same stream, same seeds: the sharded program computes the same
        # training result to float tolerance.
        v = _downloads(cluster_a, 99, 1024)
        assert abs(repl.eval_mae(*v) - mp.eval_mae(*v)) < 5e-3
        # The hop tables live SHARDED over the model axis.
        from jax.sharding import PartitionSpec as P

        assert mp.hop_feats.sharding.spec == P("model")

        # Snapshot swap on the mesh: sharded precompute re-runs, the
        # compiled dispatch is reused.
        compiles = mp._dispatch_fn._cache_size()
        cluster_b.drift(np.random.default_rng(3))
        mp.set_node_features(cluster_b._host_feature_matrix())
        mp.feed_topology(*_topo(cluster_b, seed=31))
        assert mp.refresh_snapshot() is not None
        mp.feed_downloads(*_downloads(cluster_b, 8, 4 * 256))
        assert mp.run(max_dispatches=1, idle_timeout=0.1) == 1
        assert mp._dispatch_fn._cache_size() == compiles

    def test_mesh_resume_across_refresh(self, tmp_path):
        def feed(tr, cl):
            tr.feed_topology(*_topo(cl, seed=100))
            for d in range(3):
                tr.feed_downloads(*_downloads(cl, 60 + d, 4 * 256))

        ca = _mk_cluster()
        a = self._mk(ca, tmp_path / "a", refresh_every=2)
        feed(a, ca)
        assert a.run(max_dispatches=3, idle_timeout=0.1) == 3
        assert a.snapshot_idx >= 1

        cb = _mk_cluster()
        b = self._mk(cb, tmp_path / "b", refresh_every=2)
        feed(b, cb)
        assert b.run(max_dispatches=2, idle_timeout=0.1) == 2
        b.checkpoint()
        del b
        cc = _mk_cluster()
        c = self._mk(cc, tmp_path / "b", refresh_every=2)
        assert c.resume()
        assert c.dispatch == 2 and c.snapshot_idx >= 1
        c.feed_downloads(*_downloads(cc, 62, 4 * 256))
        assert c.run(max_dispatches=1, idle_timeout=0.1) == 1
        assert _state_hash(c) == _state_hash(a)

    def test_bad_configs_rejected(self):
        from dragonfly2_tpu.parallel.mesh import MeshSpec, create_mesh

        cluster = _mk_cluster()
        with pytest.raises(ValueError, match="needs a mesh"):
            _mk_trainer(cluster, node_sharding="model")
        with pytest.raises(ValueError, match="unknown node_sharding"):
            _mk_trainer(cluster, node_sharding="bogus")
        mesh = create_mesh(MeshSpec(data=4, model=2))
        with pytest.raises(ValueError, match="not divisible"):
            _mk_trainer(
                cluster, mesh=mesh, node_sharding="model", batch_size=254
            )


class TestOnlineQuality:
    def test_refresh_tracks_drift_better_than_stale(self):
        """After load drift, FRESH hop features beat STALE ones on new
        downloads — the evidence that the mid-training refresh loop
        matters (configs[5]'s defining property)."""
        cluster = _mk_cluster()
        tr = _mk_trainer(cluster)
        # Train a while on the initial graph.
        for d in range(6):
            tr.feed_downloads(*_downloads(cluster, 200 + d, 4 * 256))
        assert tr.run(max_dispatches=6, idle_timeout=0.1) == 6

        # Drift the cluster hard (several epochs of load churn).
        rng = np.random.default_rng(42)
        for _ in range(5):
            cluster.drift(rng)

        v_es, v_ed, v_y = _downloads(cluster, 999, 2048)
        stale = tr.eval_mae(v_es, v_ed, v_y)

        tr.set_node_features(cluster._host_feature_matrix())
        tr.feed_topology(*_topo(cluster, seed=300))
        tr.refresh_snapshot()
        # Adapt briefly on post-drift downloads, then eval fresh.
        for d in range(4):
            tr.feed_downloads(*_downloads(cluster, 400 + d, 4 * 256))
        tr.run(max_dispatches=4, idle_timeout=0.1)
        fresh = tr.eval_mae(v_es, v_ed, v_y)
        assert fresh < stale, (fresh, stale)


class TestNodeLifecycle:
    """node_ttl > 0: TTL eviction + dense-id recycling in the wire
    adapter (reference host GC semantics, scheduler/config/config.go:
    176-197) — churn past capacity must not permanently freeze the
    trainer on the early-arrivals subgraph."""

    @staticmethod
    def _rows(src_b, dst_b, rng):
        from dragonfly2_tpu.records.features import DOWNLOAD_COLUMNS

        n = len(src_b)
        rows = rng.random((n, len(DOWNLOAD_COLUMNS))).astype(np.float32)
        rows[:, 0] = src_b
        rows[:, 1] = dst_b
        rows[:, -1] = np.log1p(rng.random(n).astype(np.float32) * 50.0)
        return rows

    @staticmethod
    def _embedding_leaves(tree):
        import jax

        out = []

        def f(path, leaf):
            if any(getattr(p, "key", None) == "embedding" for p in path):
                out.append(np.asarray(leaf))
            return leaf

        jax.tree_util.tree_map_with_path(f, tree)
        return out

    def test_churn_3x_capacity_recycles_without_permanent_drops(self):
        cluster = _mk_cluster()
        tr = _mk_trainer(cluster, node_ttl=10.0, native_ingest=False)
        ad = tr.make_wire_adapter()
        t = {"now": 0.0}
        ad.clock = lambda: t["now"]
        rng = np.random.default_rng(0)

        def phase_buckets(phase):
            return np.arange(N_NODES, dtype=np.int64) + 10_000 * (phase + 1)

        def feed_phase(phase):
            b = phase_buckets(phase)
            for _ in range(3):
                ad.feed_download_rows(self._rows(b, np.roll(b, 1), rng))
                t["now"] += 1.0

        # Phase 0 fills the table exactly; train so embeddings/moments
        # are live (recycling must provably clear them later).
        feed_phase(0)
        assert ad._next_id == N_NODES and ad.overflow_edges == 0
        tr.feed_downloads(*_downloads(cluster, 5, 4 * 256 * 2))
        assert tr.run(max_dispatches=2, idle_timeout=0.1) == 2

        # Full table + nothing expired: the drop is transient, counted
        # on the adapter AND in the prometheus registry.
        from dragonfly2_tpu.trainer.metrics import ONLINE_OVERFLOW_EDGES

        metric_before = ONLINE_OVERFLOW_EDGES.value()
        extra = np.array([999_999], dtype=np.int64)
        ad.feed_download_rows(self._rows(extra, phase_buckets(0)[:1], rng))
        assert ad.overflow_edges == 1
        assert ONLINE_OVERFLOW_EDGES.value() == metric_before + 1

        # Keep two phase-0 hosts warm via the TOPOLOGY stream at t=20...
        t["now"] = 20.0
        ad.feed_topology_rows(
            np.array([[10_000, 10_001, 0.01]], dtype=np.float32)
        )
        survivors = [int(ad._id_table[10_000]), int(ad._id_table[10_001])]

        # ...then a new host wave at t=25: everything else expired.
        t["now"] = 25.0
        b1 = phase_buckets(1)[: N_NODES - 2]
        ad.feed_download_rows(self._rows(b1, np.roll(b1, 1), rng))
        assert ad.evicted_nodes == N_NODES - 2
        assert ad.overflow_edges == 1  # eviction freed capacity: no new drops
        assert all(int(ad._id_table[b]) >= 0 for b in b1)

        # Row resets: evicted embedding rows AND moments zero; the two
        # survivors keep their learned state.
        n_reset = tr.apply_pending_recycles()
        assert n_reset == N_NODES - 2 and tr.nodes_recycled == N_NODES - 2
        evicted_mask = np.ones(N_NODES, bool)
        evicted_mask[survivors] = False
        param_leaves = self._embedding_leaves(tr.state.params)
        moment_leaves = self._embedding_leaves(tr.state.opt_state)
        assert param_leaves and moment_leaves
        for leaf in param_leaves + moment_leaves:
            assert not leaf[evicted_mask].any(), "recycled row not reset"
        assert all(
            np.abs(leaf[survivors]).sum() > 0 for leaf in param_leaves
        ), "survivor embedding clobbered"

        # The host dropped at capacity returns once ids free again —
        # drops are transient, never permanent.
        t["now"] = 40.0
        ad.feed_download_rows(self._rows(extra, phase_buckets(1)[:1], rng))
        assert int(ad._id_table[999_999]) >= 0
        assert ad.evicted_nodes >= N_NODES  # second wave ran

        # Training continues across recycling: loss/eval finite.
        tr.apply_pending_recycles()
        tr.feed_downloads(*_downloads(cluster, 6, 4 * 256))
        assert tr.run(max_dispatches=1, idle_timeout=0.1) == 1
        v = tr.eval_mae(*_downloads(cluster, 7, 512))
        assert np.isfinite(v)

    def test_ttl_zero_keeps_frozen_first_come_mapping(self):
        """The default stays byte-deterministic: no eviction, overflow
        drops are permanent, the original mapping is never disturbed."""
        cluster = _mk_cluster()
        tr = _mk_trainer(cluster, native_ingest=False)  # node_ttl defaults to 0
        ad = tr.make_wire_adapter()
        t = {"now": 0.0}
        ad.clock = lambda: t["now"]
        rng = np.random.default_rng(1)
        b0 = np.arange(N_NODES, dtype=np.int64) + 10_000
        ad.feed_download_rows(self._rows(b0, np.roll(b0, 1), rng))
        mapping = ad._id_table[b0].copy()
        t["now"] = 1e9  # far beyond any ttl
        extra = np.array([999_999], dtype=np.int64)
        ad.feed_download_rows(self._rows(extra, b0[:1], rng))
        assert int(ad._id_table[999_999]) == -1  # permanent drop
        assert ad.evicted_nodes == 0
        np.testing.assert_array_equal(ad._id_table[b0], mapping)
        assert tr.apply_pending_recycles() == 0

    def test_dropped_host_alone_reclaims_expired_capacity(self):
        """A -1-memoized host must itself trigger eviction when it
        returns after capacity expired — transience cannot depend on a
        brand-new bucket arriving to kick the slow path."""
        cluster = _mk_cluster()
        tr = _mk_trainer(cluster, node_ttl=10.0, native_ingest=False)
        ad = tr.make_wire_adapter()
        t = {"now": 0.0}
        ad.clock = lambda: t["now"]
        rng = np.random.default_rng(2)
        b0 = np.arange(N_NODES, dtype=np.int64) + 10_000
        ad.feed_download_rows(self._rows(b0, np.roll(b0, 1), rng))
        x = np.array([777_777], dtype=np.int64)
        ad.feed_download_rows(self._rows(x, b0[:1], rng))
        assert int(ad._id_table[777_777]) == -1  # dropped & memoized
        t["now"] = 30.0  # the original hosts all expire
        ad.feed_download_rows(self._rows(x, b0[:1], rng))
        assert int(ad._id_table[777_777]) >= 0
        assert ad.evicted_nodes > 0

    def test_returning_host_in_eviction_chunk_is_touched_not_evicted(self):
        """A long-silent host appearing in the SAME chunk as the new
        host that triggers eviction is alive right now: it keeps its id,
        its edges train, and its embedding row survives."""
        cluster = _mk_cluster()
        tr = _mk_trainer(cluster, node_ttl=10.0, native_ingest=False)
        ad = tr.make_wire_adapter()
        t = {"now": 0.0}
        ad.clock = lambda: t["now"]
        rng = np.random.default_rng(3)
        b0 = np.arange(N_NODES, dtype=np.int64) + 10_000
        ad.feed_download_rows(self._rows(b0, np.roll(b0, 1), rng))
        h_id = int(ad._id_table[10_000])
        t["now"] = 30.0  # everyone silent past ttl
        new = np.array([888_888], dtype=np.int64)
        before = ad.overflow_edges
        ad.feed_download_rows(self._rows(new, b0[:1], rng))
        assert int(ad._id_table[10_000]) == h_id, "live host lost its id"
        assert int(ad._id_table[888_888]) >= 0
        assert ad.overflow_edges == before, "live host's edge was dropped"
        tr.apply_pending_recycles()
        for leaf in self._embedding_leaves(tr.state.params):
            assert np.abs(leaf[h_id]).sum() > 0, "live host row reset"

    def test_adapter_mapping_survives_checkpoint_resume(self, tmp_path):
        """ttl-mode id mappings are clock-driven, hence non-replayable:
        they ride in the checkpoint so a restarted trainer keeps every
        host on the dense id whose embedding learned it."""
        cluster = _mk_cluster()
        tr = _mk_trainer(cluster, tmp_path, node_ttl=10.0, native_ingest=False)
        ad = tr.make_wire_adapter()
        t = {"now": 1000.0}
        ad.clock = lambda: t["now"]
        rng = np.random.default_rng(4)
        b0 = np.arange(N_NODES, dtype=np.int64) + 10_000
        ad.feed_download_rows(self._rows(b0, np.roll(b0, 1), rng))
        mapping = ad._id_table[b0].copy()
        feat_cnt = ad._feat_cnt.copy()
        tr.checkpoint()

        tr2 = _mk_trainer(cluster, tmp_path, node_ttl=10.0, native_ingest=False)
        assert tr2.resume()
        ad2 = tr2.make_wire_adapter()
        ad2.clock = lambda: t["now"] + 1.0
        np.testing.assert_array_equal(ad2._id_table[b0], mapping)
        assert ad2._next_id == N_NODES
        np.testing.assert_array_equal(ad2._feat_cnt, feat_cnt)
        # Hosts keep their ids on their next appearance after restart.
        ad2.feed_download_rows(self._rows(b0[:4], b0[4:8], rng))
        np.testing.assert_array_equal(ad2._id_table[b0], mapping)
