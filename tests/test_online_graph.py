"""Online graph trainer (BASELINE configs[5]): two-stream ingest,
mid-training snapshot refresh, byte-identical resume across a refresh
boundary (trainer/online_graph.py; reference stream demux
trainer/service/service_v1.go:128-143)."""

import numpy as np
import pytest

from dragonfly2_tpu.models.hop import HopConfig
from dragonfly2_tpu.records.synthetic import SyntheticCluster
from dragonfly2_tpu.trainer.online_graph import (
    OnlineGraphConfig,
    OnlineGraphTrainer,
    state_hash,
)
from dragonfly2_tpu.trainer.train import TrainConfig

N_NODES = 128


def _mk_cluster(seed=0):
    return SyntheticCluster(num_hosts=N_NODES, seed=seed)


def _topo(cluster, seed):
    rng = np.random.default_rng(seed)
    n = N_NODES * 8
    src = rng.integers(0, N_NODES, n)
    dst = rng.integers(0, N_NODES, n)
    keep = src != dst
    src, dst = src[keep], dst[keep]
    # Deterministic rtt (no shared-rng draw) for replayable streams.
    return src, dst, (cluster._rtt_vec(src, dst, noise=False) / 1e9).astype(
        np.float32
    )


def _downloads(cluster, seed, n):
    rng = np.random.default_rng(seed)
    es = rng.integers(0, N_NODES, n).astype(np.int32)
    ed = (es + rng.integers(1, N_NODES, n).astype(np.int32)) % N_NODES
    y = np.log1p(cluster._bandwidth_vec(es, ed, rng=rng)).astype(np.float32)
    return es, ed, y


def _mk_trainer(cluster, tmp_path=None, **cfg_kw):
    cfg = OnlineGraphConfig(
        num_nodes=N_NODES,
        max_neighbors=8,
        batch_size=256,
        super_steps=4,
        queue_capacity=16,  # tests feed the whole stream before run()
        model=HopConfig(hidden=16, out_dim=8, node_embed_dim=4, dropout=0.1),
        train=TrainConfig(warmup_steps=2),
        total_steps_hint=1000,
        **cfg_kw,
    )
    src, dst, rtt = _topo(cluster, seed=1)
    return OnlineGraphTrainer(
        cfg,
        node_feats=cluster._host_feature_matrix(),
        topo_src=src, topo_dst=dst, topo_rtt=rtt,
        checkpoint_dir=str(tmp_path) if tmp_path else None,
    )


def _state_hash(trainer) -> str:
    return state_hash(trainer.state)


class TestSnapshotRefresh:
    def test_swap_changes_graph_not_optimizer(self):
        import jax

        cluster = _mk_cluster()
        tr = _mk_trainer(cluster)
        es, ed, y = _downloads(cluster, 2, 4 * 256 * 2)
        tr.feed_downloads(es, ed, y)
        assert tr.run(max_dispatches=2, idle_timeout=0.1) == 2
        compiles_before = tr._dispatch_fn._cache_size()
        step_before = int(tr.state.step)
        params_before = jax.tree_util.tree_map(np.asarray, tr.state.params)
        digest_before = tr.snapshot_digest()

        # New topology (drifted load) → refresh swaps the hop tables only.
        cluster.drift(np.random.default_rng(7))
        tr.set_node_features(cluster._host_feature_matrix())
        src, dst, rtt = _topo(cluster, seed=9)
        tr.feed_topology(src, dst, rtt)
        assert tr.refresh_snapshot() is not None
        assert tr.snapshot_digest() != digest_before
        assert tr.snapshot_idx == 1
        assert int(tr.state.step) == step_before  # optimizer untouched
        for a, b in zip(
            jax.tree_util.tree_leaves(params_before),
            jax.tree_util.tree_leaves(tr.state.params),
        ):
            np.testing.assert_array_equal(a, np.asarray(b))

        # Training continues on the new snapshot with the SAME compiled
        # program (hop tables are arguments, shapes static).
        tr.feed_downloads(*_downloads(cluster, 3, 4 * 256))
        assert tr.run(max_dispatches=1, idle_timeout=0.1) == 1
        assert int(tr.state.step) == step_before + 4
        assert compiles_before == 1, "steady-state dispatch recompiled"
        assert tr._dispatch_fn._cache_size() == compiles_before, (
            "snapshot swap recompiled"
        )

    def test_refresh_with_no_new_topology_keeps_old_graph(self):
        """The bootstrap feed belongs to snapshot 0 — with no probes since,
        a refresh keeps serving the old graph instead of paying a rebuild
        for an identical one."""
        cluster = _mk_cluster()
        tr = _mk_trainer(cluster, topo_window=100)
        digest = tr.snapshot_digest()
        assert tr.refresh_snapshot() is None
        assert tr.snapshot_digest() == digest
        assert tr.snapshot_idx == 0
        # New probes arrive → the next refresh swaps.
        tr.feed_topology(*_topo(cluster, seed=77))
        assert tr.refresh_snapshot() is not None
        assert tr.snapshot_idx == 1

    def test_topology_window_trims_oldest(self):
        cluster = _mk_cluster()
        tr = _mk_trainer(cluster, topo_window=500)
        for seed in range(5):
            src, dst, rtt = _topo(cluster, seed=seed)
            tr.feed_topology(src, dst, rtt)
        src, dst, rtt = tr._drain_window()
        assert len(src) <= 500
        # The window holds the MOST RECENT edges (tail of the last feed).
        last_src, _, _ = _topo(cluster, seed=4)
        np.testing.assert_array_equal(src[-len(last_src):], last_src[-len(src):])


class TestResumeAcrossRefresh:
    def test_byte_identical_resume_across_refresh_boundary(self, tmp_path):
        """Kill after a swap, resume, continue → same bytes as the
        uninterrupted run (the r3 soak's proof, now with a mid-stream
        graph swap in the window)."""
        def feed_all(tr, cluster):
            # Deterministic two-stream schedule: topology for snapshot 1
            # arrives before dispatch 2's refresh.
            src, dst, rtt = _topo(cluster, seed=100)
            tr.feed_topology(src, dst, rtt)
            for d in range(4):
                tr.feed_downloads(*_downloads(cluster, 50 + d, 4 * 256))

        # Run A: uninterrupted, refresh every 2 dispatches.
        ca = _mk_cluster()
        a = _mk_trainer(ca, tmp_path / "a", refresh_every=2)
        feed_all(a, ca)
        assert a.run(max_dispatches=4, idle_timeout=0.1) == 4
        assert a.snapshot_idx >= 1

        # Run B: same stream, checkpoint at dispatch 3 (PAST the refresh
        # at 2), then a fresh process resumes and finishes.
        cb = _mk_cluster()
        b = _mk_trainer(cb, tmp_path / "b", refresh_every=2)
        feed_all(b, cb)
        assert b.run(max_dispatches=3, idle_timeout=0.1) == 3
        assert b.snapshot_idx >= 1  # the boundary is behind the checkpoint
        b.checkpoint()
        del b

        cc = _mk_cluster()
        c = _mk_trainer(cc, tmp_path / "b", refresh_every=2)
        assert c.resume()
        assert c.dispatch == 3 and c.snapshot_idx >= 1
        # Rebuilt snapshot must equal run A's post-refresh snapshot.
        assert c.snapshot_digest() == a.snapshot_digest()
        c.feed_downloads(*_downloads(cc, 53, 4 * 256))
        assert c.run(max_dispatches=1, idle_timeout=0.1) == 1
        assert _state_hash(c) == _state_hash(a)

    def test_resume_without_checkpoint_returns_false(self, tmp_path):
        cluster = _mk_cluster()
        tr = _mk_trainer(cluster, tmp_path / "none")
        assert not tr.resume()


class TestOnlineQuality:
    def test_refresh_tracks_drift_better_than_stale(self):
        """After load drift, FRESH hop features beat STALE ones on new
        downloads — the evidence that the mid-training refresh loop
        matters (configs[5]'s defining property)."""
        cluster = _mk_cluster()
        tr = _mk_trainer(cluster)
        # Train a while on the initial graph.
        for d in range(6):
            tr.feed_downloads(*_downloads(cluster, 200 + d, 4 * 256))
        assert tr.run(max_dispatches=6, idle_timeout=0.1) == 6

        # Drift the cluster hard (several epochs of load churn).
        rng = np.random.default_rng(42)
        for _ in range(5):
            cluster.drift(rng)

        v_es, v_ed, v_y = _downloads(cluster, 999, 2048)
        stale = tr.eval_mae(v_es, v_ed, v_y)

        tr.set_node_features(cluster._host_feature_matrix())
        tr.feed_topology(*_topo(cluster, seed=300))
        tr.refresh_snapshot()
        # Adapt briefly on post-drift downloads, then eval fresh.
        for d in range(4):
            tr.feed_downloads(*_downloads(cluster, 400 + d, 4 * 256))
        tr.run(max_dispatches=4, idle_timeout=0.1)
        fresh = tr.eval_mae(v_es, v_ed, v_y)
        assert fresh < stale, (fresh, stale)
