"""Deploy artifacts (VERDICT r2 next-#7): compose topology sanity and the
one-command process-cluster e2e (deploy/run_local.py runs the SAME
e2e_loop.py the compose `e2e` service runs in containers)."""

import os
import subprocess
import sys

import yaml

DEPLOY = os.path.join(os.path.dirname(os.path.dirname(__file__)), "deploy")


class TestComposeArtifacts:
    def test_compose_parses_and_covers_all_services(self):
        with open(os.path.join(DEPLOY, "docker-compose.yaml")) as f:
            compose = yaml.safe_load(f)
        services = compose["services"]
        assert set(services) == {
            "manager", "scheduler", "trainer", "seed",
            "daemon-a", "daemon-b", "e2e",
        }
        # Every service runs the shared multi-entry image and a real CLI.
        for name, svc in services.items():
            if name == "e2e":
                continue
            module = svc["command"][0]
            assert module.startswith("dragonfly2_tpu.cli."), (name, module)
            __import__(module)  # the entrypoint must actually exist

    def test_service_configs_load_with_real_schemas(self):
        from dragonfly2_tpu.config import (
            DaemonConfig,
            ManagerConfig,
            SchedulerConfigFile,
            TrainerConfigFile,
            load_config,
        )

        cfgdir = os.path.join(DEPLOY, "config")
        mapping = {
            "manager.yaml": ManagerConfig,
            "scheduler.yaml": SchedulerConfigFile,
            "trainer.yaml": TrainerConfigFile,
            "seed.yaml": DaemonConfig,
            "daemon.yaml": DaemonConfig,
        }
        for name, schema in mapping.items():
            cfg = load_config(schema, os.path.join(cfgdir, name))
            cfg.validate()
        sched = load_config(
            SchedulerConfigFile, os.path.join(cfgdir, "scheduler.yaml")
        )
        assert sched.manager_addr == "http://manager:65003"
        assert sched.trainer.enable and "trainer" in sched.trainer.addr

    def test_dockerfile_builds_native_and_sets_entrypoint(self):
        with open(os.path.join(DEPLOY, "docker", "Dockerfile")) as f:
            content = f.read()
        assert "make -C dragonfly2_tpu/native" in content
        assert 'ENTRYPOINT ["python", "-m"]' in content


class TestClusterE2E:
    def test_run_local_cluster_loop(self):
        """One command: the full cluster comes up (manager + scheduler +
        trainer + seed + 2 daemons, real processes, real wires) and the
        composed e2e loop passes end to end."""
        r = subprocess.run(
            [sys.executable, os.path.join(DEPLOY, "run_local.py")],
            capture_output=True, text=True, timeout=420,
            env={**os.environ, "PYTHONPATH": os.getcwd()},
        )
        assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
        assert "ALL STAGES PASSED" in r.stdout

    def test_run_local_cluster_loop_mtls(self):
        """The SAME composed topology with auto-issued mTLS on: every
        daemon bootstraps its identity from the manager's cluster CA at
        boot (POST /api/v1/certs:issue) and the piece plane moves bytes
        over mutual TLS end to end (VERDICT r3 next-#5 done-condition)."""
        r = subprocess.run(
            [sys.executable, os.path.join(DEPLOY, "run_local.py"), "--mtls"],
            capture_output=True, text=True, timeout=420,
            env={**os.environ, "PYTHONPATH": os.getcwd()},
        )
        assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
        assert "ALL STAGES PASSED" in r.stdout
