"""Deploy artifacts (VERDICT r2 next-#7): compose topology sanity and the
one-command process-cluster e2e (deploy/run_local.py runs the SAME
e2e_loop.py the compose `e2e` service runs in containers)."""

import os
import subprocess
import sys

import pytest
import yaml

try:
    import cryptography  # noqa: F401

    _HAS_CRYPTO = True
except ImportError:
    _HAS_CRYPTO = False

DEPLOY = os.path.join(os.path.dirname(os.path.dirname(__file__)), "deploy")


class TestComposeArtifacts:
    def test_compose_parses_and_covers_all_services(self):
        with open(os.path.join(DEPLOY, "docker-compose.yaml")) as f:
            compose = yaml.safe_load(f)
        services = compose["services"]
        assert set(services) == {
            "manager", "scheduler", "trainer", "seed",
            "daemon-a", "daemon-b", "e2e",
        }
        # Every service runs the shared multi-entry image and a real CLI.
        for name, svc in services.items():
            if name == "e2e":
                continue
            module = svc["command"][0]
            assert module.startswith("dragonfly2_tpu.cli."), (name, module)
            __import__(module)  # the entrypoint must actually exist

    def test_service_configs_load_with_real_schemas(self):
        from dragonfly2_tpu.config import (
            DaemonConfig,
            ManagerConfig,
            SchedulerConfigFile,
            TrainerConfigFile,
            load_config,
        )

        cfgdir = os.path.join(DEPLOY, "config")
        mapping = {
            "manager.yaml": ManagerConfig,
            "scheduler.yaml": SchedulerConfigFile,
            "trainer.yaml": TrainerConfigFile,
            "seed.yaml": DaemonConfig,
            "daemon.yaml": DaemonConfig,
        }
        for name, schema in mapping.items():
            cfg = load_config(schema, os.path.join(cfgdir, name))
            cfg.validate()
        sched = load_config(
            SchedulerConfigFile, os.path.join(cfgdir, "scheduler.yaml")
        )
        assert sched.manager_addr == "http://manager:65003"
        assert sched.trainer.enable and "trainer" in sched.trainer.addr

    def test_dockerfile_builds_native_and_sets_entrypoint(self):
        with open(os.path.join(DEPLOY, "docker", "Dockerfile")) as f:
            content = f.read()
        assert "make -C dragonfly2_tpu/native" in content
        assert 'ENTRYPOINT ["python", "-m"]' in content


class TestK8sManifests:
    def test_manifests_parse_and_mirror_compose(self):
        """deploy/k8s/dragonfly.yaml (VERDICT r3 next-#6): every document
        is well-formed, the workload set mirrors the compose topology
        with TWO scheduler replicas, and every CLI entrypoint exists."""
        with open(os.path.join(DEPLOY, "k8s", "dragonfly.yaml")) as f:
            docs = [d for d in yaml.safe_load_all(f) if d]
        by_kind = {}
        for d in docs:
            assert d["apiVersion"] and d["kind"] and d["metadata"]["name"]
            by_kind.setdefault(d["kind"], {})[d["metadata"]["name"]] = d

        assert set(by_kind["Service"]) == {"manager", "scheduler", "trainer"}
        assert set(by_kind["Deployment"]) == {"manager", "trainer", "seed"}
        assert set(by_kind["StatefulSet"]) == {"scheduler"}
        assert set(by_kind["DaemonSet"]) == {"daemon"}

        # Two scheduler replicas behind a HEADLESS service (steering
        # needs per-pod addresses, not a VIP).
        sched = by_kind["StatefulSet"]["scheduler"]
        assert sched["spec"]["replicas"] == 2
        # k8s headless services take the literal string "None".
        assert by_kind["Service"]["scheduler"]["spec"]["clusterIP"] in (
            "None", None,
        )

        workloads = (
            list(by_kind["Deployment"].values())
            + list(by_kind["StatefulSet"].values())
            + list(by_kind["DaemonSet"].values())
        )
        for wl in workloads:
            spec = wl["spec"]["template"]["spec"]
            c = spec["containers"][0]
            assert c["image"] == "dragonfly2-tpu"  # the compose image
            assert c["command"][:2] == ["python", "-m"]
            __import__(c["command"][2])  # entrypoint exists
            # Selector must actually match the pod template labels.
            sel = wl["spec"]["selector"]["matchLabels"]
            labels = wl["spec"]["template"]["metadata"]["labels"]
            assert all(labels.get(k) == v for k, v in sel.items())
            # Config mounted from the shared ConfigMap, like compose
            # mounts deploy/config.
            mounts = {m["name"] for m in c["volumeMounts"]}
            vols = {v["name"] for v in spec.get("volumes", [])}
            assert "config" in mounts
            assert "config" in vols

        # (Steering addresses, ports and the compose diff are covered
        # programmatically in TestK8sValidation below.)


class TestClusterE2E:
    def test_run_local_cluster_loop(self):
        """One command: the full cluster comes up (manager + scheduler +
        trainer + seed + 2 daemons, real processes, real wires) and the
        composed e2e loop passes end to end."""
        r = subprocess.run(
            [sys.executable, os.path.join(DEPLOY, "run_local.py")],
            capture_output=True, text=True, timeout=420,
            env={**os.environ, "PYTHONPATH": os.getcwd()},
        )
        assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
        assert "ALL STAGES PASSED" in r.stdout

    def test_run_local_two_scheduler_replicas(self):
        """The DEPLOYED 2-replica topology (VERDICT r3 next-#6): daemons
        steer tasks onto their consistent-hash owner, and a probe pushed
        to replica A becomes ranking input on replica B via the
        manager's shared-topology sync."""
        r = subprocess.run(
            [sys.executable, os.path.join(DEPLOY, "run_local.py"),
             "--replicas"],
            capture_output=True, text=True, timeout=420,
            env={**os.environ, "PYTHONPATH": os.getcwd()},
        )
        assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
        assert "ALL STAGES PASSED" in r.stdout
        assert "landed on their ring owners" in r.stdout
        assert "ranks on replica B" in r.stdout

    @pytest.mark.skipif(
        not _HAS_CRYPTO, reason="mTLS issuance needs `cryptography`"
    )
    def test_run_local_cluster_loop_mtls(self):
        """The SAME composed topology with auto-issued mTLS on: every
        daemon bootstraps its identity from the manager's cluster CA at
        boot (POST /api/v1/certs:issue) and the piece plane moves bytes
        over mutual TLS end to end (VERDICT r3 next-#5 done-condition)."""
        r = subprocess.run(
            [sys.executable, os.path.join(DEPLOY, "run_local.py"), "--mtls"],
            capture_output=True, text=True, timeout=420,
            env={**os.environ, "PYTHONPATH": os.getcwd()},
        )
        assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
        assert "ALL STAGES PASSED" in r.stdout


def _load_validator():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "k8s_validate", os.path.join(DEPLOY, "k8s_validate.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestK8sValidation:
    """Offline structural validation + programmatic compose diff
    (VERDICT r4 #4): a schema typo or a mis-nested field must FAIL CI,
    and the manifest↔compose equivalence is computed, not substring'd."""

    def _docs(self):
        with open(os.path.join(DEPLOY, "k8s", "dragonfly.yaml")) as f:
            return [d for d in yaml.safe_load_all(f) if d is not None]

    def test_manifests_pass_structural_validation(self):
        v = _load_validator()
        assert v.validate_documents(self._docs()) == []

    def test_deliberately_broken_manifests_fail(self):
        """Every rot class the old string asserts let through."""
        import copy

        v = _load_validator()
        base = self._docs()

        def deployment(docs, name):
            return next(
                d for d in docs
                if d["kind"] == "Deployment" and d["metadata"]["name"] == name
            )

        def service(docs, name):
            return next(
                d for d in docs
                if d["kind"] == "Service" and d["metadata"]["name"] == name
            )

        def broken(mutate):
            docs = copy.deepcopy(base)
            mutate(docs)
            return v.validate_documents(docs)

        # 1. Removed beta API group still parses as YAML — must fail.
        errs = broken(lambda d: deployment(d, "manager").__setitem__(
            "apiVersion", "apps/v1beta1"))
        assert any("apiVersion" in e for e in errs), errs

        # 2. Field nested one level too high (containers under spec).
        def misnest(docs):
            dep = deployment(docs, "manager")
            dep["spec"]["containers"] = dep["spec"]["template"]["spec"].pop(
                "containers"
            )
        errs = broken(misnest)
        assert any("unknown field 'containers'" in e for e in errs), errs
        assert any("missing required field 'containers'" in e for e in errs)

        # 3. Port out of range / wrong type.
        errs = broken(lambda d: deployment(d, "trainer")["spec"]["template"][
            "spec"]["containers"][0]["ports"][0].__setitem__(
                "containerPort", 99_090))
        assert any("port" in e for e in errs), errs
        errs = broken(lambda d: service(d, "manager")["spec"]["ports"][0]
                      .__setitem__("port", "65003"))
        assert any("port" in e for e in errs), errs

        # 4. Selector that doesn't match the pod template.
        errs = broken(lambda d: deployment(d, "manager")["spec"]["selector"][
            "matchLabels"].__setitem__("component", "managr"))
        assert any("select" in e for e in errs), errs

        # 5. volumeMount referencing a volume the pod doesn't define.
        errs = broken(lambda d: deployment(d, "manager")["spec"]["template"][
            "spec"]["containers"][0]["volumeMounts"][0].__setitem__(
                "name", "cfg"))
        assert any("mounts volume" in e for e in errs), errs

        # 6. Typo'd field name at a checked level.
        def typo(docs):
            dep = deployment(docs, "seed")
            dep["spec"]["replica"] = dep["spec"].pop("replicas")
        errs = broken(typo)
        assert any("unknown field 'replica'" in e for e in errs), errs

        # 7. DaemonSet with replicas (invalid for the kind).
        def ds_replicas(docs):
            ds = next(d for d in docs if d["kind"] == "DaemonSet")
            ds["spec"]["replicas"] = 3
        errs = broken(ds_replicas)
        assert any("DaemonSet has no replicas" in e for e in errs), errs

        # 8. Bad storage quantity in the StatefulSet claim.
        def bad_qty(docs):
            ss = next(d for d in docs if d["kind"] == "StatefulSet")
            ss["spec"]["volumeClaimTemplates"][0]["spec"]["resources"][
                "requests"]["storage"] = "one-gig"
        errs = broken(bad_qty)
        assert any("quantity" in e for e in errs), errs

        # 10. Cross-kind field mixup: Deployment with updateStrategy.
        errs = broken(lambda d: deployment(d, "manager")["spec"]
                      .__setitem__("updateStrategy", {"type": "Recreate"}))
        assert any("unknown field 'updateStrategy'" in e for e in errs), errs

        # 11. ConfigMap whose mis-indented value became a nested map.
        def bad_cm(docs):
            docs.append({
                "apiVersion": "v1", "kind": "ConfigMap",
                "metadata": {"name": "cm"},
                "data": {"daemon.yaml": {"server": {"port": 65000}}},
            })
        errs = broken(bad_cm)
        assert any("string→string map" in e for e in errs), errs

        # 9a. Selector mistyped as a string (was an unhandled crash).
        errs = broken(lambda d: service(d, "manager")["spec"].__setitem__(
            "selector", "manager"))
        assert any("string→string map" in e for e in errs), errs

        # 9. Service whose selector routes to nothing.
        errs = broken(lambda d: service(d, "manager")["spec"]["selector"]
                      .__setitem__("component", "nothing"))
        assert any("selects no workload" in e for e in errs), errs

    def test_topology_diff_against_compose(self):
        """The k8s manifests and docker-compose describe the SAME
        cluster: same entry modules, same config files, and steering
        addresses derived from the actual replica count."""
        v = _load_validator()
        k8s = v.k8s_topology(self._docs())
        with open(os.path.join(DEPLOY, "docker-compose.yaml")) as f:
            comp = v.compose_topology(yaml.safe_load(f))

        # Component mapping (compose daemon-a/daemon-b ⇒ the DaemonSet).
        pairs = {
            "manager": "manager", "scheduler": "scheduler",
            "trainer": "trainer", "seed": "seed", "daemon-a": "daemon",
            "daemon-b": "daemon",
        }
        for c_name, k_name in pairs.items():
            assert comp[c_name]["module"] == k8s[k_name]["module"], (
                c_name, comp[c_name], k8s[k_name])
            assert comp[c_name]["config"] == k8s[k_name]["config"], c_name
        # Nothing unaccounted for on either side (e2e is compose-only —
        # it is the test job, not a deployed component).
        assert set(comp) - set(pairs) == {"e2e"}
        assert set(k8s) == set(pairs.values())

        # One shared image across every workload.
        assert {w["image"] for w in k8s.values()} == {"dragonfly2-tpu"}

        # The deliberate delta: TWO scheduler replicas in k8s — and the
        # daemons' steering list must name each per-pod DNS address.
        replicas = k8s["scheduler"]["replicas"]
        assert replicas == 2
        docs = self._docs()
        for wl in ("seed", "daemon"):
            doc = next(d for d in docs if d["metadata"]["name"] == wl
                       and d["kind"] in ("Deployment", "DaemonSet"))
            cmd = doc["spec"]["template"]["spec"]["containers"][0]["command"]
            addrs = set(cmd[cmd.index("--scheduler") + 1].split(","))
            assert addrs == {
                f"http://scheduler-{i}.scheduler:8002"
                for i in range(replicas)
            }, (wl, addrs)

        # Container ports cover the ports the mounted configs bind.
        cfg = {}
        for name in ("manager", "scheduler", "trainer", "daemon", "seed"):
            with open(os.path.join(DEPLOY, "config", f"{name}.yaml")) as f:
                cfg[name] = yaml.safe_load(f)
        for comp_name in ("manager", "scheduler", "trainer"):
            bind = cfg[comp_name]["server"]["port"]
            assert bind in k8s[comp_name]["ports"], comp_name
        assert cfg["daemon"]["server"]["port"] in k8s["daemon"]["ports"]
        assert cfg["daemon"]["control_port"] in k8s["daemon"]["ports"]
        assert cfg["seed"]["server"]["port"] in k8s["seed"]["ports"]

        # Service ports route to the SAME bound ports: each Service's
        # port and targetPort must be the selected component's config
        # bind (clients dial the Service on the config's port).
        for doc in docs:
            if doc["kind"] != "Service":
                continue
            comp_name = doc["metadata"]["name"]
            bind = cfg[comp_name]["server"]["port"]
            for port in doc["spec"]["ports"]:
                assert port["port"] == bind, (comp_name, port)
                assert port.get("targetPort", port["port"]) == bind
