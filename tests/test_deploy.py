"""Deploy artifacts (VERDICT r2 next-#7): compose topology sanity and the
one-command process-cluster e2e (deploy/run_local.py runs the SAME
e2e_loop.py the compose `e2e` service runs in containers)."""

import os
import subprocess
import sys

import yaml

DEPLOY = os.path.join(os.path.dirname(os.path.dirname(__file__)), "deploy")


class TestComposeArtifacts:
    def test_compose_parses_and_covers_all_services(self):
        with open(os.path.join(DEPLOY, "docker-compose.yaml")) as f:
            compose = yaml.safe_load(f)
        services = compose["services"]
        assert set(services) == {
            "manager", "scheduler", "trainer", "seed",
            "daemon-a", "daemon-b", "e2e",
        }
        # Every service runs the shared multi-entry image and a real CLI.
        for name, svc in services.items():
            if name == "e2e":
                continue
            module = svc["command"][0]
            assert module.startswith("dragonfly2_tpu.cli."), (name, module)
            __import__(module)  # the entrypoint must actually exist

    def test_service_configs_load_with_real_schemas(self):
        from dragonfly2_tpu.config import (
            DaemonConfig,
            ManagerConfig,
            SchedulerConfigFile,
            TrainerConfigFile,
            load_config,
        )

        cfgdir = os.path.join(DEPLOY, "config")
        mapping = {
            "manager.yaml": ManagerConfig,
            "scheduler.yaml": SchedulerConfigFile,
            "trainer.yaml": TrainerConfigFile,
            "seed.yaml": DaemonConfig,
            "daemon.yaml": DaemonConfig,
        }
        for name, schema in mapping.items():
            cfg = load_config(schema, os.path.join(cfgdir, name))
            cfg.validate()
        sched = load_config(
            SchedulerConfigFile, os.path.join(cfgdir, "scheduler.yaml")
        )
        assert sched.manager_addr == "http://manager:65003"
        assert sched.trainer.enable and "trainer" in sched.trainer.addr

    def test_dockerfile_builds_native_and_sets_entrypoint(self):
        with open(os.path.join(DEPLOY, "docker", "Dockerfile")) as f:
            content = f.read()
        assert "make -C dragonfly2_tpu/native" in content
        assert 'ENTRYPOINT ["python", "-m"]' in content


class TestK8sManifests:
    def test_manifests_parse_and_mirror_compose(self):
        """deploy/k8s/dragonfly.yaml (VERDICT r3 next-#6): every document
        is well-formed, the workload set mirrors the compose topology
        with TWO scheduler replicas, and every CLI entrypoint exists."""
        with open(os.path.join(DEPLOY, "k8s", "dragonfly.yaml")) as f:
            docs = [d for d in yaml.safe_load_all(f) if d]
        by_kind = {}
        for d in docs:
            assert d["apiVersion"] and d["kind"] and d["metadata"]["name"]
            by_kind.setdefault(d["kind"], {})[d["metadata"]["name"]] = d

        assert set(by_kind["Service"]) == {"manager", "scheduler", "trainer"}
        assert set(by_kind["Deployment"]) == {"manager", "trainer", "seed"}
        assert set(by_kind["StatefulSet"]) == {"scheduler"}
        assert set(by_kind["DaemonSet"]) == {"daemon"}

        # Two scheduler replicas behind a HEADLESS service (steering
        # needs per-pod addresses, not a VIP).
        sched = by_kind["StatefulSet"]["scheduler"]
        assert sched["spec"]["replicas"] == 2
        # k8s headless services take the literal string "None".
        assert by_kind["Service"]["scheduler"]["spec"]["clusterIP"] in (
            "None", None,
        )

        workloads = (
            list(by_kind["Deployment"].values())
            + list(by_kind["StatefulSet"].values())
            + list(by_kind["DaemonSet"].values())
        )
        for wl in workloads:
            spec = wl["spec"]["template"]["spec"]
            c = spec["containers"][0]
            assert c["image"] == "dragonfly2-tpu"  # the compose image
            assert c["command"][:2] == ["python", "-m"]
            __import__(c["command"][2])  # entrypoint exists
            # Selector must actually match the pod template labels.
            sel = wl["spec"]["selector"]["matchLabels"]
            labels = wl["spec"]["template"]["metadata"]["labels"]
            assert all(labels.get(k) == v for k, v in sel.items())
            # Config mounted from the shared ConfigMap, like compose
            # mounts deploy/config.
            mounts = {m["name"] for m in c["volumeMounts"]}
            vols = {v["name"] for v in spec.get("volumes", [])}
            assert "config" in mounts
            assert "config" in vols

        # Daemons steer over BOTH replicas' stable per-pod DNS names.
        daemon_cmd = " ".join(
            by_kind["DaemonSet"]["daemon"]["spec"]["template"]["spec"][
                "containers"
            ][0]["command"]
        )
        assert "scheduler-0.scheduler" in daemon_cmd
        assert "scheduler-1.scheduler" in daemon_cmd

        # Service ports target the ports the configs bind.
        assert by_kind["Service"]["manager"]["spec"]["ports"][0]["port"] == 65003
        assert by_kind["Service"]["scheduler"]["spec"]["ports"][0]["port"] == 8002


class TestClusterE2E:
    def test_run_local_cluster_loop(self):
        """One command: the full cluster comes up (manager + scheduler +
        trainer + seed + 2 daemons, real processes, real wires) and the
        composed e2e loop passes end to end."""
        r = subprocess.run(
            [sys.executable, os.path.join(DEPLOY, "run_local.py")],
            capture_output=True, text=True, timeout=420,
            env={**os.environ, "PYTHONPATH": os.getcwd()},
        )
        assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
        assert "ALL STAGES PASSED" in r.stdout

    def test_run_local_two_scheduler_replicas(self):
        """The DEPLOYED 2-replica topology (VERDICT r3 next-#6): daemons
        steer tasks onto their consistent-hash owner, and a probe pushed
        to replica A becomes ranking input on replica B via the
        manager's shared-topology sync."""
        r = subprocess.run(
            [sys.executable, os.path.join(DEPLOY, "run_local.py"),
             "--replicas"],
            capture_output=True, text=True, timeout=420,
            env={**os.environ, "PYTHONPATH": os.getcwd()},
        )
        assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
        assert "ALL STAGES PASSED" in r.stdout
        assert "landed on their ring owners" in r.stdout
        assert "ranks on replica B" in r.stdout

    def test_run_local_cluster_loop_mtls(self):
        """The SAME composed topology with auto-issued mTLS on: every
        daemon bootstraps its identity from the manager's cluster CA at
        boot (POST /api/v1/certs:issue) and the piece plane moves bytes
        over mutual TLS end to end (VERDICT r3 next-#5 done-condition)."""
        r = subprocess.run(
            [sys.executable, os.path.join(DEPLOY, "run_local.py"), "--mtls"],
            capture_output=True, text=True, timeout=420,
            env={**os.environ, "PYTHONPATH": os.getcwd()},
        )
        assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
        assert "ALL STAGES PASSED" in r.stdout
