"""In-memory fake S3 endpoint for backend tests.

Speaks enough path-style S3 for ObjectStorageBackend: bucket PUT/HEAD,
object PUT/GET/HEAD/DELETE, x-amz-copy-source, ListObjectsV2 XML.  It
VERIFIES SigV4 signatures (recomputing them with the repo's signer from
the request it received) so the S3Backend's signing is tested against an
independent check of the algorithm's inputs, not just echoed back.
"""

from __future__ import annotations

import hashlib
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import unquote, urlsplit

from dragonfly2_tpu.source import sigv4

ACCESS_KEY = "AKFAKE"
SECRET_KEY = "sk-fake-secret"
REGION = "eu-fake-1"


class FakeS3:
    def __init__(self, auth: str = "sigv4"):
        """``auth``: "sigv4" (S3) or "obs" (Huawei OBS header scheme) —
        the same in-memory store behind either verifier, so every
        backend's signing is checked by independent recomputation."""
        self.buckets = {}  # bucket → {key: (bytes, mtime)}
        self.lock = threading.Lock()
        self.auth = auth
        self.auth_failures = 0
        fake = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _reply(self, code, body=b"", headers=None):
                headers = dict(headers or {})
                self.send_response(code)
                # HEAD replies advertise the OBJECT's length, not the
                # (empty) response body's — don't double the header.
                headers.setdefault("Content-Length", str(len(body)))
                for k, v in headers.items():
                    self.send_header(k, v)
                self.end_headers()
                if self.command != "HEAD":
                    self.wfile.write(body)

            def _check_sig(self, payload: bytes) -> bool:
                if fake.auth == "obs":
                    return self._check_obs_sig()
                auth = self.headers.get("Authorization", "")
                if not auth.startswith("AWS4-HMAC-SHA256"):
                    return False
                amz_date = self.headers.get("x-amz-date", "")
                signed_names = ""
                for part in auth.split(", "):
                    if part.startswith("SignedHeaders="):
                        signed_names = part[len("SignedHeaders="):]
                headers = {
                    name: self.headers.get(name, "")
                    for name in signed_names.split(";")
                }
                # Host: the client signed what it sent.
                if "host" in headers:
                    headers["host"] = self.headers.get("Host", "")
                expect = sigv4.sign_request(
                    self.command,
                    f"http://{self.headers.get('Host','')}{self.path}",
                    headers,
                    access_key=ACCESS_KEY, secret_key=SECRET_KEY,
                    region=REGION, service="s3", amz_date=amz_date,
                    payload_sha256=hashlib.sha256(payload).hexdigest(),
                )
                ok = expect == auth
                if not ok:
                    fake.auth_failures += 1
                return ok

            def _check_obs_sig(self) -> bool:
                from dragonfly2_tpu.source.oss import sign_oss

                auth = self.headers.get("Authorization", "")
                if not auth.startswith(f"OBS {ACCESS_KEY}:"):
                    fake.auth_failures += 1
                    return False
                bucket, key, _ = self._route()
                expect = sign_oss(
                    SECRET_KEY, self.command,
                    date=self.headers.get("Date", ""),
                    bucket=bucket, key=key,
                    content_type=self.headers.get("Content-Type", ""),
                    oss_headers=dict(self.headers),
                    resource=None if bucket else "/",
                    header_prefix="x-obs-",
                )
                ok = auth == f"OBS {ACCESS_KEY}:{expect}"
                if not ok:
                    fake.auth_failures += 1
                return ok

            def _route(self):
                split = urlsplit(self.path)
                parts = split.path.lstrip("/").split("/", 1)
                bucket = unquote(parts[0])
                key = unquote(parts[1]) if len(parts) > 1 else ""
                return bucket, key, split.query

            def do_PUT(self):
                length = int(self.headers.get("Content-Length", 0))
                payload = self.rfile.read(length)
                if not self._check_sig(payload):
                    self._reply(403)
                    return
                bucket, key, _ = self._route()
                with fake.lock:
                    if not key:  # bucket create
                        fake.buckets.setdefault(bucket, {})
                        self._reply(200)
                        return
                    if bucket not in fake.buckets:
                        self._reply(404)
                        return
                    src = self.headers.get("x-amz-copy-source") or \
                        self.headers.get("x-obs-copy-source")
                    if src:
                        sb, sk = src.lstrip("/").split("/", 1)
                        stored = fake.buckets.get(sb, {}).get(sk)
                        if stored is None:
                            self._reply(404)
                            return
                        payload = stored[0]
                    fake.buckets[bucket][key] = (payload, time.time())
                etag = hashlib.md5(payload).hexdigest()
                self._reply(200, headers={"ETag": f'"{etag}"'})

            def do_GET(self):
                if not self._check_sig(b""):
                    self._reply(403)
                    return
                bucket, key, query = self._route()
                if not bucket:  # service-level: list all buckets
                    with fake.lock:
                        rows = "".join(
                            f"<Bucket><Name>{b}</Name></Bucket>"
                            for b in sorted(fake.buckets)
                        )
                    body = (
                        "<?xml version=\"1.0\"?><ListAllMyBucketsResult>"
                        f"<Buckets>{rows}</Buckets>"
                        "</ListAllMyBucketsResult>"
                    ).encode()
                    self._reply(200, body, {"Content-Type": "application/xml"})
                    return
                with fake.lock:
                    objs = fake.buckets.get(bucket)
                    if objs is None:
                        self._reply(404)
                        return
                    if not key:  # list
                        prefix = ""
                        for pair in query.split("&"):
                            if pair.startswith("prefix="):
                                prefix = unquote(pair[len("prefix="):])
                        rows = "".join(
                            "<Contents>"
                            f"<Key>{k}</Key><Size>{len(v[0])}</Size>"
                            f"<ETag>\"{hashlib.md5(v[0]).hexdigest()}\"</ETag>"
                            "<LastModified>"
                            + time.strftime("%Y-%m-%dT%H:%M:%S.000Z",
                                            time.gmtime(v[1]))
                            + "</LastModified></Contents>"
                            for k, v in sorted(objs.items())
                            if k.startswith(prefix)
                        )
                        body = (
                            "<?xml version=\"1.0\"?><ListBucketResult>"
                            + rows + "</ListBucketResult>"
                        ).encode()
                        self._reply(200, body,
                                    {"Content-Type": "application/xml"})
                        return
                    stored = objs.get(key)
                if stored is None:
                    self._reply(404)
                    return
                self._reply(200, stored[0])

            def do_HEAD(self):
                if not self._check_sig(b""):
                    self._reply(403)
                    return
                bucket, key, _ = self._route()
                with fake.lock:
                    objs = fake.buckets.get(bucket)
                    stored = objs.get(key) if objs and key else None
                if objs is None or (key and stored is None):
                    self._reply(404)
                    return
                if not key:
                    self._reply(200)
                    return
                self._reply(200, headers={
                    "Content-Length": str(len(stored[0])),
                    "ETag": f'"{hashlib.md5(stored[0]).hexdigest()}"',
                    "Last-Modified": time.strftime(
                        "%a, %d %b %Y %H:%M:%S GMT", time.gmtime(stored[1])
                    ),
                })

            def do_DELETE(self):
                if not self._check_sig(b""):
                    self._reply(403)
                    return
                bucket, key, _ = self._route()
                with fake.lock:
                    if not key:  # bucket delete
                        if fake.buckets.pop(bucket, None) is None:
                            self._reply(404)
                        else:
                            self._reply(204)
                        return
                    objs = fake.buckets.get(bucket, {})
                    if key in objs:
                        del objs[key]
                        self._reply(204)
                    else:
                        self._reply(404)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.endpoint = f"http://127.0.0.1:{self._httpd.server_address[1]}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
