"""Sharded scheduler fleet (DESIGN.md §24): ring properties, durable
membership, ownership steering, admission shedding, and the columnar
fleet simulator's migration protocol.

The ring property tests pin the three contracts routing correctness
stands on (ISSUE 13):

- **balance** — 1k synthetic task ids spread within a bounded factor of
  the mean at N ∈ {2, 4, 8} shards (virtual nodes do their job);
- **minimal movement** — adding/removing ONE shard moves at most
  ceil(K/N) keys, and every moved key moves to/from the changed shard
  only (the consistent-hash guarantee handoff cost depends on);
- **cross-process determinism** — ownership is a pure function of the
  key bytes (sha, never ``hash()``), so a daemon, every shard, and the
  manager place a task at the same ring point under different
  PYTHONHASHSEEDs.
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import time
from collections import Counter
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from dragonfly2_tpu.manager.state import MemoryBackend  # noqa: E402
from dragonfly2_tpu.scheduler import (  # noqa: E402
    AdmissionController,
    Evaluator,
    Resource,
    SchedulerService,
    Scheduling,
    SchedulingConfig,
    ShardDirectory,
    ShardGuard,
    ShardRing,
    ShardSaturatedError,
    WrongShardError,
)
from dragonfly2_tpu.scheduler.resource import Host  # noqa: E402
from dragonfly2_tpu.utils.types import Priority  # noqa: E402

KEYS = [f"task-{i:04d}" for i in range(1000)]


def _ring(n: int, **kw) -> ShardRing:
    return ShardRing({f"s{i}": f"http://s{i}:8002" for i in range(n)}, **kw)


class TestRingProperties:
    @pytest.mark.parametrize("n", [2, 4, 8])
    def test_balance_bound(self, n):
        ring = _ring(n)
        counts = Counter(ring.owner(k) for k in KEYS)
        assert len(counts) == n, "some shard owns nothing at 1k keys"
        mean = len(KEYS) / n
        # 100 virtual nodes per member: the max/mean imbalance stays
        # bounded (observed ≤ ~1.35× across these Ns; 1.6 leaves noise
        # headroom without letting real skew through).
        assert max(counts.values()) <= 1.6 * mean, counts

    @pytest.mark.parametrize("n", [2, 4, 8])
    def test_minimal_movement_on_add(self, n):
        ring = _ring(n)
        before = {k: ring.owner(k) for k in KEYS}
        ring.add("s-new", "http://new:8002")
        after = {k: ring.owner(k) for k in KEYS}
        moved = {k for k in KEYS if before[k] != after[k]}
        assert len(moved) <= math.ceil(len(KEYS) / n)
        # Consistent hashing moves keys only TO the newcomer.
        assert all(after[k] == "s-new" for k in moved)

    @pytest.mark.parametrize("n", [2, 4, 8])
    def test_minimal_movement_on_remove(self, n):
        ring = _ring(n + 1)
        before = {k: ring.owner(k) for k in KEYS}
        ring.remove(f"s{n}")
        after = {k: ring.owner(k) for k in KEYS}
        moved = {k for k in KEYS if before[k] != after[k]}
        # Only the removed member's keys move (its former keys, all of
        # them, and nothing else).
        assert moved == {k for k in KEYS if before[k] == f"s{n}"}
        assert len(moved) <= math.ceil(len(KEYS) / (n + 1)) * 2, (
            "removed shard owned far above the balance bound"
        )

    def test_deterministic_across_processes(self):
        """Ownership must not depend on hash() randomization: a child
        interpreter with a different PYTHONHASHSEED computes identical
        owners for a key sample."""
        ring = _ring(4)
        sample = KEYS[::97]
        mine = {k: ring.owner(k) for k in sample}
        script = (
            "import json,sys\n"
            "sys.path.insert(0, sys.argv[1])\n"
            "from dragonfly2_tpu.scheduler import ShardRing\n"
            "ring = ShardRing({f's{i}': '' for i in range(4)})\n"
            "keys = json.loads(sys.argv[2])\n"
            "print(json.dumps({k: ring.owner(k) for k in keys}))\n"
        )
        out = subprocess.run(
            [sys.executable, "-c", script, str(REPO), json.dumps(sample)],
            env={**os.environ, "PYTHONHASHSEED": "12345",
                 "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, out.stderr
        theirs = json.loads(out.stdout)
        assert theirs == mine

    def test_bounded_load_pick_spills_past_hot_owner(self):
        ring = _ring(4)
        key = KEYS[0]
        owner = ring.owner(key)
        loads = {sid: 10.0 for sid in ring.members()}
        picked = ring.pick(key, load_of=loads.get)
        assert picked == owner, "uniform load must keep the plain owner"
        loads[owner] = 1000.0
        spilled = ring.pick(key, load_of=loads.get)
        assert spilled != owner, "hot owner must spill to a ring neighbor"
        # Everyone hot: fall back to the owner (shedding, not routing,
        # handles that).
        picked = ring.pick(key, load_of=lambda s: 1000.0)
        assert picked == owner

    def test_payload_round_trip(self):
        ring = _ring(3, version=7)
        clone = ShardRing.from_payload(ring.to_payload())
        assert clone.version == 7
        assert clone.members() == ring.members()
        assert [clone.owner(k) for k in KEYS[:50]] == [
            ring.owner(k) for k in KEYS[:50]
        ]


class TestShardDirectory:
    def test_version_bumps_only_on_membership_change(self):
        d = ShardDirectory(MemoryBackend())
        p1 = d.publish("default", [("a", "http://a"), ("b", "http://b")])
        p2 = d.publish("default", [("b", "http://b"), ("a", "http://a")])
        assert p1["version"] == p2["version"] == 1
        p3 = d.publish("default", [("a", "http://a")])
        assert p3["version"] == 2
        assert [m["id"] for m in p3["members"]] == ["a"]

    def test_ring_version_survives_reload(self):
        backend = MemoryBackend()
        d = ShardDirectory(backend)
        d.publish("default", [("a", "http://a")])
        d.publish("default", [("a", "http://a"), ("b", "http://b")])
        # A fresh directory over the same backend (the restarted/promoted
        # manager) continues the version line instead of restarting it.
        d2 = ShardDirectory(backend)
        assert d2.version("default") == 2
        p = d2.publish("default", [("a", "http://a"), ("b", "http://b")])
        assert p["version"] == 2


def _service(guard=None) -> SchedulerService:
    return SchedulerService(
        Resource(),
        Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)),
        None,
        None,
        shard_guard=guard,
    )


def _host(i: int = 0) -> Host:
    h = Host(id=f"shg-host-{i}", hostname=f"shg{i}", ip=f"10.9.0.{i}",
             port=8002, download_port=8001)
    h.stats.network.idc = "idc-a"
    return h


class TestManagerRingPublication:
    def test_cluster_config_carries_versioned_ring(self):
        import urllib.request

        from dragonfly2_tpu.manager.cluster import ClusterManager
        from dragonfly2_tpu.manager.registry import ModelRegistry
        from dragonfly2_tpu.manager.rest import ManagerRESTServer

        clusters = ClusterManager()
        server = ManagerRESTServer(ModelRegistry(), clusters)
        server.serve()
        try:
            base = f"http://{server.address[0]}:{server.address[1]}"

            def post(path, body):
                req = urllib.request.Request(
                    base + path, data=json.dumps(body).encode(),
                    headers={"Content-Type": "application/json"},
                )
                return json.loads(urllib.request.urlopen(req).read())

            def config():
                with urllib.request.urlopen(
                    base + "/api/v1/clusters/default:config"
                ) as resp:
                    return json.loads(resp.read())

            post("/api/v1/schedulers", {
                "id": "sa", "cluster_id": "default",
                "ip": "127.0.0.1", "port": 18001,
            })
            post("/api/v1/schedulers", {
                "id": "sb", "cluster_id": "default",
                "ip": "127.0.0.1", "port": 18002,
            })
            ring = config()["scheduler_ring"]
            assert ring["version"] == 1
            assert [m["id"] for m in ring["members"]] == ["sa", "sb"]
            assert ring["members"][0]["url"] == "http://127.0.0.1:18001"
            # Stable until membership changes; keepalive expiry bumps it.
            assert config()["scheduler_ring"]["version"] == 1
            with clusters._mu:
                clusters._schedulers["sb"].last_keepalive = 0.0
            ring2 = config()["scheduler_ring"]
            assert ring2["version"] == 2
            assert [m["id"] for m in ring2["members"]] == ["sa"]
        finally:
            server.stop()


class TestShardGuard:
    def test_wrong_shard_register_steers(self):
        ring = _ring(2, version=1)
        # Find a url whose task id is owned by s1; the guard speaks for s0.
        from dragonfly2_tpu.utils import idgen

        url = next(
            f"https://origin/{i}" for i in range(200)
            if ring.owner(idgen.task_id(f"https://origin/{i}")) == "s1"
        )
        guard = ShardGuard("s0")
        service = _service(guard)
        guard.update_ring(ring)
        with pytest.raises(WrongShardError) as exc:
            service.register_peer(host=_host(), url=url)
        assert exc.value.owner_id == "s1"
        assert exc.value.ring_version == 1
        # No split-brain residue: the mis-routed register created nothing.
        assert len(service.resource.task_manager) == 0
        assert len(service.resource.peer_manager) == 0

    def test_handoff_marks_moved_tasks_and_opens_span(self):
        from dragonfly2_tpu.utils import tracing

        guard = ShardGuard("s0")
        service = _service(guard)
        guard.update_ring(ShardRing({"s0": ""}, version=1))
        done = []
        for i in range(40):
            r = service.register_peer(host=_host(i), url=f"https://o/{i}")
            done.append(r.peer)
        prev = tracing.default_tracer.exporter
        exporter = tracing.InMemoryExporter()
        tracing.default_tracer.exporter = exporter
        try:
            moved = guard.update_ring(_ring(4, version=2))
        finally:
            tracing.default_tracer.exporter = prev
        # s0 keeps roughly a quarter; the rest are marked for steering.
        assert 0 < len(moved) < 40
        spans = exporter.find("scheduler/shard.handoff")
        assert spans and spans[0].attributes["tasks_moved"] == len(moved)
        # A handed-off task's in-flight report now steers.
        victim = next(p for p in done if p.task.id in set(moved))
        with pytest.raises(WrongShardError):
            service.report_piece_finished(victim, 0, parent_id="", length=1)

    def test_stale_ring_version_is_ignored(self):
        guard = ShardGuard("s0")
        guard.resource = Resource()
        guard.update_ring(_ring(2, version=5))
        assert guard.update_ring(_ring(4, version=4)) == []
        assert guard.ring_version() == 5

    def test_on_config_adopts_published_ring(self):
        guard = ShardGuard("s0")
        guard.resource = Resource()
        guard.on_config({"scheduler_ring": _ring(3, version=9).to_payload()})
        assert guard.ring_version() == 9
        guard.on_config({"scheduler_ring": {"members": []}})  # malformed: no-op
        assert guard.ring_version() == 9


class TestAdmissionControl:
    def _saturated(self) -> AdmissionController:
        ctl = AdmissionController(max_inflight=4, p99_budget_s=0.010)
        # Latency burn: observed p99 at 10× budget.
        for _ in range(64):
            ctl.observe(0.100)
        return ctl

    def test_sheds_lowest_priority_first(self):
        ctl = self._saturated()
        assert ctl.overload() > 0.0
        with pytest.raises(ShardSaturatedError) as exc:
            ctl.admit(Priority.LEVEL6)
        assert exc.value.retry_after_s > 0
        # LEVEL0 (interactive) rides through the priority band.
        ctl.admit(Priority.LEVEL0)

    def test_inside_budget_admits_everyone(self):
        ctl = AdmissionController(max_inflight=64, p99_budget_s=1.0)
        for _ in range(16):
            ctl.observe(0.001)
        for level in (Priority.LEVEL0, Priority.LEVEL3, Priority.LEVEL6):
            ctl.admit(level)

    def test_hard_wall_sheds_even_level0(self):
        ctl = AdmissionController(max_inflight=1)
        tracks = [ctl.track().__enter__() for _ in range(2)]
        try:
            with pytest.raises(ShardSaturatedError):
                ctl.admit(Priority.LEVEL0)
        finally:
            for t in tracks:
                t.__exit__(None, None, None)

    def test_window_recovers_after_burst(self):
        ctl = AdmissionController(
            max_inflight=64, p99_budget_s=0.010, window_s=0.05
        )
        for _ in range(64):
            ctl.observe(0.100)
        assert ctl.overload() > 0.0
        time.sleep(0.06)
        for _ in range(64):
            ctl.observe(0.001)
        time.sleep(0.06)
        ctl.observe(0.001)  # rotate the burst epoch out
        assert ctl.overload() == 0.0


class TestShardWire:
    """The steering answers over the real HTTP wire: 421 wrong-shard
    with the owner address, 503 + Retry-After on shed — both surfaced
    as their typed exceptions client-side."""

    def test_wrong_shard_answer_rides_the_wire(self):
        from dragonfly2_tpu.rpc import RemoteScheduler, SchedulerHTTPServer
        from dragonfly2_tpu.utils import idgen

        ring = _ring(2, version=3)
        guard = ShardGuard("s0")
        service = _service(guard)
        guard.update_ring(ring)
        server = SchedulerHTTPServer(service)
        server.serve()
        try:
            client = RemoteScheduler(server.url, timeout=5.0)
            url = next(
                f"https://origin/{i}" for i in range(200)
                if ring.owner(idgen.task_id(f"https://origin/{i}")) == "s1"
            )
            client.announce_host(_host(1))
            with pytest.raises(WrongShardError) as exc:
                client.register_peer(host=_host(1), url=url)
            assert exc.value.owner_id == "s1"
            assert exc.value.owner_url == "http://s1:8002"
            assert exc.value.ring_version == 3
        finally:
            server.stop()

    def test_saturated_answer_carries_retry_after(self):
        from dragonfly2_tpu.rpc import RemoteScheduler, SchedulerHTTPServer

        ctl = AdmissionController(max_inflight=4, p99_budget_s=0.001)
        for _ in range(64):
            ctl.observe(1.0)
        guard = ShardGuard("s0", admission=ctl)
        service = _service(guard)
        server = SchedulerHTTPServer(service)
        server.serve()
        try:
            client = RemoteScheduler(server.url, timeout=5.0)
            with pytest.raises(ShardSaturatedError) as exc:
                client.register_peer(
                    host=_host(2), url="https://origin/shed",
                    priority=Priority.LEVEL6,
                )
            assert exc.value.retry_after_s > 0
        finally:
            server.stop()


class TestShardRouter:
    def _router_over(self, services):
        """ShardRouter over in-process services via a stub transport."""
        from dragonfly2_tpu.rpc.resolver import ShardRouter

        class _Stub:
            def __init__(self, service):
                self.service = service

        router = ShardRouter(factory=lambda url: _Stub(services[url]))
        return router

    def test_routes_by_ring_and_follows_redirect(self):
        from dragonfly2_tpu.utils import idgen

        ring = _ring(2, version=1)
        guards = {sid: ShardGuard(sid) for sid in ring.members()}
        services = {}
        for sid, url in ring.members().items():
            svc = _service(guards[sid])
            guards[sid].update_ring(ring)
            services[url] = svc
        router = self._router_over(services)
        router.on_config({"scheduler_ring": ring.to_payload()})
        assert router.version == 1
        url = f"https://origin/route-{id(self)}"
        tid = idgen.task_id(url)
        sid, _ = router.route(tid)
        res = router.call(
            tid, lambda c: c.service.register_peer(host=_host(3), url=url)
        )
        assert res.peer.task.id == tid
        # The owning service really is the ring owner.
        owner_url = ring.url_of(ring.owner(tid))
        assert len(services[owner_url].resource.task_manager) == 1

    def test_redirect_answer_reroutes_to_hinted_owner(self):
        from dragonfly2_tpu.utils import idgen

        ring = _ring(2, version=2)
        guards = {sid: ShardGuard(sid) for sid in ring.members()}
        services = {}
        for sid, url in ring.members().items():
            svc = _service(guards[sid])
            guards[sid].update_ring(ring)
            services[url] = svc
        router = self._router_over(services)
        # Stale router ring: only s0, so every task routes there; s0's
        # guard steers the mis-routed half to s1 and the router follows.
        router.on_config({
            "scheduler_ring": {
                "version": 1,
                "members": [{"id": "s0", "url": "http://s0:8002"}],
            },
        })
        url = next(
            f"https://origin/redir-{i}" for i in range(200)
            if ring.owner(idgen.task_id(f"https://origin/redir-{i}")) == "s1"
        )
        tid = idgen.task_id(url)
        res = router.call(
            tid, lambda c: c.service.register_peer(host=_host(4), url=url)
        )
        assert res.peer.task.id == tid
        assert len(services["http://s1:8002"].resource.task_manager) == 1


class TestFleetSim:
    def test_population_tick_conserves_states(self):
        from dragonfly2_tpu.sim import ColumnarPopulation, FleetConfig

        pop = ColumnarPopulation(FleetConfig(num_peers=5000, seed=3))
        for _ in range(5):
            ev = pop.tick()
            # Event sets are disjoint where they must be.
            assert not (set(ev.joins) & set(ev.leaves))
            assert not (set(ev.leaves) & set(ev.fails))
        assert 0 < pop.online_count() <= 5000

    def test_kill_and_add_migrate_without_losing_downloads(self):
        from dragonfly2_tpu.sim import (
            ColumnarPopulation,
            FleetConfig,
            FleetSwarmDriver,
            ShardedFleet,
        )

        pop = ColumnarPopulation(
            FleetConfig(num_peers=3000, seed=11, download_rate=0.02)
        )
        fleet = ShardedFleet(3, feature_cache_hosts=2048)
        driver = FleetSwarmDriver(pop, fleet)
        driver.run(2)
        assert driver.downloads_ok > 0
        fleet.kill(sorted(fleet.shards)[-1])
        driver.run(1)
        moved = fleet.add_shard("shard-late")
        driver.run(2)
        assert driver.downloads_failed == 0
        assert sum(moved.values()) > 0, "scale-out handed off no tasks"
        total_redirects = sum(
            s.redirects_followed for s in fleet.shards.values()
        )
        assert total_redirects > 0, "stale-ring steering never exercised"


class TestBenchSwarmSmoke:
    def test_smoke_schema_gate(self):
        out = subprocess.run(
            [sys.executable, str(REPO / "tools" / "bench_swarm.py"),
             "--smoke"],
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, timeout=600, cwd=str(REPO),
        )
        assert out.returncode == 0, out.stdout + out.stderr
        data = json.loads(out.stdout.strip().splitlines()[-1])
        assert data["ok"] is True
        assert data["membership_drill"]["ran"] is True
        assert data["arms"]["sharded"]["downloads_failed"] == 0


class TestShardWireGRPCParity:
    """ISSUE 14 satellite: the steering answers on the gRPC wire.

    The HTTP wire carries wrong-shard as 421 + owner hints and shed as
    503 + Retry-After; the gRPC wire maps the SAME typed errors onto
    FAILED_PRECONDITION / RESOURCE_EXHAUSTED with trailing metadata
    (``df-owner-id`` / ``df-owner-url`` / ``df-ring-version``,
    ``retry-after``) — and on the bidi stream, onto the response error
    field — so a client raises the identical exception on either
    transport and the ShardRouter follows both without knowing which
    wire it rides.
    """

    def _grpc_server(self, guard):
        from dragonfly2_tpu.rpc.grpc_transport import SchedulerGRPCServer

        service = _service(guard)
        server = SchedulerGRPCServer(service)
        server.serve()
        return service, server

    def _owned_by(self, ring, shard_id):
        from dragonfly2_tpu.utils import idgen

        return next(
            f"https://origin/g{i}" for i in range(400)
            if ring.owner(idgen.task_id(f"https://origin/g{i}")) == shard_id
        )

    def test_unary_wrong_shard_is_typed_with_owner_hint(self):
        from dragonfly2_tpu.rpc.grpc_transport import GRPCRemoteScheduler

        ring = _ring(2, version=4)
        guard = ShardGuard("s0")
        service, server = self._grpc_server(guard)
        guard.update_ring(ring)
        try:
            client = GRPCRemoteScheduler(server.target, timeout=5.0)
            url = self._owned_by(ring, "s1")
            client.announce_host(_host(11))
            with pytest.raises(WrongShardError) as exc:
                client.register_peer(host=_host(11), url=url)
            assert exc.value.owner_id == "s1"
            assert exc.value.owner_url == "http://s1:8002"
            assert exc.value.ring_version == 4
            client.close()
        finally:
            server.stop()

    def test_stream_wrong_shard_is_typed(self):
        """register_peer rides the bidi announce stream on the streaming
        client — the steering payload must survive that wire too."""
        from dragonfly2_tpu.rpc.grpc_transport import GRPCStreamingScheduler

        ring = _ring(2, version=7)
        guard = ShardGuard("s0")
        service, server = self._grpc_server(guard)
        guard.update_ring(ring)
        try:
            client = GRPCStreamingScheduler(server.target, timeout=5.0)
            url = self._owned_by(ring, "s1")
            client.announce_host(_host(12))
            with pytest.raises(WrongShardError) as exc:
                client.register_peer(host=_host(12), url=url)
            assert exc.value.owner_id == "s1"
            assert exc.value.owner_url == "http://s1:8002"
            assert exc.value.ring_version == 7
            client.close()
        finally:
            server.stop()

    def test_unary_saturated_carries_retry_after(self):
        from dragonfly2_tpu.rpc.grpc_transport import GRPCRemoteScheduler

        ctl = AdmissionController(max_inflight=4, p99_budget_s=0.001)
        for _ in range(64):
            ctl.observe(1.0)
        guard = ShardGuard("s0", admission=ctl)
        service, server = self._grpc_server(guard)
        try:
            client = GRPCRemoteScheduler(server.target, timeout=5.0)
            client.announce_host(_host(13))
            with pytest.raises(ShardSaturatedError) as exc:
                client.register_peer(
                    host=_host(13), url="https://origin/g-shed",
                    priority=Priority.LEVEL6,
                )
            assert exc.value.retry_after_s > 0
            assert exc.value.reason
            client.close()
        finally:
            server.stop()

    def test_router_follows_grpc_steering_like_http(self):
        """A ShardRouter with a STALE ring routes to the wrong shard over
        gRPC, follows the trailing-metadata owner hint, and lands the
        register on the true owner — the exact walk the HTTP tests
        prove, transport swapped."""
        from dragonfly2_tpu.rpc.grpc_transport import GRPCRemoteScheduler
        from dragonfly2_tpu.rpc.resolver import ShardRouter
        from dragonfly2_tpu.utils import idgen

        guard0, guard1 = ShardGuard("s0"), ShardGuard("s1")
        service0, server0 = self._grpc_server(guard0)
        service1, server1 = self._grpc_server(guard1)
        clients = []

        def factory(url):
            c = GRPCRemoteScheduler(url[len("grpc://"):], timeout=5.0)
            clients.append(c)
            return c

        try:
            live = ShardRing(
                {"s0": f"grpc://{server0.target}",
                 "s1": f"grpc://{server1.target}"},
                version=2,
            )
            guard0.update_ring(live)
            guard1.update_ring(live)
            url = self._owned_by(live, "s1")
            task_id = idgen.task_id(url)
            router = ShardRouter(factory=factory)
            # Stale client view: only s0 exists → the first route is
            # wrong and the steering hint must carry the call to s1.
            router.update_ring(
                ShardRing({"s0": f"grpc://{server0.target}"}, version=1)
            )
            host = _host(14)
            reg = router.call(
                task_id,
                lambda c: (
                    c.announce_host(host),
                    c.register_peer(host=host, url=url, task_id=task_id),
                )[1],
            )
            assert reg.peer is not None
            # The register landed on the true owner, not the stale route.
            assert len(service1.resource.peer_manager) == 1
            assert len(service0.resource.peer_manager) == 0
        finally:
            for c in clients:
                c.close()
            server0.stop()
            server1.stop()

    def test_router_honors_grpc_retry_after_once(self):
        from dragonfly2_tpu.rpc.grpc_transport import GRPCRemoteScheduler
        from dragonfly2_tpu.rpc.resolver import ShardRouter
        from dragonfly2_tpu.utils import idgen

        ctl = AdmissionController(
            max_inflight=4, p99_budget_s=0.001, retry_after_s=0.05
        )
        for _ in range(64):
            ctl.observe(1.0)
        guard = ShardGuard("s0", admission=ctl)
        service, server = self._grpc_server(guard)
        clients = []

        def factory(url):
            c = GRPCRemoteScheduler(url[len("grpc://"):], timeout=5.0)
            clients.append(c)
            return c

        try:
            router = ShardRouter(factory=factory)
            router.update_ring(
                ShardRing({"s0": f"grpc://{server.target}"}, version=1)
            )
            host = _host(15)
            url = "https://origin/g-burn"
            t0 = time.monotonic()
            with pytest.raises(ShardSaturatedError):
                router.call(
                    idgen.task_id(url),
                    lambda c: (
                        c.announce_host(host),
                        c.register_peer(
                            host=host, url=url, priority=Priority.LEVEL6
                        ),
                    )[1],
                )
            # One Retry-After honored (≥ the server's 0.05 s pacing),
            # then the typed error propagated to the caller.
            assert time.monotonic() - t0 >= 0.05
        finally:
            for c in clients:
                c.close()
            server.stop()
