"""Fleet telemetry plane unit tests (ISSUE 12, DESIGN.md §23): the
mergeable percentile Sketch, the crash-safe metric journal, the SLO
burn-rate engine, and the /debug/slo endpoints."""

from __future__ import annotations

import json
import math
import sys
import urllib.request
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from dragonfly2_tpu.utils import metrics as m  # noqa: E402
from dragonfly2_tpu.utils.metric_journal import (  # noqa: E402
    MetricJournal,
    final_snapshots_by_run,
    replay_metric_journal,
)
from dragonfly2_tpu.utils.metrics import (  # noqa: E402
    Registry,
    Sketch,
    merge_sketch_states,
    sketch_state_count_below,
    sketch_state_quantile,
)
from dragonfly2_tpu.utils.slo import (  # noqa: E402
    SLO,
    SLOEngine,
    parse_slos,
    replay_fleet,
)


def _exact_quantile(samples, q):
    ordered = np.sort(np.asarray(samples))
    rank = max(int(math.ceil(q * len(ordered))), 1) - 1
    return float(ordered[rank])


class TestSketch:
    @pytest.mark.parametrize("dist", ["lognormal", "uniform", "bimodal"])
    def test_relative_error_bound(self, dist):
        rng = np.random.default_rng(7)
        if dist == "lognormal":
            samples = rng.lognormal(-3, 1.5, 8000)
        elif dist == "uniform":
            samples = rng.uniform(1e-4, 10.0, 8000)
        else:
            samples = np.concatenate(
                [rng.normal(0.01, 0.001, 4000), rng.normal(2.0, 0.2, 4000)]
            )
            samples = np.abs(samples) + 1e-9
        s = Sketch("t_seconds", "t", alpha=0.01)
        for v in samples:
            s.observe(float(v))
        for q in (0.5, 0.9, 0.99, 0.999):
            exact = _exact_quantile(samples, q)
            est = s.quantile(q)
            assert abs(est - exact) / exact <= 0.01 + 1e-9, (dist, q)

    def test_deterministic_across_instances(self):
        """Same stream → byte-identical state: the cross-process merge
        precondition (two daemons observing the same latency classify it
        into the same bucket)."""
        rng = np.random.default_rng(3)
        samples = [float(v) for v in rng.lognormal(-2, 1, 500)]
        a, b = Sketch("a_seconds", ""), Sketch("b_seconds", "")
        for v in samples:
            a.observe(v)
            b.observe(v)
        assert a.aggregate_state() == b.aggregate_state()

    def test_merge_is_lossless(self):
        """Merging per-process states equals one sketch over the whole
        stream: bucket counts add exactly (sum is float-rounding-equal)."""
        rng = np.random.default_rng(1)
        samples = [float(v) for v in rng.lognormal(-3, 1.2, 5000)]
        parts = [Sketch(f"p{i}_seconds", "") for i in range(3)]
        whole = Sketch("w_seconds", "")
        for i, v in enumerate(samples):
            parts[i % 3].observe(v)
            whole.observe(v)
        merged = merge_sketch_states([p.aggregate_state() for p in parts])
        want = whole.aggregate_state()
        for key in ("alpha", "zero", "counts", "total", "min", "max"):
            assert merged[key] == want[key], key
        assert merged["sum"] == pytest.approx(want["sum"])
        for q in (0.5, 0.99):
            assert sketch_state_quantile(merged, q) == pytest.approx(
                whole.quantile(q)
            )

    def test_merge_rejects_alpha_mismatch(self):
        a = Sketch("a_seconds", "", alpha=0.01)
        b = Sketch("b_seconds", "", alpha=0.02)
        a.observe(1.0)
        b.observe(1.0)
        with pytest.raises(ValueError, match="alpha"):
            merge_sketch_states([a.aggregate_state(), b.aggregate_state()])

    def test_serialization_roundtrip_exact(self):
        s = Sketch("x_seconds", "", ["op"])
        for v in (0.001, 0.5, 2.0, 0.0, 1e-15):
            s.observe(v, op="k")
        st = s.state()
        assert st["type"] == "sketch"
        # JSON roundtrip preserves the state exactly (ints + floats).
        back = json.loads(json.dumps(st))
        # json turns the [idx, count] pairs into lists — normalize.
        assert back["series"][0][1] == json.loads(
            json.dumps(st["series"][0][1])
        )
        restored = Sketch("y_seconds", "", ["op"])
        restored.merge_state(st["series"][0][1], op="k")
        assert restored.aggregate_state() == s.aggregate_state()

    def test_zero_and_negative_values(self):
        s = Sketch("z_seconds", "")
        s.observe(0.0)
        s.observe(-1.0)
        s.observe(1.0)
        agg = s.aggregate_state()
        assert agg["zero"] == 2 and agg["total"] == 3
        assert s.quantile(0.5) == 0.0

    def test_fixed_size_collapse_bound(self):
        """Past max_bins distinct buckets the LOW end collapses; the
        high quantiles keep full resolution."""
        s = Sketch("c_seconds", "", max_bins=32)
        # Values spanning a huge dynamic range → many distinct buckets.
        for i in range(2000):
            s.observe(1e-9 * (1.13 ** (i % 300)))
        agg = s.aggregate_state()
        assert len(agg["counts"]) <= 32
        assert agg["total"] == 2000
        # Tail estimate still within bound of the exact tail.
        samples = [1e-9 * (1.13 ** (i % 300)) for i in range(2000)]
        exact = _exact_quantile(samples, 0.99)
        assert abs(s.quantile(0.99) - exact) / exact <= 0.011

    def test_count_below_within_resolution(self):
        rng = np.random.default_rng(5)
        samples = [float(v) for v in rng.lognormal(-3, 1, 4000)]
        s = Sketch("cb_seconds", "")
        for v in samples:
            s.observe(v)
        thr = 0.05
        got = s.count_below(thr)
        # Resolution is one bucket: everything ≤ thr counts, plus at
        # most the remainder of thr's bucket (upper bound < thr·γ).
        gamma = (1 + 0.01) / (1 - 0.01)
        exact_lo = sum(1 for v in samples if v <= thr)
        exact_hi = sum(1 for v in samples if v <= thr * gamma)
        assert exact_lo <= got <= exact_hi

    def test_sketch_toggle_disables_recording(self):
        s = Sketch("tog_seconds", "")
        m.set_sketches_enabled(False)
        try:
            s.observe(1.0)
            s.labels().observe(1.0)
        finally:
            m.set_sketches_enabled(True)
        assert s.total_count() == 0
        s.observe(1.0)
        assert s.total_count() == 1

    def test_exposed_as_summary_and_parses(self):
        from tests.test_observability import parse_exposition

        reg = Registry()
        s = reg.sketch("exp_fetch_seconds", "h", ["op"])
        for v in (0.01, 0.02, 0.5):
            s.observe(v, op='evil"op\n')
        text = reg.expose_text()
        assert "# TYPE exp_fetch_seconds summary" in text
        parsed = parse_exposition(text)
        key_count = (("op", 'evil"op\n'),)
        assert parsed["exp_fetch_seconds_count"][key_count] == 3.0
        assert any(
            ("quantile", "0.5") in k for k in parsed["exp_fetch_seconds"]
        )


class TestRegistrySnapshot:
    def test_counters_gauges_sketches_serialized(self):
        reg = Registry()
        reg.counter("s_ops_total", "", ["r"]).inc(r="ok")
        reg.gauge("s_depth_rows", "").set(3.0)
        reg.sketch("s_lat_seconds", "").observe(0.2)
        reg.histogram("s_hist_seconds", "").observe(0.2)
        snap = reg.snapshot()
        assert snap["s_ops_total"]["type"] == "counter"
        assert snap["s_ops_total"]["series"] == [[["ok"], 1.0]]
        assert snap["s_depth_rows"]["series"] == [[[], 3.0]]
        assert snap["s_lat_seconds"]["type"] == "sketch"
        # Histograms are scrape-only (the sketch is the durable carrier).
        assert "s_hist_seconds" not in snap
        json.dumps(snap)  # journal payload must be JSON-clean


class TestMetricJournal:
    def _mk(self, tmp_path, interval_s=60.0):
        reg = Registry()
        c = reg.counter("j_ops_total", "")
        s = reg.sketch("j_lat_seconds", "")
        path = str(tmp_path / "m.dfmj")
        j = MetricJournal(path, registry=reg, service="t",
                          interval_s=interval_s)
        return reg, c, s, path, j

    def test_snapshots_cumulative_and_replayable(self, tmp_path):
        _reg, c, s, path, j = self._mk(tmp_path)
        c.inc()
        s.observe(0.1)
        j.write_snapshot()
        c.inc(amount=2)
        j.write_snapshot()
        j.close()  # writes the final frame
        snaps, stats = replay_metric_journal(path)
        assert stats == {"frames": 3, "corrupt": 0, "torn_tail": False}
        assert [s["seq"] for s in snaps] == [1, 2, 3]
        assert snaps[1]["metrics"]["j_ops_total"]["series"] == [[[], 3.0]]
        finals = final_snapshots_by_run(snaps)
        assert list(finals.values())[0]["seq"] == 3

    def test_torn_tail_tolerated(self, tmp_path):
        _reg, c, _s, path, j = self._mk(tmp_path)
        c.inc()
        j.write_snapshot()
        j.write_snapshot()
        j.close()
        data = Path(path).read_bytes()
        Path(path).write_bytes(data[:-20])  # SIGKILL mid-write signature
        snaps, stats = replay_metric_journal(path)
        assert stats["torn_tail"] is True
        assert stats["corrupt"] == 0
        assert stats["frames"] == 2

    def test_digest_bad_frame_never_admitted(self, tmp_path):
        _reg, c, _s, path, j = self._mk(tmp_path)
        for _ in range(3):
            c.inc()
            j.write_snapshot()
        j.close()
        data = bytearray(Path(path).read_bytes())
        i = data.find(b'"seq": 2')
        assert i > 0
        data[i + 8] ^= 0x01
        Path(path).write_bytes(bytes(data))
        snaps, stats = replay_metric_journal(path)
        assert stats["corrupt"] == 1
        assert [s["seq"] for s in snaps] == [1, 3, 4]

    def test_garbage_between_frames_resyncs(self, tmp_path):
        _reg, c, _s, path, j = self._mk(tmp_path)
        c.inc()
        j.write_snapshot()
        with open(path, "ab") as f:
            f.write(b"#### operator cat'd a logline in here ####\n")
        j.write_snapshot()
        j.close()
        snaps, stats = replay_metric_journal(path)
        assert stats["frames"] == 3 and stats["corrupt"] == 0

    def test_missing_file(self, tmp_path):
        snaps, stats = replay_metric_journal(str(tmp_path / "nope"))
        assert snaps == [] and stats["frames"] == 0

    def test_background_cadence_and_close_idempotent(self, tmp_path):
        import time

        _reg, c, _s, path, j = self._mk(tmp_path, interval_s=0.05)
        j.start()
        c.inc()
        deadline = time.monotonic() + 5.0
        while j.written < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        j.close()
        written = j.written
        assert written >= 2
        j.close()  # no second final frame
        snaps, _stats = replay_metric_journal(path)
        assert len(snaps) == written

    def test_run_identity_separates_restarts(self, tmp_path):
        """Two runs of the 'same' service in one journal: the final
        snapshot of EACH run survives — fleet counters sum both."""
        reg = Registry()
        c = reg.counter("r_ops_total", "")
        path = str(tmp_path / "r.dfmj")
        j1 = MetricJournal(path, registry=reg, service="d", run_id="run-a",
                           interval_s=60)
        c.inc(amount=5)
        j1.close()
        reg2 = Registry()  # restart: counters reset, fresh run id
        c2 = reg2.counter("r_ops_total", "")
        j2 = MetricJournal(path, registry=reg2, service="d", run_id="run-b",
                           interval_s=60)
        c2.inc(amount=2)
        j2.close()
        snaps, _ = replay_metric_journal(path)
        finals = final_snapshots_by_run(snaps)
        assert set(finals) == {("d", "run-a"), ("d", "run-b")}
        total = sum(
            v for f in finals.values()
            for _k, v in f["metrics"]["r_ops_total"]["series"]
        )
        assert total == 7.0


class TestSLOEngine:
    def _slo(self, **kw):
        d = dict(
            name="s", objective="latency", metric="l_seconds",
            threshold_ms=100.0, target=0.9, fast_window_s=10.0,
            slow_window_s=60.0, burn_threshold=2.0,
        )
        d.update(kw)
        return d

    def test_parse_validates(self):
        assert isinstance(parse_slos([self._slo()])[0], SLO)
        with pytest.raises(ValueError, match="objective"):
            parse_slos([self._slo(objective="vibes")])
        with pytest.raises(ValueError, match="target"):
            parse_slos([self._slo(target=1.0)])
        with pytest.raises(ValueError, match="unknown keys"):
            parse_slos([dict(self._slo(), extra=1)])
        with pytest.raises(ValueError, match="duplicate"):
            parse_slos([self._slo(), self._slo()])
        with pytest.raises(ValueError, match="threshold_ms"):
            parse_slos([self._slo(threshold_ms=0)])
        with pytest.raises(ValueError, match="good_metric"):
            parse_slos([{"name": "a", "objective": "availability",
                         "target": 0.9}])

    def test_burn_rate_math_latency(self):
        reg = Registry()
        sk = reg.sketch("l_seconds", "")
        eng = SLOEngine([self._slo()], registry=reg)
        t0 = 1000.0
        for i in range(10):
            sk.observe(0.01)
        eng.tick(now=t0)
        # The window delta since the baseline tick is 10 NEW events, all
        # bad; budget 0.1 → burn 10.
        for i in range(10):
            sk.observe(5.0)
        state = eng.tick(now=t0 + 5.0)["s"]
        assert state["burn_rate_fast"] == pytest.approx(10.0)
        assert state["breached"] is True
        # Mixed follow-up: 10 good / 0 bad since the last sample keeps
        # the cumulative ratios honest (burn falls).
        for i in range(20):
            sk.observe(0.01)
        state = eng.tick(now=t0 + 8.0)["s"]
        # Fast window now spans both deltas: 10 bad of 30 → burn ~3.33.
        assert state["burn_rate_fast"] == pytest.approx(10.0 / 30.0 / 0.1)

    def test_availability_objective(self):
        reg = Registry()
        good = reg.counter("g_ok_total", "")
        total = reg.counter("g_all_total", "")
        slo = {
            "name": "avail", "objective": "availability", "target": 0.99,
            "good_metric": "g_ok_total", "total_metric": "g_all_total",
            "fast_window_s": 10.0, "slow_window_s": 60.0,
            "burn_threshold": 2.0,
        }
        eng = SLOEngine([slo], registry=reg)
        good.inc(amount=100)
        total.inc(amount=100)
        eng.tick(now=0.0)
        good.inc(amount=90)
        total.inc(amount=100)
        state = eng.tick(now=5.0)["avail"]
        # 10% bad / 1% budget = burn 10.
        assert state["burn_rate_fast"] == pytest.approx(10.0)
        assert state["breached"] is True

    def test_multiwindow_requires_both(self):
        """A short spike trips the fast window but not the slow one →
        no alert (the multi-window point)."""
        reg = Registry()
        sk = reg.sketch("l_seconds", "")
        eng = SLOEngine([self._slo(fast_window_s=1.0, slow_window_s=600.0,
                                   burn_threshold=3.0)], registry=reg)
        t = 0.0
        for _ in range(600):
            sk.observe(0.01)
        eng.tick(now=t)
        # Long healthy history inside the slow window.
        for i in range(20):
            t += 10.0
            for _ in range(50):
                sk.observe(0.01)
            eng.tick(now=t)
        # One-second spike of pure badness.
        t += 1.0
        for _ in range(10):
            sk.observe(5.0)
        state = eng.tick(now=t)["s"]
        assert state["burn_rate_fast"] > 3.0
        assert state["burn_rate_slow"] < 3.0
        assert state["breached"] is False

    def test_gauges_exported(self):
        from dragonfly2_tpu.utils.slo import SLO_BREACHED, SLO_BURN_RATE

        reg = Registry()
        sk = reg.sketch("l_seconds", "")
        eng = SLOEngine([self._slo(name="gauge_probe")], registry=reg)
        sk.observe(0.01)
        eng.tick(now=0.0)
        for _ in range(10):
            sk.observe(5.0)
        eng.tick(now=5.0)
        assert SLO_BURN_RATE.value(slo="gauge_probe") > 2.0
        assert SLO_BREACHED.value(slo="gauge_probe") == 1.0

    def test_replay_fleet_merges_process_streams(self):
        """Two processes each 95% good → fleet replay sees the sum."""
        slo = self._slo(target=0.5, burn_threshold=1.5)
        snaps = []
        for pi, run in enumerate(("run-a", "run-b")):
            reg = Registry()
            sk = reg.sketch("l_seconds", "")
            for i in range(20):
                sk.observe(5.0 if i % 2 else 0.01)
            snaps.append({
                "service": f"d{pi}", "run_id": run, "seq": 1,
                "ts": 100.0 + pi, "metrics": reg.snapshot(),
            })
        eng = replay_fleet(snaps, [slo])
        state = eng.state()["slos"][0]
        # Baseline = the first fleet sample (run-a alone, 20 events);
        # the window delta is run-b's 20 events joining at t=101.
        assert state["events_slow"] == 20.0
        # run-b's delta is 50% bad / 50% budget = burn 1.0.
        assert state["burn_rate_slow"] == pytest.approx(1.0)


class TestDebugSLOEndpoints:
    def test_diagnostics_route(self):
        from dragonfly2_tpu.utils import slo as slo_mod
        from dragonfly2_tpu.utils.diagnostics import DiagnosticsServer

        reg = Registry()
        sk = reg.sketch("d_seconds", "")
        eng = SLOEngine(
            [{"name": "ep", "objective": "latency", "metric": "d_seconds",
              "threshold_ms": 100.0, "target": 0.9, "fast_window_s": 1.0,
              "slow_window_s": 10.0}],
            registry=reg,
        )
        sk.observe(0.01)
        eng.tick(now=0.0)
        for _ in range(10):
            sk.observe(5.0)
        eng.tick(now=0.5)
        slo_mod.install_engine(eng)
        srv = DiagnosticsServer(port=0)
        srv.serve()
        try:
            with urllib.request.urlopen(srv.url + "/debug/slo", timeout=5) as r:
                payload = json.loads(r.read())
        finally:
            srv.stop()
            slo_mod.install_engine(None)
        assert payload["installed"] is True
        assert payload["slos"][0]["name"] == "ep"
        assert payload["slos"][0]["breached"] is True
        # The endpoint serves EXACTLY the engine's state.
        assert payload["slos"] == eng.state()["slos"]

    def test_uninstalled_engine_empty(self):
        from dragonfly2_tpu.utils import slo as slo_mod
        from dragonfly2_tpu.utils.diagnostics import DiagnosticsServer

        slo_mod.install_engine(None)
        srv = DiagnosticsServer(port=0)
        srv.serve()
        try:
            with urllib.request.urlopen(srv.url + "/debug/slo", timeout=5) as r:
                payload = json.loads(r.read())
        finally:
            srv.stop()
        assert payload == {"slos": [], "installed": False}

    def test_manager_rest_route(self):
        from dragonfly2_tpu.manager.cluster import ClusterManager
        from dragonfly2_tpu.manager.registry import ModelRegistry
        from dragonfly2_tpu.manager.rest import ManagerRESTServer

        server = ManagerRESTServer(ModelRegistry(), ClusterManager())
        server.serve()
        try:
            with urllib.request.urlopen(
                server.url + "/debug/slo", timeout=5
            ) as r:
                payload = json.loads(r.read())
        finally:
            server.stop()
        assert "slos" in payload


class TestTelemetryConfig:
    def test_section_defaults_and_validation(self):
        from dragonfly2_tpu.config import ConfigError, SchedulerConfigFile

        cfg = SchedulerConfigFile()
        cfg.validate()
        cfg.telemetry.slos = [{"name": "x", "objective": "latency",
                               "metric": "m_seconds", "threshold_ms": 10,
                               "target": 0.9}]
        cfg.validate()
        cfg.telemetry.slos = [{"name": "x", "objective": "nope",
                               "target": 0.9}]
        with pytest.raises(ConfigError, match="telemetry.slos"):
            cfg.validate()
        cfg.telemetry.slos = []
        cfg.telemetry.journal_interval_s = 0
        with pytest.raises(ConfigError, match="journal_interval_s"):
            cfg.validate()

    def test_all_four_configs_carry_telemetry(self):
        from dragonfly2_tpu.config import (
            DaemonConfig,
            ManagerConfig,
            SchedulerConfigFile,
            TrainerConfigFile,
        )

        for cls in (SchedulerConfigFile, DaemonConfig, ManagerConfig,
                    TrainerConfigFile):
            cfg = cls()
            assert cfg.telemetry.journal_path == ""
            cfg.validate() if cls is not ManagerConfig else None

    def test_init_telemetry_wires_journal_and_engine(self, tmp_path):
        import argparse

        from dragonfly2_tpu.cli.common import init_telemetry
        from dragonfly2_tpu.config import TelemetrySection
        from dragonfly2_tpu.utils import slo as slo_mod

        args = argparse.Namespace(metric_journal=None, _prog="scheduler")
        cfg = TelemetrySection(
            journal_path=str(tmp_path / "j.dfmj"),
            journal_interval_s=60.0,
            slos=[{"name": "wired", "objective": "latency",
                   "metric": "w_seconds", "threshold_ms": 10,
                   "target": 0.9}],
        )
        journal, engine = init_telemetry(args, cfg, "scheduler")
        try:
            assert journal is not None and engine is not None
            assert slo_mod.current_engine() is engine
            journal.write_snapshot()
            snaps, _ = replay_metric_journal(str(tmp_path / "j.dfmj"))
            assert snaps and snaps[0]["service"] == "scheduler"
        finally:
            journal.close()
            engine.close()
            slo_mod.install_engine(None)

    def test_flag_overrides_config_path(self, tmp_path):
        import argparse

        from dragonfly2_tpu.cli.common import init_telemetry
        from dragonfly2_tpu.config import TelemetrySection

        flag_path = str(tmp_path / "flag.dfmj")
        args = argparse.Namespace(metric_journal=flag_path, _prog="dfdaemon")
        cfg = TelemetrySection(journal_path=str(tmp_path / "cfg.dfmj"))
        journal, engine = init_telemetry(args, cfg)
        try:
            assert journal.path == flag_path
            assert engine is None
        finally:
            journal.close()


class TestHotPathSketchesRegistered:
    """The §23 wiring contract: the hot-path sketches exist on the
    default registry (DF017's REQUIRED_METRICS is the static half)."""

    EXPECTED = (
        "daemon_piece_fetch_seconds",
        "daemon_report_linger_seconds",
        "rpc_piece_fetch_seconds",
        "scheduler_announce_seconds",
        "scheduler_eval_flush_seconds",
        "manager_replication_commit_seconds",
    )

    def test_sketches_on_default_registry(self):
        import dragonfly2_tpu.daemon.piece_pipeline  # noqa: F401
        import dragonfly2_tpu.rpc.metrics  # noqa: F401
        import dragonfly2_tpu.rpc.piece_transport  # noqa: F401
        import dragonfly2_tpu.scheduler.metrics  # noqa: F401
        from dragonfly2_tpu.utils.metrics import default_registry

        for name in self.EXPECTED:
            assert isinstance(default_registry.get(name), Sketch), name

    def test_piece_latency_tracker_feeds_sketch(self):
        from dragonfly2_tpu.daemon.piece_pipeline import (
            PIECE_FETCH_SECONDS,
            PieceLatencyTracker,
        )

        before = PIECE_FETCH_SECONDS.total_count()
        tracker = PieceLatencyTracker()
        tracker.observe(0.123)
        assert PIECE_FETCH_SECONDS.total_count() == before + 1

    def test_announce_feeds_sketch(self):
        from dragonfly2_tpu.scheduler import metrics as smetrics
        from dragonfly2_tpu.scheduler.resource import Host
        from dragonfly2_tpu.scheduler.service import SchedulerService
        from dragonfly2_tpu.scheduler import (
            Evaluator,
            NetworkTopology,
            Resource,
            Scheduling,
            SchedulingConfig,
        )
        from dragonfly2_tpu.records.storage import Storage
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            resource = Resource()
            service = SchedulerService(
                resource,
                Scheduling(Evaluator(), SchedulingConfig()),
                Storage(d, buffer_size=10),
                NetworkTopology(resource.host_manager),
            )
            before = smetrics.ANNOUNCE_SECONDS.total_count()
            service.announce_host(
                Host(id="h1", hostname="h1", ip="127.0.0.1")
            )
            assert smetrics.ANNOUNCE_SECONDS.total_count() == before + 1
