"""Aggregation ops: pallas kernel vs XLA oracle; shard_map aggregation vs
single-device; streaming trainer ingest + checkpoint/resume determinism."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from dragonfly2_tpu.models.gnn import build_neighbor_table
from dragonfly2_tpu.ops import (
    bucket_edges_by_block,
    masked_mean_aggregate,
    segment_mean,
    segment_sum,
    segment_sum_pallas,
)
from dragonfly2_tpu.parallel import create_mesh
from dragonfly2_tpu.parallel.graph_sharding import (
    make_sharded_table,
    pad_nodes_for_mesh,
    sharded_neighbor_aggregate,
)


class TestSegmentOps:
    def test_segment_sum_matches_numpy(self):
        rng = np.random.default_rng(0)
        e, d, n = 500, 16, 40
        vals = rng.normal(size=(e, d)).astype(np.float32)
        ids = rng.integers(0, n, e)
        got = np.asarray(segment_sum(jnp.asarray(vals), jnp.asarray(ids), n))
        want = np.zeros((n, d), np.float32)
        np.add.at(want, ids, vals)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_segment_mean(self):
        vals = jnp.ones((4, 2))
        ids = jnp.array([0, 0, 1, 3])
        got = np.asarray(segment_mean(vals, ids, 4))
        np.testing.assert_allclose(got[0], [1, 1])
        np.testing.assert_allclose(got[2], [0, 0])  # empty segment → 0


class TestBucketing:
    def test_bucketing_covers_all_edges(self):
        rng = np.random.default_rng(1)
        ids = rng.integers(0, 300, 1000)
        perm, dstl, w, block_node, is_first = bucket_edges_by_block(
            ids, 300, node_block=128, edge_block=128
        )
        assert w.sum() == 1000  # every real edge exactly once
        assert len(perm) % 128 == 0
        assert (dstl >= 0).all() and (dstl < 128).all()
        # every node block visited, first visit flagged once
        assert set(block_node) == {0, 1, 2}
        assert is_first.sum() == 3
        # real edges land in the right block
        real = w > 0
        global_dst = block_node.repeat(
            len(perm) // len(block_node)
        ) * 128 + dstl
        np.testing.assert_array_equal(np.sort(global_dst[real]), np.sort(ids))

    def test_empty_node_block_padded(self):
        # All edges hit node 0; blocks for nodes 128.. must still appear.
        ids = np.zeros(10, dtype=np.int64)
        perm, dstl, w, block_node, is_first = bucket_edges_by_block(
            ids, 256, node_block=128, edge_block=128
        )
        assert set(block_node) == {0, 1}
        assert is_first.sum() == 2


class TestPallasSegmentSum:
    def test_matches_oracle_interpret(self):
        rng = np.random.default_rng(2)
        e, d, n = 700, 128, 300
        vals = rng.normal(size=(e, d)).astype(np.float32)
        ids = rng.integers(0, n, e)
        want = np.asarray(segment_sum(jnp.asarray(vals), jnp.asarray(ids), n))
        # Exact path: f32-HIGHEST accumulate, tight tolerance.
        got = np.asarray(
            segment_sum_pallas(jnp.asarray(vals), ids, n, exact=True,
                               interpret=True)
        )
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
        # Native MXU path (default): bf16 multiplicands, f32 accumulate.
        got16 = np.asarray(
            segment_sum_pallas(jnp.asarray(vals), ids, n, interpret=True)
        )
        np.testing.assert_allclose(got16, want, rtol=2e-2, atol=2e-2)

    def test_presorted_skips_permutation(self):
        from dragonfly2_tpu.ops.pallas_segment import bucket_edges_by_block

        rng = np.random.default_rng(5)
        e, d, n = 500, 64, 200
        vals = rng.normal(size=(e, d)).astype(np.float32)
        ids = rng.integers(0, n, e)
        perm, *_ = bucket_edges_by_block(ids, n, node_block=128, edge_block=128)
        pre = np.zeros((len(perm), d), np.float32)
        pre[: len(perm)] = vals[perm]
        got = np.asarray(
            segment_sum_pallas(jnp.asarray(pre), ids, n, presorted=True,
                               node_block=128, edge_block=128, exact=True,
                               interpret=True)
        )
        want = np.asarray(segment_sum(jnp.asarray(vals), jnp.asarray(ids), n))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_empty_segments_are_zero(self):
        vals = np.ones((4, 8), np.float32)
        ids = np.array([5, 5, 6, 200])
        got = np.asarray(
            segment_sum_pallas(jnp.asarray(vals), ids, 256, interpret=True)
        )
        assert got[5].sum() == 16.0
        assert got[0].sum() == 0.0
        assert got[130].sum() == 0.0

    def test_neighbor_gather_vjp_matches_take(self):
        import jax

        from dragonfly2_tpu.ops.pallas_segment import make_neighbor_gather

        rng = np.random.default_rng(7)
        n, k, d = 300, 8, 64
        idx = rng.integers(0, n, (n, k)).astype(np.int32)
        table = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        g = make_neighbor_gather(idx, n, edge_block=128, interpret=True)
        assert bool(jnp.array_equal(
            g(table), jnp.take(table, jnp.asarray(idx), axis=0)
        ))
        gc = jax.grad(lambda t: jnp.sum(jnp.sin(g(t)) * 0.01))(table)
        gr = jax.grad(
            lambda t: jnp.sum(jnp.sin(jnp.take(t, jnp.asarray(idx), axis=0)) * 0.01)
        )(table)
        rel = float(jnp.max(jnp.abs(gc - gr)) / jnp.max(jnp.abs(gr)))
        assert rel < 2e-2  # bf16 accumulate in the kernel backward

    def test_gather_fn_through_gatranker(self):
        """The GNNConfig(gather_fn=...) wiring end to end: same loss and
        gradients as the default path, and a mismatched table rejected."""
        import jax

        from dragonfly2_tpu.models import (
            GATRanker,
            GNNConfig,
            build_neighbor_table,
        )
        from dragonfly2_tpu.ops.pallas_segment import make_neighbor_gather

        rng = np.random.default_rng(11)
        n = 200
        src = rng.integers(0, n, 800)
        dst = rng.integers(0, n, 800)
        table = build_neighbor_table(n, src, dst, max_neighbors=8)
        nf = jnp.asarray(rng.normal(size=(n, 6)).astype(np.float32))
        es = jnp.asarray(rng.integers(0, n, 32).astype(np.int32))
        ed = jnp.asarray(rng.integers(0, n, 32).astype(np.int32))
        y = jnp.asarray(rng.normal(size=32).astype(np.float32))

        def loss_and_gradsum(cfg):
            model = GATRanker(cfg)
            params = model.init(
                jax.random.PRNGKey(0), nf, table, es[:2], ed[:2]
            )["params"]

            def loss(p):
                return jnp.mean(
                    (model.apply({"params": p}, nf, table, es, ed) - y) ** 2
                )

            l, g = jax.value_and_grad(loss)(params)
            return float(l), sum(
                float(jnp.sum(jnp.abs(x))) for x in jax.tree.leaves(g)
            )

        base_cfg = GNNConfig(hidden=16, num_heads=2, node_embed_dim=4,
                             dropout=0.0)
        gf = make_neighbor_gather(
            np.asarray(table.indices), n, edge_block=128, interpret=True
        )
        l0, g0 = loss_and_gradsum(base_cfg)
        l1, g1 = loss_and_gradsum(
            GNNConfig(hidden=16, num_heads=2, node_embed_dim=4,
                      dropout=0.0, gather_fn=gf)
        )
        assert abs(l0 - l1) / max(abs(l0), 1e-6) < 1e-3
        assert abs(g0 - g1) / max(g0, 1e-6) < 5e-2
        # Wrong-snapshot gather_fn → loud error, not silent garbage.
        small = build_neighbor_table(50, src % 50, dst % 50, max_neighbors=4)
        bad = make_neighbor_gather(
            np.asarray(small.indices), 50, edge_block=128, interpret=True
        )
        model = GATRanker(GNNConfig(hidden=16, num_heads=2, node_embed_dim=4,
                                    dropout=0.0, gather_fn=bad))
        with pytest.raises((ValueError, TypeError)):
            model.init(jax.random.PRNGKey(0), nf, table, es[:2], ed[:2])

    def test_presorted_rejects_unbucketed_length(self):
        rng = np.random.default_rng(3)
        vals = rng.normal(size=(500, 32)).astype(np.float32)
        ids = rng.integers(0, 200, 500)
        with pytest.raises(ValueError):
            segment_sum_pallas(
                jnp.asarray(vals), ids, 200, presorted=True, interpret=True
            )


class TestShardedAggregation:
    def test_matches_single_device(self):
        mesh = create_mesh()
        rng = np.random.default_rng(3)
        n_raw, d, k = 100, 32, 8
        n = pad_nodes_for_mesh(n_raw, mesh)
        src = rng.integers(0, n_raw, 600)
        dst = rng.integers(0, n_raw, 600)
        feats = rng.normal(size=600).astype(np.float32)
        table = build_neighbor_table(n, src, dst, feats, max_neighbors=k)
        h = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))

        # Single-device oracle (same math inline).
        nbr = jnp.take(h, table.indices, axis=0)
        nbr = jnp.concatenate([nbr, table.edge_feats], axis=-1)
        m = table.mask[..., None]
        want = (nbr * m).sum(axis=1) / jnp.maximum(m.sum(axis=1), 1.0)

        from jax.sharding import NamedSharding, PartitionSpec as P

        h_sharded = jax.device_put(h, NamedSharding(mesh, P("data")))
        t_sharded = make_sharded_table(mesh, table)
        got = sharded_neighbor_aggregate(mesh, h_sharded, t_sharded)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


class TestStreamingTrainer:
    def _rows(self, cluster, n, seed):
        return cluster.generate_feature_rows(n, seed=seed)

    def test_stream_learns_and_checkpoints(self, tmp_path, cluster):
        from dragonfly2_tpu.trainer.streaming import StreamingConfig, StreamingTrainer

        cfg = StreamingConfig(
            batch_size=512, checkpoint_every=5, learning_rate=3e-3, warmup_steps=10
        )
        t = StreamingTrainer(cfg, checkpoint_dir=str(tmp_path / "ck"))
        for i in range(20):
            t.feed(self._rows(cluster, 512, seed=i))
        t.end_of_stream()
        steps = t.run()
        assert steps == 20
        assert t.records_seen == 20 * 512
        assert t.step == 20

        # Resume restores exact step/record counts and params.
        t2 = StreamingTrainer(cfg, checkpoint_dir=str(tmp_path / "ck"))
        assert t2.resume()
        assert t2.step == 20  # checkpoint_every=5 → saved at step 20
        assert t2.records_seen == t.records_seen
        p1 = jax.tree_util.tree_leaves(t.params)
        p2 = jax.tree_util.tree_leaves(t2.params)
        for a, b in zip(p1, p2):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_resume_continues_training(self, tmp_path, cluster):
        from dragonfly2_tpu.trainer.streaming import StreamingConfig, StreamingTrainer

        cfg = StreamingConfig(batch_size=256, checkpoint_every=4, warmup_steps=4)
        t = StreamingTrainer(cfg, checkpoint_dir=str(tmp_path / "ck"))
        for i in range(8):
            t.feed(self._rows(cluster, 256, seed=i))
        t.run(idle_timeout=0.1)
        t.checkpoint()

        t2 = StreamingTrainer(cfg, checkpoint_dir=str(tmp_path / "ck"))
        t2.resume()
        start = t2.step
        for i in range(4):
            t2.feed(self._rows(cluster, 256, seed=100 + i))
        t2.end_of_stream()
        t2.run()
        assert t2.step == start + 4
        scorer = t2.export_scorer()
        rows = self._rows(cluster, 500, seed=999)
        pred = scorer.score(rows[:, 2:-1])
        mae = float(np.mean(np.abs(pred - rows[:, -1])))
        assert np.isfinite(mae)

    def test_backpressure(self, cluster):
        from dragonfly2_tpu.trainer.streaming import StreamingConfig, StreamingTrainer

        cfg = StreamingConfig(batch_size=128, queue_capacity=2)
        t = StreamingTrainer(cfg)
        assert t.feed(self._rows(cluster, 128, seed=0), block=False)
        assert t.feed(self._rows(cluster, 128, seed=1), block=False)
        assert not t.feed(self._rows(cluster, 128, seed=2), block=False)


class TestHaloExchange:
    def _local_graph(self, n, shard, rng, locality=0.9, k=8, n_edges=2000):
        """Graph where ~locality of edges stay within a node's shard."""
        dst = rng.integers(0, n, n_edges)
        local = rng.random(n_edges) < locality
        shard_of = dst // shard
        src_local = shard_of * shard + rng.integers(0, shard, n_edges)
        src_any = rng.integers(0, n, n_edges)
        src = np.where(local, src_local, src_any)
        return src.astype(np.int64), dst.astype(np.int64)

    def test_matches_full_aggregation(self):
        from dragonfly2_tpu.parallel.graph_sharding import (
            build_halo_plan,
            halo_neighbor_aggregate,
        )
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = create_mesh()
        n, d, k = 128, 16, 8
        shard = n // mesh.shape["data"]
        rng = np.random.default_rng(7)
        src, dst = self._local_graph(n, shard, rng)
        feats = rng.normal(size=len(src)).astype(np.float32)
        table = build_neighbor_table(n, src, dst, feats, max_neighbors=k)
        h = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))

        # Oracle: plain full aggregation.
        nbr = jnp.take(h, table.indices, axis=0)
        nbr = jnp.concatenate([nbr, table.edge_feats], axis=-1)
        m = table.mask[..., None]
        want = (nbr * m).sum(axis=1) / jnp.maximum(m.sum(axis=1), 1.0)

        plan = build_halo_plan(table, mesh)
        h_sharded = jax.device_put(h, NamedSharding(mesh, P("data")))
        from dragonfly2_tpu.parallel.graph_sharding import make_sharded_table

        t_sharded = make_sharded_table(mesh, table)
        got = halo_neighbor_aggregate(mesh, h_sharded, t_sharded, plan)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)

    def test_sharded_precompute_matches_oracle(self):
        """precompute_hop_features_sharded (node-sharded, halo all-to-all
        per hop) equals the replicated precompute — the flagship's
        config[4] precompute path (VERDICT r3 weak-#4)."""
        from dragonfly2_tpu.models.hop import precompute_hop_features
        from dragonfly2_tpu.parallel.graph_sharding import (
            build_halo_plan,
            precompute_hop_features_sharded,
        )

        mesh = create_mesh()
        n, k = 256, 8
        shard = n // mesh.shape["data"]
        rng = np.random.default_rng(11)
        src, dst = self._local_graph(n, shard, rng, locality=0.8, n_edges=4000)
        feats = rng.random(len(src)).astype(np.float32)
        table = build_neighbor_table(n, src, dst, feats, max_neighbors=k)
        nf = rng.normal(size=(n, 12)).astype(np.float32)

        want = precompute_hop_features(jnp.asarray(nf), table, hops=2)
        plan = build_halo_plan(table, mesh)
        got = precompute_hop_features_sharded(
            mesh, jnp.asarray(nf), table, plan, hops=2
        )
        assert got.sharding.spec == jax.sharding.PartitionSpec("data")
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )

    def test_sharded_precompute_rejects_stale_plan(self):
        """A plan built for one table sampling must refuse a different
        table (digest guard), like halo_neighbor_aggregate."""
        import pytest

        from dragonfly2_tpu.parallel.graph_sharding import (
            build_halo_plan,
            precompute_hop_features_sharded,
        )

        mesh = create_mesh()
        n = 64
        rng = np.random.default_rng(3)
        src, dst = self._local_graph(n, n // mesh.shape["data"], rng, n_edges=500)
        table = build_neighbor_table(n, src, dst, max_neighbors=4)
        other = build_neighbor_table(
            n, dst, src, max_neighbors=4
        )  # different sampling
        plan = build_halo_plan(table, mesh)
        nf = jnp.asarray(rng.normal(size=(n, 4)).astype(np.float32))
        with pytest.raises(ValueError, match="different table"):
            precompute_hop_features_sharded(mesh, nf, other, plan)

    def test_halo_smaller_than_shard_with_locality(self):
        from dragonfly2_tpu.parallel.graph_sharding import build_halo_plan

        mesh = create_mesh()
        n = 1024
        shard = n // mesh.shape["data"]
        rng = np.random.default_rng(8)
        src, dst = self._local_graph(n, shard, rng, locality=0.95, n_edges=8000)
        table = build_neighbor_table(n, src, dst, max_neighbors=8)
        plan = build_halo_plan(table, mesh)
        # The exchange ships n_shards*halo rows instead of the full table:
        # with 95% locality the halo must be far below the shard size.
        assert plan.halo < plan.shard_size / 2, (plan.halo, plan.shard_size)


class TestTransposeGather:
    """Scatter-free gather VJP (ops/transpose_gather.py): backward is a
    gather over the precomputed transpose graph + tiny COO spill."""

    def _graph(self, n=300, k=8, seed=7):
        rng = np.random.default_rng(seed)
        idx = rng.integers(0, n, (n, k)).astype(np.int32)
        mask = (rng.random((n, k)) < 0.9).astype(np.float32)
        return idx, mask

    def test_vjp_matches_take_under_mask(self):
        import jax

        from dragonfly2_tpu.ops.transpose_gather import make_transpose_gather

        n, k, d = 300, 8, 32
        idx, mask = self._graph(n, k)
        rng = np.random.default_rng(1)
        table = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        m = jnp.asarray(mask)[..., None]
        g = make_transpose_gather(idx, mask, n)

        # Masked loss — the contract: downstream zeroes padded slots
        # (exactly what the GAT/SAGE layers do), so pad cotangents are 0.
        def loss(fn):
            return lambda t: jnp.sum(jnp.sin(fn(t)) * m * 0.01)

        assert bool(jnp.array_equal(
            g(table), jnp.take(table, jnp.asarray(idx), axis=0)
        ))
        gc = jax.grad(loss(g))(table)
        gr = jax.grad(
            loss(lambda t: jnp.take(t, jnp.asarray(idx), axis=0))
        )(table)
        assert float(jnp.max(jnp.abs(gc - gr))) < 1e-5

    def test_spill_tail_exact(self):
        import jax

        from dragonfly2_tpu.ops.transpose_gather import (
            build_transpose_table,
            make_transpose_gather,
        )

        n, k, d = 120, 16, 16
        idx, mask = self._graph(n, k, seed=3)
        # Tiny cap forces real spill traffic through the COO tail.
        tt = build_transpose_table(idx, mask, n, cap=8)
        assert int(tt.over_pos.shape[0]) > 0
        g = make_transpose_gather(idx, mask, n, cap=8)
        rng = np.random.default_rng(2)
        table = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
        m = jnp.asarray(mask)[..., None]
        gc = jax.grad(lambda t: jnp.sum(jnp.sin(g(t)) * m * 0.01))(table)
        gr = jax.grad(
            lambda t: jnp.sum(
                jnp.sin(jnp.take(t, jnp.asarray(idx), axis=0)) * m * 0.01
            )
        )(table)
        assert float(jnp.max(jnp.abs(gc - gr))) < 1e-5

    def test_through_gatranker(self):
        import jax

        from dragonfly2_tpu.models import GATRanker, GNNConfig, build_neighbor_table
        from dragonfly2_tpu.ops.transpose_gather import make_transpose_gather

        rng = np.random.default_rng(11)
        n = 200
        src = rng.integers(0, n, 800)
        dst = rng.integers(0, n, 800)
        table = build_neighbor_table(n, src, dst, max_neighbors=8)
        nf = jnp.asarray(rng.normal(size=(n, 6)).astype(np.float32))
        es = jnp.asarray(rng.integers(0, n, 32).astype(np.int32))
        ed = jnp.asarray(rng.integers(0, n, 32).astype(np.int32))
        y = jnp.asarray(rng.normal(size=32).astype(np.float32))

        def loss_and_gradsum(cfg):
            model = GATRanker(cfg)
            params = model.init(
                jax.random.PRNGKey(0), nf, table, es[:2], ed[:2]
            )["params"]

            def loss(p):
                return jnp.mean(
                    (model.apply({"params": p}, nf, table, es, ed) - y) ** 2
                )

            l, g = jax.value_and_grad(loss)(params)
            return float(l), sum(
                float(jnp.sum(jnp.abs(x))) for x in jax.tree.leaves(g)
            )

        gf = make_transpose_gather(
            np.asarray(table.indices), np.asarray(table.mask), n
        )
        l0, g0 = loss_and_gradsum(
            GNNConfig(hidden=16, num_heads=2, node_embed_dim=4, dropout=0.0)
        )
        l1, g1 = loss_and_gradsum(
            GNNConfig(hidden=16, num_heads=2, node_embed_dim=4, dropout=0.0,
                      gather_fn=gf)
        )
        assert abs(l0 - l1) / max(abs(l0), 1e-6) < 1e-4
        assert abs(g0 - g1) / max(g0, 1e-6) < 1e-3


# ---------------------------------------------------------------------------
# DF012 dtype/shape contracts: kernel outputs vs the declared registry
# (dragonfly2_tpu/records/contracts.py) — kernel and contract cannot drift
# apart, for the edge shapes that historically break pads/buckets: empty
# segment sets, a single segment, and bf16 inputs.
# ---------------------------------------------------------------------------


class TestOpsDtypeContracts:
    def _contract(self, key):
        from dragonfly2_tpu.records.contracts import CONTRACTS

        return CONTRACTS[key]

    def test_registry_matches_live_dfc1_columns(self):
        """The declared-once registry and the live featurizer must agree
        on the DFC1 column schema — renaming/reordering/widening a column
        without updating records/contracts.py fails by name here."""
        from dragonfly2_tpu.records.features import DOWNLOAD_COLUMNS, TOPO_COLUMNS

        dl = self._contract("dfc1.download")
        assert list(DOWNLOAD_COLUMNS) == dl["columns"]
        assert np.dtype(dl["dtype"]) == np.float32
        topo = self._contract("dfc1.topology")
        assert list(TOPO_COLUMNS) == topo["columns"]

    def test_registry_matches_columnar_defaults(self):
        from dragonfly2_tpu.records.columnar import ColumnarHeader, ColumnarWriter
        import inspect

        want = self._contract("dfc1.file")["defaults"]
        assert ColumnarHeader(columns=("a",)).dtype == want["ColumnarHeader.dtype"]
        sig = inspect.signature(ColumnarWriter.__init__)
        assert sig.parameters["dtype"].default == \
            want["ColumnarWriter.__init__.dtype"]

    def test_registry_matches_featcache_slot_dtypes(self):
        from dragonfly2_tpu.scheduler.featcache import HostFeatureCache

        cache = HostFeatureCache(max_hosts=8)
        attrs = self._contract("featcache.slots")["attrs"]
        for attr_path, want in attrs.items():
            attr = attr_path.split(".", 1)[1]
            assert getattr(cache, attr).dtype == np.dtype(want), attr_path

    def test_segment_sum_empty_edge_stream(self):
        """Zero edges: every segment must come back an exact zero row of
        the contract dtype (the all-padding block still zero-inits)."""
        want_dtype = np.dtype(self._contract("ops.segment_sum")["dtype"])
        vals = np.zeros((0, 8), np.float32)
        ids = np.zeros(0, np.int64)
        out = np.asarray(
            segment_sum_pallas(jnp.asarray(vals), ids, 64, interpret=True)
        )
        assert out.shape == (64, 8)
        assert out.dtype == want_dtype
        assert not out.any()

    def test_segment_sum_single_segment(self):
        """Every edge lands in one segment: sum parity with numpy and the
        contract dtype, others exactly zero."""
        want_dtype = np.dtype(self._contract("ops.segment_sum")["dtype"])
        rng = np.random.default_rng(3)
        vals = rng.normal(size=(37, 8)).astype(np.float32)
        ids = np.full(37, 5, np.int64)
        out = np.asarray(
            segment_sum_pallas(jnp.asarray(vals), ids, 16, exact=True,
                               interpret=True)
        )
        assert out.dtype == want_dtype
        np.testing.assert_allclose(out[5], vals.sum(axis=0), rtol=1e-5)
        mask = np.ones(16, bool)
        mask[5] = False
        assert not out[mask].any()

    def test_segment_sum_bf16_values_accumulate_f32(self):
        """bf16 values (the allowed native-MXU mode) must still ACCUMULATE
        and return in the contract float32 — the allow-list covers the
        multiplicand cast, never the output."""
        c = self._contract("ops.segment_sum")
        assert "bfloat16" in c["allow"]
        rng = np.random.default_rng(4)
        vals = rng.normal(size=(64, 8)).astype(np.float32)
        ids = rng.integers(0, 10, 64)
        out = np.asarray(
            segment_sum_pallas(
                jnp.asarray(vals, jnp.bfloat16), ids, 10, exact=False,
                interpret=True,
            )
        )
        assert out.dtype == np.dtype(c["dtype"])
        want = np.asarray(segment_sum(jnp.asarray(vals), jnp.asarray(ids), 10))
        np.testing.assert_allclose(out, want, rtol=3e-2, atol=3e-2)

    def test_transpose_gather_contract_dtypes_and_edges(self):
        """TransposeTable carries int32 positions + float32 masks per the
        registry; empty-mask (no real edges) and single-node tables build
        and differentiate without spill garbage."""
        from dragonfly2_tpu.ops.transpose_gather import (
            build_transpose_table,
            make_transpose_gather,
        )

        c = self._contract("ops.transpose_gather")
        # Empty: all-padding mask.
        idx = np.zeros((4, 3), np.int64)
        tt = build_transpose_table(idx, np.zeros((4, 3), np.float32), 4)
        assert np.asarray(tt.tmask).dtype == np.dtype(c["dtype"])
        assert np.asarray(tt.tidx).dtype == np.int32
        assert not np.asarray(tt.tmask).any()
        assert tt.over_pos.shape[0] == 0

        # Single node, self-loops: gradient of sum(gather) is the
        # out-degree per node, in the contract dtype.
        idx1 = np.zeros((1, 2), np.int64)
        mask1 = np.ones((1, 2), np.float32)
        g = make_transpose_gather(idx1, mask1, 1)
        table = jnp.asarray(np.ones((1, 4), np.float32))

        def loss(t):
            return g(t).sum()

        grad = np.asarray(jax.grad(loss)(table))
        assert grad.dtype == np.dtype(c["dtype"])
        np.testing.assert_allclose(grad, np.full((1, 4), 2.0, np.float32))

    def test_transpose_gather_bf16_table(self):
        """A bf16 parameter table must round-trip the VJP in bf16 (the
        cotangent cast matches the primal dtype — no silent f32 widening
        of gradients into the optimizer)."""
        from dragonfly2_tpu.ops.transpose_gather import make_transpose_gather

        rng = np.random.default_rng(5)
        idx = rng.integers(0, 8, (8, 4))
        mask = (rng.random((8, 4)) > 0.3).astype(np.float32)
        g = make_transpose_gather(idx, mask, 8)
        table = jnp.asarray(rng.normal(size=(8, 16)), jnp.bfloat16)

        def loss(t):
            return g(t).astype(jnp.float32).sum()

        grad = jax.grad(loss)(table)
        assert grad.dtype == jnp.bfloat16
        assert grad.shape == (8, 16)


class TestFusedGatherScore:
    """ops/pallas_score.py: fused slot-row gather + mask-folded MLP
    scoring over the columnar host store (DESIGN.md §18) — jnp fallback,
    the real pallas kernel in interpret mode, and the rule-arm matvec."""

    def _weights(self, seed=0, dims=(32, 64, 64, 1)):
        rng = np.random.default_rng(seed)
        return [
            (
                rng.standard_normal((dims[i], dims[i + 1])).astype(np.float32) * 0.3,
                rng.standard_normal(dims[i + 1]).astype(np.float32) * 0.05,
            )
            for i in range(len(dims) - 1)
        ]

    def _serving(self, n_hosts=120, seed=3, max_hosts=512):
        from dragonfly2_tpu.scheduler import HostFeatureCache, MLEvaluator
        from dragonfly2_tpu.sim.swarm import build_announce_swarm
        from dragonfly2_tpu.trainer.export import MLPScorer

        task, peers = build_announce_swarm(n_hosts, seed=seed)
        cache = HostFeatureCache(max_hosts=max_hosts)
        weights = self._weights(seed)
        ref = MLPScorer(weights=weights)
        ml_ref = MLEvaluator(ref, feature_cache=cache)
        return task, peers, cache, weights, ref, ml_ref

    def test_fused_fallback_ordering_equals_numpy_scorer(self):
        from dragonfly2_tpu.ops.pallas_score import FusedMLPScorer
        from dragonfly2_tpu.scheduler import MLEvaluator

        task, peers, cache, weights, ref, ml_ref = self._serving()
        fused = FusedMLPScorer(cache, weights, use_pallas=False)
        ml_fused = MLEvaluator(fused, feature_cache=cache)
        rng = np.random.default_rng(11)
        for _ in range(12):
            ci = int(rng.integers(0, len(peers)))
            cand = [int(c) if c < ci else int(c) + 1
                    for c in rng.choice(len(peers) - 1, size=24, replace=False)]
            child, parents = peers[ci], [peers[c] for c in cand]
            a = [p.id for p in ml_ref.evaluate_parents(
                parents, child, task.total_piece_count)]
            b = [p.id for p in ml_fused.evaluate_parents(
                parents, child, task.total_piece_count)]
            assert a == b

    def test_pallas_kernel_interpret_matches_fallback(self):
        from dragonfly2_tpu.ops.pallas_score import FusedMLPScorer

        task, peers, cache, weights, ref, ml_ref = self._serving(n_hosts=60)
        # Bind everyone, then score the same slots through both modes.
        cache.gather([p.host for p in peers])
        fb = FusedMLPScorer(cache, weights, use_pallas=False)
        kern = FusedMLPScorer(cache, weights, use_pallas=True, interpret=True,
                              cand_block=8)
        edge, slots, cslot, _, _ = ml_ref._featurize_slots(
            peers[1:25], peers[0]
        )
        dst = np.full(len(slots), cslot, dtype=np.int64)
        a = fb.score(edge, src_buckets=slots, dst_buckets=dst)
        b = kern.score(edge, src_buckets=slots, dst_buckets=dst)
        assert a.dtype == b.dtype == np.float32
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-5)
        # And both agree with the numpy serving scorer to float tolerance
        # (sum order differs across the three partial matmuls).
        feats, _, _ = ml_ref._featurize_batch(peers[1:25], peers[0])
        want = ref.score(feats)
        np.testing.assert_allclose(a, want, rtol=1e-4, atol=1e-4)

    def test_mask_folding_post_hoc_columns_have_no_effect(self):
        from dragonfly2_tpu.ops.pallas_score import fold_post_hoc_weights
        from dragonfly2_tpu.records.features import POST_HOC_FEATURE_IDX
        from dragonfly2_tpu.trainer.export import MLPScorer

        weights = self._weights(5)
        folded = fold_post_hoc_weights(weights)
        for i in POST_HOC_FEATURE_IDX:
            assert np.all(folded[0][0][i] == 0.0)
        rng = np.random.default_rng(5)
        x = rng.standard_normal((16, 32)).astype(np.float32)
        x2 = np.array(x, copy=True)
        x2[:, list(POST_HOC_FEATURE_IDX)] = rng.standard_normal(
            (16, len(POST_HOC_FEATURE_IDX))
        ).astype(np.float32)
        s = MLPScorer(weights=folded, post_hoc_masked=False)
        assert np.array_equal(s.score(x), s.score(x2))

    def test_padding_rows_do_not_bleed(self):
        from dragonfly2_tpu.ops.pallas_score import FusedMLPScorer

        task, peers, cache, weights, ref, ml_ref = self._serving(n_hosts=40)
        cache.gather([p.host for p in peers])
        fused = FusedMLPScorer(cache, weights, use_pallas=False, cand_block=16)
        edge, slots, cslot, _, _ = ml_ref._featurize_slots(peers[1:8], peers[0])
        dst = np.full(len(slots), cslot, dtype=np.int64)
        a = fused.score(edge, src_buckets=slots, dst_buckets=dst)   # n=7 → pad 16
        assert a.shape == (7,)
        # Same rows inside a differently-padded call score identically.
        edge2, slots2, cslot2, _, _ = ml_ref._featurize_slots(
            peers[1:20], peers[0]
        )
        dst2 = np.full(len(slots2), cslot2, dtype=np.int64)
        b = fused.score(edge2, src_buckets=slots2, dst_buckets=dst2)[:7]
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)

    def test_mirror_resyncs_on_column_writes(self):
        from dragonfly2_tpu.ops.pallas_score import FusedMLPScorer

        task, peers, cache, weights, ref, ml_ref = self._serving(n_hosts=30)
        cache.gather([p.host for p in peers])
        fused = FusedMLPScorer(cache, weights, use_pallas=False)
        edge, slots, cslot, _, _ = ml_ref._featurize_slots(peers[1:9], peers[0])
        dst = np.full(len(slots), cslot, dtype=np.int64)
        before = fused.score(edge, src_buckets=slots, dst_buckets=dst)
        ver = fused._mat_version
        # Announce-path write-through moves the store's row version; the
        # next flush re-uploads the mirror and the scores move.
        for p in peers[1:9]:
            p.host.upload_count += 50
        after = fused.score(edge, src_buckets=slots, dst_buckets=dst)
        assert fused._mat_version != ver
        assert not np.array_equal(before, after)

    def test_from_scorer_rejects_standardized_artifacts(self):
        from dragonfly2_tpu.ops.pallas_score import FusedMLPScorer
        from dragonfly2_tpu.scheduler import HostFeatureCache
        from dragonfly2_tpu.trainer.export import MLPScorer

        s = MLPScorer(
            weights=self._weights(1),
            feat_mean=np.zeros(32, np.float32),
            feat_std=np.ones(32, np.float32),
        )
        with pytest.raises(ValueError):
            FusedMLPScorer.from_scorer(HostFeatureCache(max_hosts=8), s)

    def test_rule_weighted_sum_matches_numpy(self):
        from dragonfly2_tpu.ops.pallas_score import (
            RULE_COMPONENT_WEIGHTS,
            rule_weighted_sum,
        )

        rng = np.random.default_rng(9)
        comp = rng.standard_normal((37, 6)).astype(np.float32)
        want = comp @ np.asarray(RULE_COMPONENT_WEIGHTS, np.float32)
        got_fb = rule_weighted_sum(comp, use_pallas=False)
        got_kern = rule_weighted_sum(comp, interpret=True)
        assert got_fb.dtype == got_kern.dtype == np.float32
        np.testing.assert_allclose(got_fb, want, rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(got_kern, want, rtol=1e-6, atol=1e-6)

    def test_quantized_scorer_dtypes_and_roundtrip(self):
        """The int8/bf16 quantized blob (scorer.quantized contract):
        payload dtypes, scale stamping next to drift histograms, exact
        dequantized-score roundtrip through the blob."""
        from dragonfly2_tpu.trainer.export import (
            MLPScorer,
            QuantizedMLPScorer,
            feature_snapshot_stats,
            load_scorer,
            quantize_scorer,
            scorer_to_bytes,
        )

        rng = np.random.default_rng(4)
        rows = rng.standard_normal((400, 32)).astype(np.float32)
        edges, fracs = feature_snapshot_stats(rows)
        base = MLPScorer(weights=self._weights(4), train_bin_edges=edges,
                         train_bin_fracs=fracs)
        want = base.score(rows)
        for mode, payload_dtype in (("int8", np.int8), ("bf16", np.uint16)):
            q = quantize_scorer(base, mode)
            assert q.model_type == f"mlp_{mode}"
            for payload, scale in q.qlayers:
                assert payload.dtype == payload_dtype
                if mode == "int8":
                    assert scale.dtype == np.float32
            for w, b in q.weights:
                assert w.dtype == np.float32 and b.dtype == np.float32
            got = q.score(rows)
            rel = np.max(np.abs(got - want)) / np.max(np.abs(want))
            assert rel < 0.05  # quantization error is bounded, not zero
            q2 = load_scorer(scorer_to_bytes(q))
            assert isinstance(q2, QuantizedMLPScorer)
            assert q2.quant_mode == mode
            assert np.array_equal(q2.score(rows), got)  # blob-exact
            assert np.array_equal(q2.train_bin_edges, edges)  # scales ride
            assert np.array_equal(q2.train_bin_fracs, fracs)  # w/ the drift baseline
