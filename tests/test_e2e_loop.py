"""End-to-end loop test (the milestone the reference never reached):

swarm sim → scheduler service → Download/topology records → announcer →
trainer → MLP+GNN trained → models in registry → activation → scheduler's
ML evaluator hot-swaps the scorer → learned ranking beats the rule-based
evaluator on ground-truth bandwidth.

Reference call stacks being exercised: SURVEY §3.1 (record birth),
§3.3 (probe loop), §3.4 (train loop — stubbed there, real here).
"""

import numpy as np
import pytest

from dragonfly2_tpu.manager import ClusterManager, ModelRegistry, ModelState
from dragonfly2_tpu.records.storage import Storage
from dragonfly2_tpu.scheduler import Announcer, Evaluator, MLEvaluator, ModelSubscriber
from dragonfly2_tpu.sim import SwarmConfig, SwarmSimulator
from dragonfly2_tpu.trainer.service import (
    GNN_MODEL_NAME,
    MLP_MODEL_NAME,
    TrainerService,
)
from dragonfly2_tpu.trainer.train import TrainConfig


@pytest.fixture(scope="module")
def loop_artifacts(tmp_path_factory):
    """Run the whole pipeline once; individual tests assert on the pieces."""
    root = tmp_path_factory.mktemp("e2e")
    storage = Storage(str(root / "scheduler-records"), buffer_size=50)
    sim = SwarmSimulator(storage, config=SwarmConfig(num_hosts=40, seed=7))

    # 1. Workload: downloads + probe rounds produce training data.
    sim.run_downloads(300, tasks=10)
    sim.run_probe_rounds(rounds=2)
    n_topo_records = sim.snapshot_topology()
    storage.flush()

    # 2. Train: announcer ships datasets to the trainer, which trains and
    #    registers models with the manager.
    registry = ModelRegistry()
    cluster_mgr = ClusterManager()
    trainer = TrainerService(
        registry,
        train_config=TrainConfig(epochs=25, learning_rate=3e-3, warmup_steps=20),
    )
    announcer = Announcer(
        "scheduler-1",
        storage,
        trainer,
        cluster_manager=cluster_mgr,
        ip="10.0.0.1",
        hostname="sched-1",
    )
    announcer.announce_to_manager()
    run_key = announcer.announce_to_trainer()
    run = trainer.runs[run_key]
    return {
        "sim": sim,
        "storage": storage,
        "registry": registry,
        "cluster_mgr": cluster_mgr,
        "trainer": trainer,
        "run": run,
        "n_topo_records": n_topo_records,
    }


class TestRecordProduction:
    def test_downloads_recorded(self, loop_artifacts):
        st = loop_artifacts["storage"]
        assert st.download_count >= 300
        downloads = st.list_download()
        with_parents = [d for d in downloads if d.parents]
        assert with_parents, "no download records carry parents"
        d = with_parents[0]
        assert d.parents[0].pieces, "parent entry lost its piece costs"
        assert d.parents[0].observed_bandwidth() > 0

    def test_topology_recorded(self, loop_artifacts):
        assert loop_artifacts["n_topo_records"] > 0
        assert loop_artifacts["storage"].network_topology_count > 0


class TestTrainRun:
    def test_run_succeeded(self, loop_artifacts):
        run = loop_artifacts["run"]
        assert run.error is None
        assert run.download_rows > 200
        assert run.topology_rows > 0
        assert len(run.models) == 2

    def test_mlp_metrics_meaningful(self, loop_artifacts):
        m = loop_artifacts["run"].metrics[MLP_MODEL_NAME]
        # log-space MAE must beat the predict-the-mean strawman by a margin.
        assert m.mae < 0.8, m
        assert m.f1 > 0.5, m

    def test_gnn_registered_with_metrics(self, loop_artifacts):
        reg = loop_artifacts["registry"]
        models = reg.list(scheduler_id="scheduler-1", name=GNN_MODEL_NAME)
        assert len(models) == 1
        assert "mae" in models[0].evaluation


class TestRegistryActivation:
    def test_single_active_per_name(self, loop_artifacts):
        reg = loop_artifacts["registry"]
        mlp = reg.list(scheduler_id="scheduler-1", name=MLP_MODEL_NAME)[0]
        reg.activate(mlp.id)
        # A second version created + activated deactivates the first.
        art = reg.load_artifact(mlp)
        m2 = reg.create_model(
            name=MLP_MODEL_NAME,
            type="mlp",
            scheduler_id="scheduler-1",
            artifact=art,
            evaluation={"mae": 0.0},
        )
        reg.activate(m2.id)
        states = {
            m.version: m.state
            for m in reg.list(scheduler_id="scheduler-1", name=MLP_MODEL_NAME)
        }
        assert states[m2.version] is ModelState.ACTIVE
        assert states[mlp.version] is ModelState.INACTIVE
        # Reactivate v1 for downstream tests.
        reg.activate(mlp.id)

    def test_keepalive_tracking(self, loop_artifacts):
        cm = loop_artifacts["cluster_mgr"]
        assert [s.id for s in cm.active_schedulers()] == ["scheduler-1"]


class TestMLEvaluatorLoop:
    def test_subscriber_hot_swaps_scorer(self, loop_artifacts):
        reg = loop_artifacts["registry"]
        mlp = reg.list(scheduler_id="scheduler-1", name=MLP_MODEL_NAME)[0]
        reg.activate(mlp.id)
        ev = MLEvaluator()
        sub = ModelSubscriber(reg, ev, scheduler_id="scheduler-1")
        assert sub.refresh() is True
        assert ev.has_model
        # Deactivate → falls back to rules.
        reg.deactivate(mlp.id)
        assert sub.refresh() is True
        assert not ev.has_model
        reg.activate(mlp.id)

    def test_learned_ranking_beats_rules(self, loop_artifacts):
        reg = loop_artifacts["registry"]
        sim = loop_artifacts["sim"]
        mlp = reg.list(scheduler_id="scheduler-1", name=MLP_MODEL_NAME)[0]
        reg.activate(mlp.id)
        ml_ev = MLEvaluator()
        ModelSubscriber(reg, ml_ev, scheduler_id="scheduler-1").refresh()
        assert ml_ev.has_model

        rules_bw = sim.measure_parent_choice_quality(Evaluator(), n_trials=60)
        ml_bw = sim.measure_parent_choice_quality(ml_ev, n_trials=60)
        # BASELINE configs[2]: the learned evaluator must beat the
        # rule-based one on achieved bandwidth of the chosen parent.
        assert ml_bw > rules_bw, (ml_bw, rules_bw)


class TestGNNServing:
    def test_gnn_scorer_artifact_serves(self, loop_artifacts):
        """The GNN model's artifact is a real scorer: embedding-table lookup
        + head, loadable by the subscriber and usable for ranking."""
        reg = loop_artifacts["registry"]
        sim = loop_artifacts["sim"]
        gnn = reg.list(scheduler_id="scheduler-1", name=GNN_MODEL_NAME)[0]
        assert len(reg.load_artifact(gnn)) > 0
        reg.activate(gnn.id)
        ev = MLEvaluator()
        sub = ModelSubscriber(
            reg, ev, scheduler_id="scheduler-1", model_name=GNN_MODEL_NAME
        )
        assert sub.refresh() is True
        assert ev.has_model
        quality = sim.measure_parent_choice_quality(ev, n_trials=40)
        assert np.isfinite(quality) and quality > 0
