"""Subprocess body for the mid-tee SIGKILL drill
(tests/test_stream_tee.py).

A wire daemon that opens a PASS-THROUGH stream (open_stream → tee
consumer) of a task it is downloading from the parent's piece server,
and consumes the chunks slowly.  The parent test installs a ``crash``
FaultSpec on the ``daemon.stream.tee`` seam (DF_FAULTINJECT), so the
process SIGKILLs itself ON THE COMMITTER THREAD, mid-publish,
mid-download, mid-serve — the worst interleaving the tee can die in.
The parent then proves the durable plane is untouched: a fresh
conductor over the same store resumes the download, completes, and the
reassembled bytes digest-check against the origin.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from dragonfly2_tpu.utils import faultinject  # noqa: E402


def main():
    scheduler_url, store_dir, url = sys.argv[1:4]
    content_length, piece_size = int(sys.argv[4]), int(sys.argv[5])
    faultinject.install_from_env()

    from dragonfly2_tpu.daemon import DaemonStorage
    from dragonfly2_tpu.daemon.conductor import Conductor
    from dragonfly2_tpu.rpc import HTTPPieceFetcher, RemoteScheduler
    from dragonfly2_tpu.scheduler.resource import Host

    host = Host(
        id="stream-child", hostname="stream-child", ip="127.0.0.1",
        port=8002, download_port=1,
    )
    host.stats.network.idc = "idc-a"
    client = RemoteScheduler(scheduler_url, timeout=5.0)
    storage = DaemonStorage(store_dir, prefer_native=False)
    conductor = Conductor(
        host, storage, client,
        piece_fetcher=HTTPPieceFetcher(client.resolve_host, timeout=5.0),
        source_fetcher=None,
        piece_parallelism=1,  # strictly sequential: the kill lands mid-task
    )
    print("stream-child: ready", flush=True)
    handle = conductor.open_stream(
        url, piece_size=piece_size, content_length=content_length
    )
    got = 0
    for chunk in handle.chunks():
        got += len(chunk)
    # Reaching here means the crash fault never fired (drill failure —
    # the parent asserts this line is absent).
    print(json.dumps({"ok": True, "bytes": got}), flush=True)


if __name__ == "__main__":
    main()
