"""Flight-recorder chaos drill (ISSUE 10 acceptance): SIGKILL a daemon
mid-download, then reconstruct the end-to-end trace from the surviving
per-process logs.

Topology: the scheduler runs IN-PROCESS with its own durable trace log
(handler spans land there); a warm parent daemon serves the piece plane
over HTTP; the downloading daemon is a REAL subprocess
(tests/_trace_child.py) with its own trace log, SIGKILLed by a
deterministic crash fault on its Nth ``report_piece_finished`` RPC —
mid-download, mid-trace.

Proven:

- ``tools/trace_assemble.py`` stitches the two surviving logs into ONE
  trace spanning both services, critical path rendered;
- no torn frame admitted: every replayed frame passed its digest, and
  every admitted batch validates against the vendored OTLP schema
  (``--validate``);
- the kill's signature is visible as anomalies: the child's unexported
  download/worker spans leave orphans behind.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from dragonfly2_tpu.utils import tracing  # noqa: E402
from dragonfly2_tpu.utils.faultinject import FaultSpec  # noqa: E402

PIECE = 32 * 1024
N_PIECES = 8


class _Origin:
    def fetch(self, url, number, piece_size):
        seed = number & 0xFF
        return bytes((seed + i) % 251 for i in range(PIECE))


class TestFlightRecorderKillDrill:
    def test_sigkill_mid_download_trace_reassembles(self, tmp_path):
        from dragonfly2_tpu.daemon import DaemonStorage, UploadManager
        from dragonfly2_tpu.daemon.conductor import Conductor
        from dragonfly2_tpu.records.storage import Storage
        from dragonfly2_tpu.rpc import (
            HTTPPieceFetcher,
            PieceHTTPServer,
            RemoteScheduler,
            SchedulerHTTPServer,
        )
        from dragonfly2_tpu.scheduler import (
            Evaluator,
            NetworkTopology,
            Resource,
            SchedulerService,
            Scheduling,
            SchedulingConfig,
        )
        from dragonfly2_tpu.scheduler.resource import Host

        resource = Resource()
        service = SchedulerService(
            resource,
            Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)),
            Storage(str(tmp_path / "records"), buffer_size=1),
            NetworkTopology(resource.host_manager),
        )
        server = SchedulerHTTPServer(service)
        server.serve()

        url = "drill://flight-recorder/blob"
        content_length = N_PIECES * PIECE

        # Warm parent (pieces on disk + registered with the scheduler)
        # BEFORE the drill exporter installs — its spans stay out of the
        # drill's logs.
        pstore = DaemonStorage(str(tmp_path / "parent"), prefer_native=False)
        upload = UploadManager(pstore)
        piece_server = PieceHTTPServer(upload)
        piece_server.serve()
        phost = Host(
            id="trace-parent", hostname="trace-parent", ip="127.0.0.1",
            download_port=piece_server.port,
        )
        phost.stats.network.idc = "idc-a"
        pclient = RemoteScheduler(server.url, timeout=5.0)
        parent = Conductor(
            phost, pstore, pclient,
            piece_fetcher=HTTPPieceFetcher(pclient.resolve_host),
            source_fetcher=_Origin(),
        )
        warm = parent.download(
            url, piece_size=PIECE, content_length=content_length
        )
        assert warm.ok and warm.pieces == N_PIECES

        sched_log = str(tmp_path / "scheduler.dftrace")
        child_log = str(tmp_path / "daemon.dftrace")
        prev_exporter = tracing.default_tracer.exporter
        drill_exporter = tracing.DurableSpanExporter(
            sched_log, service="scheduler", sample_rate=1.0
        )
        tracing.default_tracer.exporter = drill_exporter
        try:
            scenario = {
                "seed": 0,
                "faults": [
                    # Piece reports ride the batched RPC now; the child
                    # runs linger 0 so flushes track pieces closely and
                    # the 3rd flush lands mid-download.
                    FaultSpec(
                        site="rpc.client.report_pieces_finished",
                        kind="crash", at=(2,),
                    ).to_dict(),
                    # Pace fetches well below flush cadence: the kill
                    # must land with pieces still on the wire, not after
                    # a loopback burst fetched everything.
                    FaultSpec(
                        site="piece.fetch", kind="delay", every=1,
                        delay_s=0.05,
                    ).to_dict(),
                ],
            }
            proc = subprocess.Popen(
                [
                    sys.executable, str(REPO / "tests" / "_trace_child.py"),
                    server.url, str(tmp_path / "childstore"), child_log,
                    url, str(content_length), str(PIECE),
                ],
                env={
                    **os.environ,
                    "DF_FAULTINJECT": json.dumps(scenario),
                    "JAX_PLATFORMS": "cpu",
                    "DF_LOCK_WITNESS": "0",
                },
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                cwd=str(REPO),
            )
            try:
                out, err = proc.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                proc.kill()
                out, err = proc.communicate()
                pytest.fail(f"child hung: {out!r} {err!r}")
            # The crash fault SIGKILLs the child mid-download.
            assert proc.returncode == -signal.SIGKILL, (
                proc.returncode, out, err,
            )
            assert b'"ok"' not in out, "child finished before the kill"
            # Let in-flight scheduler handler spans close + export.
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if drill_exporter.exported >= 3:
                    break
                time.sleep(0.05)
        finally:
            tracing.default_tracer.exporter = prev_exporter
            drill_exporter.close()
            piece_server.stop()
            server.stop()

        from tools.trace_assemble import build_report, render_report

        # --validate semantics: every admitted frame passes the vendored
        # OTLP schema; a digest-bad frame would not be admitted at all.
        report = build_report([sched_log, child_log], validate=True)
        for log in report["logs"]:
            assert log["corrupt"] == 0, log    # no torn frame admitted
            assert log["frames"] > 0, log      # both processes left spans
        trace = report["trace"]
        # ONE trace id spans the killed daemon and the scheduler.
        assert set(trace["services"]) == {"dfdaemon", "scheduler"}
        # Cross-process reconstruction: the child's piece spans and the
        # scheduler's handler spans share the trace.
        assert "piece" in trace["phases"], trace["phases"]
        assert any(
            p.startswith(("schedule", "commit", "rpc"))
            for p in trace["phases"]
        ), trace["phases"]
        # Critical path rendered from the surviving spans.
        assert trace["critical_path"], trace
        # The kill's signature: the child's download/worker spans never
        # exported, so their children are orphans.
        assert any("orphan" in a for a in trace["anomalies"]), trace["anomalies"]
        # And the human rendering holds the whole story.
        rendered = render_report(report)
        assert "Critical path:" in rendered and "Anomalies:" in rendered

        # The child really died mid-download: strictly fewer than
        # N_PIECES piece spans made it to the durable log.
        child_spans = list(
            tracing.log_spans(tracing.replay_trace_log(child_log)[0])
        )
        piece_spans = [
            s for s in child_spans if s["name"] == "daemon/piece"
        ]
        assert 0 < len(piece_spans) < N_PIECES
