"""In-engine C++ HTTP piece server (native.cpp ps_serve): wire parity
with the Python PieceHTTPServer — same paths, same status codes — plus
the factory's selection logic.

Reference: client/daemon/upload/upload_manager.go:59-76 (compiled piece
serving is the perf-critical data plane, SURVEY §2 'no Python stand-ins').
"""

import urllib.error
import urllib.request

import pytest

from dragonfly2_tpu import native
from dragonfly2_tpu.daemon import DaemonStorage, UploadManager
from dragonfly2_tpu.rpc.piece_transport import (
    HTTPPieceFetcher,
    NativePieceServer,
    PieceHTTPServer,
    make_piece_server,
)

PIECE = 64 * 1024

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native engine unavailable"
)


@pytest.fixture()
def served(tmp_path):
    storage = DaemonStorage(str(tmp_path / "store"), prefer_native=True)
    assert storage.is_native
    upload = UploadManager(storage)
    task = "t" * 16
    storage.register_task(task, piece_size=PIECE, content_length=4 * PIECE - 100)
    pieces = []
    for n in range(4):
        size = PIECE if n < 3 else PIECE - 100
        data = bytes((n * 17 + i) % 256 for i in range(size))
        pieces.append(data)
        storage.write_piece(task, n, data)
    server = NativePieceServer(upload)
    yield {"server": server, "task": task, "pieces": pieces, "storage": storage}
    server.stop()
    storage.close()
    # A wedged shutdown used to be a stderr print nobody read; now it is
    # a process-global counter (ps_leak_stats) this teardown turns into
    # a hard failure.
    assert native.leaked_servers() == (0, 0)


class TestNativePieceServer:
    def test_piece_fetch_via_production_fetcher(self, served):
        fetcher = HTTPPieceFetcher(
            lambda hid: ("127.0.0.1", served["server"].port)
        )
        for n, want in enumerate(served["pieces"]):
            assert fetcher.fetch("h", served["task"], n) == want

    def test_bitmap(self, served):
        fetcher = HTTPPieceFetcher(
            lambda hid: ("127.0.0.1", served["server"].port)
        )
        bm = fetcher.piece_bitmap("h", served["task"])
        assert bytes(bm) == b"\x01\x01\x01\x01"

    def test_range_request(self, served):
        port = served["server"].port
        blob = b"".join(served["pieces"])
        for rng, want in [
            ("bytes=0-99", blob[:100]),
            (f"bytes={PIECE - 10}-{PIECE + 9}", blob[PIECE - 10: PIECE + 10]),
            ("bytes=-50", blob[-50:]),
            (f"bytes={len(blob) - 20}-", blob[-20:]),
        ]:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/tasks/{served['task']}",
                headers={"Range": rng},
            )
            with urllib.request.urlopen(req, timeout=5) as resp:
                assert resp.status == 206
                assert resp.read() == want, rng

    def test_missing_piece_404(self, served):
        port = served["server"].port
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/pieces/{served['task']}/9", timeout=5
            )
        assert exc.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/pieces/ghost/0", timeout=5
            )
        assert exc.value.code == 404

    def test_bitmap_long_poll(self, served):
        """?have=N&wait_ms=M defers the bitmap until a new piece commits
        (Python-server wire parity; synchronizer subscription)."""
        import threading
        import time

        port = served["server"].port
        task = served["task"]
        held = len(served["pieces"])

        # All pieces held already → the window elapses, bitmap returned.
        t0 = time.monotonic()
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/tasks/{task}/pieces?have={held}&wait_ms=300",
            timeout=5,
        ) as resp:
            bm = resp.read()
        assert time.monotonic() - t0 >= 0.25
        assert sum(bm) == held

        # A piece landing mid-window releases the poll promptly.
        storage = served["storage"]
        t2 = "u" * 16
        storage.register_task(t2, piece_size=PIECE, content_length=2 * PIECE)

        def commit_late():
            time.sleep(0.1)
            storage.write_piece(t2, 0, b"q" * PIECE)

        threading.Thread(target=commit_late).start()
        t0 = time.monotonic()
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/tasks/{t2}/pieces?have=0&wait_ms=5000",
            timeout=10,
        ) as resp:
            bm = resp.read()
        assert time.monotonic() - t0 < 2.0
        assert list(bm) == [1, 0]

    def test_path_traversal_rejected(self, served):
        """Network-supplied task components must stay inside the store
        root (ADVICE r2: GET /pieces/../N reached <root>/../meta).  Raw
        socket — urllib would normalize the dot segments away."""
        import socket

        port = served["server"].port
        for path, codes in (
            ("/pieces/../0", (b"404",)),
            ("/pieces/./0", (b"404",)),
            ("/tasks/../pieces", (b"404",)),
            # Rangeless /tasks/<id> 416s for unknown ids (parity with the
            # Python server); the invariant is "never 200, never opens
            # outside the root".
            ("/tasks/..", (b"404", b"416")),
            ("/tasks/.", (b"404", b"416")),
        ):
            sock = socket.create_connection(("127.0.0.1", port), timeout=5)
            sock.sendall(
                f"GET {path} HTTP/1.1\r\nHost: x\r\n"
                f"Connection: close\r\n\r\n".encode()
            )
            status = sock.makefile("rb").readline()
            assert any(c in status for c in codes), (path, status)
            sock.close()

    def test_bad_range_416(self, served):
        port = served["server"].port
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/tasks/{served['task']}",
            headers={"Range": "bytes=zz-5"},
        )
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=5)
        assert exc.value.code == 416

    def test_keep_alive_multiple_requests_one_connection(self, served):
        import socket

        port = served["server"].port
        sock = socket.create_connection(("127.0.0.1", port), timeout=5)
        f = sock.makefile("rb")
        for n in (0, 1, 2):
            sock.sendall(
                f"GET /pieces/{served['task']}/{n} HTTP/1.1\r\n"
                f"Host: x\r\n\r\n".encode()
            )
            status = f.readline()
            assert b"200" in status
            cl = 0
            while True:
                line = f.readline()
                if line == b"\r\n":
                    break
                if line.lower().startswith(b"content-length:"):
                    cl = int(line.split(b":")[1])
            body = f.read(cl)
            assert body == served["pieces"][n]
        sock.close()


class TestFactory:
    def test_native_selected_for_native_store(self, tmp_path):
        storage = DaemonStorage(str(tmp_path / "n"), prefer_native=True)
        srv = make_piece_server(UploadManager(storage))
        try:
            assert isinstance(srv, NativePieceServer)
        finally:
            srv.stop()

    def test_python_for_python_store_or_tls(self, tmp_path):
        storage = DaemonStorage(str(tmp_path / "p"), prefer_native=False)
        srv = make_piece_server(UploadManager(storage))
        assert isinstance(srv, PieceHTTPServer)
        # TLS → Python server even on a native store (native speaks
        # plain HTTP only).
        import ssl

        ctx = ssl.create_default_context(ssl.Purpose.CLIENT_AUTH)
        nstorage = DaemonStorage(str(tmp_path / "n2"), prefer_native=True)
        srv2 = make_piece_server(UploadManager(nstorage), ssl_context=ctx)
        assert isinstance(srv2, PieceHTTPServer)

    def test_bitmap_requests_exempt_from_serving_cap(self, tmp_path):
        """Long-poll subscriptions parked on a busy seed must not consume
        its piece-serving 503 slots (the data-plane cap)."""
        import threading
        import urllib.request

        storage = DaemonStorage(str(tmp_path / "cap"), prefer_native=True)
        task = "c" * 16
        storage.register_task(task, piece_size=PIECE, content_length=4 * PIECE)
        for n in range(2):
            storage.write_piece(task, n, bytes(PIECE))
        upload = UploadManager(storage)
        server = NativePieceServer(upload, concurrent_limit=2)
        try:
            port = server.port
            # Park MORE long-polls than the cap (have=4 never satisfied).
            def park():
                try:
                    urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/tasks/{task}/pieces"
                        f"?have=4&wait_ms=3000", timeout=10,
                    ).read()
                except OSError:
                    pass  # server shutdown cuts parked polls — expected

            parked = []
            for _ in range(4):
                t = threading.Thread(target=park, daemon=True)
                t.start()
                parked.append(t)
            import time

            time.sleep(0.3)  # all four are parked now
            # Piece serving still has its full budget.
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/pieces/{task}/0", timeout=5
            ) as r:
                assert r.status == 200
        finally:
            server.stop()
            storage.close()
