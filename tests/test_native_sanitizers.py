"""Sanitizer tier-1 gate for the C++ engine (DESIGN.md §30).

``make -C dragonfly2_tpu/native check/asan/tsan/ubsan`` build and run
the native self-test under each sanitizer; this module makes the RESULT
part of the Python tier-1 bar by re-running whichever instrumented
binaries are already built.  Compilation stays out of tier-1 (the asan
link alone is ~10s and needs the toolchain) — each test runs an
existing binary or skips clean, so a checkout without the build step
loses coverage but not greenness, while any tree that ran the Makefile
gates (CI does) gets the sanitizer verdicts enforced, not just logged.

The binaries exercise the full engine surface including the §30 ABI
manifest section (static_asserts compile into every build; section 7 of
native_test checks df_abi_manifest/df_abi_probe_fetchdone at runtime),
so a sanitizer hit in the witness path fails here by name.
"""

from __future__ import annotations

import os
import subprocess
from pathlib import Path

import pytest

NATIVE_DIR = Path(__file__).resolve().parent.parent / "dragonfly2_tpu" / "native"

# (binary, env the Makefile target runs it with)
GATES = {
    "plain": ("native_test", {}),
    "asan": ("native_test_asan", {"ASAN_OPTIONS": "detect_leaks=1"}),
    "tsan": ("native_test_tsan", {"TSAN_OPTIONS": "halt_on_error=1"}),
    "ubsan": (
        "native_test_ubsan",
        {"UBSAN_OPTIONS": "halt_on_error=1:print_stacktrace=1"},
    ),
}


def _run_gate(kind: str) -> None:
    binary, extra_env = GATES[kind]
    path = NATIVE_DIR / binary
    if not path.exists():
        pytest.skip(f"{binary} not built (run `make -C dragonfly2_tpu/native "
                    f"{'test' if kind == 'plain' else kind}`)")
    env = dict(os.environ, **extra_env)
    proc = subprocess.run(
        [str(path)],
        cwd=str(NATIVE_DIR),
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, (
        f"{binary} exited {proc.returncode}\n"
        f"--- stdout ---\n{proc.stdout[-4000:]}\n"
        f"--- stderr ---\n{proc.stderr[-4000:]}"
    )
    # the binary's own success marker, so a crash after the last assert
    # (or an exec of the wrong file) cannot pass on exit-code luck
    assert "native_test: OK" in proc.stdout, (
        f"{binary} exited 0 without the success marker:\n{proc.stdout[-2000:]}"
    )


class TestNativeSanitizerGates:
    def test_plain_self_test(self):
        _run_gate("plain")

    def test_asan_gate(self):
        _run_gate("asan")

    def test_tsan_gate(self):
        _run_gate("tsan")

    def test_ubsan_gate(self):
        _run_gate("ubsan")
