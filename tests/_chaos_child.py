"""Subprocess body for the trainer-crash chaos drill (tests/test_chaos.py).

Runs a small OnlineGraphTrainer over a DETERMINISTIC record stream in
per-dispatch blocks, checkpointing every dispatch.  Modes:

- ``fresh``   start from scratch and train ``total`` dispatches.  With a
  crash FaultSpec on the ``trainer.dispatch`` seam (via DF_FAULTINJECT),
  the process SIGKILLs itself at an exact dispatch index — the
  deterministic "trainer dies mid-online-ingest" event.
- ``resume``  orbax-restore from the checkpoint, SKIP the stream prefix
  the restored ``records_seen`` says was already trained (exactly-once:
  re-feeding it would duplicate records; skipping more would lose them),
  and finish the remaining dispatches.

Prints ONE JSON line: {"state_hash", "records_seen", "dispatch"} — the
parent test compares it against an uninterrupted reference run.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

# The environment may preset a TPU tunnel platform via sitecustomize; the
# env var alone cannot win (tests/conftest.py precedent) — force CPU.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from dragonfly2_tpu.utils import faultinject  # noqa: E402

N_NODES = 64
FEAT_DIM = 8
BATCH = 64
SUPER_STEPS = 2
PER_DISPATCH = SUPER_STEPS * BATCH


def build(ckpt_dir):
    from dragonfly2_tpu.trainer.online_graph import (
        OnlineGraphConfig,
        OnlineGraphTrainer,
    )

    rng = np.random.default_rng(0)
    node_feats = rng.normal(size=(N_NODES, FEAT_DIM)).astype(np.float32)
    src = rng.integers(0, N_NODES, 256).astype(np.int32)
    dst = (src + 1 + rng.integers(0, N_NODES - 1, 256).astype(np.int32)) % N_NODES
    rtt = rng.uniform(1e-3, 1e-1, 256).astype(np.float32)
    cfg = OnlineGraphConfig(
        num_nodes=N_NODES, max_neighbors=4, batch_size=BATCH,
        super_steps=SUPER_STEPS, refresh_every=0, checkpoint_every=1,
        native_ingest=False, total_steps_hint=100,
    )
    trainer = OnlineGraphTrainer(
        cfg, node_feats=node_feats, topo_src=src, topo_dst=dst, topo_rtt=rtt,
        checkpoint_dir=ckpt_dir,
    )
    return trainer, cfg


def stream_blocks(total):
    """The record stream: one seeded generator, one block per dispatch —
    byte-identical across processes and runs."""
    rng = np.random.default_rng(42)
    for _ in range(total):
        src = rng.integers(0, N_NODES, PER_DISPATCH).astype(np.int32)
        dst = (
            src + 1 + rng.integers(0, N_NODES - 1, PER_DISPATCH).astype(np.int32)
        ) % N_NODES
        y = rng.uniform(0.0, 1.0, PER_DISPATCH).astype(np.float32)
        yield src, dst, y


def run(mode, ckpt_dir, total):
    from dragonfly2_tpu.trainer.online_graph import state_hash

    trainer, _cfg = build(ckpt_dir)
    start = 0
    if mode == "resume":
        assert trainer.resume(), "resume found no checkpoint"
        assert trainer.records_seen % PER_DISPATCH == 0, trainer.records_seen
        start = trainer.records_seen // PER_DISPATCH
        print(f"chaos-child: resumed at dispatch {start}", flush=True)
    for i, (src, dst, y) in enumerate(stream_blocks(total)):
        if i < start:
            continue  # trained before the crash — re-feeding = duplicates
        trainer.feed_downloads(src, dst, y)
        trainer.run(max_dispatches=1, idle_timeout=10.0)
    return {
        "state_hash": state_hash(trainer.state),
        "records_seen": trainer.records_seen,
        "dispatch": trainer.dispatch,
    }


def main():
    faultinject.install_from_env()
    mode, ckpt_dir, total = sys.argv[1], sys.argv[2], int(sys.argv[3])
    print("chaos-child: ready", flush=True)
    print(json.dumps(run(mode, ckpt_dir, total)), flush=True)


if __name__ == "__main__":
    main()
