"""Sharded-fleet chaos drill (ISSUE 13 acceptance): SIGKILL one
scheduler shard mid-swarm.

Topology: an in-process manager (ShardDirectory publishes the ring with
the cluster dynconfig), TWO real scheduler shard subprocesses
(cli.scheduler with durable flight-recorder logs), an in-process warm
parent daemon, and a test-driven downloading client that routes by the
published ring over the real HTTP wire.

Proven:

- the victim dies by SIGKILL mid-download (returncode −9) and the
  manager's keepalive expiry bumps the ring version — the next
  ``:config`` poll publishes a one-member ring;
- the task MIGRATES: parent and child re-announce + re-register on the
  surviving shard (waiting out its own dynconfig adoption — a register
  that lands before it still steers to the dead owner and is retried),
  and the download completes with the remaining pieces;
- every completed download digest-checks against the origin bytes;
- ``tools/trace_assemble.py`` stitches the three surviving logs into
  ONE trace spanning both shards and the client, with ZERO corrupt
  frames, and renders the cross-shard handoff span on the critical
  path.
"""

from __future__ import annotations

import hashlib
import json
import re
import sys
import time
import urllib.request
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from dragonfly2_tpu.sim.chaos import ChaosProcess, sha256_hex  # noqa: E402
from dragonfly2_tpu.utils import tracing  # noqa: E402

PIECE = 32 * 1024
N_PIECES = 6


class _Origin:
    def fetch(self, url, number, piece_size):
        return bytes((number * 13 + i) % 251 for i in range(PIECE))


def _get_json(url: str) -> dict:
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read())


class TestShardKillDrill:
    def test_sigkill_shard_task_migrates_and_digest_checks(self, tmp_path):
        from dragonfly2_tpu.daemon import DaemonStorage, UploadManager
        from dragonfly2_tpu.daemon.conductor import Conductor
        from dragonfly2_tpu.manager.cluster import ClusterManager
        from dragonfly2_tpu.manager.registry import ModelRegistry
        from dragonfly2_tpu.manager.rest import ManagerRESTServer
        from dragonfly2_tpu.rpc import (
            HTTPPieceFetcher,
            PieceHTTPServer,
            RemoteScheduler,
        )
        from dragonfly2_tpu.scheduler.resource import Host
        from dragonfly2_tpu.scheduler.sharding import (
            ShardRing,
            WrongShardError,
            handoff_span,
        )
        from dragonfly2_tpu.utils import idgen

        clusters = ClusterManager()
        manager = ManagerRESTServer(ModelRegistry(), clusters)
        manager.serve()
        mgr_url = f"http://{manager.address[0]}:{manager.address[1]}"

        def spawn(i: int) -> ChaosProcess:
            cfg = tmp_path / f"shard{i}.yaml"
            cfg.write_text(
                "server: {host: 127.0.0.1, port: 0, grpc_port: -1}\n"
                "scheduling: {retry_interval_s: 0.0}\n"
                f"storage: {{dir: {tmp_path / f'rec{i}'}, buffer_size: 1}}\n"
                f"manager_addr: {mgr_url}\n"
                "dynconfig_refresh_s: 0.5\n"
                f"tracing: {{log_path: {tmp_path / f'shard{i}.dftrace'}, "
                "sample_rate: 1.0}\n"
            )
            return ChaosProcess(
                ["-m", "dragonfly2_tpu.cli.scheduler", "--config", str(cfg)],
                ready_prefixes=["scheduler: serving"],
            ).start()

        shards = [spawn(0), spawn(1)]
        piece_server = None
        client_log = str(tmp_path / "client.dftrace")
        prev_exporter = tracing.default_tracer.exporter
        try:
            urls_by_port: dict = {}
            for proc in shards:
                line = proc.wait_ready(120)["scheduler: serving"]
                rpc_url = re.search(r"rpc on (\S+)", line).group(1).rstrip(",")
                port = int(rpc_url.rsplit(":", 1)[1])
                urls_by_port[port] = rpc_url

            # Ring v1: both shards registered themselves with the
            # manager; the cluster dynconfig publishes them.
            deadline = time.monotonic() + 30
            ring_payload: dict = {}
            while time.monotonic() < deadline:
                cfg = _get_json(f"{mgr_url}/api/v1/clusters/default:config")
                ring_payload = cfg.get("scheduler_ring", {})
                if len(ring_payload.get("members", [])) == 2:
                    break
                time.sleep(0.3)
            assert len(ring_payload.get("members", [])) == 2, ring_payload
            ring = ShardRing.from_payload(ring_payload)
            id_by_url = {m["url"]: m["id"] for m in ring_payload["members"]}

            # A url whose task id the FIRST member owns: that shard is
            # the victim; the other survives.
            url, tid, victim_id = next(
                (u, t, ring.owner(t))
                for u, t in (
                    (f"drill://shard-chaos/{i}",
                     idgen.task_id(f"drill://shard-chaos/{i}"))
                    for i in range(64)
                )
            )
            victim_url = ring.url_of(victim_id)
            survivor_id = next(
                sid for sid in ring.members() if sid != victim_id
            )
            survivor_url = ring.url_of(survivor_id)
            victim_proc = shards[
                list(urls_by_port).index(int(victim_url.rsplit(":", 1)[1]))
            ]

            content_length = N_PIECES * PIECE
            want = hashlib.sha256(
                b"".join(
                    _Origin().fetch(url, n, PIECE) for n in range(N_PIECES)
                )
            ).hexdigest()

            # Warm parent on the victim shard (real daemon conductor:
            # registers, pulls from origin, reports pieces).
            pstore = DaemonStorage(str(tmp_path / "parent"),
                                   prefer_native=False)
            piece_server = PieceHTTPServer(UploadManager(pstore))
            piece_server.serve()
            phost = Host(
                id="drill-parent", hostname="drill-parent", ip="127.0.0.1",
                download_port=piece_server.port,
            )
            phost.stats.network.idc = "idc-a"
            victim_client = RemoteScheduler(victim_url, timeout=5.0)
            parent = Conductor(
                phost, pstore, victim_client,
                piece_fetcher=HTTPPieceFetcher(victim_client.resolve_host),
                source_fetcher=_Origin(),
            )
            warm = parent.download(
                url, piece_size=PIECE, content_length=content_length
            )
            assert warm.ok and warm.pieces == N_PIECES
            assert sha256_hex(pstore.read_task_bytes(tid)) == want

            # The drill's flight-recorder log for the client process.
            drill_exporter = tracing.DurableSpanExporter(
                client_log, service="dfdaemon", sample_rate=1.0
            )
            tracing.default_tracer.exporter = drill_exporter

            chost = Host(
                id="drill-child", hostname="drill-child", ip="127.0.0.1",
                download_port=0,
            )
            chost.stats.network.idc = "idc-a"
            fetch = HTTPPieceFetcher(
                lambda host_id: ("127.0.0.1", piece_server.port)
            )
            got: dict = {}
            with tracing.default_tracer.span("daemon/download", url=url):
                victim_client.announce_host(chost)
                reg = victim_client.register_peer(
                    host=chost, url=url, task_id=tid
                )
                parents = reg.schedule.parents
                assert parents, "child got no parents on the victim shard"
                for n in range(3):
                    got[n] = fetch.fetch(parents[0].host.id, tid, n)
                    victim_client.report_piece_finished(
                        reg.peer, n, parent_id=parents[0].id,
                        length=PIECE, cost_ns=10**6,
                    )

                # Mid-swarm kill: pieces 3..5 are still outstanding.
                victim_proc.sigkill()
                assert victim_proc.proc.returncode == -9

                # Keepalive expiry (deterministic): age the victim's
                # last tick out of the TTL instead of sleeping 60 s.
                with clusters._mu:
                    for inst in clusters._schedulers.values():
                        if victim_url.endswith(f":{inst.port}"):
                            inst.last_keepalive = 0.0
                deadline = time.monotonic() + 20
                while time.monotonic() < deadline:
                    cfg = _get_json(
                        f"{mgr_url}/api/v1/clusters/default:config"
                    )
                    members = cfg["scheduler_ring"]["members"]
                    if [m["id"] for m in members] == [survivor_id]:
                        break
                    time.sleep(0.3)
                assert [m["id"] for m in members] == [survivor_id], members
                ring_v2 = cfg["scheduler_ring"]["version"]
                assert ring_v2 > ring_payload["version"]

                # The cross-shard handoff, client side: re-announce the
                # swarm on the new owner and finish the download there.
                # Registers racing the survivor's own dynconfig adoption
                # still steer to the dead owner — retried until the
                # survivor's guard has the v2 ring.
                survivor_client = RemoteScheduler(survivor_url, timeout=5.0)
                with handoff_span(
                    tid, from_shard=victim_id, to_shard=survivor_id,
                    ring_version=ring_v2,
                ):
                    survivor_client.announce_host(phost)
                    survivor_client.announce_host(chost)
                    deadline = time.monotonic() + 20
                    preg = None
                    while preg is None and time.monotonic() < deadline:
                        try:
                            preg = survivor_client.register_peer(
                                host=phost, url=url, task_id=tid
                            )
                        except WrongShardError:
                            time.sleep(0.3)
                    assert preg is not None, (
                        "survivor never adopted the v2 ring"
                    )
                    survivor_client.set_task_info(
                        preg.peer, content_length, N_PIECES, PIECE
                    )
                    for n in range(N_PIECES):
                        survivor_client.report_piece_finished(
                            preg.peer, n, parent_id="",
                            length=PIECE, cost_ns=10**6,
                        )
                    survivor_client.report_peer_finished(preg.peer)

                    reg2 = survivor_client.register_peer(
                        host=chost, url=url, task_id=tid
                    )
                    parents2 = reg2.schedule.parents
                    assert parents2, "task did not migrate with a parent"
                    assert parents2[0].host.id == phost.id
                    for n in range(3, N_PIECES):
                        got[n] = fetch.fetch(parents2[0].host.id, tid, n)
                        survivor_client.report_piece_finished(
                            reg2.peer, n, parent_id=parents2[0].id,
                            length=PIECE, cost_ns=10**6,
                        )
                    survivor_client.report_peer_finished(reg2.peer)

            # Every completed download digest-checks.
            assert (
                hashlib.sha256(
                    b"".join(got[n] for n in range(N_PIECES))
                ).hexdigest()
                == want
            )
            drill_exporter.close()
        finally:
            tracing.default_tracer.exporter = prev_exporter
            if piece_server is not None:
                piece_server.stop()
            for proc in shards:
                proc.stop()
            manager.stop()

        # -- flight-recorder evidence ------------------------------------
        from tools.trace_assemble import build_report, render_report

        logs = [
            str(tmp_path / "shard0.dftrace"),
            str(tmp_path / "shard1.dftrace"),
            client_log,
        ]
        report = build_report(logs, validate=True)
        for log in report["logs"]:
            assert log["corrupt"] == 0, log  # zero corrupt frames
            assert log["frames"] > 0, log    # every process left spans
        trace = report["trace"]
        # ONE trace spans the client and BOTH shard processes: handler
        # spans for the task live in both logs (register/report on the
        # victim before the kill, on the survivor after).
        assert "dfdaemon" in trace["services"]
        assert "scheduler" in trace["services"]
        shard_logs = {log["path"]: log for log in report["logs"]}
        assert shard_logs[str(tmp_path / "shard0.dftrace")]["frames"] > 0
        assert shard_logs[str(tmp_path / "shard1.dftrace")]["frames"] > 0
        # The cross-shard handoff is ON the critical path.
        path_names = [hop["name"] for hop in trace["critical_path"]]
        assert any(n == "scheduler/shard.handoff" for n in path_names), (
            path_names
        )
        rendered = render_report(report)
        assert "shard.handoff" in rendered
