"""Networked peer-exchange gossip (daemon/pex_net.py): UDP membership,
advertisement, anti-entropy, reclaim-on-leave, heartbeat failure
detection, and the scheduler-down discovery flow across OS processes
(reference: client/daemon/pex/peer_exchange.go:34-50)."""

import json
import os
import select
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dragonfly2_tpu.daemon.pex import MemberMeta, PeerExchange
from dragonfly2_tpu.daemon.pex_net import (
    NetworkedGossipBus,
    pieces_to_ranges,
    ranges_to_pieces,
)

PIECE = 32 * 1024


def _node(name, seeds=(), interval=0.1):
    bus = NetworkedGossipBus(
        port=0, seeds=list(seeds), gossip_interval_s=interval
    )
    pex = PeerExchange(
        MemberMeta(host_id=name, ip="127.0.0.1", port=1000), bus
    )
    pex.serve()
    return bus, pex


def _wait(cond, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


class TestRanges:
    def test_roundtrip(self):
        for pieces in (set(), {0}, {0, 1, 2, 7, 9, 10}, set(range(100))):
            assert ranges_to_pieces(pieces_to_ranges(pieces)) == pieces

    def test_contiguous_compact(self):
        assert pieces_to_ranges(set(range(10_000))) == [[0, 9999]]


class TestGossipOverUDP:
    def test_discovery_and_late_join_sync(self):
        bus_a, pex_a = _node("host-a")
        bus_b, pex_b = _node("host-b", seeds=[bus_a.address])
        try:
            assert _wait(lambda: pex_a.member("host-b") is not None)
            assert _wait(lambda: pex_b.member("host-a") is not None)
            pex_a.advertise("task-1", {0, 1, 2})
            assert _wait(
                lambda: pex_b.find_peers_with_piece("task-1", 1) == ["host-a"]
            )
            # LATE joiner learns existing holdings via the join sync.
            bus_c, pex_c = _node("host-c", seeds=[bus_b.address])
            try:
                assert _wait(
                    lambda: "host-a" in pex_c.find_peers_with_task("task-1")
                )
                assert pex_c.member("host-a").port == 1000
            finally:
                pex_c.stop()
        finally:
            pex_b.stop()
            pex_a.stop()

    def test_retract_and_reclaim_on_leave(self):
        bus_a, pex_a = _node("host-a")
        bus_b, pex_b = _node("host-b", seeds=[bus_a.address])
        bus_c, pex_c = _node("host-c", seeds=[bus_a.address])
        try:
            assert _wait(lambda: len(pex_a.members()) == 2)
            pex_b.advertise("task-r", {0})
            pex_c.advertise("task-r", {0, 1})
            assert _wait(
                lambda: sorted(pex_a.find_peers_with_task("task-r"))
                == ["host-b", "host-c"]
            )
            pex_b.retract("task-r")
            assert _wait(
                lambda: pex_a.find_peers_with_task("task-r") == ["host-c"]
            )
            pex_c.stop()  # explicit leave → reclaim
            assert _wait(lambda: pex_a.find_peers_with_task("task-r") == [])
            assert pex_a.member("host-c") is None
        finally:
            pex_b.stop()
            pex_a.stop()

    def test_heartbeat_failure_detection(self):
        bus_a, pex_a = _node("host-a", interval=0.1)
        bus_b, pex_b = _node("host-b", seeds=[bus_a.address], interval=0.1)
        try:
            assert _wait(lambda: pex_a.member("host-b") is not None)
            pex_b.advertise("task-h", {0})
            assert _wait(lambda: pex_a.find_peers_with_task("task-h"))
            # Crash (no leave message): close the socket directly.
            bus_b._stop.set()
            bus_b._sock.close()
            assert _wait(
                lambda: pex_a.member("host-b") is None, timeout=5
            ), "dead member never reclaimed"
            assert pex_a.find_peers_with_task("task-h") == []
        finally:
            pex_a.stop()


class _RangeOrigin(BaseHTTPRequestHandler):
    BLOB = bytes(i % 253 for i in range(4 * PIECE))

    def log_message(self, *args):
        pass

    def do_HEAD(self):
        self.send_response(200)
        self.send_header("Content-Length", str(len(self.BLOB)))
        self.send_header("Accept-Ranges", "bytes")
        self.end_headers()

    def do_GET(self):
        rng = self.headers.get("Range")
        body, code = self.BLOB, 200
        if rng:
            s, e = rng.split("=", 1)[1].split("-")
            body = self.BLOB[int(s): (int(e) if e else len(self.BLOB) - 1) + 1]
            code = 206
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class TestSchedulerDownCrossProcess:
    """VERDICT r1 missing-#3 done-condition: a daemon discovers piece
    holders across OS processes with the scheduler DOWN."""

    def test_discovery_survives_scheduler_death(self, tmp_path):
        procs = []

        def spawn(argv, prefixes, extra_env=None):
            env = {**os.environ, "PYTHONPATH": os.getcwd(), **(extra_env or {})}
            proc = subprocess.Popen(
                [sys.executable, *argv], stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True, env=env,
            )
            procs.append(proc)
            found = {}
            deadline = time.time() + 30
            while time.time() < deadline and len(found) < len(prefixes):
                ready, _, _ = select.select([proc.stdout], [], [], 30)
                assert ready, f"{argv}: no output"
                line = proc.stdout.readline().strip()
                for p in prefixes:
                    if line.startswith(p):
                        found[p] = line
            assert len(found) == len(prefixes), found
            return proc, found

        origin_srv = ThreadingHTTPServer(("127.0.0.1", 0), _RangeOrigin)
        threading.Thread(target=origin_srv.serve_forever, daemon=True).start()
        url = f"http://127.0.0.1:{origin_srv.server_address[1]}/pex-blob"

        sched_cfg = tmp_path / "sched.yaml"
        sched_cfg.write_text(
            "server: {host: 127.0.0.1, port: 0, grpc_port: -1}\n"
            "scheduling: {retry_interval_s: 0.0}\n"
            f"storage: {{dir: {tmp_path / 'records'}, buffer_size: 1}}\n"
        )
        dcfg = tmp_path / "daemon.yaml"
        dcfg.write_text(
            "server: {host: 127.0.0.1, port: 0, advertise_ip: 127.0.0.1}\n"
            f"storage: {{dir: {tmp_path / 'dstore'}}}\n"
            f"piece_size: {PIECE}\n"
        )
        try:
            import re

            sproc, out = spawn(
                ["-m", "dragonfly2_tpu.cli.scheduler", "--config", str(sched_cfg)],
                ["scheduler: serving"],
            )
            sched_url = re.search(
                r"rpc on (\S+)", out["scheduler: serving"]
            ).group(1)
            _, dout = spawn(
                ["-m", "dragonfly2_tpu.cli.dfdaemon", "--scheduler", sched_url,
                 "--config", str(dcfg), "--pex-port", "0"],
                ["dfdaemon: pex gossip", "dfdaemon: serving"],
                {"DF_DAEMON_STATE": str(tmp_path / "d1.json")},
            )
            pex_port = int(dout["dfdaemon: pex gossip"].rsplit(":", 1)[1])

            # Daemon downloads the blob (and advertises it over gossip).
            from dragonfly2_tpu.rpc.daemon_control import (
                download_via_daemon,
                read_state,
            )

            control = read_state(str(tmp_path / "d1.json"))["url"]
            r = download_via_daemon(url, control)
            assert r["ok"], r

            # Scheduler DIES.
            sproc.terminate()
            sproc.wait(timeout=10)

            # A fresh client joins ONLY the gossip — and still finds the
            # holder and the bytes.
            from dragonfly2_tpu.daemon import DaemonStorage
            from dragonfly2_tpu.daemon.conductor import Conductor
            from dragonfly2_tpu.rpc import HTTPPieceFetcher, RemoteScheduler
            from dragonfly2_tpu.scheduler.resource import Host

            bus = NetworkedGossipBus(
                port=0, seeds=[("127.0.0.1", pex_port)], gossip_interval_s=0.1
            )
            pex = PeerExchange(
                MemberMeta(host_id="pex-client", ip="127.0.0.1", port=0), bus
            )
            pex.serve()
            try:
                from dragonfly2_tpu.utils import idgen

                task_id = idgen.task_id(url)
                assert _wait(
                    lambda: pex.find_peers_with_task(task_id), timeout=10
                ), "gossip never surfaced the holder"

                def resolve(host_id):
                    m = pex.member(host_id)
                    assert m is not None
                    return m.ip, m.port

                storage = DaemonStorage(
                    str(tmp_path / "clientstore"), prefer_native=False
                )
                dead = RemoteScheduler(sched_url, timeout=1.0)
                conductor = Conductor(
                    Host(id="pex-client", hostname="c", ip="127.0.0.1",
                         download_port=1),
                    storage, dead,
                    piece_fetcher=HTTPPieceFetcher(resolve),
                    source_fetcher=None, pex=pex,
                )
                r2 = conductor.download(
                    url, piece_size=PIECE, content_length=len(_RangeOrigin.BLOB)
                )
                assert r2.ok and r2.pieces == 4
                assert storage.read_task_bytes(r2.task_id) == _RangeOrigin.BLOB
            finally:
                pex.stop()
        finally:
            for proc in procs:
                proc.terminate()
            origin_srv.shutdown()
