"""Dynamic lock-witness cross-check (resolver completeness, enforced).

``tests/conftest.py`` installs ``dragonfly2_tpu.utils.dflock`` before any
project import, so every project lock created during this pytest session
records acquisition-order edges.  This module (named ``zz`` so it
collects last and sees the whole session's edges) drives a set of
deliberately cross-module concurrent workloads, then asserts that EVERY
dynamically-observed edge maps into dflint's statically-derived lock
graph (``tools/dflint/program.py``).

A failure here means the static resolver has a blind spot — a call-graph
edge, lock creation, or attribute type it cannot see — which would also
blind DF008/DF009.  Fix the resolver (or the annotation it needs), never
this test.
"""

from __future__ import annotations

import sys
import threading
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from dragonfly2_tpu.utils import dflock  # noqa: E402


def _witness():
    w = dflock.witness()
    if w is None:
        pytest.skip("lock witness disabled (DF_LOCK_WITNESS=0)")
    return w


@pytest.fixture(scope="module")
def program():
    # The suite builds this same whole-tree view in test_dflint.py;
    # reuse its session cache (read-only) instead of re-linking.
    from tests.test_dflint import _df_tree_program

    return _df_tree_program()


class _StubScorer:
    wants_features = True
    static_shapes = False

    def score(self, features, *, src_buckets=None, dst_buckets=None):
        return np.asarray(features)[:, 0]


def _drive_workloads():
    """Concurrency shapes chosen to cross module boundaries the resolver
    must follow: self-method dispatch (registry.activate → _persist),
    annotated-attribute dispatch (subscriber → registry), factory-typed
    attributes (registry._table → state backend), module-variable types
    (metrics counters), and condition-variable leader/follower flows."""
    from dragonfly2_tpu.manager.registry import ModelRegistry
    from dragonfly2_tpu.manager.state import MemoryBackend
    from dragonfly2_tpu.rollout.shadow import ShadowScorer
    from dragonfly2_tpu.scheduler.evaluator import MLEvaluator
    from dragonfly2_tpu.scheduler.microbatch import ScorerBatcher
    from dragonfly2_tpu.scheduler.model_loader import ModelSubscriber

    # registry._mu (RLock) → state table lock, via self._persist dispatch.
    registry = ModelRegistry(backend=MemoryBackend())
    model = registry.create_model(
        name="parent-bandwidth-mlp", type="mlp", scheduler_id="wit-sched",
        artifact=b"\x00" * 8,
    )
    registry.activate(model.id)

    # subscriber._refresh_mu → registry._mu (annotated attribute call).
    evaluator = MLEvaluator(None)
    sub = ModelSubscriber(registry, evaluator, scheduler_id="wit-sched")
    sub.refresh()

    # batcher cv: leader/follower coalescing under concurrent scores.
    batcher = ScorerBatcher(_StubScorer(), linger_s=0.002)
    feats = np.ones((4, 3), dtype=np.float32)

    def score_some():
        for _ in range(5):
            batcher.score(feats)

    threads = [
        threading.Thread(target=score_some, name=f"wit-score-{i}", daemon=True)
        for i in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        while t.is_alive():
            t.join(5.0)

    # shadow cv → metrics lock (offer with a full queue records a drop).
    shadow = ShadowScorer(
        _StubScorer(), candidate_version=2, active_version=1,
        sample_rate=1.0, max_queue=1,
    )
    try:
        for _ in range(8):
            shadow.offer("child-1", feats, np.zeros(4, np.int64),
                         np.zeros(4, np.int64), np.ones(4))
    finally:
        shadow.close()


class TestLockWitness:
    def test_witness_is_installed_and_recording(self):
        w = _witness()
        _drive_workloads()
        edges = w.snapshot_edges()
        assert edges, "no acquisition-order edges recorded all session"

    def test_every_dynamic_edge_is_in_the_static_graph(self, program):
        from tools.dflint.program import witness_gaps

        w = _witness()
        _drive_workloads()
        gaps = witness_gaps(program, w.snapshot_edges())
        assert not gaps, (
            "static lock-graph resolver gaps (fix tools/dflint/program.py, "
            "not this test):\n  " + "\n  ".join(gaps)
        )

    def test_driven_workload_produces_cross_module_edges(self, program):
        """The registry→state edge must be OBSERVED dynamically (if the
        workload stops exercising it, the cross-check goes vacuous)."""
        w = _witness()
        _drive_workloads()
        index = program.creation_site_index()
        mapped = set()
        for (src, dst) in w.snapshot_edges():
            if src in index and dst in index:
                mapped.add((index[src], index[dst]))
        assert any(
            s.endswith("ModelRegistry._mu") and d.endswith("_MemTable._mu")
            for s, d in mapped
        ), f"registry->state edge not observed; saw {sorted(mapped)}"

    def test_resolver_edge_deletion_is_caught(self, program):
        """Mutation sensitivity: erase the self-method-dispatch edge
        (registry.activate → self._persist → table.put_many) from the
        static graph — the dynamic witness must flag exactly that hole."""
        from tools.dflint.program import witness_gaps

        w = _witness()
        _drive_workloads()
        victim = None
        for (src, dst) in program.edge_keys():
            if src.endswith("ModelRegistry._mu") and dst.endswith("_MemTable._mu"):
                victim = (src, dst)
        assert victim is not None
        pruned = program.edge_keys() - {victim}
        gaps = witness_gaps(program, w.snapshot_edges(), static_edges=pruned)
        assert any("_MemTable._mu" in g for g in gaps), gaps

    def test_unknown_creation_site_is_a_gap(self, program):
        from tools.dflint.program import witness_gaps

        _witness()
        fake = {
            (("dragonfly2_tpu/daemon/nowhere.py", 1),
             ("dragonfly2_tpu/daemon/nowhere.py", 2)): "fabricated",
        }
        gaps = witness_gaps(program, fake)
        assert len(gaps) == 1 and "unknown lock creation site" in gaps[0]
