"""Back-to-source cloud clients: SigV4 vector, S3/OSS/WebHDFS/ORAS against
local fixture servers that validate auth server-side, and conductor
integration through the piece fetcher."""

import base64
import hashlib
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dragonfly2_tpu.source import (
    HDFSSourceClient,
    ORASSourceClient,
    OSSSourceClient,
    PieceSourceFetcher,
    S3SourceClient,
    SourceRegistry,
    configure_sources,
    default_registry,
)
from dragonfly2_tpu.source import sigv4
from dragonfly2_tpu.source.oss import sign_oss

BLOB = bytes(i % 251 for i in range(300 * 1024))  # 300 KiB, prime modulus


class TestSigV4:
    def test_aws_documented_vector(self):
        """The published AWS doc example (GET iam ListUsers)."""
        url = "https://iam.amazonaws.com/?Action=ListUsers&Version=2010-05-08"
        headers = {
            "Host": "iam.amazonaws.com",
            "Content-Type": "application/x-www-form-urlencoded; charset=utf-8",
            "X-Amz-Date": "20150830T123600Z",
        }
        canon, signed = sigv4.canonical_request(
            "GET", url, headers, sigv4.EMPTY_SHA256
        )
        assert signed == "content-type;host;x-amz-date"
        assert hashlib.sha256(canon.encode()).hexdigest() == (
            "f536975d06c0309214f805bb90ccff089219ecd68b2577efef23edd43b7e1a59"
        )
        auth = sigv4.sign_request(
            "GET", url, headers,
            access_key="AKIDEXAMPLE",
            secret_key="wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY",
            region="us-east-1", service="iam",
            amz_date="20150830T123600Z",
        )
        assert auth.endswith(
            "Signature=5d672d79c15b13162d9279b0855cfba6789a8edb4c82c400e06b5924a6f2b5d7"
        )


def _serve(handler_cls):
    srv = ThreadingHTTPServer(("127.0.0.1", 0), handler_cls)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def _range_slice(range_header, payload):
    spec = range_header.split("=", 1)[1]
    start, end = spec.split("-")
    return payload[int(start): int(end) + 1]


ACCESS, SECRET = "AKIDTEST", "secret-test-key"


class _S3Handler(BaseHTTPRequestHandler):
    """Path-style S3: /bucket/key. Re-derives the SigV4 signature."""

    def _check_auth(self):
        auth = self.headers.get("Authorization", "")
        if not auth.startswith("AWS4-HMAC-SHA256"):
            return False
        fields = dict(
            kv.split("=", 1) for kv in auth.split(" ", 1)[1].replace(",", "").split()
        )
        signed_names = fields["SignedHeaders"].split(";")
        headers = {}
        for name in signed_names:
            headers[name] = (
                self.headers.get(name)
                if name != "host" else self.headers.get("Host")
            )
        expected = sigv4.sign_request(
            self.command,
            f"http://{self.headers.get('Host')}{self.path}",
            headers,
            access_key=ACCESS, secret_key=SECRET,
            region="us-east-1", service="s3",
            amz_date=self.headers["x-amz-date"],
            payload_sha256=self.headers["x-amz-content-sha256"],
        )
        return expected == auth

    def do_HEAD(self):
        if not self._check_auth():
            self.send_error(403)
            return
        if self.path != "/bkt/data/obj.bin":
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(BLOB)))
        self.end_headers()

    def do_GET(self):
        if not self._check_auth():
            self.send_error(403)
            return
        body = _range_slice(self.headers["Range"], BLOB)
        self.send_response(206)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


class TestS3Client:
    @pytest.fixture
    def client(self):
        srv = _serve(_S3Handler)
        yield S3SourceClient(
            access_key=ACCESS, secret_key=SECRET, region="us-east-1",
            endpoint=f"http://127.0.0.1:{srv.server_address[1]}",
        )
        srv.shutdown()

    def test_head_and_ranges(self, client):
        url = "s3://bkt/data/obj.bin"
        assert client.content_length(url) == len(BLOB)
        assert client.read_range(url, 0, 1024) == BLOB[:1024]
        assert client.read_range(url, 100_000, 4096) == BLOB[100_000:104_096]
        assert client.exists(url)
        assert not client.exists("s3://bkt/missing")

    def test_bad_credentials_rejected(self, client):
        bad = S3SourceClient(
            access_key=ACCESS, secret_key="wrong", region="us-east-1",
            endpoint=client.endpoint,
        )
        assert bad.content_length("s3://bkt/data/obj.bin") == -1


class _OSSHandler(BaseHTTPRequestHandler):
    def _check_auth(self):
        auth = self.headers.get("Authorization", "")
        if not auth.startswith(f"OSS {ACCESS}:"):
            return False
        bucket, key = self.path.lstrip("/").split("/", 1)
        expected = sign_oss(
            SECRET, self.command, date=self.headers["Date"],
            bucket=bucket, key=key,
        )
        return auth.split(":", 1)[1] == expected

    def do_HEAD(self):
        if not self._check_auth():
            self.send_error(403)
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(BLOB)))
        self.end_headers()

    def do_GET(self):
        if not self._check_auth():
            self.send_error(403)
            return
        body = _range_slice(self.headers["Range"], BLOB)
        self.send_response(206)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


class TestOSSClient:
    def test_signed_roundtrip(self):
        srv = _serve(_OSSHandler)
        try:
            client = OSSSourceClient(
                access_key_id=ACCESS, access_key_secret=SECRET,
                endpoint=f"http://127.0.0.1:{srv.server_address[1]}",
            )
            url = "oss://bkt/dir/obj.bin"
            assert client.content_length(url) == len(BLOB)
            assert client.read_range(url, 5000, 100) == BLOB[5000:5100]
            bad = OSSSourceClient(
                access_key_id=ACCESS, access_key_secret="nope",
                endpoint=client.endpoint,
            )
            assert bad.content_length(url) == -1
        finally:
            srv.shutdown()


class _WebHDFSHandler(BaseHTTPRequestHandler):
    def do_GET(self):
        from urllib.parse import parse_qs, urlsplit

        parsed = urlsplit(self.path)
        qs = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        if not parsed.path.startswith("/webhdfs/v1/data/file.bin"):
            self.send_error(404)
            return
        if qs["op"] == "GETFILESTATUS":
            body = json.dumps(
                {"FileStatus": {"length": len(BLOB), "type": "FILE"}}
            ).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif qs["op"] == "OPEN":
            if "redirected" not in qs:
                # namenode → datanode redirect, as real WebHDFS does
                self.send_response(307)
                self.send_header(
                    "Location",
                    f"http://127.0.0.1:{self.server.server_address[1]}"
                    f"{parsed.path}?{parsed.query}&redirected=1",
                )
                self.end_headers()
                return
            off, ln = int(qs.get("offset", 0)), int(qs["length"])
            body = BLOB[off: off + ln]
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self.send_error(400)

    def log_message(self, *a):
        pass


class TestHDFSClient:
    def test_status_open_redirect(self):
        srv = _serve(_WebHDFSHandler)
        try:
            client = HDFSSourceClient(user="hadoop")
            url = f"hdfs://127.0.0.1:{srv.server_address[1]}/data/file.bin"
            assert client.content_length(url) == len(BLOB)
            assert client.read_range(url, 0, 512) == BLOB[:512]
            assert client.read_range(url, 9999, 2000) == BLOB[9999:11999]
            missing = f"hdfs://127.0.0.1:{srv.server_address[1]}/nope"
            assert client.content_length(missing) == -1
        finally:
            srv.shutdown()


TOKEN = "tok-abc123"


class _ORASHandler(BaseHTTPRequestHandler):
    def do_GET(self):
        if self.path.startswith("/service/token/"):
            assert "scope=repository:proj/art:pull" in self.path
            body = json.dumps({"token": TOKEN}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/v2/proj/art/manifests/v1":
            if self.headers.get("Authorization") != f"Bearer {TOKEN}":
                self.send_error(401)
                return
            body = json.dumps({
                "layers": [
                    {"digest": "sha256:aaa", "size": 11},
                    {"digest": "sha256:bbb", "size": len(BLOB)},
                ]
            }).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/v2/proj/art/blobs/sha256:bbb":
            if self.headers.get("Authorization") != f"Bearer {TOKEN}":
                self.send_error(401)
                return
            body = _range_slice(self.headers["Range"], BLOB)
            self.send_response(206)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self.send_error(404)

    def log_message(self, *a):
        pass


class TestORASClient:
    def test_token_manifest_blob_flow(self):
        srv = _serve(_ORASHandler)
        try:
            client = ORASSourceClient(
                auth_header="Basic " + base64.b64encode(b"u:p").decode(),
                insecure_http=True,
            )
            url = f"oras://127.0.0.1:{srv.server_address[1]}/proj/art:v1"
            # content_length comes from the manifest's LAST layer size,
            # no blob transfer.
            assert client.content_length(url) == len(BLOB)
            assert client.read_range(url, 0, 64) == BLOB[:64]
            assert client.read_range(url, 200_000, 8192) == BLOB[200_000:208_192]
        finally:
            srv.shutdown()


class _ExpiringORASHandler(_ORASHandler):
    """First token expires after one blob read: 401 must trigger a
    transparent re-auth + retry inside read_range."""

    issued = []

    def do_GET(self):
        if self.path.startswith("/service/token/"):
            tok = f"tok-{len(self.issued)}"
            self.issued.append(tok)
            body = json.dumps({"token": tok}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        auth = self.headers.get("Authorization", "")
        current = f"Bearer {self.issued[-1]}" if self.issued else None
        if "/blobs/" in self.path and auth != current:
            self.send_error(401)  # stale token
            return
        # Delegate manifest/blob serving with the live token expectation.
        if self.path == "/v2/proj/art/manifests/v1":
            body = json.dumps(
                {"layers": [{"digest": "sha256:bbb", "size": len(BLOB)}]}
            ).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/v2/proj/art/blobs/sha256:bbb":
            body = _range_slice(self.headers["Range"], BLOB)
            self.send_response(206)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self.send_error(404)


class TestORASTokenRefresh:
    def test_401_triggers_reauth_and_retry(self):
        _ExpiringORASHandler.issued = []
        srv = _serve(_ExpiringORASHandler)
        try:
            client = ORASSourceClient(insecure_http=True)
            url = f"oras://127.0.0.1:{srv.server_address[1]}/proj/art:v1"
            assert client.read_range(url, 0, 16) == BLOB[:16]
            # Simulate expiry: registry rotates; cached token now stale.
            _ExpiringORASHandler.issued.append("tok-rotated")
            assert client.read_range(url, 16, 16) == BLOB[16:32]
            # A fresh token was fetched (>=3: initial + rotation + re-auth).
            assert len(_ExpiringORASHandler.issued) >= 3
        finally:
            srv.shutdown()


class _NoRangeHandler(BaseHTTPRequestHandler):
    """An origin that ignores Range and answers 200 with the full body
    (the OCI spec makes blob ranges optional)."""

    def do_GET(self):
        self.send_response(200)
        self.send_header("Content-Length", str(len(BLOB)))
        self.end_headers()
        self.wfile.write(BLOB)

    def do_HEAD(self):
        self.send_response(200)
        self.send_header("Content-Length", str(len(BLOB)))
        self.end_headers()

    def log_message(self, *a):
        pass


class TestRangeFallback:
    def test_200_body_streamed_not_buffered(self):
        """The piece extraction reads prefix+piece only — the tail of the
        object is never pulled off the wire."""
        from dragonfly2_tpu.source.client import _ranged_body

        class FakeResp:
            status = 200

            def __init__(self, n):
                self.remaining = n
                self.reads = 0
                self.total_read = 0

            def read(self, n=None):
                self.reads += 1
                take = self.remaining if n is None else min(n, self.remaining)
                self.remaining -= take
                self.total_read += take
                return b"x" * take

        resp = FakeResp(1 << 30)  # "1 GiB object"
        piece = _ranged_body(resp, 100 << 20, 4 << 20)
        assert len(piece) == 4 << 20
        # Only prefix + piece consumed, not the remaining ~920 MiB.
        assert resp.total_read == (100 << 20) + (4 << 20)

    def test_200_full_body_sliced_to_piece(self):
        srv = _serve(_NoRangeHandler)
        try:
            client = OSSSourceClient(
                access_key_id=ACCESS, access_key_secret=SECRET,
                endpoint=f"http://127.0.0.1:{srv.server_address[1]}",
            )
            url = "oss://bkt/obj"
            assert client.read_range(url, 4096, 512) == BLOB[4096:4608]
            # Tail piece: slice stops at the object end.
            tail = client.read_range(url, len(BLOB) - 100, 512)
            assert tail == BLOB[-100:]
        finally:
            srv.shutdown()


class TestNetworkErrorHandling:
    def test_unreachable_endpoints_answer_minus_one(self):
        # connection refused, not a traceback (URLError ⊂ OSError).
        s3 = S3SourceClient(access_key="a", secret_key="b",
                            endpoint="http://127.0.0.1:1")
        assert s3.content_length("s3://b/k") == -1
        assert not s3.exists("s3://b/k")
        oss = OSSSourceClient(access_key_id="a", access_key_secret="b",
                              endpoint="http://127.0.0.1:1")
        assert oss.content_length("oss://b/k") == -1
        hdfs = HDFSSourceClient()
        assert hdfs.content_length("hdfs://127.0.0.1:1/x") == -1
        oci = ORASSourceClient(insecure_http=True)
        assert oci.content_length("oras://127.0.0.1:1/r:t") == -1


class TestRegistryIntegration:
    def test_configure_sources_and_piece_fetcher(self):
        srv = _serve(_ORASHandler)
        try:
            reg = SourceRegistry()
            configure_sources(
                {"oras": {"insecure_http": True}}, registry=reg
            )
            fetcher = PieceSourceFetcher(registry=reg)
            url = f"oras://127.0.0.1:{srv.server_address[1]}/proj/art:v1"
            piece = fetcher.fetch(url, 2, 65536)
            assert piece == BLOB[131072: 131072 + 65536]
            assert fetcher.content_length(url) == len(BLOB)
        finally:
            srv.shutdown()

    def test_default_registry_has_all_schemes_after_configure(self):
        reg = SourceRegistry()
        configure_sources(
            {
                "s3": {"access_key": "a", "secret_key": "b"},
                "oss": {"access_key_id": "a", "access_key_secret": "b",
                        "endpoint": "http://x"},
                "hdfs": {},
                "oci": {},
            },
            registry=reg,
        )
        for scheme in ("s3", "oss", "hdfs", "oras", "oci"):
            assert reg.client_for(f"{scheme}://h/p:t") is not None

    def test_unknown_scheme_raises(self):
        with pytest.raises(KeyError):
            default_registry.client_for("gopher://x/y")
