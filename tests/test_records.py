"""Record layer tests: schema roundtrip, columnar format, rotating storage,
featurization, synthetic generator consistency."""

import json
import os

import numpy as np
import pytest

from dragonfly2_tpu.records import schema
from dragonfly2_tpu.records.columnar import ColumnarReader, ColumnarWriter, concat_readers
from dragonfly2_tpu.records.features import (
    DOWNLOAD_COLUMNS,
    DOWNLOAD_FEATURE_DIM,
    HOST_FEATURE_DIM,
    TOPO_COLUMNS,
    download_to_rows,
    host_features,
    topology_to_rows,
)
from dragonfly2_tpu.records.storage import Storage
from dragonfly2_tpu.records.synthetic import SyntheticCluster


class TestSchema:
    def test_download_dict_roundtrip(self, cluster):
        d = cluster.generate_download()
        data = schema.to_dict(d)
        restored = schema.from_dict(schema.Download, json.loads(json.dumps(data)))
        assert restored == d

    def test_topology_dict_roundtrip(self, cluster):
        r = cluster.generate_topology_record()
        restored = schema.from_dict(
            schema.NetworkTopologyRecord, json.loads(json.dumps(schema.to_dict(r)))
        )
        assert restored == r

    def test_observed_bandwidth(self):
        p = schema.Parent(pieces=[schema.Piece(length=1 << 20, cost=int(1e9))])
        assert p.observed_bandwidth() == pytest.approx(1 << 20)
        assert schema.Parent().observed_bandwidth() == 0.0


class TestColumnar:
    def test_write_read_roundtrip(self, tmp_path):
        path = str(tmp_path / "t.dfc")
        rows = np.random.default_rng(0).normal(size=(100, 5)).astype(np.float32)
        with ColumnarWriter(path, [f"c{i}" for i in range(5)]) as w:
            w.append(rows[:50])
            w.append(rows[50:])
            assert w.tell_rows() == 100
        r = ColumnarReader(path)
        assert r.num_rows == 100
        assert r.columns == tuple(f"c{i}" for i in range(5))
        np.testing.assert_array_equal(r.to_array(), rows)

    def test_append_to_existing(self, tmp_path):
        path = str(tmp_path / "t.dfc")
        with ColumnarWriter(path, ["a", "b"]) as w:
            w.append(np.ones((3, 2), dtype=np.float32))
        with ColumnarWriter(path, ["a", "b"]) as w:
            w.append(np.zeros((2, 2), dtype=np.float32))
        r = ColumnarReader(path)
        assert r.num_rows == 5
        assert r.to_array()[-1, 0] == 0.0

    def test_column_mismatch_rejected(self, tmp_path):
        path = str(tmp_path / "t.dfc")
        with ColumnarWriter(path, ["a"]) as w:
            w.append(np.ones((1, 1), dtype=np.float32))
        with pytest.raises(ValueError):
            ColumnarWriter(path, ["x"])

    def test_batches(self, tmp_path):
        path = str(tmp_path / "t.dfc")
        with ColumnarWriter(path, ["a"]) as w:
            w.append(np.arange(10, dtype=np.float32)[:, None])
        r = ColumnarReader(path)
        got = list(r.batches(4))
        assert [len(b) for b in got] == [4, 4, 2]
        got = list(r.batches(4, drop_remainder=True))
        assert [len(b) for b in got] == [4, 4]

    def test_concat(self, tmp_path):
        paths = []
        for i in range(3):
            p = str(tmp_path / f"{i}.dfc")
            with ColumnarWriter(p, ["a"]) as w:
                w.append(np.full((2, 1), i, dtype=np.float32))
            paths.append(p)
        arr = concat_readers(paths)
        assert arr.shape == (6, 1)


class TestFeatures:
    def test_host_feature_dim(self, cluster):
        f = host_features(cluster.host_record(0))
        assert f.shape == (HOST_FEATURE_DIM,)
        assert np.all(np.isfinite(f))

    def test_download_rows(self, cluster):
        d = cluster.generate_download()
        rows = download_to_rows(d)
        assert rows.shape[1] == len(DOWNLOAD_COLUMNS)
        assert rows.shape[0] == len(d.parents)
        assert np.all(np.isfinite(rows))
        # target is log1p(bandwidth), positive for real transfers
        assert np.all(rows[:, -1] > 0)

    def test_topology_rows(self, cluster):
        r = cluster.generate_topology_record()
        rows = topology_to_rows(r)
        assert rows.shape == (len(r.dest_hosts), len(TOPO_COLUMNS))
        rtt = rows[:, TOPO_COLUMNS.index("avg_rtt_norm")]
        assert np.all((rtt >= 0) & (rtt <= 1))

    def test_target_matches_ground_truth(self, cluster):
        # featurized target ≈ log1p of the latent bandwidth (up to injected noise)
        d = cluster.generate_download()
        rows = download_to_rows(d)
        for parent, row in zip(d.parents, rows):
            assert row[-1] == pytest.approx(np.log1p(parent.observed_bandwidth()), rel=1e-5)


class TestStorage:
    def test_create_flush_list(self, tmp_path, cluster):
        st = Storage(str(tmp_path), buffer_size=10)
        downloads = cluster.generate_downloads(25)
        for d in downloads:
            st.create_download(d)
        # 20 flushed (2 full buffers), 5 still buffered
        listed = st.list_download()
        assert len(listed) == 25
        assert listed[0] == downloads[0]

    def test_columnar_mirrors_jsonl(self, tmp_path, cluster):
        st = Storage(str(tmp_path), buffer_size=5)
        for d in cluster.generate_downloads(12):
            st.create_download(d)
        st.flush()
        arr = concat_readers(st.download_columnar_paths())
        total_parents = sum(len(d.parents) for d in st.list_download())
        assert arr.shape == (total_parents, len(DOWNLOAD_COLUMNS))

    def test_rotation(self, tmp_path, cluster):
        st = Storage(str(tmp_path), buffer_size=1, max_size=20_000, max_backups=3)
        for d in cluster.generate_downloads(40):
            st.create_download(d)
        st.flush()
        paths = st.download_raw_paths()
        assert len(paths) > 1
        assert len(paths) <= 4  # active + 3 backups
        # all shards remain parseable
        assert len(st.list_download()) > 0

    def test_topology_storage(self, tmp_path, cluster):
        st = Storage(str(tmp_path), buffer_size=4)
        recs = cluster.generate_topology_records(9)
        for r in recs:
            st.create_network_topology(r)
        assert len(st.list_network_topology()) == 9
        arr = concat_readers(st.network_topology_columnar_paths())
        assert arr.shape[1] == len(TOPO_COLUMNS)

    def test_clear(self, tmp_path, cluster):
        st = Storage(str(tmp_path), buffer_size=2)
        for d in cluster.generate_downloads(4):
            st.create_download(d)
        st.flush()
        st.clear()
        assert st.list_download() == []
        assert st.download_columnar_paths() == []


class TestSynthetic:
    def test_bandwidth_structure(self, cluster):
        # same-idc edges should on average beat cross-region edges
        n = cluster.num_hosts
        rng = np.random.default_rng(1)
        same, cross = [], []
        for _ in range(400):
            a, b = rng.integers(0, n, 2)
            if a == b:
                continue
            bw = cluster.bandwidth(int(a), int(b), noise=False)
            if cluster.idc[a] == cluster.idc[b]:
                same.append(bw)
            elif cluster.region[a] != cluster.region[b]:
                cross.append(bw)
        assert np.mean(same) > 2.0 * np.mean(cross)

    def test_rtt_structure(self, cluster):
        intra = cluster.rtt_ns(0, 0, noise=False)
        assert intra < 2e6  # same host → intra-idc baseline

    def test_vectorized_rows_shape(self, cluster):
        rows = cluster.generate_feature_rows(1000, seed=7)
        assert rows.shape == (1000, len(DOWNLOAD_COLUMNS))
        assert np.all(np.isfinite(rows))
        # learnable: target correlates with parent upload capacity feature region
        assert rows[:, -1].std() > 0.1

    def test_probe_edges(self, cluster):
        src, dst, rtt = cluster.probe_edges(density=0.05)
        assert len(src) == len(dst) == len(rtt)
        assert np.all(src != dst)
        assert np.all(rtt > 0)
