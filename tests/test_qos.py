"""Multi-tenant QoS plane (DESIGN.md §26, ISSUE 15).

Covers: tenant identity + policy parsing, the consolidated
TenantAccounting (usage shares, announce caps, over-quota signal), the
hierarchical TrafficShaper (and the add_task budget-reset regression),
the upload-path bandwidth gate, the weighted-fair DRR drain property
tests (no starvation / per-tenant FIFO / single-tenant oracle parity),
tenant-aware admission shedding (noisy tenant's lowest band first), the
SLO autopilot (tighten/hysteresis-relax + journal-replay parity), the
manager's tenant_qos publication + tenant derivation, preheat's
background class, the ShardRouter saturation retry budget, and the
bench_qos --smoke schema gate.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from dragonfly2_tpu.qos import (  # noqa: E402
    DEFAULT_TENANT,
    QoSPolicy,
    SLOAutopilot,
    TenantAccounting,
    TenantQoS,
    derive_tenant,
    parse_tenant_qos,
)
from dragonfly2_tpu.scheduler.microbatch import (  # noqa: E402
    ScorerBatcher,
    _Request,
)
from dragonfly2_tpu.scheduler.sharding import (  # noqa: E402
    AdmissionController,
    ShardSaturatedError,
)
from dragonfly2_tpu.utils.types import Priority  # noqa: E402


# ---------------------------------------------------------------------------
# policy + identity
# ---------------------------------------------------------------------------


class TestTenantPolicy:
    def test_derive_tenant_is_deterministic_and_sanitized(self):
        assert derive_tenant("user-abc123") == "t-user-abc123"
        assert derive_tenant("we ird/chars!") == "t-we-ird-chars"
        assert derive_tenant("") == DEFAULT_TENANT
        assert derive_tenant("x") == derive_tenant("x")

    def test_parse_validates_entries(self):
        with pytest.raises(ValueError, match="tenant_class"):
            parse_tenant_qos({"t-a": {"tenant_class": "platinum"}})
        with pytest.raises(ValueError, match="weight"):
            parse_tenant_qos({"t-a": {"weight": 0}})
        with pytest.raises(ValueError, match="priority"):
            parse_tenant_qos({"t-a": {"priority": 9}})
        with pytest.raises(ValueError, match="unknown keys"):
            parse_tenant_qos({"t-a": {"upload_mbps": 1}})
        with pytest.raises(ValueError, match="object"):
            parse_tenant_qos({"t-a": 5})
        with pytest.raises(ValueError, match="object"):
            parse_tenant_qos([1, 2])

    def test_payload_roundtrip_and_defaults(self):
        p = QoSPolicy.from_payload({
            "t-gold": {"tenant_class": "gold", "weight": 4.0},
            "default": {"tenant_class": "bronze", "weight": 2.0},
        })
        p2 = QoSPolicy.from_payload(p.to_payload())
        assert p2.to_payload() == p.to_payload()
        # Unknown tenants inherit the default row under their own id.
        row = p.for_tenant("t-unknown")
        assert row.tenant == "t-unknown"
        assert row.tenant_class == "bronze"
        assert row.weight == 2.0
        assert p.class_of("t-gold") == "gold"
        assert p.weight_of("t-gold") == 4.0

    def test_empty_policy_serves_defaults(self):
        p = QoSPolicy()
        row = p.for_tenant("anyone")
        assert row.weight == 1.0
        assert row.announce_qps == 0.0
        row.validate()


# ---------------------------------------------------------------------------
# accounting
# ---------------------------------------------------------------------------


def _two_tenant_policy(**b_extra) -> QoSPolicy:
    return QoSPolicy.from_payload({
        "t-a": {"tenant_class": "gold", "weight": 4.0},
        "t-b": {"tenant_class": "background", "weight": 1.0, **b_extra},
    })


class TestTenantAccounting:
    def test_over_quota_and_noise_factor(self):
        acct = TenantAccounting(_two_tenant_policy(), window_s=60.0)
        for _ in range(100):
            acct.note("t-b")
        for _ in range(100):
            acct.note("t-a")
        # Equal usage on a 4:1 weight split: b is 2.5x over quota.
        assert acct.over_quota("t-b") == pytest.approx(2.5, rel=0.01)
        assert acct.over_quota("t-a") == pytest.approx(0.625, rel=0.01)
        assert acct.noise_factor("t-a") == 1.0
        assert 1.0 < acct.noise_factor("t-b") <= 3.0

    def test_announce_cap_refuses_past_bucket(self):
        acct = TenantAccounting(
            _two_tenant_policy(announce_qps=10, announce_burst=5),
            window_s=60.0,
        )
        # Make b over quota first (caps only tighten via autopilot, the
        # declared cap applies regardless).
        results = [acct.note("t-b") for _ in range(50)]
        assert results.count(False) >= 40  # burst 5 + refill crumbs
        snap = acct.snapshot()["t-b"]
        assert snap["capped"] >= 40
        assert snap["requests"] == 50  # capped requests still counted

    def test_cap_factor_tightens_only_over_quota_tenants(self):
        acct = TenantAccounting(
            QoSPolicy.from_payload({
                "t-a": {"tenant_class": "gold", "weight": 4.0,
                        "announce_qps": 1000, "announce_burst": 1000},
                "t-b": {"tenant_class": "background", "weight": 1.0,
                        "announce_qps": 1000, "announce_burst": 1000},
            }),
            window_s=60.0,
        )
        # b floods; a trickles — b over quota, a inside.
        for _ in range(400):
            acct.note("t-b")
        for _ in range(40):
            acct.note("t-a")
        # Autopilot tightening; the factor clamps at 0.05 (never a full
        # blackout), so the effective cap is 50 qps / burst 50.
        acct.set_cap_factor(0.01)
        b_ok = sum(acct.note("t-b") for _ in range(200))
        a_ok = sum(acct.note("t-a") for _ in range(200))
        assert b_ok <= 60, "over-quota tenant kept its declared cap"
        assert a_ok == 200, "within-quota tenant was tightened"

    def test_snapshot_is_deterministic_in_the_stream(self):
        def replay():
            acct = TenantAccounting(_two_tenant_policy(), window_s=1e9)
            for i in range(300):
                acct.note("t-b" if i % 3 else "t-a", now=float(i))
                if i % 7 == 0:
                    acct.record_shed("t-b")
                if i % 11 == 0:
                    acct.record_bytes("t-a", 1024)
            return acct.snapshot()

        assert replay() == replay()


# ---------------------------------------------------------------------------
# traffic shaper (tentpole hierarchy + satellite fix)
# ---------------------------------------------------------------------------


class TestTrafficShaperQoS:
    def test_hot_task_budget_survives_cold_join(self):
        """Satellite regression: add_task used to reset EVERY budget to
        an equal split, discarding allocate()'s history-weighted
        proportions."""
        from dragonfly2_tpu.daemon.traffic_shaper import TrafficShaper

        sh = TrafficShaper(100.0, min_share=0.05)
        sh.add_task("hot")
        sh.add_task("warm")
        sh.record("hot", 9000)
        sh.record("warm", 1000)
        alloc = sh.allocate()
        assert alloc["hot"] > 70.0  # history-weighted
        sh.add_task("cold")
        # The joiner gets the min-share floor; the hot task keeps its
        # proportional budget (scaled by the carve, NOT reset to 1/3).
        assert sh.budget("cold") == pytest.approx(5.0)
        assert sh.budget("hot") > 70.0
        assert sh.budget("hot") / sh.budget("warm") == pytest.approx(
            alloc["hot"] / alloc["warm"], rel=1e-6
        )

    def test_rejoin_is_idempotent(self):
        from dragonfly2_tpu.daemon.traffic_shaper import TrafficShaper

        sh = TrafficShaper(100.0)
        sh.add_task("a")
        sh.record("a", 500)
        before = sh.budget("a")
        sh.add_task("a")  # re-register must not carve again
        assert sh.budget("a") == before

    def test_tenant_weight_split_and_cap(self):
        from dragonfly2_tpu.daemon.traffic_shaper import TrafficShaper

        policy = QoSPolicy.from_payload({
            "t-a": {"tenant_class": "gold", "weight": 3.0},
            "t-b": {"tenant_class": "background", "weight": 1.0,
                    "upload_rate_bytes_s": 10.0},
        })
        sh = TrafficShaper(100.0)
        sh.set_policy(policy)
        sh.add_task("a1", "t-a")
        sh.add_task("b1", "t-b")
        sh.record("a1", 100)
        sh.record("b1", 100)
        alloc = sh.allocate()
        # b's 25-weight share clips at its 10 B/s cap; the surplus goes
        # to the uncapped tenant.
        assert alloc["b1"] == pytest.approx(10.0)
        assert alloc["a1"] == pytest.approx(90.0)

    def test_single_tenant_matches_policy_free_behavior(self):
        from dragonfly2_tpu.daemon.traffic_shaper import TrafficShaper

        def run(with_policy: bool):
            sh = TrafficShaper(100.0)
            if with_policy:
                sh.set_policy(_two_tenant_policy())
            sh.add_task("x", "t-a")
            sh.add_task("y", "t-a")
            sh.record("x", 900)
            sh.record("y", 100)
            return sh.allocate()

        assert run(True) == run(False)


# ---------------------------------------------------------------------------
# upload path bandwidth gate
# ---------------------------------------------------------------------------


class TestUploadQoS:
    def _um(self, tmp_path, policy=None):
        from dragonfly2_tpu.daemon.storage import DaemonStorage
        from dragonfly2_tpu.daemon.upload import UploadManager

        st = DaemonStorage(str(tmp_path / "s"), prefer_native=False)
        st.register_task("t", piece_size=1024, content_length=4096)
        for n in range(4):
            st.write_piece("t", n, bytes(1024))
        um = UploadManager(st, concurrent_limit=8, qos_policy=policy)
        return um

    def test_tenant_cap_throttles_and_accounts(self, tmp_path):
        from dragonfly2_tpu.daemon.upload import UploadThrottled

        policy = QoSPolicy.from_payload({
            "t-b": {"tenant_class": "background",
                    "upload_rate_bytes_s": 2048.0},
        })
        um = self._um(tmp_path, policy)
        um.register_task_tenant("t", "t-b")
        # The post-paid bucket admits while balance > 0 (one second of
        # headroom = 2048 bytes = 2 pieces), then throttles.
        assert um.serve_piece("t", 0) == bytes(1024)
        assert um.serve_piece("t", 1) == bytes(1024)
        with pytest.raises(UploadThrottled):
            for n in range(8):
                um.serve_piece("t", n % 4)
        assert um.tenant_bytes["t-b"] >= 2048
        assert um.throttled_count >= 1

    def test_uncapped_tenant_never_throttles(self, tmp_path):
        um = self._um(tmp_path, QoSPolicy())
        um.register_task_tenant("t", "t-free")
        for n in range(16):
            assert um.serve_piece("t", n % 4) == bytes(1024)
        assert um.tenant_bytes["t-free"] == 16 * 1024

    def test_no_policy_is_the_pre_qos_gate(self, tmp_path):
        um = self._um(tmp_path, None)
        for n in range(16):
            um.serve_piece("t", n % 4)
        assert um.throttled_count == 0

    def test_throttle_seam_fires(self, tmp_path):
        from dragonfly2_tpu.utils import faultinject

        um = self._um(tmp_path, None)
        inj = faultinject.FaultInjector(
            [faultinject.FaultSpec(site="daemon.upload.throttle",
                                   kind="drop", at=(0,))]
        )
        faultinject.install(inj)
        try:
            with pytest.raises(ConnectionError):
                um.serve_piece("t", 0)
        finally:
            faultinject.install(None)
        # The gate never claimed a slot on the injected refusal.
        assert um.active == 0

    def test_requester_pays_charges_requester_not_owner(self, tmp_path):
        """Requester-pays (§28 fix): a piece pull carrying a requester
        tenant charges THAT tenant's bucket — the task owner's bucket
        stays untouched, so a cross-tenant flood cannot starve the
        owner's own budget."""
        from dragonfly2_tpu.daemon.upload import UploadThrottled

        policy = QoSPolicy.from_payload({
            "t-owner": {"tenant_class": "background",
                        "upload_rate_bytes_s": 2048.0},
            # The requester must be a KNOWN tenant (policy row) for the
            # unauthenticated wire header to be honored at all.
            "t-req": {"tenant_class": "silver"},
        })
        um = self._um(tmp_path, policy)
        um.register_task_tenant("t", "t-owner")
        # A flood of requester-tagged pulls well past the owner's cap.
        for n in range(16):
            assert um.serve_piece(
                "t", n % 4, requester_tenant="t-req"
            ) == bytes(1024)
        assert um.tenant_bytes["t-req"] == 16 * 1024
        assert um.tenant_bytes.get("t-owner", 0) == 0
        # The owner's untagged pull still has its full budget; pre-fix
        # the flood above drained it and this raised UploadThrottled.
        assert um.serve_piece("t", 0) == bytes(1024)
        assert um.tenant_bytes["t-owner"] == 1024
        # And the requester's class throttles the requester, not the
        # owner, when ITS OWN bucket runs dry.
        policy2 = QoSPolicy.from_payload({
            "t-cheap": {"tenant_class": "background",
                        "upload_rate_bytes_s": 2048.0},
        })
        um2 = self._um(tmp_path / "2", policy2)
        um2.register_task_tenant("t", "t-free")
        with pytest.raises(UploadThrottled):
            for n in range(8):
                um2.serve_piece("t", n % 4, requester_tenant="t-cheap")
        assert um2.tenant_bytes.get("t-free", 0) == 0

    def test_spoofed_requester_tenant_falls_back_to_owner(self, tmp_path):
        """The X-Dragonfly-Tenant header is unauthenticated: a name the
        daemon cannot vouch for (no QoS-policy row, never registered as
        a task owner) is treated as ABSENT — attribution falls back to
        the task owner, the fabricated name gets no bucket or byte-total
        entry, and a stranger cannot steer a victim's bucket into debt
        by stamping the victim's id."""
        policy = QoSPolicy.from_payload({
            "t-owner": {"tenant_class": "background",
                        "upload_rate_bytes_s": 1 << 20},
        })
        um = self._um(tmp_path, policy)
        um.register_task_tenant("t", "t-owner")
        # Rotating fabricated names: all serves bill the owner, and the
        # accounting maps never learn the fabricated ids.
        for n in range(8):
            assert um.serve_piece(
                "t", n % 4, requester_tenant=f"t-forged-{n}"
            ) == bytes(1024)
        assert um.tenant_bytes == {"t-owner": 8 * 1024}
        assert not any(t.startswith("t-forged") for t in um._tenant_bw)
        # A tenant KNOWN from local task registration (no policy row) is
        # still honored — same-cluster cross-tenant pulls keep working.
        um.register_task_tenant("t-other-task", "t-neighbor")
        assert um.serve_piece(
            "t", 0, requester_tenant="t-neighbor"
        ) == bytes(1024)
        assert um.tenant_bytes["t-neighbor"] == 1024
        assert um.tenant_bytes["t-owner"] == 8 * 1024

    def test_requester_pays_rides_the_wire_header(self, tmp_path):
        """X-Dragonfly-Tenant on a piece GET reaches begin/end_upload:
        the serving peer's accounting lands on the requester over both
        transports (piece GET and Range GET)."""
        import urllib.request

        from dragonfly2_tpu.rpc.piece_transport import (
            HTTPPieceFetcher,
            PieceHTTPServer,
        )

        um = self._um(
            tmp_path, QoSPolicy.from_payload({"t-req": {}})
        )
        um.register_task_tenant("t", "t-owner")
        server = PieceHTTPServer(um)
        server.serve()
        try:
            fetcher = HTTPPieceFetcher(
                lambda hid: ("127.0.0.1", server.port), tenant="t-req"
            )
            assert fetcher.fetch("h", "t", 0) == bytes(1024)
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/tasks/t",
                headers={"Range": "bytes=0-511",
                         "X-Dragonfly-Tenant": "t-req"},
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                assert resp.status == 206 and len(resp.read()) == 512
            # The sendfile arm bills in the handler thread's ``finally``,
            # which can land a beat after the client drains the body.
            deadline = time.monotonic() + 5.0
            while (
                um.tenant_bytes["t-req"] != 1024 + 512
                and time.monotonic() < deadline
            ):
                time.sleep(0.01)
            assert um.tenant_bytes["t-req"] == 1024 + 512
            assert um.tenant_bytes.get("t-owner", 0) == 0
        finally:
            fetcher.close()
            server.stop()


# ---------------------------------------------------------------------------
# weighted-fair DRR drain (satellite property tests)
# ---------------------------------------------------------------------------


def _mk_req(tenant: str, rows: int, tag: float) -> _Request:
    return _Request(
        np.full((rows, 2), tag, dtype=np.float32), None, None, tenant=tenant
    )


def _enqueue(b: ScorerBatcher, req: _Request) -> None:
    lane = b._lanes.get(req.tenant)
    if lane is None:
        lane = b._lanes[req.tenant] = deque()
    lane.append(req)
    b._pending_rows += req.rows


class TestDRRWeightedFair:
    def test_flood_cannot_starve_one_weight_tenant(self):
        """(a) a 100-weight flood vs a 1-weight tenant: every
        cap-limited drain serves the small tenant SOMETHING."""
        policy = QoSPolicy.from_payload({
            "flood": {"tenant_class": "gold", "weight": 100.0},
            "small": {"tenant_class": "bronze", "weight": 1.0},
        })
        b = ScorerBatcher(max_batch_rows=256, qos_policy=policy)
        rng = np.random.default_rng(0)
        for i in range(200):
            _enqueue(b, _mk_req("flood", int(rng.integers(4, 16)), i))
        for i in range(20):
            _enqueue(b, _mk_req("small", 8, 1000 + i))
        drains = 0
        small_served_per_drain = []
        while b._pending_rows > 0 and drains < 64:
            batch = b._drain_locked()
            drains += 1
            small_served_per_drain.append(
                sum(1 for r in batch if r.tenant == "small")
            )
            if not any(
                r.tenant == "small"
                for dq in [b._lanes.get("small", deque())] for r in dq
            ):
                break  # small lane fully drained — starvation impossible now
        assert all(n >= 1 for n in small_served_per_drain), (
            "a drain passed over the 1-weight lane entirely: "
            f"{small_served_per_drain}"
        )

    def test_per_tenant_fifo_order_preserved(self):
        """(b) within a tenant, service order is arrival order —
        whatever the interleaving across tenants."""
        policy = QoSPolicy.from_payload({
            "x": {"tenant_class": "gold", "weight": 3.0},
            "y": {"tenant_class": "silver", "weight": 1.0},
        })
        rng = np.random.default_rng(7)
        b = ScorerBatcher(max_batch_rows=64, qos_policy=policy)
        seq = {"x": [], "y": []}
        for i in range(120):
            tenant = "x" if rng.random() < 0.6 else "y"
            req = _mk_req(tenant, int(rng.integers(1, 9)), i)
            seq[tenant].append(req)
            _enqueue(b, req)
        served: list = []
        while b._pending_rows > 0:
            served.extend(b._drain_locked())
        for tenant in ("x", "y"):
            order = [r for r in served if r.tenant == tenant]
            assert order == seq[tenant], f"{tenant} lane reordered"
        assert len(served) == 120

    def test_single_tenant_degrades_to_single_queue(self):
        """(c) one active tenant: the drain is the whole-queue swap —
        orderings AND scores bit-equal to the pre-QoS single-queue
        behavior (the §14 scalar-oracle discipline)."""

        class RecScorer:
            wants_features = True

            def __init__(self):
                self.calls = []

            def score(self, feats, src_buckets=None, dst_buckets=None):
                self.calls.append(np.array(feats, copy=True))
                return feats.sum(axis=1)

        policy = QoSPolicy.from_payload({
            "only": {"tenant_class": "gold", "weight": 2.0},
        })
        rng = np.random.default_rng(3)
        reqs = [
            _mk_req("only", int(rng.integers(1, 7)), i) for i in range(40)
        ]
        with_qos = ScorerBatcher(qos_policy=policy)
        for r in reqs:
            _enqueue(with_qos, r)
        batch = with_qos._drain_locked()
        assert batch == reqs, "single-lane drain is not arrival order"
        assert with_qos._pending_rows == 0 and not with_qos._lanes
        # End-to-end score parity vs the direct scorer (row independence
        # + coalesced call on the exact arrival order).
        scorer = RecScorer()
        b = ScorerBatcher(scorer, linger_s=0.0, qos_policy=policy)
        feats = rng.standard_normal((5, 3)).astype(np.float32)
        out = b.score(feats, tenant="only")
        np.testing.assert_array_equal(out, feats.sum(axis=1))

    def test_two_tenant_throughput_share_tracks_weights(self):
        """DRR proportionality: over a long backlog, rows served per
        cap-limited drain track the declared weights (loosely — DRR is
        packet-fair, not fluid-fair)."""
        policy = QoSPolicy.from_payload({
            "heavy": {"tenant_class": "gold", "weight": 3.0},
            "light": {"tenant_class": "bronze", "weight": 1.0},
        })
        b = ScorerBatcher(max_batch_rows=128, qos_policy=policy)
        for i in range(300):
            _enqueue(b, _mk_req("heavy", 8, i))
            _enqueue(b, _mk_req("light", 8, i))
        batch = b._drain_locked()
        heavy_rows = sum(r.rows for r in batch if r.tenant == "heavy")
        light_rows = sum(r.rows for r in batch if r.tenant == "light")
        assert light_rows > 0
        ratio = heavy_rows / light_rows
        assert 1.5 <= ratio <= 6.0, f"share ratio {ratio} vs weights 3:1"

    def test_threaded_two_tenant_flushes_complete(self):
        """End-to-end through score(): concurrent tenants coalesce and
        every follower gets its own rows' scores back."""

        class SumScorer:
            wants_features = True

            def score(self, feats, src_buckets=None, dst_buckets=None):
                return feats.sum(axis=1)

        policy = _two_tenant_policy()
        b = ScorerBatcher(SumScorer(), linger_s=0.002, qos_policy=policy)
        errors: list = []

        def worker(tenant, tag):
            rng = np.random.default_rng(tag)
            for _ in range(30):
                f = np.full((int(rng.integers(1, 6)), 2), float(tag),
                            dtype=np.float32)
                out = b.score(f, tenant=tenant)
                if not np.array_equal(out, f.sum(axis=1)):
                    errors.append((tenant, tag))

        threads = [
            threading.Thread(target=worker, args=("t-a", 1), daemon=True),
            threading.Thread(target=worker, args=("t-b", 2), daemon=True),
            threading.Thread(target=worker, args=("t-b", 3), daemon=True),
        ]
        for t in threads:
            t.start()
        for t in threads:
            while t.is_alive():
                t.join(5.0)
        assert not errors
        assert b._pending_rows == 0


# ---------------------------------------------------------------------------
# tenant-aware admission
# ---------------------------------------------------------------------------


def _overloaded_controller(policy, *, p99_ratio=1.3) -> AdmissionController:
    """Controller whose latency burn sits at a controlled intermediate
    overload (ratio× budget ⇒ overload = ratio − 1)."""
    ctl = AdmissionController(
        max_inflight=10_000, p99_budget_s=0.010,
        accounting=TenantAccounting(policy, window_s=1e9),
    )
    for _ in range(300):
        ctl.observe(0.010 * p99_ratio)
    return ctl


class TestTenantAdmission:
    def test_noisy_tenant_lowest_band_sheds_first(self):
        policy = _two_tenant_policy()
        ctl = _overloaded_controller(policy, p99_ratio=1.3)
        # Make t-b over quota (usage ≫ its 1/5 weight share).
        for _ in range(400):
            ctl.accounting.note("t-b")
        for _ in range(40):
            ctl.accounting.note("t-a")
        over = ctl.overload()
        assert 0.2 < over < 0.45, over
        # At this overload a within-quota tenant's LEVEL3 is ADMITTED
        # (floor ≈ 4.2) while the noisy tenant's LEVEL3 SHEDS (noise
        # scales its floor down ~3x).
        ctl.admit(Priority.LEVEL3, tenant="t-a")
        with pytest.raises(ShardSaturatedError):
            ctl.admit(Priority.LEVEL3, tenant="t-b")
        snap = ctl.accounting.snapshot()
        assert snap["t-b"]["sheds"] >= 1
        assert snap["t-a"]["sheds"] == 0

    def test_declared_class_floors_priority(self):
        """A background-class tenant cannot claim LEVEL0: its requests
        run at its declared priority, which sheds under overload."""
        policy = QoSPolicy.from_payload({
            "t-bg": {"tenant_class": "background", "weight": 1.0,
                     "priority": 6},
        })
        ctl = _overloaded_controller(policy, p99_ratio=1.2)
        with pytest.raises(ShardSaturatedError):
            ctl.admit(Priority.LEVEL0, tenant="t-bg")

    def test_announce_rate_cap_is_a_typed_refusal(self):
        policy = QoSPolicy.from_payload({
            "t-b": {"tenant_class": "background", "weight": 1.0,
                    "announce_qps": 5, "announce_burst": 2},
        })
        ctl = AdmissionController(
            max_inflight=100,
            accounting=TenantAccounting(policy, window_s=1e9),
        )
        refusals = 0
        for _ in range(40):
            try:
                ctl.admit(Priority.LEVEL0, tenant="t-b")
            except ShardSaturatedError as exc:
                refusals += 1
                assert exc.retry_after_s > 0
        assert refusals >= 30

    def test_no_accounting_is_the_pre_qos_behavior(self):
        ctl = AdmissionController(max_inflight=100)
        for _ in range(50):
            ctl.admit(Priority.LEVEL6, tenant="t-anything")

    def test_shed_bias_tightens_the_floor(self):
        ctl = AdmissionController(max_inflight=10_000, p99_budget_s=10.0)
        ctl.admit(Priority.LEVEL6)  # healthy: everything admitted
        ctl.set_shed_bias(0.3)
        with pytest.raises(ShardSaturatedError):
            ctl.admit(Priority.LEVEL6)
        ctl.admit(Priority.LEVEL0)  # LEVEL0 never band-sheds
        ctl.set_shed_bias(0.0)
        ctl.admit(Priority.LEVEL6)


# ---------------------------------------------------------------------------
# SLO autopilot
# ---------------------------------------------------------------------------


_DRILL_SLO = {
    "name": "announce-p99",
    "objective": "latency",
    "target": 0.9,
    "metric": "scheduler_announce_seconds",
    "threshold_ms": 10.0,
    "fast_window_s": 0.3,
    "slow_window_s": 1.0,
    "burn_threshold": 2.0,
}


class TestAutopilot:
    def test_tighten_and_hysteresis_relax(self):
        pilot = SLOAutopilot([_DRILL_SLO], relax_after=3, max_level=4)
        levels = [pilot._step(True, float(i)) for i in range(6)]
        assert levels == [1, 2, 3, 4, 4, 4]
        # Relax needs 3 consecutive healthy evaluations per step down.
        levels = [pilot._step(False, 10.0 + i) for i in range(7)]
        assert levels == [4, 4, 3, 3, 3, 2, 2]
        # A breach mid-recovery resets the streak AND re-tightens.
        assert pilot._step(True, 20.0) == 3
        pilot.close()

    def test_applies_to_admission_and_accounting(self):
        ctl = AdmissionController(max_inflight=100)
        acct = TenantAccounting(QoSPolicy())
        pilot = SLOAutopilot(
            [_DRILL_SLO], admission=ctl, accounting=acct,
            shed_bias_step=0.25, cap_backoff=0.5,
        )
        pilot._step(True, 0.0)
        assert ctl.shed_bias() == pytest.approx(0.25)
        assert acct.cap_factor() == pytest.approx(0.5)
        for i in range(10):
            pilot._step(False, 1.0 + i)
        assert ctl.shed_bias() == 0.0
        assert acct.cap_factor() == 1.0
        pilot.close()

    def test_overload_drill_fires_tightens_relaxes_and_replays(self):
        """ISSUE 15 acceptance: synthetic overload fires the declared
        SLO within one fast window, the shed floor tightens, recovery
        relaxes it, and journal replay reproduces the live decision
        sequence exactly (drift 0 after settle)."""
        from dragonfly2_tpu.utils.metric_journal import (
            MetricJournal,
            replay_metric_journal,
        )
        from dragonfly2_tpu.utils.metrics import Registry

        reg = Registry()
        sketch = reg.sketch(_DRILL_SLO["metric"], "drill announce latency")
        ctl = AdmissionController(max_inflight=100)
        path = tempfile.mktemp(suffix=".dfmj")
        journal = MetricJournal(
            path, registry=reg, service="qos-drill", interval_s=3600.0
        )
        live = SLOAutopilot([_DRILL_SLO], admission=ctl)
        good = _DRILL_SLO["threshold_ms"] / 1e3 * 0.1
        bad = _DRILL_SLO["threshold_ms"] / 1e3 * 4.0

        def step(latency: float):
            for _ in range(5):
                sketch.observe(latency)
            journal.write_snapshot()
            live.ingest(journal.last_snapshot)
            time.sleep(0.01)

        try:
            # Healthy phase: one slow window.
            deadline = time.monotonic() + _DRILL_SLO["slow_window_s"]
            while time.monotonic() < deadline:
                step(good)
            assert live.level == 0 and ctl.shed_bias() == 0.0
            # Overload: the breach (and the first tighten) must land
            # within ~one fast window.
            t0 = time.monotonic()
            fired_after = None
            deadline = t0 + _DRILL_SLO["fast_window_s"] * 1.5
            while time.monotonic() < deadline:
                step(bad)
                if live.level > 0:
                    fired_after = time.monotonic() - t0
                    break
            assert fired_after is not None, "autopilot never tightened"
            assert fired_after <= _DRILL_SLO["fast_window_s"] * 1.25
            # Keep burning: the bias must be tightened while breached.
            for _ in range(5):
                step(bad)
            assert ctl.shed_bias() > 0.0
            peak = live.level
            assert peak >= 2
            # Recovery: good traffic until fully relaxed, then settle.
            deadline = time.monotonic() + _DRILL_SLO["slow_window_s"] * 3
            while time.monotonic() < deadline and live.level > 0:
                step(good)
            assert live.level == 0, "autopilot never relaxed"
            assert ctl.shed_bias() == 0.0
            for _ in range(10):
                step(good)  # settle
        finally:
            journal.close()
        try:
            snaps, stats = replay_metric_journal(path)
            assert stats["corrupt"] == 0
            replayed = SLOAutopilot.replay(snaps, [_DRILL_SLO])
            n = len(live.decisions)
            # Replay sees one extra frame (journal.close's final write);
            # every LIVE decision must be reproduced exactly — breach
            # verdicts, levels, and timestamps (drift 0).
            assert replayed.decisions[:n] == live.decisions
            assert replayed.levels()[:n] == live.levels()
            assert max(replayed.levels()) == peak
            replayed.close()
        finally:
            live.close()
            os.unlink(path)


# ---------------------------------------------------------------------------
# service / wire / manager plumbing
# ---------------------------------------------------------------------------


def _service(with_batcher=False, policy=None):
    from dragonfly2_tpu.scheduler import (
        Evaluator,
        HostFeatureCache,
        Resource,
        SchedulerService,
        Scheduling,
        SchedulingConfig,
        ShardGuard,
    )

    ctl = AdmissionController(
        max_inflight=100, accounting=TenantAccounting(policy or QoSPolicy())
    )
    guard = ShardGuard("qos-s0", admission=ctl)
    service = SchedulerService(
        Resource(),
        Scheduling(
            Evaluator(feature_cache=HostFeatureCache(max_hosts=256)),
            SchedulingConfig(retry_interval=0),
        ),
        shard_guard=guard,
    )
    return service, ctl


def _host(i: int):
    from dragonfly2_tpu.scheduler.resource import Host

    h = Host(
        id=f"qh-{i}", hostname=f"qh-{i}", ip=f"10.8.0.{i}", port=8002,
        download_port=8001,
    )
    h.stats.network.idc = "idc-q"
    return h


class TestServiceQoSWiring:
    def test_register_stamps_tenant_and_set_policy_installs(self):
        policy = _two_tenant_policy()
        service, ctl = _service()
        service.set_qos_policy(policy)
        assert ctl.accounting.policy is policy
        res = service.register_peer(
            host=_host(1), url="https://o/x", tenant="t-b",
        )
        assert res.peer.tenant == "t-b"
        assert "t-b" in ctl.accounting.snapshot()

    def test_on_qos_config_skips_malformed(self):
        service, ctl = _service()
        service.on_qos_config({"tenant_qos": {"t-a": {"weight": -1}}})
        assert service.qos_policy is None
        service.on_qos_config({"tenant_qos": "nonsense"})
        assert service.qos_policy is None
        service.on_qos_config(
            {"tenant_qos": {"t-a": {"tenant_class": "gold"}}}
        )
        assert service.qos_policy is not None

    def test_announce_answer_republishes_tenant_qos(self):
        from dragonfly2_tpu.rpc.scheduler_server import SchedulerRPCAdapter
        from dragonfly2_tpu.rpc.scheduler_server import host_to_wire

        policy = _two_tenant_policy()
        service, _ctl = _service()
        service.set_qos_policy(policy)
        adapter = SchedulerRPCAdapter(service)
        out = adapter.announce_host(
            {"host": host_to_wire(_host(2)), "tenant": "t-a"}
        )
        assert out["tenant_qos"] == policy.to_payload()
        # Tenant rode the wire into accounting.
        snap = service.shard_guard.admission.accounting.snapshot()
        assert snap["t-a"]["requests"] == 1

    def test_wire_register_decodes_tenant(self):
        from dragonfly2_tpu.rpc.scheduler_server import (
            SchedulerRPCAdapter,
            host_to_wire,
        )

        service, _ctl = _service()
        adapter = SchedulerRPCAdapter(service)
        h = _host(3)
        adapter.announce_host({"host": host_to_wire(h)})
        out = adapter.register_peer({
            "host_id": h.id, "url": "https://o/y", "tenant": "t-b",
        })
        peer = service.resource.peer_manager.load(out["peer_id"])
        assert peer.tenant == "t-b"


class TestManagerTenantQoS:
    def test_cluster_blob_validated_on_write(self):
        from dragonfly2_tpu.manager.crud import CrudStore

        crud = CrudStore()
        with pytest.raises(ValueError):
            crud.create(
                "cluster", id="c1", tenant_qos={"t-a": {"weight": 0}}
            )
        crud.create(
            "cluster", id="c1",
            tenant_qos={"t-a": {"tenant_class": "gold", "weight": 2.0}},
        )
        cfg = crud.cluster_config("c1")
        assert cfg["tenant_qos"]["t-a"]["weight"] == 2.0

    def test_update_accepts_tenant_qos_on_legacy_rows(self):
        """A cluster row persisted before tenant_qos existed still
        accepts updates to it (declared fields, not row keys)."""
        from dragonfly2_tpu.manager.crud import CrudStore
        from dragonfly2_tpu.manager.state import MemoryBackend

        backend = MemoryBackend()
        # Simulate a pre-§26 persisted row (no tenant_qos key).
        backend.table("crud").put("cluster:old", {
            "id": "old", "name": "old", "is_default": False,
            "scheduler_cluster_config": {}, "client_config": {},
            "scopes": {},
        })
        crud = CrudStore(backend=backend)
        crud.update(
            "cluster", "old",
            tenant_qos={"t-x": {"tenant_class": "bronze"}},
        )
        assert crud.cluster_config("old")["tenant_qos"]["t-x"][
            "tenant_class"
        ] == "bronze"

    def test_config_route_derives_tenant_for_authenticated_poll(self):
        import urllib.request

        from dragonfly2_tpu.manager.cluster import ClusterManager
        from dragonfly2_tpu.manager.crud import CrudStore
        from dragonfly2_tpu.manager.registry import ModelRegistry
        from dragonfly2_tpu.manager.rest import ManagerRESTServer
        from dragonfly2_tpu.manager.users import UserStore
        from dragonfly2_tpu.security.tokens import Role

        users = UserStore()
        user = users.create_user("daemon-bot", "password123", role=Role.PEER)
        _pat, raw = users.create_pat(user.id, "qos")
        server = ManagerRESTServer(
            ModelRegistry(), ClusterManager(), crud=CrudStore(), users=users
        )
        server.serve()
        try:
            url = f"{server.url}/api/v1/clusters/default:config"
            with urllib.request.urlopen(url, timeout=5) as resp:
                anon = json.loads(resp.read())
            assert "tenant_id" not in anon
            req = urllib.request.Request(
                url, headers={"Authorization": f"Bearer {raw}"}
            )
            with urllib.request.urlopen(req, timeout=5) as resp:
                authed = json.loads(resp.read())
            assert authed["tenant_id"] == derive_tenant(user.id)
            assert "tenant_qos" in authed
        finally:
            server.stop()


class TestPreheatBackgroundClass:
    def test_fanout_carries_level6_and_handler_applies_it(self):
        from dragonfly2_tpu.jobs.preheat import (
            PREHEAT_PRIORITY,
            make_preheat_handler,
            preheat,
        )
        from dragonfly2_tpu.jobs.queue import JobQueue

        assert PREHEAT_PRIORITY is Priority.LEVEL6
        broker = JobQueue()
        job = preheat(broker, ["https://o/a"], ["scheduler:s1"])
        queued = broker.get("scheduler:s1", timeout=1.0)
        assert queued is not None
        assert queued.args["priority"] == int(Priority.LEVEL6)
        assert job.urls == ["https://o/a"]

        calls = []

        class SeedStub:
            def download(self, url, **kw):
                calls.append(kw)

                class R:
                    ok = True
                    pieces = 1

                return R()

        handler = make_preheat_handler(SeedStub())
        handler({"urls": ["https://o/a"], "piece_size": 4096,
                 "priority": int(Priority.LEVEL6)})
        assert calls[0]["priority"] is Priority.LEVEL6
        # Legacy args without a priority key default to the background
        # class too (an old manager fanning to a new scheduler).
        handler({"urls": ["https://o/a"], "piece_size": 4096})
        assert calls[1]["priority"] is Priority.LEVEL6


class TestShardRouterRetryBudget:
    """Satellite: a briefly-saturated shard is a wait, not a failure."""

    def _router(self, answers, **kw):
        from dragonfly2_tpu.rpc.resolver import ShardRouter
        from dragonfly2_tpu.scheduler.sharding import ShardRing
        import random

        calls = {"n": 0}

        class Client:
            def hit(self):
                i = calls["n"]
                calls["n"] += 1
                a = answers[min(i, len(answers) - 1)]
                if isinstance(a, Exception):
                    raise a
                return a

        router = ShardRouter(
            factory=lambda url: Client(),
            backoff_rng=random.Random(1),
            **kw,
        )
        router.update_ring(ShardRing({"s0": "http://s0:1"}, version=1))
        return router, calls

    def test_second_retry_after_still_succeeds_within_budget(self):
        router, calls = self._router([
            ShardSaturatedError(retry_after_s=0.01),
            ShardSaturatedError(retry_after_s=0.01),
            "ok",
        ])
        t0 = time.monotonic()
        assert router.call("task-1", lambda c: c.hit()) == "ok"
        assert calls["n"] == 3
        assert time.monotonic() - t0 < 2.0

    def test_budget_bounds_the_waits(self):
        router, calls = self._router(
            [ShardSaturatedError(retry_after_s=0.005)] * 50,
            saturation_retries=2,
        )
        with pytest.raises(ShardSaturatedError):
            router.call("task-1", lambda c: c.hit())
        assert calls["n"] == 3  # initial + 2 budgeted retries

    def test_zero_budget_propagates_immediately(self):
        router, calls = self._router(
            [ShardSaturatedError(retry_after_s=0.005), "ok"],
            saturation_retries=0,
        )
        with pytest.raises(ShardSaturatedError):
            router.call("task-1", lambda c: c.hit())
        assert calls["n"] == 1


class TestBenchQoSSmoke:
    def test_smoke_schema_gate(self):
        out = subprocess.run(
            [sys.executable, str(REPO / "tools" / "bench_qos.py"), "--smoke"],
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, timeout=600, cwd=str(REPO),
        )
        assert out.returncode == 0, out.stdout + out.stderr
        data = json.loads(out.stdout.strip().splitlines()[-1])
        assert data["ok"] is True
        assert data["metric"] == "qos_isolation_score"
        shaped = data["arms"]["shaped"]
        assert shaped["b_sheds"] + shaped["b_throttled"] > 0
        assert shaped["a_downloads_ok"] == data["config"]["a_downloads"]
