"""Daemon data plane tests: native/python piece stores, upload caps,
conductor-driven P2P transfer through the real scheduler, pex, shaper,
quota GC, crash reload."""

import numpy as np
import pytest

from dragonfly2_tpu import native
from dragonfly2_tpu.daemon import (
    Daemon,
    DaemonStorage,
    TrafficShaper,
    UploadManager,
)
from dragonfly2_tpu.daemon.pex import GossipBus, MemberMeta, PeerExchange
from dragonfly2_tpu.daemon.upload import UploadBusy
from dragonfly2_tpu.records.storage import Storage
from dragonfly2_tpu.scheduler import (
    Evaluator,
    NetworkTopology,
    Resource,
    SchedulerService,
    Scheduling,
    SchedulingConfig,
)
from dragonfly2_tpu.scheduler.resource import Host
from dragonfly2_tpu.utils.types import HostType

PIECE = 64 * 1024  # 64 KiB pieces keep the tests fast


def make_host(i, **kw):
    h = Host(
        id=f"host-{i}", hostname=f"host-{i}", ip=f"10.0.0.{i}", port=8002,
        download_port=8001, **kw,
    )
    h.stats.network.idc = "idc-a"
    return h


class FakeOrigin:
    """Deterministic origin content, piece-addressable."""

    def __init__(self, total_pieces=4):
        self.total_pieces = total_pieces
        self.fetches = 0

    def content(self, url, number):
        seed = (hash(url) ^ number) & 0xFFFF
        return bytes((seed + i) % 256 for i in range(PIECE))

    def fetch(self, url, number, piece_size):
        self.fetches += 1
        return self.content(url, number)


@pytest.fixture(params=["native", "python"])
def engine_pref(request):
    if request.param == "native" and not native.available():
        pytest.skip("native library not buildable")
    return request.param == "native"


class TestDaemonStorage:
    def test_write_read_bitmap(self, tmp_path, engine_pref):
        st = DaemonStorage(str(tmp_path / "s"), prefer_native=engine_pref)
        assert st.is_native == engine_pref
        st.register_task("t1", piece_size=PIECE, content_length=4 * PIECE)
        st.write_piece("t1", 0, b"a" * PIECE)
        st.write_piece("t1", 2, b"c" * 100)
        assert st.read_piece("t1", 0) == b"a" * PIECE
        assert st.read_piece("t1", 2) == b"c" * 100
        assert list(st.piece_bitmap("t1", 4)) == [1, 0, 1, 0]
        assert st.task_bytes("t1") == PIECE + 100

    def test_crash_reload(self, tmp_path, engine_pref):
        root = str(tmp_path / "s")
        st = DaemonStorage(root, prefer_native=engine_pref)
        st.register_task("t1", piece_size=PIECE, content_length=2 * PIECE)
        st.write_piece("t1", 1, b"x" * PIECE)
        st.close()
        st2 = DaemonStorage(root, prefer_native=engine_pref)
        assert st2.reload_persistent_tasks(st2.scan_disk_tasks()) == ["t1"]
        assert st2.read_piece("t1", 1) == b"x" * PIECE

    def test_quota_reclaims_lru(self, tmp_path, engine_pref):
        st = DaemonStorage(
            str(tmp_path / "s"), quota_bytes=3 * PIECE, prefer_native=engine_pref
        )
        import time

        for i, tid in enumerate(["old", "mid", "new"]):
            st.register_task(tid, piece_size=PIECE, content_length=2 * PIECE)
            st.write_piece(tid, 0, b"d" * PIECE)
            st.write_piece(tid, 1, b"d" * PIECE)
            time.sleep(0.01)
        reclaimed = st.reclaim()
        assert "old" in reclaimed
        assert st.total_bytes() <= 3 * PIECE


class TestUploadManager:
    def test_concurrency_cap(self, tmp_path):
        st = DaemonStorage(str(tmp_path / "s"), prefer_native=False)
        st.register_task("t", piece_size=PIECE, content_length=PIECE)
        st.write_piece("t", 0, b"z" * PIECE)
        um = UploadManager(st, concurrent_limit=0)
        with pytest.raises(UploadBusy):
            um.serve_piece("t", 0)
        um.concurrent_limit = 1
        assert um.serve_piece("t", 0) == b"z" * PIECE
        assert um.upload_count == 1

    def test_serve_range(self, tmp_path):
        st = DaemonStorage(str(tmp_path / "s"), prefer_native=False)
        st.register_task("t", piece_size=4, content_length=12)
        st.write_piece("t", 0, b"abcd")
        st.write_piece("t", 1, b"efgh")
        st.write_piece("t", 2, b"ijkl")
        um = UploadManager(st)
        assert um.serve_range("t", 2, 8, 4) == b"cdefghij"


class TestTrafficShaper:
    def test_proportional_allocation(self):
        ts = TrafficShaper(100.0, min_share=0.1)
        ts.add_task("a")
        ts.add_task("b")
        assert ts.budget("a") == 50.0
        ts.record("a", 900)
        ts.record("b", 100)
        alloc = ts.allocate()
        assert alloc["a"] > alloc["b"]
        assert alloc["a"] + alloc["b"] == pytest.approx(100.0)
        assert alloc["b"] >= 10.0  # floor


class TestPeerExchange:
    def test_advertise_and_reclaim(self):
        bus = GossipBus()
        a = PeerExchange(MemberMeta("host-a"), bus)
        b = PeerExchange(MemberMeta("host-b"), bus)
        a.serve()
        b.serve()
        a.advertise("task-1", {0, 1, 2})
        assert b.find_peers_with_task("task-1") == ["host-a"]
        assert b.find_peers_with_piece("task-1", 1) == ["host-a"]
        assert b.find_peers_with_piece("task-1", 9) == []
        # Late joiner learns existing holdings.
        c = PeerExchange(MemberMeta("host-c"), bus)
        c.serve()
        assert c.find_peers_with_task("task-1") == ["host-a"]
        # Leave reclaims.
        a.stop()
        assert b.find_peers_with_task("task-1") == []
        assert {m.host_id for m in b.members()} == {"host-c"}


class _Swarm:
    """Scheduler + N daemons in one process."""

    def __init__(self, tmp_path, n_hosts=4, record_storage=None):
        self.resource = Resource()
        self.scheduler = SchedulerService(
            self.resource,
            Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)),
            record_storage,
            NetworkTopology(self.resource.host_manager),
        )
        self.origin = FakeOrigin()
        self.registry = {}
        self.bus = GossipBus()
        self.daemons = []
        for i in range(n_hosts):
            host = make_host(i)
            self.resource.store_host(host)
            d = Daemon(
                host,
                self.scheduler,
                storage_root=str(tmp_path / f"d{i}"),
                daemon_registry=self.registry,
                gossip_bus=self.bus,
                source_fetcher=self.origin,
                prefer_native=False,
            )
            self.daemons.append(d)


class TestConductorE2E:
    def test_first_peer_back_to_source_then_p2p(self, tmp_path):
        swarm = _Swarm(tmp_path)
        url = "https://origin/blob-1"
        # First peer: no parents → back-to-source.
        r0 = swarm.daemons[0].download(
            url, piece_size=PIECE, content_length=4 * PIECE
        )
        assert r0.ok and r0.back_to_source and r0.pieces == 4
        fetches_after_seed = swarm.origin.fetches
        assert fetches_after_seed == 4

        # Second peer: scheduler must hand it daemon 0 as parent; the bytes
        # flow through daemon 0's upload manager, not the origin.
        r1 = swarm.daemons[1].download(url, piece_size=PIECE)
        assert r1.ok and not r1.back_to_source
        assert swarm.origin.fetches == fetches_after_seed  # origin untouched
        assert swarm.daemons[0].upload.upload_count == 4
        # Bytes identical to origin content.
        for n in range(4):
            assert swarm.daemons[1].storage.read_piece(r1.task_id, n) == \
                swarm.origin.content(url, n)

        # Third peer: two candidate parents now.
        r2 = swarm.daemons[2].download(url, piece_size=PIECE)
        assert r2.ok and not r2.back_to_source
        # pex knows the holders.
        assert set(
            swarm.daemons[3].pex.find_peers_with_task(r1.task_id)
        ) >= {"host-0", "host-1"}

    def test_concurrent_back_to_source_groups(self, tmp_path):
        """piece_manager.go:793-873: range groups fetched concurrently."""
        import threading
        import time as _time

        class SlowOrigin(FakeOrigin):
            def __init__(self):
                super().__init__(total_pieces=8)
                self.in_flight = 0
                self.max_in_flight = 0
                self._lock = threading.Lock()

            def fetch(self, url, number, piece_size):
                with self._lock:
                    self.in_flight += 1
                    self.max_in_flight = max(self.max_in_flight, self.in_flight)
                _time.sleep(0.02)
                try:
                    return super().fetch(url, number, piece_size)
                finally:
                    with self._lock:
                        self.in_flight -= 1

        swarm = _Swarm(tmp_path)
        origin = SlowOrigin()
        d = swarm.daemons[0]
        d.conductor.source_fetcher = origin
        d.conductor.concurrent_source_groups = 4
        url = "https://origin/concurrent-blob"
        r = d.download(url, piece_size=PIECE, content_length=8 * PIECE)
        assert r.ok and r.back_to_source and r.pieces == 8
        assert origin.max_in_flight > 1  # groups genuinely overlapped
        for n in range(8):
            assert d.storage.read_piece(r.task_id, n) == origin.content(url, n)
        # Next peer still gets the bytes over P2P.
        r1 = swarm.daemons[1].download(url, piece_size=PIECE)
        assert r1.ok and not r1.back_to_source

    def test_concurrent_back_to_source_group_failure_cancels(self, tmp_path):
        class FlakyOrigin(FakeOrigin):
            def fetch(self, url, number, piece_size):
                if number == 5:
                    raise IOError("origin 500")
                return super().fetch(url, number, piece_size)

        swarm = _Swarm(tmp_path)
        d = swarm.daemons[0]
        d.conductor.source_fetcher = FlakyOrigin()
        d.conductor.concurrent_source_groups = 4
        r = d.download(
            "https://origin/flaky-blob", piece_size=PIECE, content_length=8 * PIECE
        )
        assert not r.ok

    def test_download_records_written(self, tmp_path):
        store = Storage(str(tmp_path / "records"), buffer_size=1)
        swarm = _Swarm(tmp_path, record_storage=store)
        url = "https://origin/blob-2"
        swarm.daemons[0].download(url, piece_size=PIECE, content_length=2 * PIECE)
        swarm.daemons[1].download(url, piece_size=PIECE)
        store.flush()
        downloads = store.list_download()
        assert len(downloads) == 2
        p2p = [d for d in downloads if d.parents]
        assert len(p2p) == 1
        assert p2p[0].parents[0].observed_bandwidth() > 0

    def test_parent_failure_reschedules(self, tmp_path):
        swarm = _Swarm(tmp_path)
        url = "https://origin/blob-3"
        swarm.daemons[0].download(url, piece_size=PIECE, content_length=2 * PIECE)
        swarm.daemons[1].download(url, piece_size=PIECE)
        # Sabotage daemon 0's storage so piece fetches from it fail; the
        # conductor must blocklist it and still finish via daemon 1 or source.
        task_id = swarm.daemons[1].storage.scan_disk_tasks()[0]
        swarm.daemons[0].storage.delete_task(task_id)
        r = swarm.daemons[2].download(url, piece_size=PIECE)
        assert r.ok

    def test_daemon_reload_advertises(self, tmp_path):
        swarm = _Swarm(tmp_path)
        url = "https://origin/blob-4"
        r = swarm.daemons[0].download(url, piece_size=PIECE, content_length=2 * PIECE)
        # Simulate restart: new daemon object on the same storage root.
        swarm.daemons[0].stop()
        d0b = Daemon(
            make_host(0),
            swarm.scheduler,
            storage_root=str(tmp_path / "d0"),
            daemon_registry=swarm.registry,
            gossip_bus=swarm.bus,
            source_fetcher=swarm.origin,
            prefer_native=False,
        )
        assert d0b.reload() == 1
        assert swarm.daemons[1].pex.find_peers_with_task(r.task_id) == ["host-0"]


class TestReviewRegressions:
    def test_large_piece_native_roundtrip(self, tmp_path):
        """Pieces larger than the old 8 MiB buffer cap must read back."""
        import pytest as _pytest
        from dragonfly2_tpu import native as _native

        if not _native.available():
            _pytest.skip("native library not buildable")
        st = DaemonStorage(str(tmp_path / "big"), prefer_native=True)
        big = 12 << 20
        st.register_task("t", piece_size=big, content_length=big)
        data = bytes(range(256)) * (big // 256)
        st.write_piece("t", 0, data)
        assert st.read_piece("t", 0) == data

    def test_shaper_many_tasks_no_negative_budget(self):
        ts = TrafficShaper(100.0, min_share=0.05)
        for i in range(40):
            ts.add_task(f"t{i}")
        ts.record("t0", 10_000)  # t0 hogs the window
        alloc = ts.allocate()
        assert all(v >= 0 for v in alloc.values()), alloc
        assert alloc["t0"] == max(alloc.values())
        assert sum(alloc.values()) == pytest.approx(100.0, rel=1e-6)

    def test_reload_advertises_tail_pieces(self, tmp_path):
        """A daemon holding only tail pieces must advertise them after reload."""
        swarm = _Swarm(tmp_path, n_hosts=2)
        d = swarm.daemons[0]
        tid = "tail-task"
        d.storage.register_task(tid, piece_size=PIECE, content_length=300 * PIECE)
        for n in range(250, 300):
            d.storage.write_piece(tid, n, b"x" * 10)
        d.stop()
        d0b = Daemon(
            make_host(0),
            swarm.scheduler,
            storage_root=str(tmp_path / "d0"),
            daemon_registry=swarm.registry,
            gossip_bus=swarm.bus,
            prefer_native=False,
        )
        assert d0b.reload() == 1
        holders = swarm.daemons[1].pex.find_peers_with_piece(tid, 299)
        assert holders == ["host-0"]

    def test_reclaim_retracts_pex_advertisement(self, tmp_path):
        swarm = _Swarm(tmp_path, n_hosts=2)
        url = "https://origin/evictable"
        r = swarm.daemons[0].download(url, piece_size=PIECE, content_length=2 * PIECE)
        assert swarm.daemons[1].pex.find_peers_with_task(r.task_id) == ["host-0"]
        swarm.daemons[0].delete_task(r.task_id)
        assert swarm.daemons[1].pex.find_peers_with_task(r.task_id) == []

    def test_back_to_source_resumes_not_restarts(self, tmp_path):
        """P2P pieces already on disk are not re-fetched from the origin,
        and their parent attribution survives."""
        swarm = _Swarm(tmp_path)
        url = "https://origin/resume"
        swarm.daemons[0].download(url, piece_size=PIECE, content_length=4 * PIECE)
        fetches_before = swarm.origin.fetches

        # Child daemon: manually drive the conductor so the parent dies
        # mid-download (after serving half the pieces).
        child = swarm.daemons[1]
        reg = swarm.scheduler.register_peer(host=child.host, url=url)
        task = reg.peer.task
        child.storage.register_task(task.id, piece_size=PIECE, content_length=task.content_length)
        parents = reg.schedule.parents
        for n in (0, 1):
            data = child.conductor.piece_fetcher.fetch(parents[0].host.id, task.id, n)
            child.storage.write_piece(task.id, n, data)
            swarm.scheduler.report_piece_finished(
                reg.peer, n, parent_id=parents[0].id, length=len(data), cost_ns=1000
            )
        # Origin serves only the remaining pieces.
        res = child.conductor._pull_from_source(reg.peer, 4, PIECE, 0.0)
        assert res.ok
        assert swarm.origin.fetches == fetches_before + 2  # pieces 2,3 only
        # Parent attribution for pieces 0,1 intact on the peer record.
        assert reg.peer.pieces[0].parent_id == parents[0].id
        assert reg.peer.pieces[2].parent_id == ""


class TestHostAnnouncer:
    def test_embedded_and_wire_announce(self, tmp_path):
        from dragonfly2_tpu.daemon.host_announcer import HostAnnouncer

        swarm = _Swarm(tmp_path, n_hosts=1)
        host = swarm.daemons[0].host
        host.stats.cpu.percent = 0.0
        ann = HostAnnouncer(host, swarm.scheduler, collect_stats=True)
        ann.announce_once()
        stored = swarm.scheduler.resource.host_manager.load(host.id)
        assert stored is host
        # Stats were refreshed from the real machine (memory is nonzero).
        assert host.stats.memory.total > 0


class TestSwarmChurn:
    def test_quota_eviction_mid_swarm_recovers(self, tmp_path):
        """A parent evicts a hot task under quota pressure mid-swarm; later
        children still finish (reschedule or back-to-source) and pex no
        longer routes to the evicted holder."""
        swarm = _Swarm(tmp_path)
        url = "https://origin/churn"
        r0 = swarm.daemons[0].download(url, piece_size=PIECE, content_length=3 * PIECE)
        swarm.daemons[1].download(url, piece_size=PIECE)
        # Daemon 0 hits quota: its copy of the task evicts + retracts.
        swarm.daemons[0].storage.quota_bytes = 0
        evicted = swarm.daemons[0].reclaim()
        assert r0.task_id in evicted
        assert swarm.daemons[2].pex.find_peers_with_task(r0.task_id) == ["host-1"]
        # New child still completes.
        r2 = swarm.daemons[2].download(url, piece_size=PIECE)
        assert r2.ok

    def test_host_leave_reaps_peers_and_topology(self, tmp_path):
        swarm = _Swarm(tmp_path, n_hosts=3)
        url = "https://origin/leaver"
        swarm.daemons[0].download(url, piece_size=PIECE, content_length=2 * PIECE)
        # Probe edges exist touching host-0.
        swarm.scheduler.networktopology.enqueue_probe(
            "host-0", "host-1", __import__("dragonfly2_tpu.scheduler.networktopology",
                fromlist=["Probe"]).Probe("host-1", 1000)
        )
        host0 = swarm.scheduler.resource.host_manager.load("host-0")
        swarm.scheduler.leave_host(host0)
        assert swarm.scheduler.networktopology.edge_count() == 0
        # Peers on host-0 are in Leave and get reaped by GC.
        reaped = swarm.scheduler.resource.peer_manager.run_gc()
        assert reaped >= 1


class TestNativeRecordPath:
    def test_storage_flush_uses_native_when_available(self, tmp_path):
        from dragonfly2_tpu import native
        from dragonfly2_tpu.records.columnar import ColumnarReader
        from dragonfly2_tpu.records.storage import Storage
        from dragonfly2_tpu.records.synthetic import SyntheticCluster

        st = Storage(str(tmp_path / "recs"), buffer_size=10)
        cluster = SyntheticCluster(num_hosts=16, seed=0)
        for dl in cluster.generate_downloads(25):
            st.create_download(dl)
        st.flush()
        paths = st.download_columnar_paths()
        assert paths
        r = ColumnarReader(paths[0])
        assert len(r) > 0
        assert np.isfinite(r.to_array()).all()
        # Mixed writers across flushes stay format-compatible.
        for dl in cluster.generate_downloads(5):
            st.create_download(dl)
        st.flush()
        r2 = ColumnarReader(paths[0])
        assert len(r2) > len(r)


class TestTinyAndSeedTrigger:
    def test_tiny_direct_piece_roundtrip(self, tmp_path):
        """First peer publishes a <=128B task inline; the second peer gets
        the bytes with registration — zero transfers."""
        swarm = _Swarm(tmp_path, n_hosts=2)
        url = "https://origin/tiny-manifest"
        payload = b"x" * 100

        class TinyOrigin:
            def fetch(self, u, n, ps):
                return payload

        swarm.daemons[0].conductor.source_fetcher = TinyOrigin()
        r0 = swarm.daemons[0].download(url, piece_size=65536, content_length=100)
        assert r0.ok and r0.back_to_source
        task = swarm.scheduler.resource.task_manager.load(r0.task_id)
        assert task.direct_piece == payload
        # Second peer: inline bytes, no fetch, no parent.
        r1 = swarm.daemons[1].download(url, piece_size=65536)
        assert r1.ok and not r1.back_to_source and r1.bytes == 100
        assert swarm.daemons[1].storage.read_piece(r1.task_id, 0) == payload
        assert swarm.daemons[0].upload.upload_count == 0

    def test_seed_peer_trigger_warms_cold_task(self, tmp_path):
        """A cold task triggers a seed-peer download so the first normal
        peer gets a parent instead of going back-to-source."""
        swarm = _Swarm(tmp_path, n_hosts=3)
        seed = swarm.daemons[0]
        swarm.scheduler.seed_peer_trigger = lambda url, tid: seed.download(
            url, piece_size=PIECE, content_length=2 * PIECE
        ).ok
        r = swarm.daemons[1].download(
            "https://origin/cold", piece_size=PIECE, content_length=2 * PIECE
        )
        assert r.ok and not r.back_to_source
        assert seed.upload.upload_count == 2  # served both pieces


class TestPeerEngine:
    """The concurrent peer engine (VERDICT r2 missing-#1/#8 done-
    conditions): parallel piece workers, streaming tasks, completed-task
    reuse, piece-metadata subscription to mid-download parents."""

    def _seed(self, swarm, url, n_pieces):
        r = swarm.daemons[0].download(
            url, piece_size=PIECE, content_length=n_pieces * PIECE
        )
        assert r.ok
        return r.task_id

    def test_pieces_fetched_concurrently_with_speedup(self, tmp_path):
        """One task's pieces overlap across 3 parents: wall-clock beats the
        sequential bound (peertask_conductor.go:1009-1077 worker pool)."""
        import time

        swarm = _Swarm(tmp_path, n_hosts=5)
        url = "https://origin/parallel-blob"
        n_pieces = 12
        self._seed(swarm, url, n_pieces)
        for i in (1, 2):  # 3 serveable parents total
            assert swarm.daemons[i].download(url, piece_size=PIECE).ok

        # The in-process fixture's piece costs are microseconds, so ONE
        # noisy fetch under full-suite load (GC pause, CPU contention)
        # trips the 20x-mean bad-node outlier rule on the seed parents
        # (evaluator.is_bad_node) and the scheduler hands the child a
        # single candidate — observed as {'host-0': 12} fan-in.  This
        # test proves the WORKER POOL fans out; bad-node filtering has
        # its own tests.  Level the stats so the candidate set is
        # deterministically all three parents.
        for p in swarm.resource.peer_manager.items():
            with p._mu:
                p.piece_costs_ns.clear()

        child = swarm.daemons[4]
        inner = child.conductor.piece_fetcher
        served_by = {}
        delay = 0.05
        import threading

        gauge = {"now": 0, "max": 0}
        gauge_mu = threading.Lock()

        class SlowFetcher:
            def fetch(self, host_id, task_id, number):
                with gauge_mu:
                    gauge["now"] += 1
                    gauge["max"] = max(gauge["max"], gauge["now"])
                try:
                    time.sleep(delay)
                    data = inner.fetch(host_id, task_id, number)
                finally:
                    with gauge_mu:
                        gauge["now"] -= 1
                served_by.setdefault(host_id, 0)
                served_by[host_id] += 1
                return data

            def piece_bitmap(self, host_id, task_id):
                return inner.piece_bitmap(host_id, task_id)

        child.conductor.piece_fetcher = SlowFetcher()
        t0 = time.monotonic()
        r = child.download(url, piece_size=PIECE)
        wall = time.monotonic() - t0
        assert r.ok and not r.back_to_source and r.pieces == n_pieces
        # Direct concurrency evidence (load-independent, unlike a wall-
        # clock bound): multiple fetches were IN FLIGHT simultaneously,
        # across multiple parents.  Wall time only guards against a fully
        # serialized regression with a generous margin.
        assert gauge["max"] >= 2, f"pieces never overlapped (max={gauge['max']})"
        assert len(served_by) >= 2, f"single-parent fan-in: {served_by}"
        assert wall < n_pieces * delay, f"slower than sequential: {wall:.2f}s"

    def test_completed_task_reuse_skips_scheduler(self, tmp_path):
        """A locally-complete task serves from disk with zero scheduler
        contact (peertask_reuse.go:49)."""
        swarm = _Swarm(tmp_path, n_hosts=2)
        url = "https://origin/reuse-blob"
        self._seed(swarm, url, 4)
        calls = []
        orig = swarm.scheduler.register_peer

        def counting(*a, **k):
            calls.append(1)
            return orig(*a, **k)

        swarm.scheduler.register_peer = counting
        try:
            r = swarm.daemons[0].download(
                url, piece_size=PIECE, content_length=4 * PIECE
            )
        finally:
            swarm.scheduler.register_peer = orig
        assert r.ok and r.reused
        assert r.pieces == 4 and r.bytes == 4 * PIECE
        assert not calls, "reuse path contacted the scheduler"

    def test_concurrent_same_task_downloads_join(self, tmp_path):
        """Two simultaneous downloads of one task run ONE conductor; the
        second attaches (findPeerTaskConductor semantics)."""
        import threading
        import time

        swarm = _Swarm(tmp_path, n_hosts=3)
        url = "https://origin/join-blob"
        n_pieces = 6
        self._seed(swarm, url, n_pieces)

        child = swarm.daemons[2]
        inner = child.conductor.piece_fetcher

        class SlowFetcher:
            def fetch(self, host_id, task_id, number):
                time.sleep(0.03)
                return inner.fetch(host_id, task_id, number)

            def piece_bitmap(self, host_id, task_id):
                return inner.piece_bitmap(host_id, task_id)

        child.conductor.piece_fetcher = SlowFetcher()
        results = []

        def dl():
            results.append(child.download(url, piece_size=PIECE))

        threads = [threading.Thread(target=dl) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(r.ok for r in results)
        assert any(r.reused for r in results), "both runs fetched"
        # The parent served each piece exactly once.
        assert swarm.daemons[0].upload.upload_count == n_pieces

    def test_stream_serves_bytes_before_task_finishes(self, tmp_path):
        """open_stream yields committed pieces while the download is still
        running (StartStreamTask, peertask_manager.go:357-423)."""
        import time

        swarm = _Swarm(tmp_path, n_hosts=3)
        url = "https://origin/stream-early-blob"
        n_pieces = 6
        tid = self._seed(swarm, url, n_pieces)
        expected = b"".join(swarm.origin.content(url, n) for n in range(n_pieces))

        child = swarm.daemons[2]
        child.conductor.piece_parallelism = 1  # strictly one piece at a time
        inner = child.conductor.piece_fetcher

        class SlowFetcher:
            def fetch(self, host_id, task_id, number):
                time.sleep(0.08)
                return inner.fetch(host_id, task_id, number)

            def piece_bitmap(self, host_id, task_id):
                return inner.piece_bitmap(host_id, task_id)

        child.conductor.piece_fetcher = SlowFetcher()
        handle = child.open_stream(url, piece_size=PIECE)
        assert handle.content_length == n_pieces * PIECE
        chunks = handle.chunks()
        first = next(chunks)
        # The run is still alive after the first chunk arrives: bytes
        # flowed BEFORE the task finished.
        assert child.conductor.active_run(tid) is not None
        body = first + b"".join(chunks)
        assert body == expected
        # And the finished task is now reusable with no new traffic.
        h2 = child.open_stream(url, piece_size=PIECE)
        assert h2.reused and h2.read_all() == expected

    def test_child_completes_from_initially_empty_parent(self, tmp_path):
        """VERDICT r2 next-#8 done-condition: the child's only parent
        starts with ZERO pieces; bitmap subscription picks pieces up as
        the parent commits them mid-download."""
        import threading
        import time

        swarm = _Swarm(tmp_path, n_hosts=2)
        url = "https://origin/empty-parent-blob"
        n_pieces = 6

        real_fetch = swarm.origin.fetch

        def slow_fetch(u, number, piece_size):
            time.sleep(0.08)
            return real_fetch(u, number, piece_size)

        swarm.origin.fetch = slow_fetch

        parent = swarm.daemons[0]
        child = swarm.daemons[1]
        child.conductor.piece_poll_interval_s = 0.02
        results = {}

        def parent_dl():
            results["parent"] = parent.download(
                url, piece_size=PIECE, content_length=n_pieces * PIECE
            )

        t = threading.Thread(target=parent_dl)
        t.start()
        # Wait until the parent's run exists and is sized (registered with
        # the scheduler, zero or near-zero pieces on disk yet).
        from dragonfly2_tpu.utils import idgen

        tid = idgen.task_id(url)
        deadline = time.time() + 5
        while time.time() < deadline:
            run = parent.conductor.active_run(tid)
            if run is not None and run.n_pieces > 0:
                break
            time.sleep(0.01)
        assert parent.conductor.active_run(tid) is not None

        r = child.download(url, piece_size=PIECE)
        t.join(timeout=10)
        assert results["parent"].ok and results["parent"].back_to_source
        assert r.ok and not r.back_to_source, "child should ride the parent"
        # Child never touched the origin: 6 fetches total (parent's own).
        assert swarm.origin.fetches == n_pieces
        assert child.read_task_bytes(tid) == b"".join(
            swarm.origin.content(url, n) for n in range(n_pieces)
        )


class TestPexWorkerPool:
    def test_scheduler_down_fallback_overlaps_pieces(self, tmp_path):
        """The pex fallback uses the same worker-pool shape as the
        scheduled path: pieces overlap across gossip-discovered holders."""
        import threading
        import time

        swarm = _Swarm(tmp_path, n_hosts=3)
        url = "https://origin/pex-pool-blob"
        n_pieces = 8
        r = swarm.daemons[0].download(
            url, piece_size=PIECE, content_length=n_pieces * PIECE
        )
        assert r.ok
        assert swarm.daemons[1].download(url, piece_size=PIECE).ok

        child = swarm.daemons[2]
        inner = child.conductor.piece_fetcher
        gauge = {"now": 0, "max": 0}
        mu = threading.Lock()

        class SlowFetcher:
            def fetch(self, host_id, task_id, number):
                with mu:
                    gauge["now"] += 1
                    gauge["max"] = max(gauge["max"], gauge["now"])
                try:
                    time.sleep(0.03)
                    return inner.fetch(host_id, task_id, number)
                finally:
                    with mu:
                        gauge["now"] -= 1

            def piece_bitmap(self, host_id, task_id):
                return inner.piece_bitmap(host_id, task_id)

        child.conductor.piece_fetcher = SlowFetcher()

        # Scheduler down: registration raises → the pex pool takes over.
        def dead_register(**kw):
            raise ConnectionError("scheduler down")

        child.conductor.scheduler = type(
            "Down", (), {"register_peer": staticmethod(dead_register)}
        )()
        r2 = child.conductor.download(
            url, piece_size=PIECE, content_length=n_pieces * PIECE
        )
        assert r2.ok and r2.pieces == n_pieces
        assert gauge["max"] >= 2, f"pex pieces never overlapped: {gauge}"
        assert child.read_task_bytes(r2.task_id) == b"".join(
            swarm.origin.content(url, n) for n in range(n_pieces)
        )[: n_pieces * PIECE]
