"""Seed-sweep reproducibility child (DESIGN.md §27).

Run OUTSIDE conftest by ``tests/test_sim_determinism.py``: the parent
launches this script twice with different ``PYTHONHASHSEED`` values and
asserts stdout is byte-identical — same seed, same simulated behavior,
regardless of interpreter hash salting.

Modes:

``fleet``  — drive the columnar swarm (``sim/fleet.py``) for a few
    ticks against two real scheduler shards; print the deterministic
    projection of the run report (wall-time keys dropped).
``qos``    — run one baseline arm of the QoS drill (``sim/qos.py``,
    no flood threads) plus a digest sweep over the synthetic origin
    content (the ``hash(url)`` regression this gate was built for);
    print the deterministic arm projection + digest.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def run_fleet() -> None:
    from dragonfly2_tpu.sim.fleet import (
        ColumnarPopulation,
        FleetConfig,
        FleetSwarmDriver,
        ShardedFleet,
        deterministic_summary,
    )

    cfg = FleetConfig(
        num_peers=1500, seed=11, download_rate=0.01, task_catalog=16
    )
    driver = FleetSwarmDriver(ColumnarPopulation(cfg), ShardedFleet(2))
    report = driver.run(5)
    sys.stdout.write(json.dumps(deterministic_summary(report), sort_keys=True))


def run_qos() -> None:
    from dragonfly2_tpu.sim import qos as simqos

    cfg = simqos.QoSDrillConfig(
        a_announces=80, a_downloads=2, pieces_per_task=2,
        piece_size=16 * 1024, b_threads=1,
    )
    arm = simqos._run_arm(cfg, shaped=False, burst=False)
    out = {"baseline": simqos.deterministic_summary(arm)}
    origin = simqos._Origin(4096)
    digest = hashlib.sha256()
    for url in ("https://origin.qos/a-0", "https://origin.qos/b-1",
                "https://origin.qos/warm"):
        for number in range(4):
            digest.update(origin.fetch(url, number, 4096))
    out["origin_sha256"] = digest.hexdigest()
    sys.stdout.write(json.dumps(out, sort_keys=True))


def main() -> int:
    mode = sys.argv[1]
    if mode == "fleet":
        run_fleet()
    elif mode == "qos":
        run_qos()
    else:
        raise SystemExit(f"unknown mode {mode!r}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
