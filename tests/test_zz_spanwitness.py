"""Runtime span-witness cross-validation (DESIGN.md §21).

``zz`` prefix: runs LAST, after the suite has exercised every plane, so
the witness (``utils/dfspan.py``, installed by conftest before any test)
has seen the session's full span traffic.

Three directions of validation against DF016's static inventory
(``tools/dflint/checkers/df016_spans.py`` REQUIRED_SPANS):

1. **inventory staleness** — every inventoried module exists and the
   static extractor finds every inventoried site in its AST (the same
   discipline as baseline.toml / the §16 lock graph);
2. **extractor blind spots** — every span the suite OBSERVED from an
   inventoried module must match a site the static extractor found
   there: an unmatched observation means spans are being opened through
   a pattern the extractor cannot see (failure, not silent rot);
3. **runtime coverage** — every inventoried site of every module the
   suite imported must have been observed at runtime: deleting a
   ``remote_span`` (or orphaning its call path) fails HERE as well as in
   the static rule — the acceptance mutation's second half.
"""

from __future__ import annotations

import sys
from pathlib import Path
from typing import Dict, List, Set, Tuple

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from dragonfly2_tpu.utils import dfspan  # noqa: E402
from tools.dflint.checkers.df016_spans import (  # noqa: E402
    REQUIRED_SPANS,
    site_matches,
    span_sites,
    stale_inventory_entries,
)
from tools.dflint.core import load_module  # noqa: E402

pytestmark = pytest.mark.skipif(
    dfspan.witness() is None,
    reason="span witness disabled (DF_SPAN_WITNESS=0)",
)


def _static_sites(rel: str) -> Set[str]:
    return span_sites(load_module(REPO / rel, REPO))


def _module_imported(rel: str) -> bool:
    target = str((REPO / rel).resolve())
    for mod in list(sys.modules.values()):
        f = getattr(mod, "__file__", None)
        if f and str(Path(f).resolve()) == target:
            return True
    return False


def missing_coverage(
    names_by_module: Dict[str, Set[str]], imported: Set[str]
) -> List[Tuple[str, str]]:
    """Inventoried (module, site) pairs the run did NOT observe, for
    modules the run imported.  The mutation test drives this directly
    with a doctored observation set."""
    out: List[Tuple[str, str]] = []
    for rel, sites in REQUIRED_SPANS.items():
        if rel not in imported:
            continue
        names = names_by_module.get(rel, set())
        for site in sites:
            if not any(site_matches(site, n) for n in names):
                out.append((rel, site))
    return out


class TestSpanWitness:
    def test_inventory_not_stale(self):
        assert stale_inventory_entries(REPO) == [], (
            "REQUIRED_SPANS names modules that no longer exist — update "
            "tools/dflint/checkers/df016_spans.py"
        )

    def test_static_extractor_finds_every_inventoried_site(self):
        for rel, sites in REQUIRED_SPANS.items():
            present = _static_sites(rel)
            for site in sites:
                assert site in present, (
                    f"{rel}: inventoried span site {site!r} not found by "
                    "the static extractor — site deleted or renamed "
                    "without updating REQUIRED_SPANS"
                )

    def test_observed_spans_match_static_sites(self):
        """Extractor blind-spot check: a span observed at runtime from an
        inventoried module must correspond to a statically-visible
        site."""
        by_mod = dfspan.witness().names_by_module()
        for rel in REQUIRED_SPANS:
            static = _static_sites(rel)
            for name in by_mod.get(rel, set()):
                assert any(site_matches(s, name) for s in static), (
                    f"{rel}: runtime span {name!r} matches no "
                    "statically-extracted site — the DF016 extractor has "
                    "a blind spot for how this span is opened"
                )

    def test_inventoried_sites_observed_at_runtime(self):
        """The runtime half of the DF016 acceptance bar: every
        inventoried site of every imported module was actually opened
        during this tier-1 run."""
        by_mod = dfspan.witness().names_by_module()
        imported = {rel for rel in REQUIRED_SPANS if _module_imported(rel)}
        # The suite certainly imports the core planes — an empty imported
        # set would make this test vacuously green.
        assert "dragonfly2_tpu/daemon/conductor.py" in imported
        assert "dragonfly2_tpu/rpc/scheduler_server.py" in imported
        missing = missing_coverage(by_mod, imported)
        assert not missing, (
            "inventoried span sites never observed at runtime (span "
            f"deleted, or its call path orphaned): {missing}"
        )

    def test_witness_catches_deleted_span_site(self):
        """Mutation sensitivity, runtime half: drop one module's rpc/*
        observations from the witnessed set — exactly what deleting the
        scheduler_server remote_span would produce — and the coverage
        check must name it."""
        by_mod = dfspan.witness().names_by_module()
        imported = {rel for rel in REQUIRED_SPANS if _module_imported(rel)}
        assert missing_coverage(by_mod, imported) == []
        doctored = {
            rel: (
                {n for n in names if not n.startswith("rpc/")}
                if rel == "dragonfly2_tpu/rpc/scheduler_server.py"
                else names
            )
            for rel, names in by_mod.items()
        }
        missing = missing_coverage(doctored, imported)
        assert ("dragonfly2_tpu/rpc/scheduler_server.py", "rpc/*") in missing
