"""Subprocess body for the multi-tenant QoS chaos drill
(tests/test_qos_chaos.py, DESIGN.md §26).

Modes:

- ``hammer``  build the tenant-aware admission plane (SchedulerService +
  ShardGuard + AdmissionController + TenantAccounting + a two-tenant
  policy) and flood it from announcer threads — tenant B at ~10× tenant
  A, so rate caps and priority-band sheds fire continuously.  Prints
  ``qos-child: ready`` once the storm is running; the parent installs a
  ``crash`` FaultSpec on the ``scheduler.qos.shed`` seam, so the
  process SIGKILLs itself at a deterministic shed mid-burst.
- ``rebuild`` the restarted shard: a fresh process replays the SAME
  deterministic single-threaded request stream (nothing is persisted —
  tenant accounting is rebuilt from traffic, which is the restart
  contract) and prints ONE JSON verdict line: the accounting snapshot
  plus internal-consistency invariants.  The parent asserts two
  independent rebuilds produce IDENTICAL snapshots (deterministic
  rebuild ⇒ no torn state survived the kill).
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

ANNOUNCERS_A = 1
ANNOUNCERS_B = 4


def build():
    from dragonfly2_tpu.qos import QoSPolicy, TenantAccounting
    from dragonfly2_tpu.scheduler import (
        AdmissionController,
        Evaluator,
        HostFeatureCache,
        Resource,
        SchedulerService,
        Scheduling,
        SchedulingConfig,
        ShardGuard,
    )

    policy = QoSPolicy.from_payload({
        "t-a": {"tenant_class": "gold", "weight": 4.0},
        "t-b": {"tenant_class": "background", "weight": 1.0, "priority": 6,
                "announce_qps": 200, "announce_burst": 50},
    })
    ctl = AdmissionController(
        max_inflight=128, p99_budget_s=0.005,
        accounting=TenantAccounting(policy, window_s=1e9),
    )
    guard = ShardGuard("qos-chaos", admission=ctl)
    service = SchedulerService(
        Resource(),
        Scheduling(
            Evaluator(feature_cache=HostFeatureCache(max_hosts=512)),
            SchedulingConfig(retry_interval=0),
        ),
        shard_guard=guard,
    )
    service.set_qos_policy(policy)
    return service, ctl


def _host(tenant: str, i: int):
    from dragonfly2_tpu.scheduler.resource import Host

    h = Host(
        id=f"qc-{tenant}-{i}", hostname=f"qc-{tenant}-{i}",
        ip=f"10.7.0.{i & 255}", port=8002, download_port=8001,
    )
    h.stats.network.idc = "idc-qc"
    return h


def hammer():
    from dragonfly2_tpu.scheduler import ShardSaturatedError
    from dragonfly2_tpu.utils import faultinject

    # The parent's DF_FAULTINJECT scenario (the crash FaultSpec on the
    # scheduler.qos.shed seam) arms the deterministic kill switch.
    faultinject.install_from_env()
    service, ctl = build()
    # Pressure the latency signal so band sheds fire alongside rate
    # caps: the admission sketch sees slow announces.
    for _ in range(200):
        ctl.observe(0.008)
    stop = threading.Event()

    def worker(tenant: str, tid: int):
        hosts = [_host(tenant, tid * 32 + i) for i in range(8)]
        rng = np.random.default_rng(tid)
        while not stop.is_set():
            try:
                service.announce_host(
                    hosts[int(rng.integers(0, len(hosts)))], tenant=tenant
                )
            except ShardSaturatedError:
                pass

    threads = [
        threading.Thread(target=worker, args=("t-a", i), daemon=True)
        for i in range(ANNOUNCERS_A)
    ] + [
        threading.Thread(target=worker, args=("t-b", 100 + i), daemon=True)
        for i in range(ANNOUNCERS_B)
    ]
    for t in threads:
        t.start()
    print("qos-child: ready", flush=True)
    while True:  # the crash fault SIGKILLs us at the Nth shed
        time.sleep(0.1)


def rebuild():
    from dragonfly2_tpu.scheduler import ShardSaturatedError

    service, ctl = build()
    for _ in range(200):
        ctl.observe(0.008)
    # Deterministic replay: single thread, fixed interleave (9 B : 1 A —
    # the same 10x shape the killed process served), fixed virtual clock
    # into the accounting window.
    outcomes = {"t-a": {"ok": 0, "shed": 0}, "t-b": {"ok": 0, "shed": 0}}
    hosts = {
        "t-a": [_host("t-a", i) for i in range(8)],
        "t-b": [_host("t-b", 100 + i) for i in range(8)],
    }
    for i in range(3000):
        tenant = "t-a" if i % 10 == 0 else "t-b"
        try:
            service.announce_host(hosts[tenant][i % 8], tenant=tenant)
            outcomes[tenant]["ok"] += 1
        except ShardSaturatedError:
            outcomes[tenant]["shed"] += 1
    snap = ctl.accounting.snapshot()
    # Internal consistency: every request accounted exactly once, caps
    # a subset of sheds, the noisy tenant identified.
    invariants = {
        "requests_match": all(
            snap[t]["requests"]
            == outcomes[t]["ok"] + outcomes[t]["shed"]
            for t in ("t-a", "t-b")
        ),
        "caps_within_sheds": snap["t-b"]["capped"] <= snap["t-b"]["sheds"],
        "noisy_is_b": snap["t-b"]["over_quota"] > snap["t-a"]["over_quota"],
        "a_never_capped": snap["t-a"]["capped"] == 0,
    }
    print(json.dumps({
        "snapshot": snap,
        "outcomes": outcomes,
        "invariants": invariants,
    }, sort_keys=True), flush=True)


def main():
    mode = sys.argv[1]
    if mode == "hammer":
        hammer()
    elif mode == "rebuild":
        rebuild()
    else:
        raise SystemExit(f"unknown mode {mode!r}")


if __name__ == "__main__":
    main()
