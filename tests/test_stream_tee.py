"""Pass-through streaming tests (ISSUE 14, DESIGN.md §25): the commit
tee's refcount/spill lifecycle, the zero-disk-read witness on live
streams, ranged task streams with range-priority piece ordering, the
RFC-7233 conformance sweep proved byte-identical across the upload
server / proxy / gateway, and the mid-tee SIGKILL drill."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from dragonfly2_tpu.daemon.piece_pipeline import (  # noqa: E402
    CommitTee,
    RefCountedBuffer,
)
from dragonfly2_tpu.utils import faultinject  # noqa: E402
from dragonfly2_tpu.utils.faultinject import FaultInjector, FaultSpec  # noqa: E402
from dragonfly2_tpu.utils.httprange import (  # noqa: E402
    RangeNotSatisfiable,
    parse_range,
)

from tests.test_daemon import PIECE, _Swarm  # noqa: E402


def _count_engine_reads(storage):
    """Wrap the engine's read_piece with a counter — the zero-disk-read
    witness (serve-plane reads are the ONLY callers during a stream)."""
    counts = {"n": 0}
    orig = storage.engine.read_piece

    def counting(*a, **kw):
        counts["n"] += 1
        return orig(*a, **kw)

    storage.engine.read_piece = counting
    return counts


def _slow_fetcher(daemon, delay_s=0.05):
    inner = daemon.conductor.piece_fetcher

    class SlowFetcher:
        def fetch(self, host_id, task_id, number):
            time.sleep(delay_s)
            return inner.fetch(host_id, task_id, number)

        def piece_bitmap(self, host_id, task_id):
            return inner.piece_bitmap(host_id, task_id)

        def wait_piece_bitmap(self, *a, **kw):
            wait = getattr(inner, "wait_piece_bitmap", None)
            return wait(*a, **kw) if wait else None

    daemon.conductor.piece_fetcher = SlowFetcher()


def _seed(swarm, url, n_pieces):
    r = swarm.daemons[0].download(
        url, piece_size=PIECE, content_length=n_pieces * PIECE
    )
    assert r.ok and r.pieces == n_pieces
    return r.task_id


def _expected(swarm, url, n_pieces):
    return b"".join(swarm.origin.content(url, n) for n in range(n_pieces))


class TestRangeParser:
    TOTAL = 1000

    @pytest.mark.parametrize("header,want", [
        ("bytes=0-999", (0, 999)),          # whole representation
        ("bytes=0-99", (0, 99)),            # head
        ("bytes=200-299", (200, 299)),      # middle
        ("bytes=950-", (950, 999)),         # open-ended
        ("bytes=-100", (900, 999)),         # suffix
        ("bytes=-5000", (0, 999)),          # suffix > total clamps to all
        ("bytes=999-999", (999, 999)),      # last byte
        ("bytes=0-5000", (0, 999)),         # end clamps to total-1
    ])
    def test_satisfiable_shapes(self, header, want):
        assert parse_range(header, self.TOTAL) == want

    @pytest.mark.parametrize("header", [
        None, "", "items=0-5", "bytes=", "bytes=abc-def",
        "bytes=5-2",                 # inverted → RFC says ignore
        "bytes=0-10,20-30",          # multi-range → ignore (single only)
        "bytes=--5",
    ])
    def test_ignorable_headers_serve_full_body(self, header):
        assert parse_range(header, self.TOTAL) is None

    @pytest.mark.parametrize("header", [
        "bytes=1000-", "bytes=1000-1005", "bytes=99999-", "bytes=-0",
    ])
    def test_unsatisfiable_raises_416(self, header):
        with pytest.raises(RangeNotSatisfiable) as exc:
            parse_range(header, self.TOTAL)
        assert exc.value.total == self.TOTAL

    def test_zero_length_representation_has_no_ranges(self):
        with pytest.raises(RangeNotSatisfiable):
            parse_range("bytes=0-", 0)
        with pytest.raises(RangeNotSatisfiable):
            parse_range("bytes=-5", 0)


class TestCommitTeeUnit:
    def test_publish_take_releases_refcounted_buffer(self):
        tee = CommitTee()
        c1 = tee.register(depth=4)
        c2 = tee.register(depth=4)
        body = b"piece-0" * 100
        assert tee.publish(0, body) == 2
        # Both consumers hold one ref on the SAME buffer.
        buf = c1._buffered[0]
        assert buf is c2._buffered[0]
        assert buf.refs == 2 and buf.data == body
        assert c1.take(0) == body
        assert buf.refs == 1
        assert c2.take(0) == body
        # Last release frees the bytes.
        assert buf.refs == 0 and buf.data is None
        # Re-take → None (fall back to disk).
        assert c1.take(0) is None

    def test_depth_bound_spills_never_blocks(self):
        tee = CommitTee()
        c = tee.register(depth=2)
        assert tee.publish(0, b"a") == 1
        assert tee.publish(1, b"b") == 1
        t0 = time.monotonic()
        assert tee.publish(2, b"c") == 0  # full → spill, instantly
        assert time.monotonic() - t0 < 0.5
        assert c.spilled == 1 and c.delivered == 2
        assert c.take(2) is None          # spilled piece: disk path
        assert c.take(0) == b"a"
        assert tee.publish(3, b"d") == 1  # space freed → delivered again

    def test_closed_consumer_is_skipped_and_buffers_released(self):
        tee = CommitTee()
        c = tee.register(depth=4)
        tee.publish(0, b"x")
        buf = c._buffered[0]
        c.close()
        assert buf.refs == 0 and buf.data is None
        assert tee.consumer_count() == 0
        assert tee.publish(1, b"y") == 0  # no consumers → no-op
        assert c.take(1) is None
        c.close()  # idempotent

    def test_no_consumers_is_a_cheap_noop(self):
        tee = CommitTee()
        assert tee.publish(0, b"x") == 0
        assert tee.published == 0

    def test_injected_tee_fault_degrades_not_raises(self):
        """A drop on daemon.stream.tee models failed delivery: publish
        absorbs it (consumers go to disk), the commit path never sees
        an exception."""
        tee = CommitTee()
        c = tee.register(depth=4)
        inj = FaultInjector(
            [FaultSpec(site="daemon.stream.tee", kind="drop", at=(0,))]
        )
        with faultinject.installed(inj):
            assert tee.publish(0, b"x") == 0   # faulted → spill-for-all
            assert tee.publish(1, b"y") == 1   # next publish delivers
        assert c.take(0) is None
        assert c.take(1) == b"y"

    def test_injected_spill_fault_is_absorbed(self):
        tee = CommitTee()
        tee.register(depth=1)
        inj = FaultInjector(
            [FaultSpec(site="daemon.stream.spill", kind="drop", every=1)]
        )
        with faultinject.installed(inj):
            tee.publish(0, b"a")
            assert tee.publish(1, b"b") == 0  # spill + injected drop → absorbed

    def test_refcounted_buffer_zero_refs_frees_immediately(self):
        buf = RefCountedBuffer(0, b"data", 0)
        assert buf.data is None


class TestStreamTeeE2E:
    def test_zero_disk_reads_on_fast_path(self, tmp_path):
        """The tentpole witness: a consumer registered before the
        download starts serves EVERY piece from the tee — the engine
        sees zero reads, and the bytes digest-check against origin."""
        swarm = _Swarm(tmp_path, n_hosts=3)
        url = "https://origin/tee-zero-read"
        n_pieces = 6
        _seed(swarm, url, n_pieces)
        child = swarm.daemons[2]
        child.conductor.piece_parallelism = 1
        _slow_fetcher(child, 0.02)
        reads = _count_engine_reads(child.storage)
        handle = child.open_stream(url, piece_size=PIECE)
        body = handle.read_all()
        assert body == _expected(swarm, url, n_pieces)
        assert handle.tee_hits == n_pieces
        assert handle.disk_reads == 0
        assert reads["n"] == 0, "fast path touched the disk"
        assert handle.wait_result(timeout_s=10).ok

    def test_slow_consumer_spills_and_stays_correct(self, tmp_path):
        """A stalled reader cannot wedge the download: its tee buffer
        bounds, overflow spills to disk, bytes stay identical."""
        swarm = _Swarm(tmp_path, n_hosts=3)
        url = "https://origin/tee-slow-consumer"
        n_pieces = 8
        tid = _seed(swarm, url, n_pieces)
        child = swarm.daemons[2]
        child.conductor.stream_tee_depth = 1  # tiny window → spills
        handle = child.open_stream(url, piece_size=PIECE)
        # Let the (loopback-fast) download finish while we stall.
        run = child.conductor.active_run(tid)
        if run is not None:
            assert run.wait_done(30.0) is not None
        body = handle.read_all()
        assert body == _expected(swarm, url, n_pieces)
        # The depth-1 window forced disk reads for the overflow…
        assert handle.disk_reads > 0
        # …and the download itself completed untouched.
        assert child.storage.held_pieces(tid) == n_pieces

    def test_consumer_disconnect_mid_download_releases_tee(self, tmp_path):
        swarm = _Swarm(tmp_path, n_hosts=3)
        url = "https://origin/tee-disconnect"
        n_pieces = 6
        tid = _seed(swarm, url, n_pieces)
        child = swarm.daemons[2]
        child.conductor.piece_parallelism = 1
        _slow_fetcher(child, 0.03)
        handle = child.open_stream(url, piece_size=PIECE)
        run = child.conductor.active_run(tid)
        assert run is not None
        chunks = handle.chunks()
        first = next(chunks)
        assert first == swarm.origin.content(url, 0)
        chunks.close()  # client hung up mid-response
        # The consumer detached (no pinned buffers, no more offers)…
        assert run.tee.consumer_count() == 0
        # …and the download still completes and digest-checks.
        result = run.wait_done(30.0)
        assert result is not None and result.ok
        assert child.read_task_bytes(tid) == _expected(swarm, url, n_pieces)

    def test_two_consumers_share_refcounted_buffers(self, tmp_path):
        swarm = _Swarm(tmp_path, n_hosts=3)
        url = "https://origin/tee-two-consumers"
        n_pieces = 5
        _seed(swarm, url, n_pieces)
        child = swarm.daemons[2]
        child.conductor.piece_parallelism = 1
        _slow_fetcher(child, 0.02)
        h1 = child.open_stream(url, piece_size=PIECE)
        h2 = child.open_stream(url, piece_size=PIECE)
        out = {}

        def drain(name, h):
            out[name] = h.read_all()

        threads = [
            threading.Thread(target=drain, args=("a", h1), daemon=True),
            threading.Thread(target=drain, args=("b", h2), daemon=True),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        expected = _expected(swarm, url, n_pieces)
        assert out["a"] == expected and out["b"] == expected
        # Both rode the tee (second attaches to the running task).
        assert h1.tee_hits + h2.tee_hits >= n_pieces

    def test_reuse_handle_serves_from_disk(self, tmp_path):
        """Cache-hit replay is the DOCUMENTED disk path: a completed
        task's stream has no run and no consumer."""
        swarm = _Swarm(tmp_path, n_hosts=2)
        url = "https://origin/tee-reuse"
        n_pieces = 3
        _seed(swarm, url, n_pieces)
        handle = swarm.daemons[0].open_stream(url, piece_size=PIECE)
        assert handle.reused
        assert handle.read_all() == _expected(swarm, url, n_pieces)
        assert handle.tee_hits == 0 and handle.disk_reads == n_pieces


class TestRangedStreams:
    def test_ranged_stream_yields_exact_window(self, tmp_path):
        swarm = _Swarm(tmp_path, n_hosts=3)
        url = "https://origin/range-window"
        n_pieces = 6
        _seed(swarm, url, n_pieces)
        expected = _expected(swarm, url, n_pieces)
        child = swarm.daemons[2]
        # Straddles pieces 1-3, odd offsets.
        start, length = PIECE + 17, 2 * PIECE + 100
        handle = child.open_stream(
            url, piece_size=PIECE, start=start, length=length
        )
        body = handle.read_all()
        assert body == expected[start : start + length]
        # Only the overlapping pieces were served.
        assert handle.tee_hits + handle.disk_reads == 3

    def test_range_priority_orders_window_pieces_first(self, tmp_path):
        """The scheduling half: a tail range's pieces commit BEFORE the
        rest of the task (range-priority ordering in the piece pull)."""
        swarm = _Swarm(tmp_path, n_hosts=3)
        url = "https://origin/range-priority"
        n_pieces = 8
        _seed(swarm, url, n_pieces)
        child = swarm.daemons[2]
        child.conductor.piece_parallelism = 1
        _slow_fetcher(child, 0.02)
        committed = []
        orig_write = child.storage.write_piece

        def recording_write(task_id, number, data):
            committed.append(number)
            return orig_write(task_id, number, data)

        child.storage.write_piece = recording_write
        start = 6 * PIECE + 10  # pieces 6..7
        handle = child.open_stream(
            url, piece_size=PIECE, start=start, length=None
        )
        body = handle.read_all()
        assert body == _expected(swarm, url, n_pieces)[start:]
        assert handle.wait_result(timeout_s=10).ok
        # The window pieces {6, 7} were fetched before everything else.
        assert set(committed[:2]) == {6, 7}, committed

    def test_ranged_stream_of_completed_task(self, tmp_path):
        swarm = _Swarm(tmp_path, n_hosts=2)
        url = "https://origin/range-reuse"
        n_pieces = 4
        _seed(swarm, url, n_pieces)
        expected = _expected(swarm, url, n_pieces)
        handle = swarm.daemons[0].open_stream(
            url, piece_size=PIECE, start=PIECE - 5, length=10
        )
        assert handle.read_all() == expected[PIECE - 5 : PIECE + 5]


class TestRangeConformance:
    """The satellite sweep: every RFC-7233 shape byte-identical across
    the three range-serving surfaces — the upload piece server's
    ``/tasks/<id>`` endpoint, the dfdaemon proxy, and the object
    gateway — all fed by the same content."""

    SHAPES = [
        "bytes=0-99",                        # head
        "bytes={p}-{p2}",                    # exactly one piece
        "bytes={pm50}-{pp49}",               # straddles a piece boundary
        "bytes={tail}-",                     # open-ended
        "bytes=-100",                        # suffix
        "bytes={last}-{last}",               # single last byte
        "bytes=0-{huge}",                    # end past EOF clamps
    ]

    def _shapes(self, total):
        p = PIECE
        subs = dict(
            p=p, p2=2 * p - 1, pm50=p - 50, pp49=p + 49,
            tail=total - 77, last=total - 1, huge=total * 10,
        )
        return [s.format(**subs) for s in self.SHAPES]

    def _slice(self, blob, header):
        rng = parse_range(header, len(blob))
        assert rng is not None
        return blob[rng[0] : rng[1] + 1]

    def test_sweep_byte_identical_across_surfaces(self, tmp_path):
        from dragonfly2_tpu.daemon.gateway import GatewayConfig, ObjectGateway
        from dragonfly2_tpu.daemon.proxy import (
            P2PProxy,
            ProxyRouter,
            ProxyRule,
        )
        from dragonfly2_tpu.objectstorage.backend import FilesystemBackend
        from dragonfly2_tpu.rpc.piece_transport import PieceHTTPServer

        swarm = _Swarm(tmp_path, n_hosts=2)
        d = swarm.daemons[0]
        backend = FilesystemBackend(str(tmp_path / "objects"))
        gw = ObjectGateway(d, backend, GatewayConfig(piece_size=PIECE))
        blob = os.urandom(3 * PIECE + 123)
        gw.put_object("sweep/blob.bin", blob)
        total = len(blob)
        task_id = gw._task_id("sweep/blob.bin")

        upload_srv = PieceHTTPServer(d.upload)
        upload_srv.serve()
        # The proxy serves the gateway's dfstore:// task through the
        # same conductor; route its url scheme into P2P.
        proxy = P2PProxy(
            d, ProxyRouter([ProxyRule.compile(r"^dfstore://")]),
            piece_size=PIECE,
        )
        proxy.serve()
        object_url = gw._object_url("sweep/blob.bin")
        try:
            for header in self._shapes(total):
                want = self._slice(blob, header)
                # 1) upload server /tasks/<id> (the piece plane's wire).
                req = urllib.request.Request(
                    f"http://127.0.0.1:{upload_srv.port}/tasks/{task_id}",
                    headers={"Range": header},
                )
                with urllib.request.urlopen(req, timeout=10) as resp:
                    assert resp.status == 206, header
                    upload_body = resp.read()
                # 2) proxy (pass-through streaming plane).
                req = urllib.request.Request(
                    f"http://127.0.0.1:{proxy.port}/{object_url}",
                    headers={"Range": header},
                )
                with urllib.request.urlopen(req, timeout=10) as resp:
                    assert resp.status == 206, header
                    rng = parse_range(header, total)
                    assert resp.headers["Content-Range"] == (
                        f"bytes {rng[0]}-{rng[1]}/{total}"
                    ), header
                    proxy_body = resp.read()
                # 3) gateway ranged read.
                (s, e, t), chunks = gw.get_object_range(
                    "sweep/blob.bin", header
                )
                gw_body = b"".join(chunks)
                assert (s, e, t) == (rng[0], rng[1], total), header
                assert upload_body == proxy_body == gw_body == want, header

            # 416 parity: past-EOF start answers 416 on every surface.
            bad = f"bytes={total + 5}-"
            req = urllib.request.Request(
                f"http://127.0.0.1:{upload_srv.port}/tasks/{task_id}",
                headers={"Range": bad},
            )
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=10)
            assert exc.value.code == 416
            req = urllib.request.Request(
                f"http://127.0.0.1:{proxy.port}/{object_url}",
                headers={"Range": bad},
            )
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=10)
            assert exc.value.code == 416
            assert exc.value.headers["Content-Range"] == f"bytes */{total}"
            with pytest.raises(RangeNotSatisfiable):
                gw.get_object_range("sweep/blob.bin", bad)
        finally:
            proxy.stop()
            upload_srv.stop()

    def test_proxy_malformed_range_serves_full_200(self, tmp_path):
        from dragonfly2_tpu.daemon.gateway import GatewayConfig, ObjectGateway
        from dragonfly2_tpu.daemon.proxy import (
            P2PProxy,
            ProxyRouter,
            ProxyRule,
        )
        from dragonfly2_tpu.objectstorage.backend import FilesystemBackend

        swarm = _Swarm(tmp_path, n_hosts=2)
        d = swarm.daemons[0]
        backend = FilesystemBackend(str(tmp_path / "objects"))
        gw = ObjectGateway(d, backend, GatewayConfig(piece_size=PIECE))
        blob = os.urandom(PIECE + 17)
        gw.put_object("sweep/full.bin", blob)
        proxy = P2PProxy(
            d, ProxyRouter([ProxyRule.compile(r"^dfstore://")]),
            piece_size=PIECE,
        )
        proxy.serve()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{proxy.port}/"
                f"{gw._object_url('sweep/full.bin')}",
                headers={"Range": "bytes=9-2"},  # inverted → RFC: ignore
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                assert resp.status == 200
                assert resp.read() == blob
        finally:
            proxy.stop()


class TestStreamChaosKill:
    def test_sigkill_mid_tee_leaves_durable_plane_resumable(self, tmp_path):
        """SIGKILL on the committer thread INSIDE a tee publish (the
        daemon.stream.tee crash seam): the child dies mid-download,
        mid-serve — then a fresh conductor over the same store resumes,
        completes, and digest-checks.  The tee can die at its worst
        moment without corrupting the durable plane."""
        import numpy as np

        from dragonfly2_tpu.daemon import DaemonStorage, UploadManager
        from dragonfly2_tpu.daemon.conductor import Conductor
        from dragonfly2_tpu.records.storage import Storage
        from dragonfly2_tpu.rpc import HTTPPieceFetcher, RemoteScheduler
        from dragonfly2_tpu.rpc.piece_transport import PieceHTTPServer
        from dragonfly2_tpu.rpc.scheduler_server import SchedulerHTTPServer
        from dragonfly2_tpu.scheduler import (
            Evaluator,
            NetworkTopology,
            Resource,
            SchedulerService,
            Scheduling,
            SchedulingConfig,
        )
        from dragonfly2_tpu.scheduler.resource import Host

        n_pieces = 6
        content_length = n_pieces * PIECE
        url = "https://origin/tee-kill-blob"
        rng = np.random.default_rng(5)
        pieces = [
            rng.integers(0, 256, PIECE, dtype=np.uint8).tobytes()
            for _ in range(n_pieces)
        ]

        resource = Resource()
        service = SchedulerService(
            resource,
            Scheduling(Evaluator(), SchedulingConfig(retry_interval=0)),
            Storage(str(tmp_path / "records"), buffer_size=8),
            NetworkTopology(resource.host_manager),
        )
        server = SchedulerHTTPServer(service)
        server.serve()

        # Warm wire parent holding every piece.
        pstore = DaemonStorage(str(tmp_path / "parent"), prefer_native=False)
        pstore.register_task(
            "ignored", piece_size=PIECE, content_length=content_length
        )
        piece_server = PieceHTTPServer(UploadManager(pstore))
        piece_server.serve()
        phost = Host(
            id="tee-parent", hostname="tee-parent", ip="127.0.0.1",
            port=8002, download_port=piece_server.port,
        )
        phost.stats.network.idc = "idc-a"
        pclient = RemoteScheduler(server.url, timeout=5.0)

        class _Origin:
            def fetch(self, u, number, piece_size):
                return pieces[number]

        parent = Conductor(
            phost, pstore, pclient,
            piece_fetcher=HTTPPieceFetcher(pclient.resolve_host),
            source_fetcher=_Origin(),
        )
        warm = parent.download(
            url, piece_size=PIECE, content_length=content_length
        )
        assert warm.ok and warm.pieces == n_pieces

        child_store = str(tmp_path / "childstore")
        scenario = {
            "seed": 0,
            "faults": [
                # The 3rd tee publish dies ON the committer thread.
                FaultSpec(
                    site="daemon.stream.tee", kind="crash", at=(2,)
                ).to_dict(),
                # Pace fetches so the kill lands mid-download.
                FaultSpec(
                    site="piece.fetch", kind="delay", every=1, delay_s=0.03
                ).to_dict(),
            ],
        }
        try:
            proc = subprocess.Popen(
                [
                    sys.executable, str(REPO / "tests" / "_stream_child.py"),
                    server.url, child_store, url,
                    str(content_length), str(PIECE),
                ],
                env={
                    **os.environ,
                    "DF_FAULTINJECT": json.dumps(scenario),
                    "JAX_PLATFORMS": "cpu",
                    "DF_LOCK_WITNESS": "0",
                },
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                cwd=str(REPO),
            )
            try:
                out, err = proc.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                proc.kill()
                out, err = proc.communicate()
                pytest.fail(f"child hung: {out!r} {err!r}")
            assert proc.returncode == -signal.SIGKILL, (
                proc.returncode, out, err,
            )
            assert b'"ok"' not in out, "child finished before the kill"

            # Resume over the same store: the tee's death left the
            # durable plane intact — a fresh conductor completes the
            # task and every byte digest-checks.
            storage2 = DaemonStorage(child_store, prefer_native=False)
            loaded = storage2.reload_persistent_tasks(
                storage2.scan_disk_tasks()
            )
            assert loaded, "no partial task survived the kill"
            held_before = storage2.held_pieces(loaded[0])
            assert 0 < held_before < n_pieces, (
                f"kill landed outside the download ({held_before} pieces)"
            )
            client2 = RemoteScheduler(server.url, timeout=5.0)
            chost = Host(
                id="stream-child-2", hostname="stream-child-2",
                ip="127.0.0.1", port=8002, download_port=1,
            )
            chost.stats.network.idc = "idc-a"
            resumer = Conductor(
                chost, storage2, client2,
                piece_fetcher=HTTPPieceFetcher(
                    client2.resolve_host, timeout=5.0
                ),
                source_fetcher=None,
            )
            r = resumer.download(
                url, piece_size=PIECE, content_length=content_length
            )
            assert r.ok
            assert storage2.read_task_bytes(r.task_id) == b"".join(pieces)
        finally:
            piece_server.stop()
            server.stop()


class TestBenchStreamSmoke:
    def test_smoke_schema_gates_stream_scenario(self, capsys):
        from tools import bench_download

        rc = bench_download.main(["--smoke"])
        line = capsys.readouterr().out.strip().splitlines()[-1]
        out = json.loads(line)
        assert rc == 0 and out["ok"], out
        for arm in ("stream_disk", "stream_tee"):
            assert arm in out["arms"]
            for k in bench_download.ARM_KEYS:
                assert k in out["arms"][arm], (arm, k)
        assert "speedup_stream" in out
        st = out["stream"]
        for k in ("consumers", "disk_reads_tee", "disk_reads_disk",
                  "tee_delivered", "tee_spilled"):
            assert k in st, k
        # The tee arm really rode the tee; the disk arm really paid the
        # round-trip.
        assert st["tee_delivered"] > 0
        assert st["disk_reads_disk"] > st["disk_reads_tee"]
