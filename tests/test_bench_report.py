"""Perf-trajectory table gate (tools/bench_report.py).

BENCHMARKS.md's generated round-trajectory block must match a fresh
render of the ``BENCH_r*.json`` files on disk — the same staleness
discipline as the §16 lock graph and the compile budget, so the perf
history is never again reconstructed by hand from raw JSON."""

from __future__ import annotations

import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.bench_report import (  # noqa: E402
    DOWNLOAD_BEGIN,
    DOWNLOAD_END,
    LIFECYCLE_BEGIN,
    LIFECYCLE_END,
    QOS_BEGIN,
    QOS_END,
    SWARM_BEGIN,
    SWARM_END,
    TELEMETRY_BEGIN,
    TELEMETRY_END,
    TRAJECTORY_BEGIN,
    TRAJECTORY_END,
    collect_download_rounds,
    collect_lifecycle_rounds,
    collect_qos_rounds,
    collect_rounds,
    collect_swarm_rounds,
    collect_telemetry_rounds,
    render_download,
    render_lifecycle,
    render_qos,
    render_swarm,
    render_telemetry,
    render_trajectory,
    update_file,
)


class TestTrajectoryStaleness:
    def test_committed_table_is_current(self):
        rounds = collect_rounds(REPO)
        assert rounds, "no BENCH_r*.json rounds found at the repo root"
        text = (REPO / "BENCHMARKS.md").read_text(encoding="utf-8")
        begin = text.find(TRAJECTORY_BEGIN)
        end = text.find(TRAJECTORY_END)
        assert begin >= 0 and end > begin, (
            "BENCHMARKS.md trajectory markers missing"
        )
        committed = text[begin : end + len(TRAJECTORY_END)]
        fresh = render_trajectory(rounds)
        assert committed == fresh, (
            "BENCHMARKS.md round trajectory is stale — regenerate with "
            "`python -m tools.bench_report --update`"
        )

    def test_every_round_has_a_row(self):
        rounds = collect_rounds(REPO)
        table = render_trajectory(rounds)
        for data in rounds:
            assert f"| r{data['round']:02d} |" in table

    def test_committed_download_table_is_current(self):
        """Same staleness gate for the download-plane rounds
        (tools/bench_download.py → BENCH_DL_r*.json)."""
        dl_rounds = collect_download_rounds(REPO)
        assert dl_rounds, "no BENCH_DL_r*.json rounds found at the repo root"
        text = (REPO / "BENCHMARKS.md").read_text(encoding="utf-8")
        begin = text.find(DOWNLOAD_BEGIN)
        end = text.find(DOWNLOAD_END)
        assert begin >= 0 and end > begin, (
            "BENCHMARKS.md download markers missing"
        )
        committed = text[begin : end + len(DOWNLOAD_END)]
        fresh = render_download(dl_rounds)
        assert committed == fresh, (
            "BENCHMARKS.md download table is stale — regenerate with "
            "`python -m tools.bench_report --update`"
        )
        for data in dl_rounds:
            assert f"| r{data['round']:02d} |" in committed

    def test_committed_telemetry_table_is_current(self):
        """Same staleness gate for the fleet-telemetry drill rounds
        (python -m dragonfly2_tpu.sim.telemetry → TELEMETRY_r*.json)."""
        tel_rounds = collect_telemetry_rounds(REPO)
        assert tel_rounds, "no TELEMETRY_r*.json rounds found at the repo root"
        text = (REPO / "BENCHMARKS.md").read_text(encoding="utf-8")
        begin = text.find(TELEMETRY_BEGIN)
        end = text.find(TELEMETRY_END)
        assert begin >= 0 and end > begin, (
            "BENCHMARKS.md telemetry markers missing"
        )
        committed = text[begin : end + len(TELEMETRY_END)]
        fresh = render_telemetry(tel_rounds)
        assert committed == fresh, (
            "BENCHMARKS.md telemetry table is stale — regenerate with "
            "`python -m tools.bench_report --update`"
        )
        for data in tel_rounds:
            assert f"| r{data['round']:02d} |" in committed

    def test_committed_swarm_table_is_current(self):
        """Same staleness gate for the fleet-swarm rounds
        (tools/bench_swarm.py → BENCH_SW_r*.json)."""
        sw_rounds = collect_swarm_rounds(REPO)
        assert sw_rounds, "no BENCH_SW_r*.json rounds found at the repo root"
        text = (REPO / "BENCHMARKS.md").read_text(encoding="utf-8")
        begin = text.find(SWARM_BEGIN)
        end = text.find(SWARM_END)
        assert begin >= 0 and end > begin, (
            "BENCHMARKS.md swarm markers missing"
        )
        committed = text[begin : end + len(SWARM_END)]
        fresh = render_swarm(sw_rounds)
        assert committed == fresh, (
            "BENCHMARKS.md swarm table is stale — regenerate with "
            "`python -m tools.bench_report --update`"
        )
        for data in sw_rounds:
            assert f"| r{data['round']:02d} |" in committed

    def test_committed_qos_table_is_current(self):
        """Same staleness gate for the multi-tenant QoS rounds
        (tools/bench_qos.py → BENCH_QOS_r*.json)."""
        qos_rounds = collect_qos_rounds(REPO)
        assert qos_rounds, "no BENCH_QOS_r*.json rounds found at the repo root"
        text = (REPO / "BENCHMARKS.md").read_text(encoding="utf-8")
        begin = text.find(QOS_BEGIN)
        end = text.find(QOS_END)
        assert begin >= 0 and end > begin, "BENCHMARKS.md qos markers missing"
        committed = text[begin : end + len(QOS_END)]
        fresh = render_qos(qos_rounds)
        assert committed == fresh, (
            "BENCHMARKS.md qos table is stale — regenerate with "
            "`python -m tools.bench_report --update`"
        )
        for data in qos_rounds:
            assert f"| r{data['round']:02d} |" in committed

    def test_committed_lifecycle_table_is_current(self):
        """Same staleness gate for the self-driving-lifecycle rounds
        (tools/bench_lifecycle.py → BENCH_LC_r*.json)."""
        lc_rounds = collect_lifecycle_rounds(REPO)
        assert lc_rounds, "no BENCH_LC_r*.json rounds found at the repo root"
        text = (REPO / "BENCHMARKS.md").read_text(encoding="utf-8")
        begin = text.find(LIFECYCLE_BEGIN)
        end = text.find(LIFECYCLE_END)
        assert begin >= 0 and end > begin, (
            "BENCHMARKS.md lifecycle markers missing"
        )
        committed = text[begin : end + len(LIFECYCLE_END)]
        fresh = render_lifecycle(lc_rounds)
        assert committed == fresh, (
            "BENCHMARKS.md lifecycle table is stale — regenerate with "
            "`python -m tools.bench_report --update`"
        )
        for data in lc_rounds:
            assert f"| r{data['round']:02d} |" in committed

    def test_lifecycle_round_holds_the_acceptance_evidence(self):
        """ISSUE 19 acceptance: every committed round's drill promoted
        unattended, rolled the injected regression back, and resumed the
        bounce to exactly one ACTIVE."""
        for data in collect_lifecycle_rounds(REPO):
            assert data["ok"] is True, data.get("error")
            assert data["drill_ok"] is True
            stages = data["stages"]
            assert stages["stage1"]["active_version"] == 1
            assert stages["stage2"]["rolled_back"] is True
            assert stages["stage2"]["active_version"] == 1
            assert stages["stage3"]["active_count"] == 1
            assert stages["stage3"]["promoted_resumed_candidate"] is True

    def test_qos_round_holds_the_isolation_evidence(self):
        """ISSUE 15 acceptance: the committed round's shaped burst moved
        tenant A's announce p99 and TTLB by <10% while the unshaped arm
        documents real interference, and the flood was actually
        shed/capped."""
        for data in collect_qos_rounds(REPO):
            assert data["ok"] is True, data.get("error")
            assert data["value"] >= 90.0, (
                "isolation bar: shaped movement must stay <10%"
            )
            move = data["movement"]
            assert max(
                move["shaped_announce_p99_pct"], move["shaped_ttlb_pct"]
            ) < 10.0
            assert move["unshaped_ttlb_pct"] > 50.0, (
                "the unshaped arm shows no interference — vacuous drill"
            )
            shaped = data["arms"]["shaped"]
            assert shaped["b_sheds"] + shaped["b_throttled"] > 0
            assert (
                shaped["a_downloads_ok"]
                == data["config"]["a_downloads"]
            )

    def test_swarm_round_holds_the_acceptance_evidence(self):
        """The committed fleet round really drove ≥100k simulated peers
        through the sharded fleet, ran the membership drill, and lost no
        downloads to migration."""
        for data in collect_swarm_rounds(REPO):
            assert data["ok"] is True, data.get("error")
            assert data["peers"] >= 100_000
            assert data["unique_hosts"] >= 90_000
            drill = data["membership_drill"]
            assert drill["ran"] is True
            assert drill["handed_off_tasks"] >= 1
            assert data["arms"]["sharded"]["downloads_failed"] == 0

    def test_telemetry_round_drill_outcomes_recorded(self):
        """The committed drill round really holds the acceptance
        evidence: kill drill within the sketch bound, burn alert fired
        and cleared, replay parity."""
        for data in collect_telemetry_rounds(REPO):
            assert data["ok"] is True, data.get("error")
            kill = data["kill_drill"]
            assert kill["victim_sigkilled"] and kill["torn_tail_tolerated"]
            assert kill["corrupt_rejected"] >= 1
            for chk in kill["quantile_checks"].values():
                assert chk["rel_error"] <= kill["alpha"] * 1.0001
            burn = data["burnrate_drill"]
            assert burn["fired_within_fast_window"] is True
            assert burn["replay_matches_live"] is True


class TestRenderSemantics:
    def _rounds(self, tmp_path, payloads):
        for i, payload in enumerate(payloads, start=1):
            (tmp_path / f"BENCH_r{i:02d}.json").write_text(
                json.dumps(payload), encoding="utf-8"
            )
        return collect_rounds(tmp_path)

    def test_ok_skip_error_guard_rows(self, tmp_path):
        rounds = self._rounds(tmp_path, [
            {"rc": 0, "parsed": {"value": 4.8e6, "unit": "rec/s",
                                 "step_ms": 27.4, "mfu": 0.457}},
            {"rc": 1, "parsed": None},
            {"rc": 0, "parsed": {"skipped": "backend_unavailable"}},
            {"rc": 0, "parsed": {"value": 2700.0, "unit": "rec/s",
                                 "backend": "cpu",
                                 "regression_warning": {"dropped_to": 0.001,
                                                        "vs_round": 1}},
             "note": "cpu smoke"},
        ])
        table = render_trajectory(rounds)
        assert "| r01 | ok | 4.80M rec/s | tpu | 27.4 ms | 45.7% |" in table
        assert "| r02 | error (rc=1) | — | — | — | — |" in table
        assert "| r03 | skipped (backend_unavailable) |" in table
        assert "| r04 | guarded (×0.001 of r1) |" in table
        assert "cpu smoke" in table

    def test_unparseable_round_is_an_error_row(self, tmp_path):
        (tmp_path / "BENCH_r01.json").write_text("{not json", encoding="utf-8")
        rounds = collect_rounds(tmp_path)
        assert "| r01 | error (rc=-1) |" in render_trajectory(rounds)

    def test_update_file_is_idempotent(self, tmp_path):
        doc = tmp_path / "BENCHMARKS.md"
        doc.write_text(
            f"# doc\n\n{TRAJECTORY_BEGIN}\nstale\n{TRAJECTORY_END}\ntail\n",
            encoding="utf-8",
        )
        (tmp_path / "BENCH_r01.json").write_text(
            json.dumps({"rc": 0, "parsed": {"value": 1.0, "unit": "x"}}),
            encoding="utf-8",
        )
        rounds = collect_rounds(tmp_path)
        assert update_file(doc, rounds) is True
        body = doc.read_text(encoding="utf-8")
        assert "stale" not in body and "| r01 | ok |" in body and "tail" in body
        assert update_file(doc, rounds) is False
