"""Subprocess body for the columnar host-state chaos drill
(tests/test_chaos.py::TestColumnarRebuildDrill, DESIGN.md §18).

Modes:

- ``hammer``  build the serving plane (SchedulerService + columnar host
  store + rule evaluator) and churn it from announcer threads — host
  announces (column writes on arrival), upload accounting write-through,
  evaluate_parents gathers, leave_host slot recycling — FOREVER.  Prints
  ``columnar-child: ready`` once the storm is running; the parent
  SIGKILLs the process mid-announce.
- ``rebuild`` the restarted scheduler: a fresh process replays the SAME
  deterministic announce stream (nothing is persisted — columnar state
  is rebuilt from announces, which is the restart contract), then
  validates that NO slot row is torn: ``validate_consistency`` must come
  back empty, every bound row must byte-match a recompute off the
  column-backed accessors, and the columnar rule scores must bit-match
  the scalar oracle.  Prints ONE JSON verdict line.
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

N_HOSTS = 48
MAX_SLOTS = 32  # smaller than the fleet: eviction/recycle is exercised
ANNOUNCERS = 6


def build():
    from dragonfly2_tpu.scheduler import (
        Evaluator,
        HostFeatureCache,
        Resource,
        SchedulerService,
        Scheduling,
        SchedulingConfig,
    )
    from dragonfly2_tpu.sim.swarm import build_announce_swarm

    task, peers = build_announce_swarm(N_HOSTS, seed=0)
    cache = HostFeatureCache(max_hosts=MAX_SLOTS)
    evaluator = Evaluator(feature_cache=cache)
    scheduling = Scheduling(evaluator, SchedulingConfig(retry_interval=0))
    service = SchedulerService(Resource(), scheduling)
    return task, peers, cache, evaluator, service


def churn_step(rng, task, peers, evaluator, service):
    """One deterministic slice of announce-path churn."""
    p = peers[int(rng.integers(0, len(peers)))]
    r = rng.random()
    if r < 0.35:
        cands = [peers[int(c)] for c in rng.integers(0, len(peers), size=9)]
        evaluator.evaluate_parents(cands, p, task.total_piece_count)
    elif r < 0.55:
        service.announce_host(p.host)  # columns written on arrival
    elif r < 0.7:
        if p.host.acquire_upload():
            p.host.release_upload(succeeded=rng.random() < 0.9)
    elif r < 0.85:
        p.host.upload_count += 1
    else:
        service.leave_host(p.host)  # detach + slot recycle


def hammer():
    task, peers, cache, evaluator, service = build()
    stop = threading.Event()

    def worker(tid):
        rng = np.random.default_rng(tid)
        while not stop.is_set():
            churn_step(rng, task, peers, evaluator, service)

    threads = [
        threading.Thread(target=worker, args=(i,), daemon=True)
        for i in range(ANNOUNCERS)
    ]
    for t in threads:
        t.start()
    print("columnar-child: ready", flush=True)
    while True:  # the parent SIGKILLs us mid-announce
        time.sleep(0.1)


def rebuild():
    from dragonfly2_tpu.records.features import host_features
    from dragonfly2_tpu.scheduler import Evaluator

    task, peers, cache, evaluator, service = build()
    # The restarted scheduler rebuilds its columnar state from the
    # announce stream alone (deterministic here so the verdict is too).
    rng = np.random.default_rng(1234)
    for _ in range(2000):
        churn_step(rng, task, peers, evaluator, service)
    problems = cache.validate_consistency()
    rows_checked = 0
    row_mismatch = 0
    for p in peers:
        h = p.host
        if h._cols is None or h._cols[0] is not cache:
            continue
        rows_checked += 1
        got = cache.features(h)
        if not np.array_equal(got, host_features(h.to_record())):
            row_mismatch += 1
    oracle = Evaluator()
    child, parents = peers[0], peers[1:17]
    vec = evaluator.evaluate_all(parents, child, task.total_piece_count)
    ref = np.array(
        [oracle.evaluate(q, child, task.total_piece_count) for q in parents]
    )
    print(json.dumps({
        "torn": problems,
        "rows_checked": rows_checked,
        "row_mismatch": row_mismatch,
        "scores_bit_equal": bool(np.array_equal(vec, ref)),
    }), flush=True)


def main():
    mode = sys.argv[1]
    if mode == "hammer":
        hammer()
    elif mode == "rebuild":
        rebuild()
    else:
        raise SystemExit(f"unknown mode {mode!r}")


if __name__ == "__main__":
    main()
