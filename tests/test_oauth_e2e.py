"""OAuth against a real (fake) IdP over real HTTP (VERDICT r4 #8).

Reference ships provider configs exercised by console sign-in
(manager/models/oauth.go).  Here a fake IdP process-local HTTP server
implements /authorize (302 with code), /token (code + refresh grants,
revocation) and /profile, and the e2e drives the MANAGER's REST surface
end to end with the default urllib transport: authorize → code → token
→ profile → manager session → refresh (handle + provider token both
rotate) → revocation at the IdP degrades to re-authentication.
"""

import json
import threading
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dragonfly2_tpu.manager import ClusterManager, ModelRegistry
from dragonfly2_tpu.manager.oauth import OAuthProvider, OAuthSignin
from dragonfly2_tpu.manager.rest import ManagerRESTServer
from dragonfly2_tpu.manager.users import UserStore
from dragonfly2_tpu.security.tokens import TokenIssuer, TokenVerifier


class FakeIdP:
    """A minimal OAuth2 provider: auth codes, bearer tokens, refresh
    tokens with rotation, and operator revocation."""

    def __init__(self):
        self.codes = set()
        self.access = set()
        self.refresh = set()
        self.revoked = set()
        self._n = 0
        srv = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                url = urllib.parse.urlsplit(self.path)
                q = dict(urllib.parse.parse_qsl(url.query))
                if url.path == "/authorize":
                    code = srv._mint("code")
                    srv.codes.add(code)
                    sep = "&" if "?" in q["redirect_uri"] else "?"
                    dest = (
                        q["redirect_uri"] + sep
                        + urllib.parse.urlencode(
                            {"code": code, "state": q.get("state", "")}
                        )
                    )
                    self.send_response(302)
                    self.send_header("Location", dest)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                elif url.path == "/profile":
                    tok = self.headers.get("Authorization", "")[len("Bearer "):]
                    if tok not in srv.access:
                        self._json(401, {"error": "bad token"})
                        return
                    self._json(200, {"login": "octocat",
                                     "email": "octo@cat.example"})
                else:
                    self._json(404, {})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                form = dict(urllib.parse.parse_qsl(
                    self.rfile.read(n).decode()
                ))
                if self.path != "/token":
                    self._json(404, {})
                    return
                grant = form.get("grant_type")
                if grant == "authorization_code":
                    if form.get("code") not in srv.codes:
                        self._json(400, {"error": "invalid_grant"})
                        return
                    srv.codes.discard(form["code"])  # single-use
                    self._json(200, srv._issue())
                elif grant == "refresh_token":
                    rt = form.get("refresh_token", "")
                    if rt not in srv.refresh or rt in srv.revoked:
                        self._json(400, {"error": "invalid_grant"})
                        return
                    srv.refresh.discard(rt)  # rotation: single-use
                    self._json(200, srv._issue())
                else:
                    self._json(400, {"error": "unsupported_grant_type"})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def _mint(self, kind):
        self._n += 1
        return f"{kind}-{self._n}"

    def _issue(self):
        a, r = self._mint("at"), self._mint("rt")
        self.access.add(a)
        self.refresh.add(r)
        return {"access_token": a, "refresh_token": r, "expires_in": 3600}

    def revoke_all_refresh(self):
        self.revoked |= set(self.refresh)

    def stop(self):
        self.httpd.shutdown()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def _post(url, body, token=None):
    headers = {"Content-Type": "application/json"}
    if token:
        headers["Authorization"] = f"Bearer {token}"
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(), headers=headers, method="POST"
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


class _NoRedirect(urllib.request.HTTPRedirectHandler):
    def redirect_request(self, *a, **k):
        return None


@pytest.fixture()
def stack():
    idp = FakeIdP()
    users = UserStore()
    secret = b"manager-secret-0123456789abcd"
    oauth = OAuthSignin(users)  # DEFAULT transport: real HTTP to the IdP
    oauth.register(OAuthProvider(
        name="hub", client_id="cid", client_secret="cs",
        auth_url=idp.url + "/authorize",
        token_url=idp.url + "/token",
        profile_url=idp.url + "/profile",
    ))
    server = ManagerRESTServer(
        ModelRegistry(), ClusterManager(),
        token_verifier=TokenVerifier(secret),
        token_issuer=TokenIssuer(secret),
        users=users, oauth=oauth,
    )
    server.serve()
    yield idp, server
    server.stop()
    idp.stop()


def _authorize(idp, server, cb="https://console/cb"):
    """Drive the authorize leg: manager URL → IdP 302 → code + state."""
    out = _get(
        server.url + "/api/v1/oauth/hub:authorize-url?redirect_uri="
        + urllib.parse.quote(cb)
    )
    opener = urllib.request.build_opener(_NoRedirect())
    try:
        opener.open(out["url"], timeout=10)
        raise AssertionError("IdP did not redirect")
    except urllib.error.HTTPError as exc:
        assert exc.code == 302
        loc = exc.headers["Location"]
    q = dict(urllib.parse.parse_qsl(urllib.parse.urlsplit(loc).query))
    return q["code"], q["state"]


class TestOAuthE2E:
    def test_full_flow_with_refresh_and_revocation(self, stack):
        idp, server = stack
        cb = "https://console/cb"

        # authorize → code → token → profile → manager session
        code, state = _authorize(idp, server, cb)
        out = _post(server.url + "/api/v1/oauth/hub:signin",
                    {"code": code, "state": state, "redirect_uri": cb})
        assert out["role"] == "readonly" and out["refresh_id"]
        token, rid = out["token"], out["refresh_id"]
        # The session works on an authed route (own PATs listing).
        with urllib.request.urlopen(urllib.request.Request(
            server.url + "/api/v1/pats",
            headers={"Authorization": f"Bearer {token}"},
        ), timeout=10) as r:
            assert r.status == 200

        # refresh: new session, BOTH the handle and the provider token
        # rotate (the old handle is dead).
        out2 = _post(server.url + "/api/v1/oauth:refresh",
                     {"refresh_id": rid})
        assert out2["token"] and out2["refresh_id"] != rid
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(server.url + "/api/v1/oauth:refresh", {"refresh_id": rid})
        assert exc.value.code == 403

        # Revocation at the IdP: the next refresh degrades to re-auth...
        idp.revoke_all_refresh()
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(server.url + "/api/v1/oauth:refresh",
                  {"refresh_id": out2["refresh_id"]})
        assert exc.value.code == 403
        assert "re-authenticate" in json.loads(exc.value.read())["error"]
        # ...and the authorize flow still signs the SAME user in.
        code, state = _authorize(idp, server, cb)
        out3 = _post(server.url + "/api/v1/oauth/hub:signin",
                     {"code": code, "state": state, "redirect_uri": cb})
        assert out3["token"] and out3["refresh_id"]

    def test_console_ships_the_oauth_flow(self):
        from dragonfly2_tpu.manager.console import CONSOLE_HTML

        for needle in (
            "oauthStart", "oauthCallback", "oauthRefresh",
            '"/oauth:refresh"', ":authorize-url", "df_refresh_id",
        ):
            assert needle in CONSOLE_HTML, needle
