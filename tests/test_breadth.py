"""Breadth components: object storage + gateway + dfstore, proxy, tracing,
plugins, manager REST."""

import json
import os
import urllib.request

import numpy as np
import pytest

from dragonfly2_tpu.daemon.gateway import GatewayConfig, GatewaySourceFetcher, ObjectGateway
from dragonfly2_tpu.daemon.proxy import P2PProxy, ProxyRouter, ProxyRule
from dragonfly2_tpu.manager import ClusterManager, ModelRegistry, SchedulerInstance
from dragonfly2_tpu.manager.rest import ManagerRESTServer
from dragonfly2_tpu.objectstorage import FilesystemBackend
from dragonfly2_tpu.utils.plugin import PluginError, list_plugins, load_plugin, plugin_filename
from dragonfly2_tpu.utils.tracing import InMemoryExporter, Tracer

from tests.test_daemon import PIECE, _Swarm


class TestFilesystemBackend:
    def test_crud(self, tmp_path):
        b = FilesystemBackend(str(tmp_path))
        b.create_bucket("bkt")
        meta = b.put_object("bkt", "a/b/key.bin", b"hello")
        assert meta.content_length == 5
        assert b.get_object("bkt", "a/b/key.bin") == b"hello"
        assert b.object_exists("bkt", "a/b/key.bin")
        b.copy_object("bkt", "a/b/key.bin", "copy.bin")
        keys = [m.key for m in b.list_objects("bkt")]
        assert sorted(keys) == ["a/b/key.bin", "copy.bin"]
        assert [m.key for m in b.list_objects("bkt", prefix="a/")] == ["a/b/key.bin"]
        b.delete_object("bkt", "copy.bin")
        assert not b.object_exists("bkt", "copy.bin")
        with pytest.raises(KeyError):
            b.get_object("bkt", "missing")

    def test_path_traversal_rejected(self, tmp_path):
        b = FilesystemBackend(str(tmp_path))
        b.create_bucket("bkt")
        with pytest.raises(ValueError):
            b.put_object("bkt", "../escape", b"x")
        with pytest.raises(ValueError):
            b.create_bucket("../up")


class TestObjectGateway:
    def test_put_seeds_p2p_and_peer_gets_from_swarm(self, tmp_path):
        swarm = _Swarm(tmp_path, n_hosts=3)
        backend = FilesystemBackend(str(tmp_path / "objects"))
        gws = []
        for d in swarm.daemons[:2]:
            d.conductor.source_fetcher = GatewaySourceFetcher(backend)
            gws.append(ObjectGateway(d, backend, GatewayConfig(piece_size=PIECE)))
        payload = os.urandom(3 * PIECE + 100)
        gws[0].put_object("models/v1.bin", payload)
        assert gws[0].object_exists("models/v1.bin")

        # Second daemon reads: P2P from daemon 0 (it seeded the pieces).
        got = gws[1].get_object("models/v1.bin")
        assert got == payload
        assert swarm.daemons[0].upload.upload_count > 0

    def test_delete_evicts_pieces(self, tmp_path):
        swarm = _Swarm(tmp_path, n_hosts=2)
        backend = FilesystemBackend(str(tmp_path / "objects"))
        d = swarm.daemons[0]
        d.conductor.source_fetcher = GatewaySourceFetcher(backend)
        gw = ObjectGateway(d, backend, GatewayConfig(piece_size=PIECE))
        gw.put_object("k", b"x" * PIECE)
        tid = gw._task_id("k")
        assert d.storage.engine.piece_count(tid) == 1
        gw.delete_object("k")
        assert not gw.object_exists("k")
        assert d.storage.engine.piece_count(tid) == 0


class TestProxy:
    def test_rules_route_and_rewrite(self):
        router = ProxyRouter(
            [
                ProxyRule.compile(r"^http://registry\.local/", redirect="http://mirror.local/"),
                ProxyRule.compile(r"\.layer$", use_p2p=True),
                ProxyRule.compile(r"^http://direct\.", use_p2p=False),
            ]
        )
        use, url = router.route("http://registry.local/v2/blob")
        assert use and url == "http://mirror.local/v2/blob"
        assert router.route("http://x/foo.layer") == (True, "http://x/foo.layer")
        assert router.route("http://direct.example/a") == (False, "http://direct.example/a")
        assert router.route("http://other/a") == (False, "http://other/a")

    def test_proxy_serves_p2p_content(self, tmp_path):
        swarm = _Swarm(tmp_path, n_hosts=2)
        proxy = P2PProxy(
            swarm.daemons[0],
            ProxyRouter([ProxyRule.compile(r"^https://origin/")]),
            piece_size=PIECE,
        )
        proxy.serve()
        try:
            url = f"http://127.0.0.1:{proxy.port}/https://origin/blob-via-proxy"
            # content_length resolvable? FakeOrigin has no content_length →
            # conductor needs it; give the origin a content_length method.
            swarm.origin.content_length = lambda u: 2 * PIECE
            with urllib.request.urlopen(url, timeout=10) as resp:
                body = resp.read()
            assert len(body) == 2 * PIECE
            assert proxy.stats["p2p"] == 1
        finally:
            proxy.stop()

    def test_proxy_streams_before_task_finishes(self, tmp_path):
        """VERDICT r2 next-#3 done-condition: a proxy response's first
        bytes arrive while the underlying task is still downloading
        (the stream-task consumer, not a buffered whole-body fetch)."""
        import socket
        import time

        from dragonfly2_tpu.utils import idgen

        swarm = _Swarm(tmp_path, n_hosts=2)
        url = "https://origin/proxied-stream-blob"
        n_pieces = 6
        seed = swarm.daemons[0].download(
            url, piece_size=PIECE, content_length=n_pieces * PIECE
        )
        assert seed.ok

        child = swarm.daemons[1]
        child.conductor.piece_parallelism = 1
        inner = child.conductor.piece_fetcher

        class SlowFetcher:
            def fetch(self, host_id, task_id, number):
                time.sleep(0.08)
                return inner.fetch(host_id, task_id, number)

            def piece_bitmap(self, host_id, task_id):
                return inner.piece_bitmap(host_id, task_id)

        child.conductor.piece_fetcher = SlowFetcher()
        proxy = P2PProxy(
            child, ProxyRouter([ProxyRule.compile(r"^https://origin/")]),
            piece_size=PIECE,
        )
        proxy.serve()
        try:
            swarm.origin.content_length = lambda u: n_pieces * PIECE
            tid = idgen.task_id(url)
            sock = socket.create_connection(("127.0.0.1", proxy.port), timeout=10)
            sock.sendall(
                f"GET /{url} HTTP/1.1\r\nHost: x\r\n\r\n".encode()
            )
            f = sock.makefile("rb")
            status = f.readline()
            assert b"200" in status
            cl = 0
            while True:
                line = f.readline()
                if line == b"\r\n":
                    break
                if line.lower().startswith(b"content-length:"):
                    cl = int(line.split(b":")[1])
            assert cl == n_pieces * PIECE
            first = f.read(PIECE)  # first piece of the body
            # The task is still mid-download when the first bytes land.
            assert child.conductor.active_run(tid) is not None, (
                "body only started after the task finished"
            )
            rest = f.read(cl - PIECE)
            sock.close()
            body = first + rest
            assert body == b"".join(
                swarm.origin.content(url, n) for n in range(n_pieces)
            )
        finally:
            proxy.stop()


class TestTracing:
    def test_nested_spans_and_status(self):
        exp = InMemoryExporter()
        tracer = Tracer(exporter=exp)
        with tracer.span("download", task="t1") as outer:
            with tracer.span("piece", number=3):
                pass
            outer.set(pieces=1)
        with pytest.raises(ValueError):
            with tracer.span("boom"):
                raise ValueError("x")
        piece = exp.find("piece")[0]
        download = exp.find("download")[0]
        assert piece.parent_id == download.span_id
        assert piece.trace_id == download.trace_id
        assert download.attributes == {"task": "t1", "pieces": 1}
        assert exp.find("boom")[0].status == "error: ValueError"
        assert download.duration_ms >= 0


class TestPlugins:
    def test_load_and_list(self, tmp_path):
        (tmp_path / plugin_filename("evaluator", "myeval")).write_text(
            "def create_plugin(weight=1.0):\n"
            "    class Eval:\n"
            "        def evaluate_parents(self, parents, child, total):\n"
            "            return sorted(parents, key=lambda p: p.id)\n"
            "        w = weight\n"
            "    return Eval()\n"
        )
        plug = load_plugin(str(tmp_path), "evaluator", "myeval", weight=2.5)
        assert plug.w == 2.5
        listed = list_plugins(str(tmp_path))
        assert listed == [
            {"type": "evaluator", "name": "myeval", "file": plugin_filename("evaluator", "myeval")}
        ]
        with pytest.raises(PluginError):
            load_plugin(str(tmp_path), "evaluator", "missing")

    def test_factory_required(self, tmp_path):
        (tmp_path / plugin_filename("searcher", "bad")).write_text("x = 1\n")
        with pytest.raises(PluginError):
            load_plugin(str(tmp_path), "searcher", "bad")


class TestManagerREST:
    @pytest.fixture()
    def rest(self):
        registry = ModelRegistry()
        clusters = ClusterManager()
        clusters.register_scheduler(SchedulerInstance(id="s1", cluster_id="c1", ip="10.0.0.1"))
        m = registry.create_model(
            name="parent-bandwidth-mlp", type="mlp", scheduler_id="s1",
            artifact=b"blob", evaluation={"mae": 0.4},
        )
        server = ManagerRESTServer(registry, clusters)
        server.serve()
        yield server, registry, m
        server.stop()

    def _get(self, url):
        with urllib.request.urlopen(url, timeout=5) as resp:
            return json.loads(resp.read())

    def _post(self, url):
        req = urllib.request.Request(url, data=b"", method="POST")
        with urllib.request.urlopen(req, timeout=5) as resp:
            return json.loads(resp.read())

    def test_list_and_activate(self, rest):
        server, registry, m = rest
        assert self._get(server.url + "/api/v1/healthy") == {"ok": True}
        models = self._get(server.url + "/api/v1/models?scheduler_id=s1")
        assert len(models) == 1 and models[0]["state"] == "inactive"
        out = self._post(server.url + f"/api/v1/models/{m.id}:activate")
        assert out["state"] == "active"
        assert registry.active_model("s1", "parent-bandwidth-mlp") is not None
        scheds = self._get(server.url + "/api/v1/schedulers")
        assert [s["id"] for s in scheds] == ["s1"]

    def test_unknown_model_404(self, rest):
        server, _, _ = rest
        req = urllib.request.Request(
            server.url + "/api/v1/models/nope:activate", data=b"", method="POST"
        )
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req)
        assert exc.value.code == 404


import urllib.error  # noqa: E402  (used in the 404 assertion above)


class TestProxyConnect:
    def test_https_tunnel_passthrough(self, tmp_path):
        """CONNECT relays raw bytes: an http.client through the tunnel
        reaches a local origin server."""
        import http.client
        from http.server import BaseHTTPRequestHandler
        from dragonfly2_tpu.rpc._server import ThreadedHTTPService

        class Origin(BaseHTTPRequestHandler):
            def log_message(self, *a): pass
            def do_GET(self):
                body = b"tunneled!"
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        origin = ThreadedHTTPService(Origin, "127.0.0.1", 0, "origin")
        origin.serve()
        swarm = _Swarm(tmp_path, n_hosts=1)
        proxy = P2PProxy(swarm.daemons[0], ProxyRouter([]))
        proxy.serve()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", proxy.port, timeout=10)
            conn.set_tunnel("127.0.0.1", origin.port)
            conn.request("GET", "/anything")
            resp = conn.getresponse()
            assert resp.status == 200 and resp.read() == b"tunneled!"
            assert proxy.stats["tunnel"] == 1
        finally:
            proxy.stop()
            origin.stop()

    def test_connect_bad_target_502(self, tmp_path):
        import http.client

        swarm = _Swarm(tmp_path, n_hosts=1)
        proxy = P2PProxy(swarm.daemons[0], ProxyRouter([]))
        proxy.serve()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", proxy.port, timeout=5)
            conn.set_tunnel("127.0.0.1", 1)  # closed port
            with pytest.raises(OSError):
                conn.request("GET", "/")
                conn.getresponse()
        finally:
            proxy.stop()


class TestOpenAPISurface:
    def test_swagger_covers_served_routes(self):
        """Every documented path answers on the live server (no phantom
        docs), and the doc covers the big route families."""
        from dragonfly2_tpu.manager import ClusterManager, ModelRegistry
        from dragonfly2_tpu.manager.rest import ManagerRESTServer

        server = ManagerRESTServer(ModelRegistry(), ClusterManager())
        server.serve()
        try:
            with urllib.request.urlopen(server.url + "/swagger.json", timeout=5) as r:
                spec = json.loads(r.read())
            assert spec["openapi"].startswith("3.")
            paths = spec["paths"]
            for family in ("/api/v1/models", "/api/v1/schedulers",
                           "/api/v1/clusters", "/api/v1/applications",
                           "/api/v1/buckets", "/api/v1/jobs",
                           "/api/v1/topology", "/api/v1/users:signin",
                           "/api/v1/pats"):
                assert family in paths, family
            # Spot-check a documented GET actually serves (not a phantom).
            with urllib.request.urlopen(
                server.url + "/api/v1/clusters/default:config", timeout=5
            ) as r:
                assert json.loads(r.read())["cluster_id"] == "default"
        finally:
            server.stop()
