"""configs[5] AS WRITTEN: the online 1B-edge graph trainer, for real.

The r3 soak (tools/soak_1b.py) trained 1B records against ONE static
graph snapshot.  This run is the *online* loop the config describes
(VERDICT r3 next-#1): BOTH record streams flow continuously —

- **downloads**: position-seeded edge batches whose ground-truth
  bandwidth reflects the cluster's CURRENT (drifting) load state;
- **topology**: per-epoch probe sweeps of the drifted cluster;

and every ``--refresh-every`` dispatches the trainer rebuilds the graph
snapshot from the topology window — ``build_neighbor_table`` +
``precompute_hop_features`` re-run mid-training, hop tables hot-swap,
optimizer/params/LR-position continue (trainer/online_graph.py).

Load drift happens at epoch boundaries (``SyntheticCluster.drift``,
seeded by epoch → a resumed run replays the identical world).  At every
boundary the tool logs val MAE on POST-drift edges twice: with the
STALE snapshot (pre-swap) and the FRESH one (post-swap) — the measured
evidence that the refresh loop chases the drift.

Kill/resume: --kill-after-dispatch exits hard after a checkpoint
(placed PAST a refresh boundary to prove resume across the swap);
--resume restores params/opt/stream position AND rebuilds the snapshot
from the checkpointed window; --hash-out proves the continuation
byte-identical to an uninterrupted run.

Usage (BENCHMARKS.md "online 1B" section records the measured runs):
  python tools/soak_online_1b.py --records 1e9 --ckpt-dir /tmp/og \\
      [--kill-after-dispatch 70] [--resume] [--hash-out H]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

BATCH = 131_072
SUPER = 64


def main() -> int:
    global BATCH, SUPER
    ap = argparse.ArgumentParser()
    ap.add_argument("--records", type=float, default=1e9)
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--refresh-every", type=int, default=30, help="dispatches per epoch")
    ap.add_argument("--ckpt-every", type=int, default=30, help="dispatches")
    ap.add_argument("--eval-every", type=int, default=15, help="dispatches")
    ap.add_argument("--kill-after-dispatch", type=int, default=None)
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--hash-out", default=None)
    ap.add_argument("--nodes", type=int, default=100_000)
    ap.add_argument("--hidden", type=int, default=1024)
    ap.add_argument("--batch", type=int, default=BATCH)
    ap.add_argument("--super", dest="super_steps", type=int, default=SUPER)
    args = ap.parse_args()
    BATCH, SUPER = args.batch, args.super_steps

    t_wall0 = time.time()
    import jax

    from dragonfly2_tpu.models.hop import HopConfig
    from dragonfly2_tpu.records.synthetic import SyntheticCluster
    from dragonfly2_tpu.trainer.online_graph import (
        OnlineGraphConfig,
        OnlineGraphTrainer,
        state_hash,
    )
    from dragonfly2_tpu.trainer.train import TrainConfig

    R = args.refresh_every
    n_dispatch_total = int(np.ceil(args.records / (BATCH * SUPER)))
    n_probe = args.nodes * 16  # one probe sweep per epoch ≈ table capacity

    # -- the (drifting) world, position-deterministic ------------------------
    cluster = SyntheticCluster(num_hosts=args.nodes, seed=0)

    def apply_drifts(up_to_epoch: int) -> None:
        """Replay epochs 1..up_to_epoch of load drift (seeded per epoch —
        a resumed process reconstructs the identical world state)."""
        for e in range(1, up_to_epoch + 1):
            cluster.drift(np.random.default_rng(77_000 + e))

    def probe_sweep(epoch: int):
        """Topology records for this epoch's world (prober → probed)."""
        rng = np.random.default_rng(88_000 + epoch)
        src = rng.integers(0, args.nodes, n_probe)
        dst = rng.integers(0, args.nodes, n_probe)
        keep = src != dst
        src, dst = src[keep], dst[keep]
        rtt = cluster._rtt_vec(src, dst, rng=rng) / 1e9
        return src, dst, rtt.astype(np.float32)

    # The producer runs AHEAD of the train loop (queue backpressure ≠
    # lockstep), so it generates against its OWN world replica, drifted at
    # its own generation position — sharing the main thread's cluster
    # would race its epoch-boundary drift and break position determinism.
    producer_cluster = SyntheticCluster(num_hosts=args.nodes, seed=0)

    def download_block(d: int):
        """Download records for dispatch d, against dispatch d's world."""
        rng = np.random.default_rng(10_000 + d)
        es = rng.integers(0, args.nodes, SUPER * BATCH).astype(np.int32)
        ed = (es + rng.integers(1, args.nodes, SUPER * BATCH).astype(np.int32)) % args.nodes
        y = np.log1p(
            producer_cluster._bandwidth_vec(es, ed, rng=rng)
        ).astype(np.float32)
        return es, ed, y

    def val_set(epoch: int):
        rng = np.random.default_rng(999_000 + epoch)
        es = rng.integers(0, args.nodes, 2 * BATCH).astype(np.int32)
        ed = (es + rng.integers(1, args.nodes, 2 * BATCH).astype(np.int32)) % args.nodes
        y = np.log1p(cluster._bandwidth_vec(es, ed, rng=rng)).astype(np.float32)
        return es, ed, y

    # -- trainer -------------------------------------------------------------
    t0 = time.time()
    src0, dst0, rtt0 = probe_sweep(0)
    cfg = OnlineGraphConfig(
        num_nodes=args.nodes,
        max_neighbors=16,
        batch_size=BATCH,
        super_steps=SUPER,
        refresh_every=0,   # the tool drives refreshes (stale/fresh eval around them)
        topo_window=n_probe,
        queue_capacity=2,
        model=HopConfig(hidden=args.hidden),
        train=TrainConfig(warmup_steps=100),
        total_steps_hint=n_dispatch_total * SUPER,
    )
    trainer = OnlineGraphTrainer(
        cfg,
        node_feats=cluster._host_feature_matrix(),
        topo_src=src0, topo_dst=dst0, topo_rtt=rtt0,
        checkpoint_dir=args.ckpt_dir,
    )
    print(f"soak-online: snapshot 0 built in {time.time() - t0:.1f}s "
          f"({args.nodes} nodes, {len(src0)} probes)", flush=True)

    start_dispatch = 0
    if args.resume:
        if not trainer.resume():
            print("soak-online: no checkpoint to resume", flush=True)
            return 1
        start_dispatch = trainer.dispatch
        # Rebuild the WORLD to match the restored stream position.
        apply_drifts(start_dispatch // R)
        print(f"soak-online: resumed at dispatch {start_dispatch} "
              f"(step {int(trainer.state.step)}, "
              f"snapshot {trainer.snapshot_idx})", flush=True)

    # -- producer: both streams, interleaved deterministically ---------------
    stop = threading.Event()

    def producer() -> None:
        for e in range(1, start_dispatch // R + 1):
            producer_cluster.drift(np.random.default_rng(77_000 + e))
        for d in range(start_dispatch, n_dispatch_total):
            if stop.is_set():
                return
            if d and d % R == 0 and d != start_dispatch:
                # Dispatch d is the first of epoch d//R: drift first.  On
                # resume the pre-loop already replayed start_dispatch//R
                # epochs — drifting again here would over-drift the world
                # and break byte-identity with the uninterrupted run.
                producer_cluster.drift(np.random.default_rng(77_000 + d // R))
            # Blocks on the queue (ingest backpressure).
            trainer.feed_downloads(*download_block(d))
        trainer.end_of_stream()

    threading.Thread(target=producer, daemon=True).start()

    # -- the run -------------------------------------------------------------
    curve = []
    refreshes = []
    t_train0 = time.time()
    d = start_dispatch
    while d < n_dispatch_total:
        ran = trainer.run(max_dispatches=1, idle_timeout=30.0)
        if ran == 0:
            break
        d += 1
        epoch = d // R
        if (d % args.eval_every == 0) or d == n_dispatch_total:
            # The boundary drift for epoch d//R runs BELOW — the world at
            # eval time is still dispatch d's epoch.
            es, ed, y = val_set((d - 1) // R)
            mae = trainer.eval_mae(es, ed, y)
            curve.append({"dispatch": d, "records": d * SUPER * BATCH,
                          "snapshot": trainer.snapshot_idx,
                          "val_log_mae": round(mae, 4)})
            print(f"soak-online: dispatch {d}/{n_dispatch_total} "
                  f"({d * SUPER * BATCH / 1e6:.0f}M records) "
                  f"snapshot={trainer.snapshot_idx} val_log_mae={mae:.4f}",
                  flush=True)
        if d % R == 0 and d < n_dispatch_total:
            # Epoch boundary: the world drifts; measure the model on the
            # NEW world with the STALE snapshot, refresh, measure FRESH.
            t_r0 = time.time()
            cluster.drift(np.random.default_rng(77_000 + epoch))
            es, ed, y = val_set(epoch)  # post-drift targets
            stale = trainer.eval_mae(es, ed, y)
            trainer.set_node_features(cluster._host_feature_matrix())
            trainer.feed_topology(*probe_sweep(epoch))
            digest = trainer.refresh_snapshot()
            fresh = trainer.eval_mae(es, ed, y)
            refreshes.append({
                "dispatch": d, "epoch": epoch,
                "stale_mae": round(stale, 4), "fresh_mae": round(fresh, 4),
                "refresh_s": round(time.time() - t_r0, 2),
                "hop_digest": digest[:12] if digest else None,
            })
            print(f"soak-online: REFRESH at dispatch {d}: "
                  f"stale={stale:.4f} fresh={fresh:.4f} "
                  f"({refreshes[-1]['refresh_s']}s)", flush=True)
        saved = False
        if d % args.ckpt_every == 0 or d == n_dispatch_total:
            trainer.checkpoint()
            saved = True
        if args.kill_after_dispatch is not None and d >= args.kill_after_dispatch:
            if not saved:
                trainer.checkpoint()
            stop.set()
            if args.hash_out:
                with open(args.hash_out + ".at_kill", "w") as f:
                    f.write(state_hash(trainer.state) + "\n")
            print(f"soak-online: KILLING after dispatch {d} "
                  f"(checkpoint written, snapshot {trainer.snapshot_idx})",
                  flush=True)
            os._exit(137)

    jax.block_until_ready(trainer.state.params)
    train_s = time.time() - t_train0
    wall_s = time.time() - t_wall0
    records_done = (d - start_dispatch) * SUPER * BATCH

    if args.hash_out:
        digest = state_hash(trainer.state)
        with open(args.hash_out, "w") as f:
            f.write(digest + "\n")
        print(f"soak-online: state sha256 {digest[:16]}…", flush=True)

    print(json.dumps({
        "records_this_run": records_done,
        "dispatches": d - start_dispatch,
        "snapshots": trainer.snapshot_idx,
        "train_s": round(train_s, 1),
        "wall_s": round(wall_s, 1),
        "records_per_s_incl_refresh": round(records_done / train_s, 1),
        "refreshes": refreshes,
        "val_curve": curve,
        "resumed": args.resume,
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
