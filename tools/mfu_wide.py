"""MFU headroom demo: the hop ranker train step at compute-bound widths.

The accuracy-optimal flagship (hidden 128) is memory-bound — its ~97
GFLOP/step would take 0.5 ms at peak, so even a perfect schedule caps
MFU at ~5% of a 10 ms step (BENCHMARKS.md roofline section).  This tool
shows the SAME train step saturating the MXU when the model is wide
enough to be FLOPs-dominated: widths 512/1024/2048 with XLA-cost-model
MFU per step.  Chained-slope timing (see bench.py).

Usage: PYTHONPATH=/root/repo:/root/.axon_site python tools/mfu_wide.py
"""

from __future__ import annotations

import json
import time
from functools import partial

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    from dragonfly2_tpu.models import (
        HopConfig,
        HopRanker,
        build_neighbor_table,
        precompute_hop_features,
    )
    from dragonfly2_tpu.records.synthetic import SyntheticCluster
    from dragonfly2_tpu.trainer.train import (
        TrainConfig, TrainState, _graph_train_step, _make_optimizer,
    )

    on_tpu = jax.devices()[0].platform != "cpu"
    n_nodes = 100_000 if on_tpu else 2048
    batch = 131_072 if on_tpu else 4096
    peak = 197e12 if on_tpu else 1e12

    cluster = SyntheticCluster(num_hosts=n_nodes, seed=0)
    src, dst, rtt = cluster.probe_edges(density=16 / (n_nodes - 1), seed=0)
    table = build_neighbor_table(n_nodes, src, dst, rtt / 1e9, max_neighbors=16)
    node_feats = jnp.asarray(cluster._host_feature_matrix())
    rng = np.random.default_rng(0)
    e_src = jnp.asarray(rng.integers(0, n_nodes, batch), jnp.int32)
    e_dst = jnp.asarray(rng.integers(0, n_nodes, batch), jnp.int32)
    y = jnp.asarray(rng.normal(size=batch).astype(np.float32))

    for hidden in (128, 512, 1024, 2048):
        mcfg = HopConfig(hidden=hidden, dropout=0.0)
        hop_feats = precompute_hop_features(node_feats, table, hops=mcfg.hops)
        model = HopRanker(mcfg)
        params = model.init(
            jax.random.PRNGKey(0), hop_feats, table, e_src[:2], e_dst[:2]
        )["params"]
        state = TrainState.create(
            apply_fn=model.apply, params=params,
            tx=_make_optimizer(TrainConfig(), 100),
            dropout_rng=jax.random.PRNGKey(1),
        )

        @partial(jax.jit, static_argnums=(6,))
        def chain(s, nf, t, a, b, yy, n):
            def body(_, c):
                ns, _l = _graph_train_step(c, nf, t, a, b, yy, None)
                return ns
            out = jax.lax.fori_loop(0, n, body, s)
            return out.params["Dense_0"]["bias"][0]

        n_short, n_long = (4, 24) if on_tpu else (2, 6)
        float(chain(state, hop_feats, table, e_src, e_dst, y, n_short))
        float(chain(state, hop_feats, table, e_src, e_dst, y, n_long))
        per_step = None
        for _ in range(2):
            t0 = time.perf_counter()
            float(chain(state, hop_feats, table, e_src, e_dst, y, n_short))
            ts = time.perf_counter() - t0
            t0 = time.perf_counter()
            float(chain(state, hop_feats, table, e_src, e_dst, y, n_long))
            tl = time.perf_counter() - t0
            est = (tl - ts) / (n_long - n_short)
            per_step = est if per_step is None else min(per_step, est)

        flops = None
        try:
            sj = jax.jit(lambda s, nf, t, a, b, yy: _graph_train_step(
                s, nf, t, a, b, yy, None))
            cost = sj.lower(
                state, hop_feats, table, e_src, e_dst, y
            ).compile().cost_analysis()
            flops = float(cost["flops"]) if cost and "flops" in cost else None
        except Exception:
            pass
        out = {
            "hidden": hidden,
            "step_ms": round(per_step * 1e3, 2),
            "records_per_sec": round(batch / per_step, 1),
        }
        if flops:
            out["step_gflop"] = round(flops / 1e9, 1)
            out["mfu"] = round(flops / per_step / peak, 4)
        print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
