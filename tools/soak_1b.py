"""The 1B-record north-star run, FOR REAL (VERDICT r2 next-#2).

BASELINE north star: "train the peer-bandwidth GNN on 1B download
records over a 100k-node peer graph ... in ≤10 min at ≥30% MFU".  This
tool runs it end to end on the chip, not by extrapolation:

- Phase 0 (counted in wall time): 100k-node probe graph build + hop-
  feature precompute for the flagship hop ranker (hidden 1024 — the
  quality-validated ≥30%-MFU width, tools/ablate_width.py).
- Ingest: a producer thread generates download-record superbatches
  (HOST-side, bounded queue, backpressure — the streaming-trainer
  boundary) that ride the relay as [K, B] arrays; targets normalize
  with log1p in the path.
- Train: one jitted lax.scan steps K batches per dispatch; a held-out
  edge set scores val log-MAE periodically (the quality curve).
- Checkpoint/resume: orbax snapshots (params + opt state + step +
  stream position); --kill-after-dispatch exits HARD right after a
  snapshot (crash simulation), --resume restores and continues the
  deterministic stream from the recorded position.  --hash-out writes a
  sha256 over the final params+opt_state bytes so a kill+resume run can
  be proven BYTE-IDENTICAL to an uninterrupted one.

Usage (see BENCHMARKS.md "1B-record north-star run" for the measured
invocations):
  python tools/soak_1b.py --records 1e9 --ckpt-dir /tmp/soak \\
      [--kill-after-dispatch 60] [--resume] [--hash-out H]
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import sys
import threading
import time

import numpy as np

BATCH = 131_072
SUPER = 64  # steps per dispatch: 8.39M records ride each relay transfer


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--records", type=float, default=1e9)
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--ckpt-every", type=int, default=30, help="dispatches")
    ap.add_argument("--eval-every", type=int, default=15, help="dispatches")
    ap.add_argument("--kill-after-dispatch", type=int, default=None)
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--hash-out", default=None)
    ap.add_argument("--hash-restored", default=None,
                    help="with --resume: hash the state right after "
                         "restore and exit (roundtrip diagnostics)")
    ap.add_argument("--host-roundtrip-at", type=int, default=None,
                    help="diagnostics: after dispatch N, pull the state "
                         "to host numpy and push it back (no checkpoint)")
    ap.add_argument("--nodes", type=int, default=100_000)
    ap.add_argument("--hidden", type=int, default=1024)
    args = ap.parse_args()
    if args.hash_restored and not args.resume:
        ap.error("--hash-restored requires --resume")

    t_wall0 = time.time()
    import jax
    import jax.numpy as jnp
    import orbax.checkpoint as ocp

    from dragonfly2_tpu.models import (
        HopConfig,
        HopRanker,
        build_neighbor_table,
        precompute_hop_features,
    )
    from dragonfly2_tpu.records.synthetic import SyntheticCluster
    from dragonfly2_tpu.trainer.online_graph import state_hash
    from dragonfly2_tpu.trainer.train import (
        TrainConfig,
        TrainState,
        _graph_train_step,
        _make_optimizer,
    )

    # -- phase 0: graph + hop features (counted) ----------------------------
    t0 = time.time()
    cluster = SyntheticCluster(num_hosts=args.nodes, seed=0)
    src, dst, rtt = cluster.probe_edges(
        density=16 / max(args.nodes - 1, 1), seed=0
    )
    table = build_neighbor_table(
        args.nodes, src, dst, rtt / 1e9, max_neighbors=16
    )
    node_feats = jnp.asarray(cluster._host_feature_matrix())
    mcfg = HopConfig(hidden=args.hidden)
    hop_feats = jax.jit(
        lambda nf, t: precompute_hop_features(nf, t, hops=mcfg.hops)
    )(node_feats, table)
    hop_feats.block_until_ready()
    precompute_s = time.time() - t0
    print(f"soak: hop-feature precompute {precompute_s:.1f}s "
          f"({args.nodes} nodes)", flush=True)

    # -- model / optimizer ---------------------------------------------------
    n_dispatch_total = int(np.ceil(args.records / (BATCH * SUPER)))
    model = HopRanker(mcfg)
    rng0 = np.random.default_rng(123)
    init_src = jnp.asarray(rng0.integers(0, args.nodes, 2), jnp.int32)
    params = model.init(
        jax.random.PRNGKey(0), hop_feats, table, init_src, init_src
    )["params"]
    cfg = TrainConfig(warmup_steps=100)
    tx = _make_optimizer(cfg, n_dispatch_total * SUPER // max(cfg.epochs, 1))
    state = TrainState.create(
        apply_fn=model.apply, params=params, tx=tx,
        dropout_rng=jax.random.PRNGKey(1),
    )

    # -- deterministic stream (ingest) ---------------------------------------
    def edge_targets(rng, es, ed):
        """Bandwidth targets with the measurement noise drawn from the
        CALLER's rng — the cluster's shared stateful generator would make
        the stream depend on how many draws happened before (a resumed
        process would regenerate a DIFFERENT continuation and break
        byte-identity).  The noise model stays in ONE place
        (synthetic.py _bandwidth_vec)."""
        return np.log1p(cluster._bandwidth_vec(es, ed, rng=rng)).astype(np.float32)

    def make_superbatch(d: int):
        """Download records for dispatch d — seeded by the STREAM position
        so a resumed run regenerates the identical continuation."""
        rng = np.random.default_rng(10_000 + d)
        es = rng.integers(0, args.nodes, SUPER * BATCH).astype(np.int32)
        ed = (es + rng.integers(1, args.nodes, SUPER * BATCH).astype(np.int32)) % args.nodes
        y = edge_targets(rng, es, ed)
        return (
            es.reshape(SUPER, BATCH), ed.reshape(SUPER, BATCH),
            y.reshape(SUPER, BATCH),
        )

    # Held-out quality set (disjoint seed from every dispatch).
    vrng = np.random.default_rng(999_999)
    v_es = vrng.integers(0, args.nodes, 2 * BATCH).astype(np.int32)
    v_ed = (v_es + vrng.integers(1, args.nodes, 2 * BATCH).astype(np.int32)) % args.nodes
    v_y = edge_targets(vrng, v_es, v_ed)
    v_es, v_ed, v_y = (jnp.asarray(a) for a in (v_es, v_ed, v_y))

    @jax.jit
    def train_dispatch(s, es, ed, y):
        def body(carry, xs):
            b_es, b_ed, b_y = xs
            new_s, loss = _graph_train_step(
                carry, hop_feats, table, b_es, b_ed, b_y, None
            )
            return new_s, loss

        s, losses = jax.lax.scan(body, s, (es, ed, y))
        return s, losses.mean()

    @jax.jit
    def val_mae(s):
        pred = s.apply_fn(
            {"params": s.params}, hop_feats, table, v_es, v_ed, train=False
        )
        return jnp.abs(pred - v_y).mean()

    # -- checkpoint / resume -------------------------------------------------
    ckpt_path = os.path.join(os.path.abspath(args.ckpt_dir), "soak")

    def save(dispatch: int) -> None:
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(
            ckpt_path,
            {
                "params": state.params, "opt_state": state.opt_state,
                "step": int(state.step), "dispatch": dispatch,
                "dropout_rng": state.dropout_rng,
            },
            force=True,
        )
        ckptr.wait_until_finished()

    start_dispatch = 0
    if args.resume:
        ckptr = ocp.StandardCheckpointer()
        abstract = {
            "params": state.params, "opt_state": state.opt_state,
            "step": 0, "dispatch": 0, "dropout_rng": state.dropout_rng,
        }
        restored = ckptr.restore(ckpt_path, abstract)
        # step must restore as a STRONG int32 device scalar — the mid-run
        # state carries one, and a weak-typed Python int would compile a
        # DIFFERENT XLA program whose float-reduction order diverges from
        # the uninterrupted run (measured: byte-identity holds only with
        # matching avals).
        state = state.replace(
            params=restored["params"], opt_state=restored["opt_state"],
            step=jnp.asarray(restored["step"], jnp.int32),
            dropout_rng=jnp.asarray(restored["dropout_rng"], jnp.uint32),
        )
        start_dispatch = int(restored["dispatch"])
        print(f"soak: resumed at dispatch {start_dispatch} "
              f"(step {int(state.step)})", flush=True)
        if args.hash_restored:
            with open(args.hash_restored, "w") as f:
                f.write(state_hash(state) + "\n")
            print("soak: restored-state hash written; exiting", flush=True)
            return 0

    # -- producer (bounded queue = ingest backpressure) ----------------------
    feed: "queue.Queue" = queue.Queue(maxsize=2)

    def producer() -> None:
        for d in range(start_dispatch, n_dispatch_total):
            feed.put((d, make_superbatch(d)))
        feed.put(None)

    threading.Thread(target=producer, daemon=True).start()

    # -- the soak ------------------------------------------------------------
    curve = []
    t_train0 = time.time()
    while True:
        item = feed.get()
        if item is None:
            break
        d, (es, ed, y) = item
        state, loss = train_dispatch(
            state, jnp.asarray(es), jnp.asarray(ed), jnp.asarray(y)
        )
        if (d + 1) % args.eval_every == 0 or d == n_dispatch_total - 1:
            mae = float(val_mae(state))
            records = (d + 1) * SUPER * BATCH
            curve.append({"dispatch": d + 1, "records": records,
                          "val_log_mae": round(mae, 4)})
            print(f"soak: dispatch {d + 1}/{n_dispatch_total} "
                  f"({records / 1e6:.0f}M records) val_log_mae={mae:.4f} "
                  f"loss={float(loss):.4f}", flush=True)
        if args.host_roundtrip_at is not None and d + 1 == args.host_roundtrip_at:
            state = jax.tree_util.tree_map(
                lambda leaf: jnp.asarray(np.asarray(leaf))
                if hasattr(leaf, "dtype") else leaf,
                state,
            )
            print(f"soak: host roundtrip after dispatch {d + 1}", flush=True)
        saved = (d + 1) % args.ckpt_every == 0 or d == n_dispatch_total - 1
        if saved:
            save(d + 1)
        if args.kill_after_dispatch is not None and d + 1 >= args.kill_after_dispatch:
            if not saved:  # the periodic branch may have JUST written it
                save(d + 1)
            if args.hash_out:
                with open(args.hash_out + ".at_kill", "w") as f:
                    f.write(state_hash(state) + "\n")
            print(f"soak: KILLING after dispatch {d + 1} "
                  f"(checkpoint written)", flush=True)
            os._exit(137)

    jax.block_until_ready(state.params)
    train_s = time.time() - t_train0
    wall_s = time.time() - t_wall0
    records_done = (n_dispatch_total - start_dispatch) * SUPER * BATCH

    if args.hash_out:
        digest = state_hash(state)
        with open(args.hash_out, "w") as f:
            f.write(digest + "\n")
        print(f"soak: state sha256 {digest[:16]}…", flush=True)

    print(json.dumps({
        "records_this_run": records_done,
        "dispatches": n_dispatch_total - start_dispatch,
        "precompute_s": round(precompute_s, 1),
        "train_s": round(train_s, 1),
        "wall_s": round(wall_s, 1),
        "records_per_s": round(records_done / train_s, 1),
        "val_curve": curve,
        "resumed": args.resume,
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
