"""Scheduler serving-path benchmark: announces/sec, CPU-runnable.

Measures ``evaluate_parents`` — the per-announce ranking hot path — over
a synthetic swarm (sim.swarm.build_announce_swarm), comparing the
pre-vectorization scalar implementations (kept as ``*_reference``
oracles in scheduler/evaluator.py) against the serving engine
(vectorized scoring + HostFeatureCache + ScorerBatcher micro-batching),
under genuinely concurrent announcer threads like the RPC handlers.

Four paths:

- ``scalar_rule`` / ``vector_rule`` — base rule evaluator, per-parent
  Python lambda sort vs one numpy expression over all parents;
- ``scalar_ml``  / ``vector_ml``  — ML evaluator with an MLP scorer:
  per-parent ``to_parent_record`` + ``np.concatenate`` featurize + one
  call into the seed commit's verbatim scorer internals per announce,
  vs cache-gather featurize + the PR's scorer (mask folded into W1,
  powf-free gelu) + cross-request coalesced scoring.

The four paths are measured in INTERLEAVED rounds (after one unmeasured
warm-up round, with the GC quiesced) so machine-wide noise on a shared
box lands on every path roughly equally and the speedup ratios stay
meaningful even when absolute numbers wobble.

Prints ONE JSON line: per-path announces/sec and p50/p99 evaluate
latency, cache hit rate, mean batch occupancy, per-path steady-state
recompiles, the headline ``speedup_ml`` / ``speedup_rule``, and a
per-shape ``sweep`` (default 50 and 400 candidates — the rule-path
speedup is reported PER SHAPE; acceptance bars: rule ≥ 5× and ml ≥
6.05× at 1k hosts / 50 parents / 32 announcers — ISSUE 3/7).

Usage: PYTHONPATH=/root/repo python tools/bench_sched.py
       [--hosts 1000 --parents 50 --announcers 32 --announces 2048]
       [--rounds 6] [--sweep-parents 50,400]
       [--smoke]   # --smoke: tiny tier-1 schema gate
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

SCHEMA_KEYS = (
    "ok",
    "metric",
    "config",
    "paths",
    "speedup_ml",
    "speedup_rule",
    "cache_hit_rate",
    "mean_batch_occupancy",
    "steady_state_recompiles",
    "tracing_overhead",
    "telemetry_overhead",
    "qos_overhead",
    "sweep",
    "det_witness_disarmed",
)


def _det_witness_disarmed() -> bool:
    """True when the determinism witness (utils/dfdet.py) is absent or
    off for this process — stamped into the report so a benchmark run
    measured without the replay-determinism guard is visible in the
    artifact (DESIGN.md §27)."""
    mod = sys.modules.get("dragonfly2_tpu.utils.dfdet")
    if mod is None:
        return True
    w = getattr(mod, "witness", lambda: None)()
    return w is None


def _make_weights(seed: int = 0):
    """Deterministic 32→64→64→1 MLP weights (random but fixed)."""
    from dragonfly2_tpu.records.features import DOWNLOAD_FEATURE_DIM

    rng = np.random.default_rng(seed)
    dims = (DOWNLOAD_FEATURE_DIM, 64, 64, 1)
    return [
        (
            rng.standard_normal((dims[i], dims[i + 1])).astype(np.float32) * 0.3,
            rng.standard_normal(dims[i + 1]).astype(np.float32) * 0.05,
        )
        for i in range(len(dims) - 1)
    ]


def _make_scorer(seed: int = 0):
    from dragonfly2_tpu.trainer.export import MLPScorer

    return MLPScorer(weights=_make_weights(seed))


class _PrePRScorer:
    """The seed commit's ``MLPScorer.score`` + ``mask_post_hoc``, kept
    VERBATIM (per-call mask copy with a rebuilt index list, ``x**3``
    integer-power gelu that lowers to per-element libm ``powf``): the
    scorer-internal fixes — mask folded into W1, two-multiply cube — are
    part of this PR's serving work, so the scalar baseline must not
    silently inherit them through the shared scorer object."""

    def __init__(self, weights) -> None:
        self.weights = weights

    def score(self, features, **_buckets):
        from dragonfly2_tpu.records.features import POST_HOC_FEATURE_IDX

        x = np.array(features, dtype=np.float32, copy=True)
        x[..., list(POST_HOC_FEATURE_IDX)] = 0.0
        n = len(self.weights)
        for i, (w, b) in enumerate(self.weights):
            x = x @ w + b
            if i < n - 1:
                # gelu (tanh approx — matches flax nn.gelu default)
                x = 0.5 * x * (1.0 + np.tanh(0.7978845608 * (x + 0.044715 * x**3)))
        return x[..., 0]


def _make_plans(n_hosts, *, parents_per_announce, announcers, announces, seed):
    """Pre-draw every announce's (child index, candidate index list) so
    the measured region is ranking work only, identical across paths
    per seed.  ``_resolve_plans`` turns indices into peer objects ONCE,
    outside the timed region — the per-announce index→object listcomp
    used to sit inside every ranked call's wall, a fixed ~3 µs that
    taxed the fast paths several percent and the slow ones not at all."""
    rng = np.random.default_rng(seed)
    per_thread = max(announces // announcers, 1)
    plans = []
    for _ in range(announcers):
        thread_plan = []
        for _ in range(per_thread):
            child_i = int(rng.integers(0, n_hosts))
            cand = rng.choice(n_hosts - 1, size=parents_per_announce,
                              replace=False)
            cand = [c if c < child_i else c + 1 for c in cand]
            thread_plan.append((child_i, cand))
        plans.append(thread_plan)
    return plans


def _resolve_plans(plans, peers):
    """Index plans → (child peer, [candidate peers]) plans."""
    return [
        [(peers[ci], [peers[c] for c in cand]) for ci, cand in tp]
        for tp in plans
    ]


class _AnnouncerPool:
    """Persistent announcer threads reused across every measured round.

    Spawning 32 OS threads per round cost 2-4 ms — noise floor for the
    slow paths but a systematic multi-percent tax on the fast ones
    (a vectorized round is tens of ms of wall).  The pool parks workers
    on a barrier between rounds, so a round's wall clock is pure ranking
    work for every path alike."""

    def __init__(self, announcers: int) -> None:
        self.announcers = announcers
        self._start = threading.Barrier(announcers + 1)
        self._done = threading.Barrier(announcers + 1)
        self._work = None
        self._stop = False
        self._threads = [
            threading.Thread(target=self._loop, args=(i,), daemon=True)
            for i in range(announcers)
        ]
        for t in self._threads:
            t.start()

    def _loop(self, tid: int) -> None:
        while True:
            self._start.wait()
            if self._stop:
                return
            evaluate, tpc, plans, latencies, spans, errors = self._work
            lat = latencies[tid]
            # The round's wall clock is measured INSIDE the workers
            # (max end − min start): the main thread can sit unscheduled
            # for tens of ms after the start barrier on a busy 1-CPU
            # box, which silently shrank main-measured walls and
            # inflated throughput for the fast paths.
            t_start = time.perf_counter()
            try:
                for child, candidates in plans[tid]:
                    t0 = time.perf_counter()
                    ranked = evaluate(candidates, child, tpc)
                    lat.append(time.perf_counter() - t0)
                    if len(ranked) != len(candidates):
                        raise RuntimeError("ranking dropped candidates")
            except Exception as exc:  # noqa: BLE001 — surfaced to the main thread
                errors.append(exc)
            spans[tid] = (t_start, time.perf_counter())
            self._done.wait()

    def run_round(self, evaluate, task, peers, plans):
        """One round of ``evaluate(candidates, child, tpc)`` across the
        pool; ``plans`` are index plans (resolved here, untimed).
        Returns (wall_s, latencies)."""
        resolved = _resolve_plans(plans, peers)
        latencies = [[] for _ in range(self.announcers)]
        spans = [(0.0, 0.0)] * self.announcers
        errors: list = []
        self._work = (
            evaluate, task.total_piece_count, resolved, latencies, spans,
            errors,
        )
        self._start.wait()
        self._done.wait()
        if errors:
            raise errors[0]
        wall = max(s[1] for s in spans) - min(s[0] for s in spans)
        return wall, [x for lat in latencies for x in lat]

    def shutdown(self) -> None:
        self._stop = True
        self._start.wait()
        for t in self._threads:
            t.join()


def _run_round(evaluate, task, peers, plans, announcers):
    """One-shot convenience wrapper (kept for external callers): spins a
    pool for a single round."""
    pool = _AnnouncerPool(announcers)
    try:
        return pool.run_round(evaluate, task, peers, plans)
    finally:
        pool.shutdown()


def _run_path(evaluate, task, peers, *, parents_per_announce, announcers,
              announces, seed):
    """Single-path convenience wrapper around ``_run_round`` (one round)."""
    plans = _make_plans(
        len(peers), parents_per_announce=parents_per_announce,
        announcers=announcers, announces=announces, seed=seed,
    )
    wall, lat = _run_round(evaluate, task, peers, plans, announcers)
    return _summarize(wall, lat)


def _summarize(wall, latencies):
    lat = np.sort(np.asarray(latencies))
    total = len(lat)
    return {
        "announces_per_sec": round(total / wall, 1),
        "p50_ms": round(float(lat[int(total * 0.50)]) * 1e3, 4),
        "p99_ms": round(float(lat[min(int(total * 0.99), total - 1)]) * 1e3, 4),
        "announces": total,
    }


def run(hosts: int, parents: int, announcers: int, announces: int,
        linger_ms: float, seed: int = 0, rounds: int = 4) -> dict:
    import gc

    # Compile witness FIRST (patches jax.jit before any project module can
    # construct one): the measured rounds are bracketed with compile-count
    # snapshots, so the JSON line reports steady-state recompiles per path
    # — the serving acceptance bar is vector_ml == 0 (a retrace mid-round
    # would erase the micro-batching win on a jit/TPU scorer backend).
    from dragonfly2_tpu.utils import dftrace

    witness = dftrace.install()

    from dragonfly2_tpu.scheduler import (
        Evaluator,
        HostFeatureCache,
        MLEvaluator,
        ScorerBatcher,
    )
    from dragonfly2_tpu.sim.swarm import build_announce_swarm

    task, peers = build_announce_swarm(hosts, seed=seed)
    scorer = _make_scorer(seed)

    # ONE columnar host store shared by the rule and ML serving paths
    # (DESIGN.md §18: one service owns one store; hosts bind once and
    # both vectorized paths ride owner gathers).
    cache = HostFeatureCache(max_hosts=max(hosts * 2, 1024))
    rule = Evaluator(feature_cache=cache)
    # The scalar baseline runs the seed commit's scorer internals too —
    # the serving PR's scorer fixes must not leak into the baseline.
    ml_scalar = MLEvaluator(_PrePRScorer(_make_weights(seed)))
    batcher = ScorerBatcher(linger_s=linger_ms / 1e3)
    ml_vec = MLEvaluator(scorer, feature_cache=cache, batcher=batcher)
    named = (
        ("scalar_rule", rule.evaluate_parents_reference),
        ("vector_rule", rule.evaluate_parents),
        ("scalar_ml", ml_scalar._evaluate_parents_reference),
        ("vector_ml", ml_vec.evaluate_parents),
    )

    # The paths are measured in INTERLEAVED rounds (scalar round, vector
    # round, …, repeated): on a shared/noisy box, machine-wide slowdowns
    # then land on every path roughly equally instead of poisoning
    # whichever path happened to run during the bad minute — the speedup
    # ratios stay meaningful even when absolute numbers wobble.
    rounds = max(rounds, 1)
    per_round = max(announces // rounds, announcers)
    walls = {name: 0.0 for name, _ in named}
    lats = {name: [] for name, _ in named}
    recompiles = {name: 0 for name, _ in named}
    # Warm-up round (caches, lru memos, numpy first-call machinery), then
    # GC quiesced for the measured rounds: collector pauses hit the
    # allocation-heavy scalar paths hardest and were a major variance
    # source (p99 spikes of hundreds of ms).  One persistent announcer
    # pool serves every round — per-round thread spawns taxed the fast
    # paths multiple percent.
    pool = _AnnouncerPool(announcers)
    try:
        for r in range(rounds + 1):
            plans = _make_plans(
                len(peers), parents_per_announce=parents,
                announcers=announcers, announces=per_round, seed=seed + r,
            )
            measured = r > 0
            if r == 1:
                gc.collect()
                gc.disable()
            for name, evaluate in named:
                compiles_before = witness.total_compiles()
                wall, lat = pool.run_round(evaluate, task, peers, plans)
                if measured:
                    walls[name] += wall
                    lats[name].extend(lat)
                    recompiles[name] += witness.total_compiles() - compiles_before
        # Tracing-overhead rounds (ISSUE 10 acceptance: ≤3% on vector_ml
        # at the default sampling rate).  Interleaved on/off like the
        # main rounds: "on" = flight recorder live (durable export at
        # the config-default 0.1 head-sampling, flush spans firing);
        # "off" = tracing.set_enabled(False), the operator's off switch.
        import os as _os
        import tempfile

        from dragonfly2_tpu.utils import tracing as _tr

        trace_walls = {"on": 0.0, "off": 0.0}
        trace_counts = {"on": 0, "off": 0}
        fd, trace_path = tempfile.mkstemp(suffix=".dftrace")
        _os.close(fd)
        durable = _tr.DurableSpanExporter(
            trace_path, service="bench_sched", sample_rate=0.1
        )
        prev_exporter = _tr.default_tracer.exporter
        try:
            for r in range(rounds):
                plans = _make_plans(
                    len(peers), parents_per_announce=parents,
                    announcers=announcers, announces=per_round,
                    seed=seed + 1000 + r,
                )
                # Unmeasured warm pass over THIS plan set: whichever arm
                # runs first would otherwise pay the cold feature-cache
                # rows for the round's new children — a systematic bias
                # against it.  Arm order still alternates per round.
                _tr.set_enabled(False)
                pool.run_round(ml_vec.evaluate_parents, task, peers, plans)
                arms = ("on", "off") if r % 2 == 0 else ("off", "on")
                for arm in arms:
                    if arm == "on":
                        _tr.set_enabled(True)
                        _tr.default_tracer.exporter = durable
                    else:
                        _tr.set_enabled(False)
                    wall, lat = pool.run_round(
                        ml_vec.evaluate_parents, task, peers, plans
                    )
                    trace_walls[arm] += wall
                    trace_counts[arm] += len(lat)
        finally:
            _tr.set_enabled(True)
            _tr.default_tracer.exporter = prev_exporter
            durable.close()
            try:
                _os.unlink(trace_path)
            except OSError:
                pass
        # Telemetry-overhead rounds (ISSUE 12 acceptance: sketch
        # recording ≤3% on vector_ml).  Same bench discipline as the
        # tracing guard: unmeasured warm pass per plan set, interleaved
        # on/off rounds with alternating arm order.  "on" = the §23
        # sketches recording (scheduler_eval_flush_seconds fires per
        # flush on this path); "off" = metrics.set_sketches_enabled(False),
        # the operator's off switch.
        from dragonfly2_tpu.utils import metrics as _metrics

        sk_walls = {"on": 0.0, "off": 0.0}
        sk_counts = {"on": 0, "off": 0}
        from dragonfly2_tpu.scheduler.metrics import EVAL_FLUSH_SECONDS

        sketch_before = EVAL_FLUSH_SECONDS.total_count()
        try:
            for r in range(rounds):
                plans = _make_plans(
                    len(peers), parents_per_announce=parents,
                    announcers=announcers, announces=per_round,
                    seed=seed + 2000 + r,
                )
                _metrics.set_sketches_enabled(False)
                pool.run_round(ml_vec.evaluate_parents, task, peers, plans)
                arms = ("on", "off") if r % 2 == 0 else ("off", "on")
                for arm in arms:
                    _metrics.set_sketches_enabled(arm == "on")
                    wall, lat = pool.run_round(
                        ml_vec.evaluate_parents, task, peers, plans
                    )
                    sk_walls[arm] += wall
                    sk_counts[arm] += len(lat)
        finally:
            _metrics.set_sketches_enabled(True)
        sketch_observed = EVAL_FLUSH_SECONDS.total_count() - sketch_before
        # QoS-overhead rounds (ISSUE 15 acceptance: the §26 tenant plane
        # ≤3% on vector_ml with ONE tenant and NO contention).  Same
        # discipline: unmeasured warm pass per plan set, interleaved
        # arms, alternating order.  "on" = a QoSPolicy installed on the
        # batcher + every announce stamped with the tenant (the single
        # active lane rides the whole-queue-swap fast path, so this
        # measures the §26 plumbing, not DRR arbitration); "off" = no
        # policy, default lane.
        from dragonfly2_tpu.qos import QoSPolicy as _QoSPolicy

        qos_policy = _QoSPolicy.from_payload(
            {"t-bench": {"tenant_class": "gold", "weight": 2.0}}
        )
        qos_walls = {"on": 0.0, "off": 0.0}
        qos_counts = {"on": 0, "off": 0}
        try:
            for r in range(rounds):
                plans = _make_plans(
                    len(peers), parents_per_announce=parents,
                    announcers=announcers, announces=per_round,
                    seed=seed + 3000 + r,
                )
                batcher.set_qos_policy(None)
                pool.run_round(ml_vec.evaluate_parents, task, peers, plans)
                arms = ("on", "off") if r % 2 == 0 else ("off", "on")
                for arm in arms:
                    if arm == "on":
                        batcher.set_qos_policy(qos_policy)
                        for p in peers:
                            p.tenant = "t-bench"
                    else:
                        batcher.set_qos_policy(None)
                        for p in peers:
                            p.tenant = ""
                    wall, lat = pool.run_round(
                        ml_vec.evaluate_parents, task, peers, plans
                    )
                    qos_walls[arm] += wall
                    qos_counts[arm] += len(lat)
        finally:
            batcher.set_qos_policy(None)
            for p in peers:
                p.tenant = ""
    finally:
        gc.enable()
        pool.shutdown()
    paths = {name: _summarize(walls[name], lats[name]) for name, _ in named}
    on_aps = trace_counts["on"] / trace_walls["on"]
    off_aps = trace_counts["off"] / trace_walls["off"]
    sk_on_aps = sk_counts["on"] / sk_walls["on"]
    sk_off_aps = sk_counts["off"] / sk_walls["off"]
    qos_on_aps = qos_counts["on"] / qos_walls["on"]
    qos_off_aps = qos_counts["off"] / qos_walls["off"]

    return {
        "ok": True,
        "metric": "scheduler_announces_per_sec",
        "config": {
            "hosts": hosts,
            "parents_per_announce": parents,
            "announcers": announcers,
            "announces_per_path": paths["vector_ml"]["announces"],
            "rounds": rounds,
            "linger_ms": linger_ms,
            "seed": seed,
        },
        "paths": paths,
        "speedup_rule": round(
            paths["vector_rule"]["announces_per_sec"]
            / paths["scalar_rule"]["announces_per_sec"], 2,
        ),
        "speedup_ml": round(
            paths["vector_ml"]["announces_per_sec"]
            / paths["scalar_ml"]["announces_per_sec"], 2,
        ),
        "cache_hit_rate": round(cache.hit_rate(), 4),
        "mean_batch_occupancy": round(batcher.mean_occupancy(), 2),
        # XLA compiles observed DURING measured rounds, per path (compile
        # witness, utils/dftrace.py).  The warm-up round absorbs first
        # compiles; anything here is a steady-state retrace.
        "steady_state_recompiles": recompiles,
        # Flight-recorder overhead on the vector_ml serving path:
        # interleaved tracing-on (durable export, 0.1 head-sampling,
        # flush spans live) vs tracing-off rounds.  overhead_pct is the
        # throughput given up with tracing on; negative values are box
        # noise (BENCHMARKS.md documents the ±4% envelope).
        "tracing_overhead": {
            "on_announces_per_sec": round(on_aps, 1),
            "off_announces_per_sec": round(off_aps, 1),
            "overhead_pct": round(100.0 * (off_aps - on_aps) / off_aps, 2),
            "sample_rate": 0.1,
            "spans_durable": durable.exported,
        },
        # Sketch-recording overhead on the vector_ml serving path
        # (DESIGN.md §23 telemetry guard, ≤3% bar in BENCHMARKS.md):
        # interleaved sketches-on vs sketches-off rounds; negative
        # values are box noise.
        "telemetry_overhead": {
            "on_announces_per_sec": round(sk_on_aps, 1),
            "off_announces_per_sec": round(sk_off_aps, 1),
            "overhead_pct": round(
                100.0 * (sk_off_aps - sk_on_aps) / sk_off_aps, 2
            ),
            "sketch_observes": sketch_observed,
        },
        # Tenant-QoS overhead on the vector_ml serving path (DESIGN.md
        # §26 guard, ≤3% bar in BENCHMARKS.md): single tenant, no
        # contention — the weighted-fair lane plumbing with policy
        # installed vs the default lane; negative values are box noise.
        "qos_overhead": {
            "on_announces_per_sec": round(qos_on_aps, 1),
            "off_announces_per_sec": round(qos_off_aps, 1),
            "overhead_pct": round(
                100.0 * (qos_off_aps - qos_on_aps) / qos_off_aps, 2
            ),
        },
    }


def _sweep_entry(result: dict, hosts: int, parents: int) -> dict:
    """Per-shape summary line: the rule-path speedup PER SHAPE is the
    headline (BENCHMARKS.md used to narrate the 50-candidate ~1× number
    in prose only; now every shape reports it in the JSON)."""
    paths = result["paths"]
    return {
        "hosts": hosts,
        "parents": parents,
        "speedup_rule": result["speedup_rule"],
        "speedup_ml": result["speedup_ml"],
        "scalar_rule_announces_per_sec": paths["scalar_rule"]["announces_per_sec"],
        "vector_rule_announces_per_sec": paths["vector_rule"]["announces_per_sec"],
        "vector_ml_announces_per_sec": paths["vector_ml"]["announces_per_sec"],
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--hosts", type=int, default=1000)
    p.add_argument("--parents", type=int, default=50)
    p.add_argument("--announcers", type=int, default=32)
    p.add_argument("--announces", type=int, default=2048,
                   help="total announces per measured path")
    p.add_argument("--linger-ms", type=float, default=1.5)
    p.add_argument("--rounds", type=int, default=6,
                   help="interleaved measurement rounds per path "
                        "(+1 unmeasured warm-up round); more rounds "
                        "average shared-box noise out of the ratios")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--sweep-parents", default="50,400",
                   help="comma-separated candidate-set sizes for the "
                        "per-shape sweep (announces scale down so each "
                        "shape does comparable total ranking work)")
    p.add_argument("--smoke", action="store_true",
                   help="tiny sizes: the tier-1 JSON-schema gate")
    args = p.parse_args(argv)
    if args.smoke:
        args.hosts, args.parents = 64, 8
        args.announcers, args.announces = 4, 64
        args.linger_ms, args.rounds = 0.2, 1
        args.sweep_parents = "8,16"
    try:
        out = run(args.hosts, args.parents, args.announcers, args.announces,
                  args.linger_ms, args.seed, args.rounds)
        sweep = [_sweep_entry(out, args.hosts, args.parents)]
        for par in [int(x) for x in args.sweep_parents.split(",") if x]:
            if par == args.parents:
                continue  # primary shape already measured above
            ann = max(
                args.announces * args.parents // max(par, 1),
                args.announcers * max(args.rounds, 1),
            )
            r = run(args.hosts, par, args.announcers, ann,
                    args.linger_ms, args.seed, args.rounds)
            sweep.append(_sweep_entry(r, args.hosts, par))
        out["sweep"] = sweep
        out["det_witness_disarmed"] = _det_witness_disarmed()
        missing = [k for k in SCHEMA_KEYS if k not in out]
        if missing:
            raise RuntimeError(f"schema keys missing: {missing}")
    except Exception as exc:  # noqa: BLE001 — one parseable line, never a traceback
        print(json.dumps({
            "ok": False,
            "metric": "scheduler_announces_per_sec",
            "error": f"{type(exc).__name__}: {exc}"[:300],
        }, sort_keys=True))
        return 1
    print(json.dumps(out, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
