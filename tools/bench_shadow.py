"""Shadow-scoring overhead benchmark: announces/sec with shadow off vs on.

Measures the rollout plane's marginal cost on the announce hot path
(ISSUE 4 acceptance: shadow mode at a 10 % sample rate must cost < 5 %
announces/s): the SAME vectorized ML serving path bench_sched.py
measures — cache-gather featurize + micro-batched scoring under
concurrent announcer threads — run in INTERLEAVED rounds with and
without a ShadowScorer attached, so machine noise lands on both paths
equally (the bench_sched discipline).

The shadow engine runs for real: deterministic hash sampling, the
worker thread re-scoring candidates, and the columnar replay log (to a
temp file), so the measured overhead includes queue handoff and any GIL
pressure from the worker — not just the sampling branch.

Prints ONE JSON line: per-path announces/sec + latency percentiles,
overhead percent, and the shadow engine's own accounting (sampled /
scored / dropped / logged rows).

Usage: PYTHONPATH=/root/repo python tools/bench_shadow.py
       [--hosts 1000 --parents 50 --announcers 32 --announces 2048]
       [--sample-rate 0.1] [--rounds 4] [--smoke]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from bench_sched import _make_plans, _make_weights, _run_round, _summarize  # noqa: E402

SCHEMA_KEYS = (
    "ok",
    "metric",
    "config",
    "paths",
    "overhead_pct",
    "shadow",
)


def run(hosts: int, parents: int, announcers: int, announces: int,
        sample_rate: float, linger_ms: float, seed: int = 0,
        rounds: int = 4) -> dict:
    import gc

    from dragonfly2_tpu.rollout import ShadowScorer
    from dragonfly2_tpu.scheduler import (
        HostFeatureCache,
        MLEvaluator,
        ScorerBatcher,
    )
    from dragonfly2_tpu.sim.swarm import build_announce_swarm
    from dragonfly2_tpu.trainer.export import MLPScorer

    task, peers = build_announce_swarm(hosts, seed=seed)

    def make_eval():
        return MLEvaluator(
            MLPScorer(weights=_make_weights(seed)),
            feature_cache=HostFeatureCache(max_hosts=max(hosts * 2, 1024)),
            batcher=ScorerBatcher(linger_s=linger_ms / 1e3),
        )

    ml_off = make_eval()
    ml_on = make_eval()
    log_dir = tempfile.mkdtemp(prefix="bench-shadow-")
    log_path = os.path.join(log_dir, "shadow_replay.dfc")
    shadow = ShadowScorer(
        MLPScorer(weights=_make_weights(seed + 1)),  # a DIFFERENT candidate
        candidate_version=2,
        active_version=1,
        sample_rate=sample_rate,
        log_path=log_path,
    )
    ml_on.set_shadow(shadow)

    named = (
        ("shadow_off", ml_off.evaluate_parents),
        ("shadow_on", ml_on.evaluate_parents),
    )
    rounds = max(rounds, 1)
    per_round = max(announces // rounds, announcers)
    walls = {name: 0.0 for name, _ in named}
    lats = {name: [] for name, _ in named}
    # Interleaved rounds + warm-up + GC quiesced: bench_sched's recipe.
    for r in range(rounds + 1):
        plans = _make_plans(
            len(peers), parents_per_announce=parents,
            announcers=announcers, announces=per_round, seed=seed + r,
        )
        measured = r > 0
        if r == 1:
            gc.collect()
            gc.disable()
        for name, evaluate in named:
            wall, lat = _run_round(evaluate, task, peers, plans, announcers)
            if measured:
                walls[name] += wall
                lats[name].extend(lat)
    gc.enable()
    shadow.drain(timeout=60.0)
    stats = shadow.stats()
    shadow.close()
    paths = {name: _summarize(walls[name], lats[name]) for name, _ in named}
    off = paths["shadow_off"]["announces_per_sec"]
    on = paths["shadow_on"]["announces_per_sec"]
    return {
        "ok": True,
        "metric": "scheduler_shadow_overhead_pct",
        "config": {
            "hosts": hosts,
            "parents_per_announce": parents,
            "announcers": announcers,
            "announces_per_path": paths["shadow_on"]["announces"],
            "sample_rate": sample_rate,
            "rounds": rounds,
            "linger_ms": linger_ms,
            "seed": seed,
        },
        "paths": paths,
        "overhead_pct": round((1.0 - on / off) * 100.0, 2) if off else 0.0,
        "shadow": stats,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--hosts", type=int, default=1000)
    p.add_argument("--parents", type=int, default=50)
    p.add_argument("--announcers", type=int, default=32)
    p.add_argument("--announces", type=int, default=2048,
                   help="total announces per measured path")
    p.add_argument("--sample-rate", type=float, default=0.1)
    p.add_argument("--linger-ms", type=float, default=1.5)
    p.add_argument("--rounds", type=int, default=4,
                   help="interleaved measurement rounds per path "
                        "(+1 unmeasured warm-up round)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--smoke", action="store_true",
                   help="tiny sizes: the tier-1 JSON-schema gate")
    args = p.parse_args(argv)
    if args.smoke:
        args.hosts, args.parents = 64, 8
        args.announcers, args.announces = 4, 64
        args.linger_ms, args.rounds = 0.2, 1
    try:
        out = run(args.hosts, args.parents, args.announcers, args.announces,
                  args.sample_rate, args.linger_ms, args.seed, args.rounds)
        missing = [k for k in SCHEMA_KEYS if k not in out]
        if missing:
            raise RuntimeError(f"schema keys missing: {missing}")
    except Exception as exc:  # noqa: BLE001 — one parseable line, never a traceback
        print(json.dumps({
            "ok": False,
            "metric": "scheduler_shadow_overhead_pct",
            "error": f"{type(exc).__name__}: {exc}"[:300],
        }))
        return 1
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
