"""Whole-program state-machine & crash-consistency analysis (DF013-DF015).

The concurrency pass (``program.py``) guards locks, the trace pass
(``tracerules.py``) guards the XLA layer; this module guards the
*stateful* invariants the Manager-HA and sharded-scheduler roadmap items
stand on — invariants that until now lived only in docstrings.  All
three rule families key off ONE declared-once literal registry,
``dragonfly2_tpu/records/state_contracts.py``, read with
``ast.literal_eval`` (no import — dflint stays stdlib-only), and are
built on :class:`tools.dflint.program.Program`'s symbol table and call
graph.

**DF013 — FSM transition legality.**  For each declared machine:

- the ``EventDesc`` literals in the defining module are cross-checked
  edge-for-edge against the registry (drift fails BY MACHINE+EVENT
  name, so neither side can rot);
- every ``fsm.event("X")`` site (including declared forwarders like
  ``_try_event(peer.fsm, "X")``) must name a declared event of the
  machine the receiver resolves to;
- ``fsm.set_state("S")`` is legal only in the machine's declared
  mirror modules and only with a declared state;
- mirror attributes (``fsm_state``/``fsm_elevated``) are written only
  by the declared writers (construction + the ``enter_state``
  callback);
- enum machines (ModelState, RolloutPhase): a direct ``.state = Enum.X``
  write outside the owning module fails; registry gateway calls
  (``set_state``/``activate``/``deactivate``) are checked against the
  per-module mutator table — an undeclared (module, target-state) pair
  fails by machine and state name.

**DF014 — crash-consistency over StateBackend/KVTable.**

- every ``.table("ns")`` namespace must be declared (with owner, lock,
  loader, invariant);
- declared multi-row sites must persist through ONE ``put_many`` —
  a single ``put`` inside one fails (the split-transaction mutation);
- every write site must hold the namespace's owning lock, either
  lexically or inherited from all callers (boot-time writers are
  declared ``unlocked_ok``); a read in a writing function is held to
  the same bar (get→mutate→put races);
- every namespace must have a recovery loader: a ``load_all`` consumer
  reachable from a constructor — an orphan table fails by namespace;
- declared write-order pairs: in a function writing both namespaces,
  the referencing row's namespace must not commit first;
- declared foreign keys: the parent's delete primitive may only be
  called by the declared cleanup (which must delete child rows).

**DF015 — RPC contract parity.**

- every client ``_call("method", ...)`` literal must have a dispatch
  handler in the inproc server's METHODS set AND a message mapping in
  the gRPC transport's method table (a deleted handler fails by method
  name);
- every gRPC table entry must map onto a server handler, and every
  METHODS entry onto a defined adapter method;
- every retried client method must be classified ``idempotent`` or
  ``deduped`` (with the named server-side dedup seam verified to
  exist); stale classifications fail.

The static inventory is cross-validated at runtime by the **crash
witness** (``dragonfly2_tpu/utils/dfcrash.py`` +
``tests/test_zz_crashwitness.py``): every KVTable write observed during
tier-1 must map into :meth:`StateAnalysis.persistence_site_index`, and
declared multi-row sites must only ever be observed as ``put_many``.
A static blind spot is a witness failure — a resolver fix, never
silent rot.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from .core import Finding, dotted
from .program import (
    ClassInfo,
    FuncInfo,
    ModuleInfo,
    Program,
    _calls_in,
    _calls_in_expr,
    _stmt_bodies,
    _stmt_exprs,
    _walk_skipping_defs,
)

RULE_FSM = "DF013"
TITLE_FSM = "illegal state-machine transition / mirror write"
RULE_CRASH = "DF014"
TITLE_CRASH = "crash-consistency violation at a persistence site"
RULE_RPC = "DF015"
TITLE_RPC = "RPC contract parity / idempotency violation"

STATE_CONTRACTS_RELPATH = "dragonfly2_tpu/records/state_contracts.py"

_TABLE_METHODS = {"put", "put_many", "get", "delete", "load_all"}
_WRITE_METHODS = {"put", "put_many", "delete"}


class TableOp:
    """One statically-resolved KVTable operation site."""

    __slots__ = ("ns", "method", "node", "held", "fi")

    def __init__(self, ns: str, method: str, node: ast.Call,
                 held: FrozenSet[str], fi: FuncInfo) -> None:
        self.ns = ns
        self.method = method
        self.node = node
        self.held = held
        self.fi = fi


class StateAnalysis:
    """DF013-DF015 over a linked :class:`Program`."""

    def __init__(self, program: Program, root: Optional[Path] = None) -> None:
        self.program = program
        self.root = root
        self._findings: List[Finding] = []
        self.contracts = self._load_contracts()
        self.machines: Dict[str, dict] = dict(
            self.contracts.get("machines", {})
        )
        self.persistence: dict = dict(self.contracts.get("persistence", {}))
        self.rpc: Dict[str, dict] = dict(self.contracts.get("rpc", {}))
        # -- persistence model ------------------------------------------
        # (relpath, class name) -> {attr: ns}
        self._class_bindings: Dict[Tuple[str, str], Dict[str, str]] = {}
        # attr name -> ns (only when unique project-wide), for receivers
        # the type resolver cannot follow (closure aliases like
        # `server._topology_table`).
        self._attr_bindings: Dict[str, Optional[str]] = {}
        # FuncInfo.key -> {local var: [(lineno, ns), ...]} — flow
        # sensitive: migrate_legacy_sqlite rebinds one local per table.
        self._local_tables: Dict[str, Dict[str, List[Tuple[int, str]]]] = {}
        self._binding_sites: List[Tuple[str, ast.AST, ModuleInfo]] = []
        self._ops: List[TableOp] = []
        self._call_edges: Dict[str, List[Tuple[str, FrozenSet[str]]]] = {}
        # enum machines: enum class name -> (machine key, {MEMBER: value})
        self._enums: Dict[str, Tuple[str, Dict[str, str]]] = {}
        if self.contracts:
            self._collect_bindings()
            self._collect_enum_members()
            for fi in self.program.funcs.values():
                self._walk_function(fi)
            self._check_df013()
            self._check_df014()
            self._check_df015()
        self._findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    # ------------------------------------------------------------------
    # Shared plumbing
    # ------------------------------------------------------------------

    def findings(self) -> List[Finding]:
        return list(self._findings)

    def _emit(self, rule: str, mi: ModuleInfo, node: ast.AST, message: str) -> None:
        module = mi.module
        line = getattr(node, "lineno", 1)
        if module.suppressed(rule, line):
            return
        self._findings.append(
            Finding(
                rule=rule,
                path=mi.relpath,
                line=line,
                col=getattr(node, "col_offset", 0) + 1,
                message=message,
                qual=module.qualname(node),
            )
        )

    def _load_contracts(self) -> dict:
        mi = self.program.modules.get(STATE_CONTRACTS_RELPATH)
        tree = None
        if mi is not None:
            tree = mi.module.tree
        elif self.root is not None:
            path = self.root / STATE_CONTRACTS_RELPATH
            if path.exists():
                tree = ast.parse(path.read_text(encoding="utf-8"))
        if tree is None:
            return {}
        for stmt in tree.body:
            if (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id == "STATE_CONTRACTS"
            ):
                try:
                    return ast.literal_eval(stmt.value)
                except ValueError:
                    if mi is not None:
                        self._emit(
                            RULE_FSM, mi, stmt,
                            "STATE_CONTRACTS must stay a pure literal "
                            "(ast.literal_eval failed — dflint reads it "
                            "without importing)",
                        )
                    return {}
        return {}

    # ------------------------------------------------------------------
    # Persistence model: table bindings + lock-region walk
    # ------------------------------------------------------------------

    @staticmethod
    def _table_ns_of(value: ast.AST) -> Optional[Tuple[str, ast.Call]]:
        """The namespace literal when ``value`` contains a
        ``<backend>.table("ns")`` call (direct, IfExp branch, or BoolOp
        operand)."""
        candidates: List[ast.AST] = [value]
        if isinstance(value, ast.IfExp):
            candidates = [value.body, value.orelse]
        elif isinstance(value, ast.BoolOp):
            candidates = list(value.values)
        for cand in candidates:
            if (
                isinstance(cand, ast.Call)
                and isinstance(cand.func, ast.Attribute)
                and cand.func.attr == "table"
                and cand.args
                and isinstance(cand.args[0], ast.Constant)
                and isinstance(cand.args[0].value, str)
            ):
                return cand.args[0].value, cand
        return None

    def _collect_bindings(self) -> None:
        ambiguous: Set[str] = set()
        for mi in self.program.modules.values():
            for node in ast.walk(mi.module.tree):
                target = value = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    target, value = node.targets[0], node.value
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    target, value = node.target, node.value
                if value is None:
                    continue
                hit = self._table_ns_of(value)
                if hit is None:
                    continue
                ns, call = hit
                self._binding_sites.append((ns, call, mi))
                if (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id in ("self", "cls")
                ):
                    cls = mi.module.enclosing_class(node)
                    if cls is not None:
                        self._class_bindings.setdefault(
                            (mi.relpath, cls.name), {}
                        )[target.attr] = ns
                    prev = self._attr_bindings.get(target.attr)
                    if prev is not None and prev != ns:
                        ambiguous.add(target.attr)
                    self._attr_bindings[target.attr] = ns
                elif isinstance(target, ast.Name):
                    fn = mi.module.enclosing_function(node)
                    if fn is not None:
                        qual = mi.module.qualname(fn)
                        self._local_tables.setdefault(
                            f"{mi.relpath}:{qual}", {}
                        ).setdefault(target.id, []).append((node.lineno, ns))
        for attr in ambiguous:
            self._attr_bindings[attr] = None

    def _binding_of_class(self, ci: Optional[ClassInfo], attr: str) -> Optional[str]:
        if ci is None:
            return None
        for c in ci.mro():
            ns = self._class_bindings.get((c.module.relpath, c.name), {}).get(attr)
            if ns is not None:
                return ns
        return None

    def _table_op_of(self, fi: FuncInfo, call: ast.Call) -> Optional[Tuple[str, str]]:
        """(namespace, method) when ``call`` is a KVTable op on a bound
        table receiver, else None."""
        func = call.func
        if not isinstance(func, ast.Attribute) or func.attr not in _TABLE_METHODS:
            return None
        recv = func.value
        if isinstance(recv, ast.Name):
            rebinds = self._local_tables.get(fi.key, {}).get(recv.id)
            if not rebinds:
                return None
            # Nearest preceding rebinding wins (flow sensitivity for
            # one local reused across tables, e.g. migrate_legacy_sqlite).
            ns = None
            for line, bound in rebinds:
                if line <= call.lineno:
                    ns = bound
            return (ns, func.attr) if ns is not None else None
        if not isinstance(recv, ast.Attribute):
            return None
        attr = recv.attr
        base = recv.value
        if isinstance(base, ast.Name) and base.id in ("self", "cls"):
            # Class-scoped lookup ONLY: a same-named plain attribute on
            # another class (UserStore._users, a dict) must not alias the
            # table binding.
            ns = self._binding_of_class(fi.cls, attr)
            return (ns, func.attr) if ns is not None else None
        ns = self._attr_bindings.get(attr)
        if ns is not None:
            return ns, func.attr
        return None

    # -- lock tokens ----------------------------------------------------

    def _lock_tokens(self, fi: FuncInfo, expr: ast.AST) -> Set[str]:
        toks: Set[str] = set()
        lock = self.program.resolve_lock_expr(fi, expr, fi._types, fi._locks)
        if lock is not None:
            toks.add(lock.base().key)
        if isinstance(expr, ast.Attribute):
            toks.add(f"tail::{expr.attr}")
        return toks

    def _walk_function(self, fi: FuncInfo) -> None:
        if not hasattr(fi, "_types"):
            return
        self._walk_body(fi, list(fi.node.body), frozenset())

    def _walk_body(self, fi: FuncInfo, body: List[ast.stmt], held: FrozenSet[str]) -> None:
        i = 0
        while i < len(body):
            stmt = body[i]
            acquired = self.program._manual_acquire(fi, stmt)
            if acquired is not None:
                lock, node = acquired
                rest = body[i + 1:]
                cut = len(rest)
                for j, s in enumerate(rest):
                    if self.program._manual_release(fi, s) is lock:
                        cut = j
                        break
                self._walk_body(fi, rest[:cut], held | {lock.base().key})
                i += 1 + cut
                continue
            self._walk_stmt(fi, stmt, held)
            i += 1

    def _walk_stmt(self, fi: FuncInfo, stmt: ast.stmt, held: FrozenSet[str]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            entered = set(held)
            for item in stmt.items:
                self._scan_expr(fi, item.context_expr, frozenset(entered))
                entered |= self._lock_tokens(fi, item.context_expr)
            self._walk_body(fi, list(stmt.body), frozenset(entered))
            return
        for expr in _stmt_exprs(stmt):
            self._scan_expr(fi, expr, held)
        for sub_body in _stmt_bodies(stmt):
            self._walk_body(fi, list(sub_body), held)

    def _scan_expr(self, fi: FuncInfo, expr: ast.AST, held: FrozenSet[str]) -> None:
        for call in _calls_in_expr(expr):
            op = self._table_op_of(fi, call)
            if op is not None:
                self._ops.append(TableOp(op[0], op[1], call, held, fi))
            for target in self.program.resolve_calls(fi, call, fi._types, fi._locks):
                if target is not fi:
                    self._call_edges.setdefault(target.key, []).append(
                        (fi.key, held)
                    )

    # ------------------------------------------------------------------
    # DF013 — FSM transition legality
    # ------------------------------------------------------------------

    def _collect_enum_members(self) -> None:
        for key, m in self.machines.items():
            if m.get("kind") != "enum":
                continue
            mi = self.program.modules.get(m.get("file", ""))
            if mi is None:
                continue
            members: Dict[str, str] = {}
            for node in ast.walk(mi.module.tree):
                if not (isinstance(node, ast.ClassDef) and node.name == m["enum"]):
                    continue
                for stmt in node.body:
                    if (
                        isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                        and isinstance(stmt.value, ast.Constant)
                        and isinstance(stmt.value.value, str)
                    ):
                        members[stmt.targets[0].id] = stmt.value.value
            self._enums[m["enum"]] = (key, members)

    def _check_df013(self) -> None:
        for key, m in self.machines.items():
            if m.get("kind") == "fsm":
                self._check_fsm_literals(key, m)
            else:
                self._check_enum_literals(key, m)
        self._check_event_sites()
        self._check_mirror_writes()
        self._check_enum_writes()
        self._check_gateway_calls()

    # -- declared-graph ↔ code staleness --------------------------------

    def _check_fsm_literals(self, key: str, m: dict) -> None:
        """The EventDesc tuple in the defining module must match the
        registry edge-for-edge (mini-evaluation of the module's simple
        string/tuple constants)."""
        mi = self.program.modules.get(m.get("file", ""))
        if mi is None:
            return
        env: Dict[str, object] = {}
        tree = mi.module.tree

        def ev(node: ast.AST):
            if isinstance(node, ast.Constant):
                return node.value
            if isinstance(node, ast.Name):
                return env.get(node.id)
            if isinstance(node, ast.Tuple):
                parts = [ev(e) for e in node.elts]
                return None if any(p is None for p in parts) else tuple(parts)
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
                left, right = ev(node.left), ev(node.right)
                if isinstance(left, tuple) and isinstance(right, tuple):
                    return left + right
            return None

        events_node = None
        for stmt in tree.body:
            if (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
            ):
                name = stmt.targets[0].id
                if name == m.get("events_var"):
                    events_node = stmt.value
                else:
                    val = ev(stmt.value)
                    if val is not None:
                        env[name] = val
        if events_node is None or not isinstance(events_node, ast.Tuple):
            self._emit(
                RULE_FSM, mi, tree,
                f"machine {key!r}: declared events_var "
                f"{m.get('events_var')!r} not found in {mi.relpath} — "
                "registry and code drifted",
            )
            return
        code_events: Dict[str, Set[Tuple[str, str]]] = {}
        for elt in events_node.elts:
            if not (isinstance(elt, ast.Call) and elt.args):
                continue
            args = list(elt.args)
            kwargs = {k.arg: k.value for k in elt.keywords}
            name = ev(args[0] if args else kwargs.get("name"))
            src = ev(args[1] if len(args) > 1 else kwargs.get("src"))
            dst = ev(args[2] if len(args) > 2 else kwargs.get("dst"))
            if not isinstance(name, str) or not isinstance(dst, str) or \
                    not isinstance(src, tuple):
                self._emit(
                    RULE_FSM, mi, elt,
                    f"machine {key!r}: EventDesc not statically evaluable "
                    "— keep sources as module-level string/tuple constants",
                )
                continue
            code_events.setdefault(name, set()).update(
                (s, dst) for s in src
            )
        declared = {
            name: {tuple(edge) for edge in edges}
            for name, edges in m.get("events", {}).items()
        }
        for name in sorted(set(code_events) | set(declared)):
            got = code_events.get(name)
            want = declared.get(name)
            if got is None:
                self._emit(
                    RULE_FSM, mi, events_node,
                    f"machine {key!r}: event {name!r} declared in "
                    "records/state_contracts.py but missing from "
                    f"{m.get('events_var')} — stale registry entry",
                )
            elif want is None:
                self._emit(
                    RULE_FSM, mi, events_node,
                    f"machine {key!r}: event {name!r} defined in code but "
                    "not declared in records/state_contracts.py — declare "
                    "the new edge(s) with a review",
                )
            elif got != want:
                drift = sorted(got ^ want)
                self._emit(
                    RULE_FSM, mi, events_node,
                    f"machine {key!r}: event {name!r} edges drifted from "
                    f"the registry (difference: {drift}) — update "
                    "records/state_contracts.py with a review",
                )
        states = set(m.get("states", []))
        code_states = {s for edges in code_events.values() for e in edges for s in e}
        code_states |= {m.get("initial", "")} - {""}
        for s in sorted(code_states - states):
            self._emit(
                RULE_FSM, mi, events_node,
                f"machine {key!r}: state {s!r} used by the code but not "
                "declared in records/state_contracts.py",
            )

    def _check_enum_literals(self, key: str, m: dict) -> None:
        mi = self.program.modules.get(m.get("file", ""))
        if mi is None:
            return
        hit = self._enums.get(m.get("enum", ""))
        if hit is None or not hit[1]:
            self._emit(
                RULE_FSM, mi, mi.module.tree,
                f"machine {key!r}: enum {m.get('enum')!r} not found in "
                f"{mi.relpath} — registry and code drifted",
            )
            return
        members = set(hit[1].values())
        declared = set(m.get("states", []))
        for s in sorted(members - declared):
            self._emit(
                RULE_FSM, mi, mi.module.tree,
                f"machine {key!r}: enum member value {s!r} not declared in "
                "records/state_contracts.py — declare the new state (and "
                "its edges) with a review",
            )
        for s in sorted(declared - members):
            self._emit(
                RULE_FSM, mi, mi.module.tree,
                f"machine {key!r}: declared state {s!r} has no enum member "
                f"in {m.get('enum')} — stale registry entry",
            )
        for src, dst in m.get("edges", []):
            if src not in declared or dst not in declared:
                self._emit(
                    RULE_FSM, mi, mi.module.tree,
                    f"machine {key!r}: edge {src!r}->{dst!r} names an "
                    "undeclared state",
                )

    # -- event / set_state sites ----------------------------------------

    def _fsm_machines(self) -> List[Tuple[str, dict]]:
        return [(k, m) for k, m in self.machines.items() if m.get("kind") == "fsm"]

    def _machine_of_receiver(self, fi: FuncInfo, recv: ast.AST) -> Optional[Tuple[str, dict]]:
        """Which FSM machine ``<recv>.event(...)`` belongs to, via the
        receiver's resolved class (``peer.fsm`` → Peer → "peer")."""
        if not (isinstance(recv, ast.Attribute)):
            return None
        base = recv.value
        ci = None
        if isinstance(base, ast.Name):
            if base.id in ("self", "cls"):
                ci = fi.cls
            else:
                ci = getattr(fi, "_types", {}).get(base.id)
        elif isinstance(base, ast.Attribute):
            resolved = self.program._resolve_attr_chain(
                fi, base, getattr(fi, "_types", {}), getattr(fi, "_locks", {})
            )
            if isinstance(resolved, ClassInfo):
                ci = resolved
        if ci is None:
            return None
        names = {c.name for c in ci.mro()}
        for key, m in self._fsm_machines():
            if m.get("class") in names and m.get("attr") == recv.attr:
                return key, m
        return None

    def _is_fsm_receiver(self, fi: FuncInfo, recv: ast.AST) -> bool:
        if isinstance(recv, ast.Attribute) and recv.attr == "fsm":
            return True
        if isinstance(recv, ast.Name) and recv.id == "fsm":
            return True
        return False

    def _check_event_sites(self) -> None:
        fsm_ms = self._fsm_machines()
        if not fsm_ms:
            return
        all_events: Set[str] = set()
        all_states: Set[str] = set()
        all_set_state_modules: Set[str] = set()
        for _k, m in fsm_ms:
            all_events |= set(m.get("events", {}))
            all_states |= set(m.get("states", []))
            all_set_state_modules |= set(m.get("set_state_modules", []))
        # Declared forwarders: project functions whose first param is the
        # FSM and whose second arg is the event literal (e.g. _try_event).
        forwarders: Set[str] = set()
        for fi in self.program.funcs.values():
            params = [a.arg for a in fi.node.args.args]
            if params[:1] == ["fsm"] and len(params) >= 2:
                forwarders.add(fi.key)
        for fi in self.program.funcs.values():
            if not hasattr(fi, "_types"):
                continue
            mi = fi.module
            if mi.relpath == "dragonfly2_tpu/utils/fsm.py":
                continue  # the FSM implementation itself
            for call in _calls_in(fi.node):
                func = call.func
                if isinstance(func, ast.Attribute) and func.attr == "event":
                    if not self._is_fsm_receiver(fi, func.value):
                        continue
                    if not (call.args and isinstance(call.args[0], ast.Constant)
                            and isinstance(call.args[0].value, str)):
                        continue
                    self._check_event_name(
                        fi, call, func.value, call.args[0].value,
                        all_events,
                    )
                elif isinstance(func, ast.Attribute) and func.attr == "set_state":
                    if not self._is_fsm_receiver(fi, func.value):
                        continue
                    hit = self._machine_of_receiver(fi, func.value)
                    modules = (
                        set(hit[1].get("set_state_modules", []))
                        if hit is not None else all_set_state_modules
                    )
                    states = (
                        set(hit[1].get("states", []))
                        if hit is not None else all_states
                    )
                    mname = hit[0] if hit is not None else "?"
                    if mi.relpath not in modules:
                        self._emit(
                            RULE_FSM, mi, call,
                            f"machine {mname!r}: fsm.set_state() outside the "
                            "declared mirror modules — transitions must go "
                            "through fsm.event() so illegal states stay "
                            "unrepresentable",
                        )
                    if (call.args and isinstance(call.args[0], ast.Constant)
                            and isinstance(call.args[0].value, str)
                            and call.args[0].value not in states):
                        self._emit(
                            RULE_FSM, mi, call,
                            f"machine {mname!r}: set_state targets "
                            f"undeclared state {call.args[0].value!r}",
                        )
                else:
                    # Forwarder: _try_event(peer.fsm, "Download").
                    targets = self.program.resolve_calls(
                        fi, call, fi._types, fi._locks
                    )
                    if not any(t.key in forwarders for t in targets):
                        continue
                    if len(call.args) < 2:
                        continue
                    recv, name_arg = call.args[0], call.args[1]
                    if not (isinstance(name_arg, ast.Constant)
                            and isinstance(name_arg.value, str)):
                        continue
                    if not self._is_fsm_receiver(fi, recv):
                        continue
                    self._check_event_name(
                        fi, call, recv, name_arg.value, all_events,
                    )

    def _check_event_name(
        self, fi: FuncInfo, call: ast.Call, recv: ast.AST, event: str,
        all_events: Set[str],
    ) -> None:
        hit = self._machine_of_receiver(fi, recv)
        if hit is not None:
            key, m = hit
            if event not in m.get("events", {}):
                self._emit(
                    RULE_FSM, fi.module, call,
                    f"machine {key!r}: event {event!r} is not a declared "
                    "transition — add the edge to "
                    "records/state_contracts.py (and the EventDesc) with "
                    "a review",
                )
        elif event not in all_events:
            self._emit(
                RULE_FSM, fi.module, call,
                f"event {event!r} is not declared by any state machine in "
                "records/state_contracts.py",
            )

    def _check_mirror_writes(self) -> None:
        mirrors: Dict[str, Tuple[str, Set[str]]] = {}
        for key, m in self._fsm_machines():
            for attr, writers in m.get("mirrors", {}).items():
                mirrors[attr] = (key, set(writers))
        if not mirrors:
            return
        for mi in self.program.modules.values():
            for node in ast.walk(mi.module.tree):
                if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                    continue
                target = node.targets[0]
                if not (isinstance(target, ast.Attribute)
                        and target.attr in mirrors):
                    continue
                key, writers = mirrors[target.attr]
                qual = mi.module.qualname(node)
                if qual not in writers:
                    self._emit(
                        RULE_FSM, mi, node,
                        f"machine {key!r}: mirror {target.attr!r} written "
                        f"outside its declared writers ({sorted(writers)}) "
                        "— mirrors are maintained ONLY by the enter_state "
                        "callback",
                    )

    # -- enum machines ---------------------------------------------------

    def _enum_member_of(self, value: ast.AST) -> Optional[Tuple[str, dict, str]]:
        """(machine key, machine, state value) when ``value`` references
        ``<Enum>.<MEMBER>`` (optionally ``.value``) of a declared enum."""
        name = dotted(value)
        if not name:
            return None
        parts = name.split(".")
        if parts and parts[-1] == "value":
            parts = parts[:-1]
        if len(parts) < 2:
            return None
        enum_name, member = parts[-2], parts[-1]
        hit = self._enums.get(enum_name)
        if hit is None:
            return None
        key, members = hit
        m = self.machines.get(key, {})
        return key, m, members.get(member, member.lower())

    def _check_enum_writes(self) -> None:
        for mi in self.program.modules.values():
            for node in ast.walk(mi.module.tree):
                if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                    continue
                target = node.targets[0]
                if not isinstance(target, ast.Attribute):
                    continue
                hit = self._enum_member_of(node.value)
                if hit is None:
                    continue
                key, m, state = hit
                if target.attr != m.get("state_attr"):
                    continue
                if mi.relpath not in m.get("owner_modules", []):
                    self._emit(
                        RULE_FSM, mi, node,
                        f"machine {key!r}: direct .{target.attr} = write "
                        f"outside the owning module "
                        f"({m.get('owner_modules')}) — go through the "
                        "registry gateway so the flip persists in one "
                        "transaction",
                    )
                elif state not in m.get("states", []):
                    self._emit(
                        RULE_FSM, mi, node,
                        f"machine {key!r}: write targets undeclared state "
                        f"{state!r}",
                    )

    def _check_gateway_calls(self) -> None:
        gateway_attrs: Set[str] = set()
        for _k, m in self.machines.items():
            gateway_attrs |= set(m.get("gateway_attrs", []))
        for fi in self.program.funcs.values():
            if not hasattr(fi, "_types"):
                continue
            mi = fi.module
            for call in _calls_in(fi.node):
                func = call.func
                if not isinstance(func, ast.Attribute):
                    continue
                if func.attr not in ("set_state", "activate", "deactivate"):
                    continue
                machine = None
                state: Optional[str] = None
                for arg in list(call.args) + [k.value for k in call.keywords]:
                    hit = self._enum_member_of(arg)
                    if hit is not None:
                        machine, state = (hit[0], hit[1]), hit[2]
                        break
                if machine is None:
                    if func.attr == "set_state":
                        continue  # no enum arg: a different set_state
                    owner = self._receiver_owner_machine(fi, func.value)
                    if owner is None:
                        continue
                    machine = owner
                    state = "active" if func.attr == "activate" else "inactive"
                key, m = machine
                if m.get("kind") != "enum":
                    continue
                mutators = m.get("mutators", {})
                allowed = mutators.get(mi.relpath)
                if allowed is None:
                    self._emit(
                        RULE_FSM, mi, call,
                        f"machine {key!r}: {func.attr}() from "
                        f"{mi.relpath}, which is not a declared mutator "
                        "module — state flips are restricted to the "
                        "registry/rollout/REST/gRPC gateways",
                    )
                elif state is not None and state not in allowed:
                    self._emit(
                        RULE_FSM, mi, call,
                        f"machine {key!r}: {mi.relpath} may not request "
                        f"state {state!r} (allowed: {sorted(allowed)})",
                    )
                elif state is not None and state not in m.get("states", []):
                    self._emit(
                        RULE_FSM, mi, call,
                        f"machine {key!r}: {func.attr}() targets "
                        f"undeclared state {state!r}",
                    )

    def _receiver_owner_machine(self, fi: FuncInfo, recv: ast.AST) -> Optional[Tuple[str, dict]]:
        """Machine for an activate()/deactivate() receiver: resolved
        registry type, or the declared gateway attribute name."""
        chain_attrs: Set[str] = set()
        cur = recv
        while isinstance(cur, ast.Attribute):
            chain_attrs.add(cur.attr)
            cur = cur.value
        if isinstance(cur, ast.Name):
            chain_attrs.add(cur.id)
        resolved = None
        if isinstance(recv, ast.Attribute):
            resolved = self.program._resolve_attr_chain(
                fi, recv, getattr(fi, "_types", {}), getattr(fi, "_locks", {})
            )
        elif isinstance(recv, ast.Name):
            if recv.id in ("self", "cls"):
                resolved = fi.cls
            else:
                resolved = getattr(fi, "_types", {}).get(recv.id)
        for key, m in self.machines.items():
            if m.get("kind") != "enum":
                continue
            if isinstance(resolved, ClassInfo):
                owner_file = m.get("file")
                if resolved.module.relpath == owner_file:
                    return key, m
            if chain_attrs & set(m.get("gateway_attrs", [])):
                return key, m
        return None

    # ------------------------------------------------------------------
    # DF014 — crash consistency
    # ------------------------------------------------------------------

    def _declared_lock_key(self, spec: List[str]) -> Optional[str]:
        relpath, cls_name, attr = spec
        mi = self.program.modules.get(relpath)
        if mi is None:
            return None
        ci = mi.classes.get(cls_name)
        if ci is None:
            return None
        lock = ci.attr_lock(attr)
        return lock.base().key if lock is not None else None

    def _held_ok(self, held: FrozenSet[str], lock_key: Optional[str],
                 lock_attr: str) -> bool:
        if lock_key is not None and lock_key in held:
            return True
        return f"tail::{lock_attr}" in held

    def _covered_by_callers(
        self, fkey: str, lock_key: Optional[str], lock_attr: str,
        memo: Dict[str, bool],
    ) -> bool:
        """True when every project call path into ``fkey`` holds the
        lock at the call site (transitively)."""
        if fkey in memo:
            return memo[fkey]
        memo[fkey] = True  # optimistic on cycles (greatest fixpoint)
        edges = self._call_edges.get(fkey)
        if not edges:
            memo[fkey] = False
            return False
        ok = all(
            self._held_ok(held, lock_key, lock_attr)
            or self._covered_by_callers(caller, lock_key, lock_attr, memo)
            for caller, held in edges
        )
        memo[fkey] = ok
        return ok

    def _check_df014(self) -> None:
        namespaces: Dict[str, dict] = self.persistence.get("namespaces", {})
        impl = set(self.persistence.get("implementation", []))
        # Declared dynamic-namespace writers (replication apply paths,
        # the one-transaction migration commit) must exist — a stale
        # entry would silently widen the witness's wildcard coverage.
        for relpath, quals in self.persistence.get("replicators", {}).items():
            mi = self.program.modules.get(relpath)
            for qual in quals:
                if self.program.funcs.get(f"{relpath}:{qual}") is None:
                    if mi is not None:
                        self._emit(
                            RULE_CRASH, mi, mi.module.tree,
                            f"declared replicator {qual!r} missing from "
                            f"{relpath} — stale records/state_contracts.py "
                            "entry (the crash witness's wildcard coverage "
                            "no longer matches the code)",
                        )
        # 1. every namespace in code is declared
        seen_ns: Set[str] = set()
        for ns, call, mi in self._binding_sites:
            seen_ns.add(ns)
            if ns not in namespaces:
                self._emit(
                    RULE_CRASH, mi, call,
                    f"namespace {ns!r} is not declared in "
                    "records/state_contracts.py — every durable table "
                    "needs an owner, lock, recovery loader and invariant",
                )
        for ns in sorted(set(namespaces) - seen_ns):
            mi = self.program.modules.get(namespaces[ns].get("owner", ""))
            if mi is not None:
                self._emit(
                    RULE_CRASH, mi, mi.module.tree,
                    f"namespace {ns!r} declared in "
                    "records/state_contracts.py but never bound by a "
                    ".table() call — stale registry entry",
                )
        ops_by_ns: Dict[str, List[TableOp]] = {}
        for op in self._ops:
            ops_by_ns.setdefault(op.ns, []).append(op)
        # 2-4. per-namespace rules
        for ns, spec in sorted(namespaces.items()):
            ops = ops_by_ns.get(ns, [])
            self._check_ns_locks(ns, spec, ops, impl)
            self._check_ns_multirow(ns, spec, ops)
            self._check_ns_loader(ns, spec, ops)
        self._check_write_order()
        self._check_foreign_keys(ops_by_ns)

    def _check_ns_locks(self, ns: str, spec: dict, ops: List[TableOp],
                        impl: Set[str]) -> None:
        lock_spec = spec.get("lock")
        if not lock_spec:
            return
        lock_key = self._declared_lock_key(list(lock_spec))
        lock_attr = lock_spec[2]
        unlocked_ok = set(spec.get("unlocked_ok", []))
        memo: Dict[str, bool] = {}
        writers = {op.fi.key for op in ops if op.method in _WRITE_METHODS}
        for op in ops:
            if op.fi.module.relpath in impl and op.fi.qual in unlocked_ok:
                continue
            if op.fi.qual in unlocked_ok or op.fi.name in unlocked_ok:
                continue
            is_write = op.method in _WRITE_METHODS
            if not is_write:
                # Reads are held to the lock bar only in read-modify-write
                # functions (get→mutate→put races); loaders are free.
                if op.fi.key not in writers:
                    continue
            if self._held_ok(op.held, lock_key, lock_attr):
                continue
            if self._covered_by_callers(op.fi.key, lock_key, lock_attr, memo):
                continue
            kind = "write" if is_write else "read (in a writing function)"
            self._emit(
                RULE_CRASH, op.fi.module, op.node,
                f"namespace {ns!r}: {op.method}() {kind} without the "
                f"owning lock {lock_spec[1]}.{lock_attr} — a concurrent "
                "get→mutate→put tears the row (declare the site "
                "unlocked_ok only for single-threaded boot paths)",
            )

    def _check_ns_multirow(self, ns: str, spec: dict, ops: List[TableOp]) -> None:
        for qual in spec.get("multi_row", []):
            fkey = f"{spec.get('owner')}:{qual}"
            fi = self.program.funcs.get(fkey)
            if fi is None:
                mi = self.program.modules.get(spec.get("owner", ""))
                if mi is not None:
                    self._emit(
                        RULE_CRASH, mi, mi.module.tree,
                        f"namespace {ns!r}: declared multi-row site "
                        f"{qual!r} missing from {spec.get('owner')} — "
                        "update records/state_contracts.py with the rename",
                    )
                continue
            mine = [op for op in ops if op.fi is fi]
            puts = [op for op in mine if op.method == "put"]
            put_manys = [op for op in mine if op.method == "put_many"]
            if puts:
                for op in puts:
                    self._emit(
                        RULE_CRASH, fi.module, op.node,
                        f"namespace {ns!r}: single put() inside declared "
                        f"multi-row site {qual} — a crash between rows "
                        "tears the invariant; batch every touched row "
                        "into ONE put_many()",
                    )
            elif not put_manys:
                self._emit(
                    RULE_CRASH, fi.module, fi.node,
                    f"namespace {ns!r}: declared multi-row site {qual} "
                    "performs no put_many() — the transactional flip is "
                    "gone",
                )

    def _check_ns_loader(self, ns: str, spec: dict, ops: List[TableOp]) -> None:
        owner = spec.get("owner", "")
        mi = self.program.modules.get(owner)
        loader_qual = spec.get("loader", "")
        fkey = f"{owner}:{loader_qual}"
        fi = self.program.funcs.get(fkey)
        if fi is None:
            if mi is not None:
                self._emit(
                    RULE_CRASH, mi, mi.module.tree,
                    f"namespace {ns!r}: declared recovery loader "
                    f"{loader_qual!r} missing from {owner} — an "
                    "unreloaded table is an orphan after restart",
                )
            return
        has_load = any(
            op.fi is fi and op.method == "load_all" for op in ops
        )
        if not has_load:
            self._emit(
                RULE_CRASH, fi.module, fi.node,
                f"namespace {ns!r}: recovery loader {loader_qual} no "
                "longer calls load_all() on the table — rows written "
                "before a restart are never read back",
            )
            return
        if not self._reachable_from_constructor(fi):
            self._emit(
                RULE_CRASH, fi.module, fi.node,
                f"namespace {ns!r}: recovery loader {loader_qual} is not "
                "reachable from any constructor — recovery never runs",
            )
        if not spec.get("invariant"):
            if mi is not None:
                self._emit(
                    RULE_CRASH, mi, mi.module.tree,
                    f"namespace {ns!r}: no declared recovery invariant — "
                    "the crash witness has nothing to assert after reload",
                )

    def _reachable_from_constructor(self, target: FuncInfo) -> bool:
        if target.name == "__init__":
            return True
        seen: Set[str] = set()
        stack = [
            fi for fi in self.program.funcs.values() if fi.name == "__init__"
        ]
        while stack:
            fi = stack.pop()
            if fi.key in seen:
                continue
            seen.add(fi.key)
            for _call, t in fi.calls:
                if t is target:
                    return True
                if t.key not in seen:
                    stack.append(t)
        return False

    def _trans_ns_writes(self) -> Dict[str, Set[str]]:
        """FuncInfo.key -> namespaces (transitively) written."""
        out: Dict[str, Set[str]] = {}
        for op in self._ops:
            if op.method in _WRITE_METHODS:
                out.setdefault(op.fi.key, set()).add(op.ns)
        changed = True
        while changed:
            changed = False
            for fi in self.program.funcs.values():
                mine = out.setdefault(fi.key, set())
                for _call, target in fi.calls:
                    extra = out.get(target.key, set()) - mine
                    if extra:
                        mine |= extra
                        changed = True
        return out

    def _check_write_order(self) -> None:
        pairs = [tuple(p) for p in self.persistence.get("write_order", [])]
        if not pairs:
            return
        trans = self._trans_ns_writes()
        for fi in self.program.funcs.values():
            events: List[Tuple[int, str, ast.AST]] = []
            for op in self._ops:
                if op.fi is fi and op.method in _WRITE_METHODS:
                    events.append((op.node.lineno, op.ns, op.node))
            for call, target in fi.calls:
                for ns in trans.get(target.key, ()):
                    events.append((call.lineno, ns, call))
            if not events:
                continue
            events.sort(key=lambda e: e[0])
            for first_ns, then_ns in pairs:
                first_a = next((e for e in events if e[1] == first_ns), None)
                first_b = next((e for e in events if e[1] == then_ns), None)
                if first_a is None or first_b is None:
                    continue
                if first_b[0] < first_a[0]:
                    self._emit(
                        RULE_CRASH, fi.module, first_b[2],
                        f"write-order violation: {then_ns!r} row committed "
                        f"before the {first_ns!r} row it references "
                        f"(declared order: {first_ns} before {then_ns}) — "
                        "a crash between them leaves a dangling reference",
                    )

    def _check_foreign_keys(self, ops_by_ns: Dict[str, List[TableOp]]) -> None:
        for fk in self.persistence.get("foreign_keys", []):
            parent, child = fk.get("parent"), fk.get("child")
            parent_spec = self.persistence.get("namespaces", {}).get(parent, {})
            owner = parent_spec.get("owner", "")
            prim_key = f"{owner}:{fk.get('primitive')}"
            prim = self.program.funcs.get(prim_key)
            cleanup_key = f"{fk.get('cleanup_file')}:{fk.get('cleanup')}"
            cleanup = self.program.funcs.get(cleanup_key)
            anchor_mi = self.program.modules.get(owner)
            if prim is None:
                if anchor_mi is not None:
                    self._emit(
                        RULE_CRASH, anchor_mi, anchor_mi.module.tree,
                        f"foreign key {parent}->{child}: delete primitive "
                        f"{fk.get('primitive')!r} missing from {owner}",
                    )
                continue
            if cleanup is None:
                if anchor_mi is not None:
                    self._emit(
                        RULE_CRASH, anchor_mi, anchor_mi.module.tree,
                        f"foreign key {parent}->{child}: declared cleanup "
                        f"{fk.get('cleanup')!r} missing from "
                        f"{fk.get('cleanup_file')} — a model delete "
                        "strands its rollout rows",
                    )
                continue
            # Cleanup must (transitively) delete child rows.
            if not self._reaches_child_delete(cleanup, child):
                self._emit(
                    RULE_CRASH, cleanup.module, cleanup.node,
                    f"foreign key {parent}->{child}: cleanup "
                    f"{fk.get('cleanup')} never deletes {child!r} rows — "
                    "the dangling-reference guard is vacuous",
                )
            # Every caller of the primitive must be the cleanup.
            for caller_key, _held in self._call_edges.get(prim.key, []):
                if caller_key == cleanup.key:
                    continue
                caller = self.program.funcs.get(caller_key)
                if caller is None:
                    continue
                self._emit(
                    RULE_CRASH, caller.module, caller.node,
                    f"foreign key {parent}->{child}: "
                    f"{fk.get('primitive')} called outside the declared "
                    f"cleanup {fk.get('cleanup')} — this delete path can "
                    f"strand {child!r} rows",
                )
            # No raw delete-site on the parent table outside the primitive.
            for op in ops_by_ns.get(parent, []):
                if op.method == "delete" and op.fi is not prim:
                    self._emit(
                        RULE_CRASH, op.fi.module, op.node,
                        f"foreign key {parent}->{child}: raw delete on "
                        f"{parent!r} outside {fk.get('primitive')} — all "
                        "deletes must flow through the guarded primitive",
                    )

    def _reaches_child_delete(self, fi: FuncInfo, child: str) -> bool:
        seen: Set[str] = set()
        stack = [fi]
        while stack:
            cur = stack.pop()
            if cur.key in seen:
                continue
            seen.add(cur.key)
            for op in self._ops:
                if op.fi is cur and op.ns == child and op.method in ("delete", "put", "put_many"):
                    return True
            for _call, t in cur.calls:
                stack.append(t)
        return False

    # ------------------------------------------------------------------
    # DF015 — RPC contract parity
    # ------------------------------------------------------------------

    def _literal_set_of(self, mi: ModuleInfo, container: str, name: str) -> Optional[Tuple[Set[str], ast.AST]]:
        """String literals of ``name = frozenset({...})`` /
        ``name = {...dict...}`` assigned at module level or inside class
        ``container`` (empty container name = module level)."""
        tree: ast.AST = mi.module.tree
        if container:
            found = None
            for node in ast.walk(mi.module.tree):
                if isinstance(node, ast.ClassDef) and node.name == container:
                    found = node
                    break
            if found is None:
                return None
            tree = found
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            target = node.targets[0]
            if not (isinstance(target, ast.Name) and target.id == name):
                continue
            value = node.value
            if isinstance(value, ast.Call) and value.args:
                value = value.args[0]
            out: Set[str] = set()
            if isinstance(value, ast.Dict):
                for k in value.keys:
                    if isinstance(k, ast.Constant) and isinstance(k.value, str):
                        out.add(k.value)
                return out, node
            if isinstance(value, (ast.Set, ast.Tuple, ast.List)):
                for e in value.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, str):
                        out.add(e.value)
                return out, node
        return None

    def _client_call_literals(
        self, mi: ModuleInfo, cls_name: str
    ) -> List[Tuple[str, ast.Call]]:
        ci = mi.classes.get(cls_name)
        if ci is None:
            return []
        out: List[Tuple[str, ast.Call]] = []
        for fn in ci.methods.values():
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if (
                    isinstance(func, ast.Attribute)
                    and func.attr == "_call"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    out.append((node.args[0].value, node))
        return out

    def _check_df015(self) -> None:
        for service, spec in sorted(self.rpc.items()):
            self._check_service_parity(service, spec)

    def _check_service_parity(self, service: str, spec: dict) -> None:
        server_file, server_cls, server_var = spec.get("server", ("", "", ""))
        grpc_file, grpc_var = spec.get("grpc", ("", ""))
        server_mi = self.program.modules.get(server_file)
        grpc_mi = self.program.modules.get(grpc_file)
        if server_mi is None:
            return  # sub-tree lint run without the rpc layer
        server_hit = self._literal_set_of(server_mi, server_cls, server_var)
        if server_hit is None:
            self._emit(
                RULE_RPC, server_mi, server_mi.module.tree,
                f"service {service!r}: dispatch set "
                f"{server_cls}.{server_var} not found — the wire has no "
                "method inventory to check against",
            )
            return
        server_methods, server_node = server_hit
        grpc_methods: Optional[Set[str]] = None
        grpc_node: Optional[ast.AST] = None
        if grpc_mi is not None:
            grpc_hit = self._literal_set_of(grpc_mi, "", grpc_var)
            if grpc_hit is None:
                self._emit(
                    RULE_RPC, grpc_mi, grpc_mi.module.tree,
                    f"service {service!r}: transport table {grpc_var} not "
                    f"found in {grpc_file}",
                )
            else:
                grpc_methods, grpc_node = grpc_hit
        # Adapter handler defs behind every METHODS entry.
        adapter = server_mi.classes.get(server_cls)
        for name in sorted(server_methods):
            if adapter is not None and adapter.find_method(name) is None:
                self._emit(
                    RULE_RPC, server_mi, server_node,
                    f"service {service!r}: METHODS entry {name!r} has no "
                    f"handler def on {server_cls} — dispatch would "
                    "AttributeError",
                )
        # gRPC table entries must be dispatchable.
        if grpc_methods is not None and grpc_node is not None:
            for name in sorted(grpc_methods - server_methods):
                self._emit(
                    RULE_RPC, grpc_mi, grpc_node,
                    f"service {service!r}: gRPC method {name!r} has no "
                    "inproc dispatch handler — the two transports drifted",
                )
        # Client literals against both transports + classification.
        idempotent = set(spec.get("idempotent", []))
        deduped: Dict[str, str] = dict(spec.get("deduped", {}))
        client_literals: Set[str] = set()
        for client_file, classes in spec.get("clients", {}).items():
            client_mi = self.program.modules.get(client_file)
            if client_mi is None:
                continue
            for cls_name in classes:
                for name, node in self._client_call_literals(client_mi, cls_name):
                    client_literals.add(name)
                    if name not in server_methods:
                        self._emit(
                            RULE_RPC, client_mi, node,
                            f"service {service!r}: client method {name!r} "
                            "has no registered server dispatch handler "
                            f"({server_cls}.{server_var}) — the call can "
                            "only 404",
                        )
                    if grpc_methods is not None and name not in grpc_methods:
                        self._emit(
                            RULE_RPC, client_mi, node,
                            f"service {service!r}: client method {name!r} "
                            f"missing from the gRPC transport table "
                            f"({grpc_var}) — the gRPC binding of this "
                            "client KeyErrors",
                        )
                    if name not in idempotent and name not in deduped:
                        self._emit(
                            RULE_RPC, client_mi, node,
                            f"service {service!r}: retried method {name!r} "
                            "is neither declared idempotent nor deduped in "
                            "records/state_contracts.py — a wire retry "
                            "may double-apply it; classify it (and add a "
                            "dedup seam if needed)",
                        )
        # Dedup seams must exist.
        seam_files = list(spec.get("seam_files", []))
        for method, seam in sorted(deduped.items()):
            if not self._seam_exists(seam, seam_files):
                self._emit(
                    RULE_RPC, server_mi, server_node,
                    f"service {service!r}: declared dedup seam {seam!r} "
                    f"for {method!r} not found in {seam_files} — the "
                    "idempotency claim is vacuous",
                )
        # Stale classification entries.
        known = client_literals | server_methods
        for name in sorted((idempotent | set(deduped)) - known):
            self._emit(
                RULE_RPC, server_mi, server_node,
                f"service {service!r}: classified method {name!r} is "
                "neither client-called nor server-dispatched — stale "
                "registry entry",
            )

    def _seam_exists(self, seam: str, seam_files: List[str]) -> bool:
        suffix = f":{seam}"
        for key in self.program.funcs:
            relpath = key.split(":", 1)[0]
            if seam_files and relpath not in seam_files:
                continue
            if key.endswith(suffix):
                return True
        return False

    # ------------------------------------------------------------------
    # Public surface (crash witness + FSM graph)
    # ------------------------------------------------------------------

    def persistence_site_index(self) -> Dict[Tuple[str, int], Tuple[str, str]]:
        """(relpath, lineno) covered by any static KVTable op →
        (namespace, method).  The runtime crash witness maps each
        observed write's caller frame through this; an unknown frame is
        a stale static inventory.  Declared replicator functions (the
        dynamic-namespace apply/migration paths) index their whole span
        as the wildcard namespace ``"*"`` — any declared namespace may
        be observed there."""
        out: Dict[Tuple[str, int], Tuple[str, str]] = {}
        for op in self._ops:
            start = op.node.lineno
            end = getattr(op.node, "end_lineno", start) or start
            for line in range(start, end + 1):
                out.setdefault(
                    (op.fi.module.relpath, line), (op.ns, op.method)
                )
        for relpath, quals in self.persistence.get("replicators", {}).items():
            for qual in quals:
                fi = self.program.funcs.get(f"{relpath}:{qual}")
                if fi is None:
                    continue
                start = fi.node.lineno
                end = getattr(fi.node, "end_lineno", start) or start
                for line in range(start, end + 1):
                    out.setdefault((relpath, line), ("*", "*"))
        return out

    def multi_row_sites(self) -> Dict[str, str]:
        """Declared multi-row transaction sites: "relpath:qual" →
        namespace.  The witness asserts these are only ever observed as
        put_many."""
        out: Dict[str, str] = {}
        for ns, spec in self.persistence.get("namespaces", {}).items():
            for qual in spec.get("multi_row", []):
                out[f"{spec.get('owner')}:{qual}"] = ns
        return out

    def namespace_invariants(self) -> Dict[str, str]:
        return {
            ns: spec.get("invariant", "")
            for ns, spec in self.persistence.get("namespaces", {}).items()
        }

    def fsm_graph_markdown(self) -> str:
        """The committed DESIGN.md §19 block: one table per declared
        machine, sorted, stable across runs."""
        lines: List[str] = []
        for key in sorted(self.machines):
            m = self.machines[key]
            lines.append(f"**machine `{key}`** — "
                         + ("event-driven FSM" if m.get("kind") == "fsm"
                            else f"enum `{m.get('enum')}`")
                         + f" ({m.get('file')})")
            lines.append("")
            if m.get("kind") == "fsm":
                lines.append("| event | transition |")
                lines.append("| --- | --- |")
                for name in sorted(m.get("events", {})):
                    for src, dst in sorted(map(tuple, m["events"][name])):
                        lines.append(f"| `{name}` | {src} → {dst} |")
            else:
                lines.append("| from | to |")
                lines.append("| --- | --- |")
                for src, dst in sorted(map(tuple, m.get("edges", []))):
                    lines.append(f"| {src} | {dst} |")
            lines.append("")
        return "\n".join(lines)

    def fsm_graph_dot(self) -> str:
        out: List[str] = []
        for key in sorted(self.machines):
            m = self.machines[key]
            out.append(f"digraph {key} {{")
            out.append('  rankdir="LR";')
            edges: List[Tuple[str, str, str]] = []
            if m.get("kind") == "fsm":
                for name in sorted(m.get("events", {})):
                    for src, dst in sorted(map(tuple, m["events"][name])):
                        edges.append((src, dst, name))
            else:
                for src, dst in sorted(map(tuple, m.get("edges", []))):
                    edges.append((src, dst, ""))
            nodes = sorted({n for e in edges for n in (e[0], e[1])})
            for n in nodes:
                out.append(f'  "{n}";')
            for src, dst, label in edges:
                suffix = f' [label="{label}"]' if label else ""
                out.append(f'  "{src}" -> "{dst}"{suffix};')
            out.append("}")
        return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# Runner integration
# ---------------------------------------------------------------------------


def crash_witness_gaps(
    analysis: StateAnalysis,
    observed: Dict[Tuple[str, int], List[dict]],
) -> List[str]:
    """Cross-validate runtime KVTable writes (from
    ``dragonfly2_tpu.utils.dfcrash``) against the static persistence
    inventory.  ``observed`` maps write site (relpath, lineno) → list of
    {"namespace", "method", "rows"} records.

    Empty result == every runtime write is statically known, its
    namespace matches, and declared multi-row sites were only observed
    as one-transaction ``put_many`` calls.  A gap is a STALE INVENTORY
    (fix staterules' binding resolution or declare the namespace) or a
    TORN MULTI-ROW FLIP (the split-put mutation) — never a thing to
    silence in the test."""
    index = analysis.persistence_site_index()
    multi = analysis.multi_row_sites()
    multi_lines: Dict[Tuple[str, int], str] = {}
    for key, ns in multi.items():
        relpath, qual = key.split(":", 1)
        fi = analysis.program.funcs.get(key)
        if fi is None:
            continue
        start = fi.node.lineno
        end = getattr(fi.node, "end_lineno", start) or start
        for line in range(start, end + 1):
            multi_lines[(relpath, line)] = key
    gaps: List[str] = []
    for (relpath, lineno), records in sorted(observed.items()):
        known = index.get((relpath, lineno))
        if known is None:
            nss = sorted({r.get("namespace", "?") for r in records})
            gaps.append(
                f"KVTable write at {relpath}:{lineno} (namespaces {nss}) "
                "is unknown to the static persistence inventory — a "
                "binding the resolver missed or an undeclared table"
            )
            continue
        ns, _method = known
        declared_ns = set(analysis.persistence.get("namespaces", {}))
        for r in records:
            if ns == "*":
                # Replicator wildcard: any DECLARED namespace is fine;
                # an undeclared one is still a gap.
                if r.get("namespace") not in declared_ns:
                    gaps.append(
                        f"{relpath}:{lineno}: replicator wrote undeclared "
                        f"namespace {r.get('namespace')!r}"
                    )
            elif r.get("namespace") != ns:
                gaps.append(
                    f"{relpath}:{lineno}: observed namespace "
                    f"{r.get('namespace')!r} but the static inventory "
                    f"says {ns!r}"
                )
        site_key = multi_lines.get((relpath, lineno))
        if site_key is not None:
            for r in records:
                if r.get("method") != "put_many":
                    gaps.append(
                        f"declared multi-row site {site_key} observed "
                        f"issuing {r.get('method')}() — the transactional "
                        "flip has been split; a crash between rows tears "
                        f"the {multi[site_key]!r} invariant"
                    )
                    break
    return gaps


def state_findings(program: Program, root: Optional[Path] = None) -> List[Finding]:
    return StateAnalysis(program, root).findings()
