"""Whole-program replay-determinism analysis (DF018 / DF019).

The concurrency pass (``program.py``) guards locks, the trace pass
(``tracerules.py``) guards the XLA layer, the state pass
(``staterules.py``) guards persistence; this module guards the property
every autonomous decision stands on — **replay equals live off the
journal** (§23 burn-rate replay, §26 autopilot drift-0, the accounting
rebuild drill).  Both rule families key off ONE declared-once literal
registry, ``dragonfly2_tpu/records/determinism_contracts.py``, read
with ``ast.literal_eval`` (no import — dflint stays stdlib-only), and
are built on :class:`tools.dflint.program.Program`'s symbol table and
call graph.

**DF018 — ambient nondeterminism on a replay path.**  Every function
statically reachable from a declared replay root is *tainted*.  Inside
the taint closure the analyzer fails:

- wall-clock reads (``time.time``/``monotonic``/``perf_counter`` and
  their ``_ns`` twins, ``datetime.now``/``utcnow``/``today``);
- unseeded randomness: ambient ``random.*`` / ``numpy.random.*`` module
  calls, unseeded ``random.Random()`` / ``numpy.random.default_rng()``
  factories, ``random.SystemRandom`` / ``os.urandom`` / ``uuid.uuid1``/
  ``uuid4`` / ``secrets.*`` entropy;
- the randomized builtins ``hash()`` and ``id()`` (PYTHONHASHSEED /
  address-order leaks);
- set-iteration feeding ordered output (a ``for`` / comprehension
  iterating a set display, set comprehension, or ``set()``/
  ``frozenset()`` call directly — ``sorted(...)`` around it is the
  canonical fix and is naturally clean).

Nondeterminism enters a replay path ONLY through a declared **injection
seam** — a declared parameter (clock params like ``now``, seeded-RNG
factories, ``run_id`` identity) on a declared function.  The live edge
samples the ambient source *outside* the closure and passes the value
through the seam; replay passes journal timestamps through the same
door.  Declared-but-unresolvable roots/seams/sinks fail by name (a
stale contract is a finding, not silent rot).  Declared observability
*sinks* (the flight recorder, gauge/counter writes, the chaos seam)
stop taint propagation: their values never flow back into decision
output.

**DF019 — canonical serialization on artifact paths.**  Every declared
journal/replay artifact writer (DFMJ1 metric frames, DFTL1 trace
frames, DFC1 columnar headers, the assemble/bench JSON reports) and
every function in the DF018 taint closure must pin
``sort_keys=True`` on each ``json.dumps``; declared frame-payload
builders must build their payload dict from exactly the declared
bounded key set (drift fails in BOTH directions).

The static inventory is cross-validated at runtime by the determinism
witness (``dragonfly2_tpu/utils/dfdet.py`` +
``tests/test_zz_detwitness.py``): ambient sources are patched with
call-site recorders armed while a declared replay root is on the
stack.  Every runtime observation must map to a static DF018 site or a
declared sink span (:func:`det_witness_gaps`) — a resolver blind spot
is a tier-1 failure.  The same test re-runs every root twice over
identical journal bytes in subprocesses with different PYTHONHASHSEED;
decision output must be byte-identical.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, collect_files, dotted, load_module
from .program import (
    FuncInfo,
    ModuleInfo,
    Program,
    _calls_in,
    _walk_skipping_defs,
)

RULE_DET = "DF018"
TITLE_DET = "ambient nondeterminism on a replay path"
RULE_CANON = "DF019"
TITLE_CANON = "non-canonical serialization on a journal/replay artifact path"

DETERMINISM_CONTRACTS_RELPATH = (
    "dragonfly2_tpu/records/determinism_contracts.py"
)

# -- ambient-source classification tables -----------------------------------

# Canonical dotted names (import-resolved) that read the wall clock.
_WALL_CLOCK = {
    "time.time", "time.time_ns",
    "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "time.process_time", "time.process_time_ns",
    "time.clock_gettime", "time.clock_gettime_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
}

# Canonical names that are entropy sources no matter the arguments.
_ENTROPY = {
    "os.urandom",
    "uuid.uuid1", "uuid.uuid4",
    "secrets.token_bytes", "secrets.token_hex", "secrets.token_urlsafe",
    "secrets.randbits", "secrets.choice", "secrets.randbelow",
    "random.SystemRandom",
}

# RNG *factories*: deterministic iff called with an explicit seed.
_RNG_FACTORIES = {
    "random.Random",
    "numpy.random.default_rng",
    "numpy.random.RandomState",
    "numpy.random.Generator",
    "jax.random.PRNGKey", "jax.random.key",
}

# Module prefixes whose bare function calls hit the AMBIENT global RNG.
_AMBIENT_RNG_PREFIXES = ("random.", "numpy.random.")

# numpy.random attributes that are types/helpers, not ambient draws.
_RNG_NON_DRAWS = {
    "numpy.random.Generator", "numpy.random.BitGenerator",
    "numpy.random.SeedSequence", "numpy.random.Philox",
    "numpy.random.PCG64",
}

_HASHSEED_BUILTINS = {"hash", "id"}


class AmbientSite:
    """One statically-detected ambient-nondeterminism call site."""

    __slots__ = ("relpath", "line", "source", "root", "chain", "node", "fi")

    def __init__(self, relpath: str, line: int, source: str, root: str,
                 chain: str, node: ast.AST, fi: FuncInfo) -> None:
        self.relpath = relpath
        self.line = line
        self.source = source
        self.root = root
        self.chain = chain
        self.node = node
        self.fi = fi


class DetAnalysis:
    """DF018-DF019 over a linked :class:`Program`.

    The declared roots span ``dragonfly2_tpu/`` *and* ``tools/`` (the
    assemble CLIs are replay consumers); when the supplied program does
    not hold a declared file, the analysis transparently rebuilds an
    extended program over the union so tool-side roots resolve without
    widening the caller's program (and its DF008/DF009 scope).
    """

    def __init__(self, program: Program, root: Optional[Path] = None) -> None:
        self.root = root
        self._findings: List[Finding] = []
        self.contracts = self._load_contracts(program)
        self.program = self._extend_program(program)
        self.roots: Dict[str, FuncInfo] = {}
        # FuncInfo.key -> (root name, human call chain)
        self.closure: Dict[str, Tuple[str, str]] = {}
        self.ambient_sites: List[AmbientSite] = []
        self._sink_prefixes: List[Tuple[str, str]] = []
        if self.contracts:
            self._sink_prefixes = self._parse_sinks()
            self._resolve_roots()
            self._build_closure()
            self._check_df018()
            self._check_seams()
            self._check_df019()
        self._findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    # ------------------------------------------------------------------
    # Shared plumbing
    # ------------------------------------------------------------------

    def findings(self) -> List[Finding]:
        return list(self._findings)

    def _emit(self, rule: str, mi: ModuleInfo, node: ast.AST, message: str) -> None:
        module = mi.module
        line = getattr(node, "lineno", 1)
        if module.suppressed(rule, line):
            return
        self._findings.append(
            Finding(
                rule=rule,
                path=mi.relpath,
                line=line,
                col=getattr(node, "col_offset", 0) + 1,
                message=message,
                qual=module.qualname(node),
            )
        )

    def _load_contracts(self, program: Program) -> dict:
        mi = program.modules.get(DETERMINISM_CONTRACTS_RELPATH)
        tree = None
        if mi is not None:
            tree = mi.module.tree
        elif self.root is not None and any(
            rp.startswith(("dragonfly2_tpu/", "tools/"))
            for rp in program.modules
        ):
            # Fall back to the on-disk registry only when the analyzed
            # program is actually part of the project tree — an
            # out-of-tree run (absolute relpaths) gets no det contracts,
            # otherwise every declared root would report as stale.
            path = self.root / DETERMINISM_CONTRACTS_RELPATH
            if path.exists():
                tree = ast.parse(path.read_text(encoding="utf-8"))
        if tree is None:
            return {}
        for stmt in tree.body:
            if (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id == "DETERMINISM_CONTRACTS"
            ):
                try:
                    return ast.literal_eval(stmt.value)
                except ValueError:
                    if mi is not None:
                        self._emit(
                            RULE_DET, mi, stmt,
                            "DETERMINISM_CONTRACTS must stay a pure literal "
                            "(ast.literal_eval failed — dflint reads it "
                            "without importing)",
                        )
                    return {}
        return {}

    def _declared_files(self) -> Set[str]:
        files: Set[str] = set()
        for spec in self.contracts.get("replay_roots", {}).values():
            files.add(str(spec.get("file", "")))
        for spec in self.contracts.get("serialization", {}).values():
            files.add(str(spec.get("file", "")))
        files.discard("")
        return files

    def _extend_program(self, program: Program) -> Program:
        """Rebuild with the declared tool-side files added when absent.
        No-op (same object) when every declared file is already loaded."""
        missing = [
            f for f in sorted(self._declared_files())
            if f not in program.modules
        ]
        if not missing or self.root is None:
            return program
        modules = [mi.module for mi in program.modules.values()]
        have = {m.relpath for m in modules}
        for relpath in missing:
            path = self.root / relpath
            if not path.exists():
                continue  # staleness finding fires in _resolve_roots
            for loaded in collect_files([path], self.root):
                try:
                    module = load_module(loaded, self.root)
                except (SyntaxError, UnicodeDecodeError):
                    continue
                if module.relpath not in have:
                    have.add(module.relpath)
                    modules.append(module)
        return Program(modules)

    def _contracts_mi(self) -> Optional[ModuleInfo]:
        return self.program.modules.get(DETERMINISM_CONTRACTS_RELPATH)

    def _emit_contract(self, rule: str, message: str) -> None:
        """A staleness finding anchored on the registry itself."""
        mi = self._contracts_mi()
        if mi is None:
            # Registry outside the analyzed tree: surface on the first
            # analyzed module so the finding is not silently dropped.
            for mi in self.program.modules.values():
                break
            else:
                return
        self._emit(rule, mi, mi.module.tree, message)

    # ------------------------------------------------------------------
    # Roots, sinks, taint closure
    # ------------------------------------------------------------------

    def _parse_sinks(self) -> List[Tuple[str, str]]:
        out: List[Tuple[str, str]] = []
        for entry in self.contracts.get("sinks", []):
            if ":" not in str(entry):
                self._emit_contract(
                    RULE_DET,
                    f"declared sink {entry!r} must be 'relpath:qual' or "
                    "'relpath:*'",
                )
                continue
            relpath, qual = str(entry).rsplit(":", 1)
            if relpath not in self.program.modules:
                self._emit_contract(
                    RULE_DET,
                    f"declared sink module {relpath!r} is not in the "
                    "analyzed tree — stale contract",
                )
                continue
            if qual != "*" and (
                f"{relpath}:{qual}" not in self.program.funcs
            ):
                self._emit_contract(
                    RULE_DET,
                    f"declared sink {relpath}:{qual} does not resolve to a "
                    "function — stale contract",
                )
                continue
            out.append((relpath, qual))
        return out

    def _is_sink(self, key: str) -> bool:
        relpath, _, qual = key.partition(":")
        for s_rel, s_qual in self._sink_prefixes:
            if relpath != s_rel:
                continue
            if s_qual == "*" or qual == s_qual or qual.startswith(s_qual + "."):
                return True
        return False

    def _resolve_roots(self) -> None:
        for name in sorted(self.contracts.get("replay_roots", {})):
            spec = self.contracts["replay_roots"][name]
            key = f"{spec.get('file')}:{spec.get('qual')}"
            fi = self.program.funcs.get(key)
            if fi is None:
                self._emit_contract(
                    RULE_DET,
                    f"declared replay root {name!r} ({key}) does not "
                    "resolve to a project function — stale contract",
                )
                continue
            self.roots[name] = fi

    def _build_closure(self) -> None:
        for name in sorted(self.roots):
            fi = self.roots[name]
            if fi.key not in self.closure:
                self.closure[fi.key] = (name, fi.qual)
            stack = [fi]
            while stack:
                cur = stack.pop()
                root, chain = self.closure[cur.key]
                if root != name:
                    continue  # claimed by an earlier root; already walked
                for _call, target in cur.calls:
                    if target.key in self.closure:
                        continue
                    if self._is_sink(target.key):
                        continue
                    self.closure[target.key] = (
                        name, f"{chain} -> {target.qual}"
                    )
                    stack.append(target)

    # ------------------------------------------------------------------
    # DF018: ambient-source scan over the closure
    # ------------------------------------------------------------------

    def _canonical_callee(self, mi: ModuleInfo, call: ast.Call) -> Optional[str]:
        """Import-resolved dotted name of the callee:
        ``time.time()`` / ``from time import time; time()`` both map to
        ``"time.time"``; ``np.random.default_rng`` maps to
        ``"numpy.random.default_rng"``."""
        name = dotted(call.func)
        if not name:
            return None
        head, _, rest = name.partition(".")
        imp = mi.imports.get(head)
        if imp is None:
            return name
        base, attr = imp
        parts = [base]
        if attr:
            parts.append(attr)
        if rest:
            parts.append(rest)
        return ".".join(parts)

    @staticmethod
    def _seeded(call: ast.Call) -> bool:
        """An RNG factory call is deterministic iff it receives an
        explicit non-None seed (positionally or by keyword)."""
        for arg in call.args:
            if not (isinstance(arg, ast.Constant) and arg.value is None):
                return True
        for kw in call.keywords:
            if kw.arg in ("seed", "x") and not (
                isinstance(kw.value, ast.Constant) and kw.value.value is None
            ):
                return True
        return False

    def _classify_ambient(
        self, fi: FuncInfo, call: ast.Call
    ) -> Optional[Tuple[str, str]]:
        """(canonical source, human description) when ``call`` reads an
        ambient nondeterminism source, else None."""
        mi = fi.module
        canon = self._canonical_callee(mi, call)
        if canon is None:
            return None
        if canon in _WALL_CLOCK:
            return canon, f"wall-clock read {canon}()"
        if canon in _ENTROPY:
            return canon, f"entropy source {canon}()"
        if canon in _RNG_FACTORIES:
            if self._seeded(call):
                return None
            return canon, f"unseeded RNG factory {canon}()"
        for prefix in _AMBIENT_RNG_PREFIXES:
            if canon.startswith(prefix) and canon not in _RNG_NON_DRAWS:
                return canon, (
                    f"{canon}() draws from the ambient global RNG "
                    "(seed a Generator through a declared seam instead)"
                )
        if (
            canon in _HASHSEED_BUILTINS
            and isinstance(call.func, ast.Name)
            and canon not in mi.functions
            and canon not in mi.imports
            and canon not in mi.aliases
        ):
            return f"builtins.{canon}", (
                f"builtin {canon}() is randomized per process "
                "(PYTHONHASHSEED / address order)"
            )
        return None

    def _is_set_expr(self, mi: ModuleInfo, expr: ast.AST) -> bool:
        if isinstance(expr, (ast.Set, ast.SetComp)):
            return True
        if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name):
            name = expr.func.id
            if name in ("set", "frozenset") and (
                name not in mi.functions
                and name not in mi.imports
                and name not in mi.aliases
            ):
                return True
        return False

    def _scan_function(self, fi: FuncInfo, root: str, chain: str) -> None:
        mi = fi.module
        for call in _calls_in(fi.node):
            hit = self._classify_ambient(fi, call)
            if hit is None:
                continue
            source, desc = hit
            site = AmbientSite(
                mi.relpath, getattr(call, "lineno", 1), source,
                root, chain, call, fi,
            )
            self.ambient_sites.append(site)
            self._emit(
                RULE_DET, mi, call,
                f"{desc} on the replay path of root {root!r} "
                f"(chain: {chain}) — thread the value through a declared "
                "injection seam (records/determinism_contracts.py)",
            )
        for node in _walk_skipping_defs(fi.node):
            iters: List[ast.AST] = []
            if isinstance(node, ast.For):
                iters.append(node.iter)
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp)):
                iters.extend(gen.iter for gen in node.generators)
            for it in iters:
                if self._is_set_expr(mi, it):
                    self._emit(
                        RULE_DET, mi, it,
                        "set iteration feeds ordered output on the replay "
                        f"path of root {root!r} (chain: {chain}) — wrap in "
                        "sorted(...) to pin the order",
                    )

    def _check_df018(self) -> None:
        for key in sorted(self.closure):
            fi = self.program.funcs.get(key)
            if fi is None:
                continue
            root, chain = self.closure[key]
            self._scan_function(fi, root, chain)

    # ------------------------------------------------------------------
    # Injection-seam staleness (both directions)
    # ------------------------------------------------------------------

    @staticmethod
    def _param_names(node: ast.FunctionDef) -> Set[str]:
        args = node.args
        names = {a.arg for a in args.args}
        names.update(a.arg for a in args.posonlyargs)
        names.update(a.arg for a in args.kwonlyargs)
        if args.vararg:
            names.add(args.vararg.arg)
        if args.kwarg:
            names.add(args.kwarg.arg)
        return names

    def _class_field_names(self, relpath: str, qual: str) -> Optional[Set[str]]:
        """Annotated field names of class ``qual`` in ``relpath`` (the
        dataclass case — no explicit __init__ to hold the seam param)."""
        mi = self.program.modules.get(relpath)
        if mi is None:
            return None
        ci = mi.classes.get(qual)
        if ci is None:
            return None
        names: Set[str] = set()
        for stmt in ci.node.body:
            if isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                names.add(stmt.target.id)
        return names

    def _check_seams(self) -> None:
        for seam in self.contracts.get("injection_seams", []):
            relpath = str(seam.get("file", ""))
            qual = str(seam.get("qual", ""))
            params = [str(p) for p in seam.get("params", [])]
            key = f"{relpath}:{qual}"
            fi = self.program.funcs.get(key)
            if fi is not None:
                have = self._param_names(fi.node)
            else:
                have = self._class_field_names(relpath, qual)
            if have is None:
                self._emit_contract(
                    RULE_DET,
                    f"declared injection seam {key} does not resolve to a "
                    "function or class — stale contract",
                )
                continue
            for param in params:
                if param not in have:
                    self._emit_contract(
                        RULE_DET,
                        f"declared injection seam {key} has no parameter/"
                        f"field {param!r} — stale contract",
                    )

    # ------------------------------------------------------------------
    # DF019: canonical serialization
    # ------------------------------------------------------------------

    def _dumps_calls(self, fi: FuncInfo) -> List[ast.Call]:
        out = []
        for call in _calls_in(fi.node):
            if self._canonical_callee(fi.module, call) == "json.dumps":
                out.append(call)
        return out

    @staticmethod
    def _pins_sort_keys(call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg == "sort_keys":
                return (
                    isinstance(kw.value, ast.Constant)
                    and kw.value.value is True
                )
        return False

    def _payload_literal_keys(self, fi: FuncInfo) -> Optional[Set[str]]:
        """Constant keys of the payload dict a builder produces: a
        returned dict literal, or a dict literal passed straight into
        ``json.dumps``.  None when no statically-visible literal exists."""
        dicts: List[ast.Dict] = []
        for node in _walk_skipping_defs(fi.node):
            if isinstance(node, ast.Return) and isinstance(node.value, ast.Dict):
                dicts.append(node.value)
        for call in self._dumps_calls(fi):
            if call.args and isinstance(call.args[0], ast.Dict):
                dicts.append(call.args[0])
        if not dicts:
            return None
        keys: Set[str] = set()
        for d in dicts:
            for k in d.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.add(k.value)
                else:
                    return None  # computed key: not a bounded literal
        return keys

    def _check_df019(self) -> None:
        serialization = self.contracts.get("serialization", {})
        writer_keys: Set[str] = set()
        for name in sorted(serialization):
            spec = serialization[name]
            relpath = str(spec.get("file", ""))
            qual = str(spec.get("qual", ""))
            key = f"{relpath}:{qual}"
            writer_keys.add(key)
            fi = self.program.funcs.get(key)
            if fi is None:
                self._emit_contract(
                    RULE_CANON,
                    f"declared artifact writer {name!r} ({key}) does not "
                    "resolve to a project function — stale contract",
                )
                continue
            for call in self._dumps_calls(fi):
                if not self._pins_sort_keys(call):
                    self._emit(
                        RULE_CANON, fi.module, call,
                        f"json.dumps in declared artifact writer {name!r} "
                        "must pin sort_keys=True — replay byte-identity "
                        "depends on canonical key order",
                    )
            declared = spec.get("keys")
            builder_qual = spec.get("builder")
            if declared is None or builder_qual is None:
                continue
            b_fi = self.program.funcs.get(f"{relpath}:{builder_qual}")
            if b_fi is None:
                self._emit_contract(
                    RULE_CANON,
                    f"declared payload builder {relpath}:{builder_qual} "
                    f"for writer {name!r} does not resolve — stale contract",
                )
                continue
            built = self._payload_literal_keys(b_fi)
            if built is None:
                self._emit(
                    RULE_CANON, b_fi.module, b_fi.node,
                    f"payload builder {builder_qual} of writer {name!r} has "
                    "no statically-visible payload dict literal — the "
                    "declared bounded key set cannot be checked",
                )
                continue
            declared_set = {str(k) for k in declared}
            for extra in sorted(built - declared_set):
                self._emit(
                    RULE_CANON, b_fi.module, b_fi.node,
                    f"frame payload key {extra!r} built by {builder_qual} "
                    f"is not in writer {name!r}'s declared bounded key set "
                    "— declare it in records/determinism_contracts.py",
                )
            for missing in sorted(declared_set - built):
                self._emit_contract(
                    RULE_CANON,
                    f"writer {name!r} declares frame key {missing!r} that "
                    f"{builder_qual} no longer builds — stale contract",
                )
        # Sweep: any json.dumps inside the DF018 closure must be
        # canonical too (assemble/report helpers feeding artifacts).
        for key in sorted(self.closure):
            if key in writer_keys:
                continue
            fi = self.program.funcs.get(key)
            if fi is None:
                continue
            root, chain = self.closure[key]
            for call in self._dumps_calls(fi):
                if not self._pins_sort_keys(call):
                    self._emit(
                        RULE_CANON, fi.module, call,
                        "json.dumps on the replay path of root "
                        f"{root!r} (chain: {chain}) must pin "
                        "sort_keys=True",
                    )

    # ------------------------------------------------------------------
    # Public surface (determinism witness + DESIGN.md §27 inventory)
    # ------------------------------------------------------------------

    def replay_root_index(self) -> Dict[str, Tuple[str, str]]:
        """root name -> (relpath, qual) for every resolved root — the
        runtime witness wraps exactly these."""
        return {
            name: (fi.module.relpath, fi.qual)
            for name, fi in self.roots.items()
        }

    def ambient_site_index(self) -> Dict[Tuple[str, int], List[str]]:
        """(relpath, line) -> ambient source names statically known
        there (pragma-suppressed sites included — the witness maps
        observations against *knowledge*, not against open findings)."""
        out: Dict[Tuple[str, int], List[str]] = {}
        for site in self.ambient_sites:
            out.setdefault((site.relpath, site.line), []).append(site.source)
        return out

    def sink_spans(self) -> List[Tuple[str, int, int]]:
        """(relpath, first line, last line) per declared-sink function —
        plus (relpath, 0, 0) wildcards for whole-module sinks.  Runtime
        ambient reads observed inside one of these are excused."""
        out: List[Tuple[str, int, int]] = []
        for relpath, qual in self._sink_prefixes:
            if qual == "*":
                out.append((relpath, 0, 0))
                continue
            for key, fi in self.program.funcs.items():
                k_rel, _, k_qual = key.partition(":")
                if k_rel != relpath:
                    continue
                if k_qual == qual or k_qual.startswith(qual + "."):
                    start = fi.node.lineno
                    end = getattr(fi.node, "end_lineno", start) or start
                    out.append((relpath, start, end))
        return sorted(out)

    def taint_report(self) -> Dict[str, Tuple[str, str]]:
        """FuncInfo.key -> (root, chain) for the whole closure."""
        return dict(self.closure)

    def det_inventory_markdown(self) -> str:
        """The committed DESIGN.md §27 block: declared roots with their
        closure sizes, seams, and artifact writers.  Sorted, stable."""
        per_root: Dict[str, int] = {name: 0 for name in self.roots}
        for _key, (root, _chain) in self.closure.items():
            if root in per_root:
                per_root[root] += 1
        lines = [
            "| replay root | function | tainted functions |",
            "| --- | --- | --- |",
        ]
        for name in sorted(self.roots):
            fi = self.roots[name]
            lines.append(
                f"| `{name}` | `{fi.module.relpath}:{fi.qual}` | "
                f"{per_root.get(name, 0)} |"
            )
        lines += ["", "| injection seam | params | kind |", "| --- | --- | --- |"]
        for seam in sorted(
            self.contracts.get("injection_seams", []),
            key=lambda s: (str(s.get("file")), str(s.get("qual"))),
        ):
            lines.append(
                f"| `{seam.get('file')}:{seam.get('qual')}` | "
                f"`{', '.join(str(p) for p in seam.get('params', []))}` | "
                f"{seam.get('kind', '')} |"
            )
        lines += ["", "| artifact writer | format | bounded keys |",
                  "| --- | --- | --- |"]
        serialization = self.contracts.get("serialization", {})
        for name in sorted(serialization):
            spec = serialization[name]
            keys = spec.get("keys")
            lines.append(
                f"| `{spec.get('file')}:{spec.get('qual')}` | "
                f"{spec.get('format', '')} | "
                + (f"`{', '.join(str(k) for k in keys)}`" if keys else "—")
                + " |"
            )
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# Runner integration
# ---------------------------------------------------------------------------


def det_witness_gaps(
    analysis: DetAnalysis,
    observed: Sequence[dict],
) -> List[str]:
    """Cross-validate runtime ambient-source observations (from
    ``dragonfly2_tpu.utils.dfdet``) against the static taint report.
    ``observed`` entries carry ``relpath``, ``lineno``, ``source`` and
    the armed ``root`` name.

    Empty result == every ambient read that happened while a replay
    root was on the stack is either statically known at that site
    (a DF018 finding or a pragma-reviewed site) or sits inside a
    declared observability sink.  A gap is a RESOLVER BLIND SPOT (the
    static taint closure missed a call edge) or a STALE CONTRACT (a
    root the registry does not declare) — never a thing to silence in
    the test."""
    index = analysis.ambient_site_index()
    sink_modules = {rel for rel, s, e in analysis.sink_spans() if s == 0}
    sink_ranges: Dict[str, List[Tuple[int, int]]] = {}
    for rel, start, end in analysis.sink_spans():
        if start:
            sink_ranges.setdefault(rel, []).append((start, end))
    declared_roots = set(analysis.replay_root_index())
    gaps: List[str] = []
    for rec in sorted(
        observed,
        key=lambda r: (str(r.get("relpath")), int(r.get("lineno", 0))),
    ):
        relpath = str(rec.get("relpath", ""))
        lineno = int(rec.get("lineno", 0))
        source = str(rec.get("source", ""))
        root = str(rec.get("root", ""))
        if root and root not in declared_roots:
            gaps.append(
                f"runtime witness armed by root {root!r} that the "
                "determinism contracts no longer declare — stale contract"
            )
            continue
        if relpath in sink_modules:
            continue
        if any(
            start <= lineno <= end
            for start, end in sink_ranges.get(relpath, [])
        ):
            continue
        if (relpath, lineno) in index:
            continue
        gaps.append(
            f"ambient read {source} at {relpath}:{lineno} observed at "
            f"runtime under replay root {root!r} is unknown to the static "
            "taint report — a call edge the resolver missed or an "
            "undeclared path into the root"
        )
    return gaps


def det_findings(program: Program, root: Optional[Path] = None) -> List[Finding]:
    return DetAnalysis(program, root).findings()
