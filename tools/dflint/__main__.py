"""CLI: ``python -m tools.dflint <paths...>``.

Exit codes: 0 — no new findings (baseline-accepted ones are counted but
don't fail); 1 — new findings (the CI gate); 2 — usage/parse errors.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .baseline import DEFAULT_PATH, Baseline, render
from .checkers import CHECKERS
from .core import run_paths


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.dflint",
        description="AST-based project invariant checker (DF001-DF007)",
    )
    parser.add_argument("paths", nargs="*", default=["dragonfly2_tpu"],
                        help="files/directories to check (default: dragonfly2_tpu)")
    parser.add_argument("--baseline", default=str(DEFAULT_PATH),
                        help="baseline file (accepted pre-existing findings)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, accepted or not")
    parser.add_argument("--write-baseline", action="store_true",
                        help="accept ALL current findings into the baseline file")
    parser.add_argument("--select", default="",
                        help="comma-separated rules to run (e.g. DF001,DF004)")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="summary only, no per-finding lines")
    args = parser.parse_args(argv)

    if args.list_rules:
        for c in CHECKERS:
            print(f"{c.RULE}  {c.TITLE}")
        return 0

    checkers = None
    if args.select:
        wanted = {r.strip().upper() for r in args.select.split(",") if r.strip()}
        unknown = wanted - {c.RULE for c in CHECKERS}
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}", file=sys.stderr)
            return 2
        checkers = [c for c in CHECKERS if c.RULE in wanted]

    root = Path.cwd()
    result = run_paths([Path(p) for p in args.paths], root, checkers)
    for err in result.errors:
        print(f"error: {err}", file=sys.stderr)

    if args.write_baseline:
        Path(args.baseline).write_text(render(result.findings), encoding="utf-8")
        print(f"wrote {len(result.findings)} accepted finding(s) to {args.baseline}")
        return 0

    if args.no_baseline:
        new, accepted = list(result.findings), []
        stale = []
    else:
        baseline = Baseline.load(Path(args.baseline))
        new, accepted = baseline.split(result.findings)
        stale = baseline.stale_keys(result.findings)

    if not args.quiet:
        for f in new:
            print(f.render())
        for key in stale:
            print(f"note: stale baseline entry (violation fixed?): {key}")
    print(
        f"dflint: {len(new)} new finding(s), {len(accepted)} baseline-accepted, "
        f"{len(result.errors)} parse error(s)"
    )
    if result.errors:
        return 2
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
