"""Baseline (accepted pre-existing findings) for dflint.

``baseline.toml`` pins the findings that predate a rule or were reviewed
and accepted, keyed by ``RULE:relpath:qualname`` (see
``Finding.key()``) — stable across line-number churn, while any NEW
violation in the same file still fails the gate.  Each key carries an
integer budget: a file may hold at most that many findings with the key,
so adding a second violation next to an accepted one is caught too.

The file is real TOML, but the interpreter here is a deliberate subset
(Python 3.10 ships no ``tomllib`` and the container must not grow deps):
``[section]`` headers, ``key = int``, ``key = "str"`` and
``key = [ "str", ... ]`` arrays, ``#`` comments.  Keys with dots/colons
must be quoted — the writer below always quotes.
"""

from __future__ import annotations

import re
from collections import Counter
from pathlib import Path
from typing import Dict, Iterable, List, Tuple

from .core import Finding

DEFAULT_PATH = Path(__file__).with_name("baseline.toml")

_SECTION = re.compile(r"^\[([^\]]+)\]\s*$")
_KV = re.compile(r'^(?:"([^"]+)"|([A-Za-z0-9_.:-]+))\s*=\s*(.+)$')


def _parse_value(raw: str):
    raw = raw.strip()
    if raw.startswith("["):
        inner = raw.strip()[1:-1]
        return [v.strip().strip('"') for v in inner.split(",") if v.strip()]
    if raw.startswith('"'):
        return raw.strip('"')
    return int(raw)


def parse_toml_subset(text: str) -> Dict[str, dict]:
    data: Dict[str, dict] = {}
    section: Dict[str, object] = data.setdefault("", {})  # top level
    for i, line in enumerate(text.splitlines(), 1):
        # Strip a trailing comment: the first '#' preceded by an even
        # number of quotes is outside any string.
        cut = len(line)
        for j, ch in enumerate(line):
            if ch == "#" and line[:j].count('"') % 2 == 0:
                cut = j
                break
        stripped = line[:cut].strip()
        if not stripped:
            continue
        m = _SECTION.match(stripped)
        if m:
            section = data.setdefault(m.group(1), {})
            continue
        m = _KV.match(stripped)
        if not m:
            raise ValueError(f"baseline.toml:{i}: cannot parse {line!r}")
        key = m.group(1) or m.group(2)
        section[key] = _parse_value(m.group(3))
    return data


class Baseline:
    """Budgeted accepted-finding set: ``key -> max count``."""

    def __init__(self, budgets: Dict[str, int]) -> None:
        self.budgets = dict(budgets)

    @classmethod
    def load(cls, path: Path = DEFAULT_PATH) -> "Baseline":
        if not path.exists():
            return cls({})
        data = parse_toml_subset(path.read_text(encoding="utf-8"))
        budgets: Dict[str, int] = {}
        for key, value in data.get("accepted", {}).items():
            budgets[key] = int(value)
        return cls(budgets)

    def split(
        self, findings: Iterable[Finding]
    ) -> Tuple[List[Finding], List[Finding]]:
        """(new, accepted): per key, the first ``budget`` findings are
        accepted (source order), the overflow is new."""
        used: Counter = Counter()
        new: List[Finding] = []
        accepted: List[Finding] = []
        for f in findings:
            key = f.key()
            if used[key] < self.budgets.get(key, 0):
                used[key] += 1
                accepted.append(f)
            else:
                new.append(f)
        return new, accepted

    def stale_keys(self, findings: Iterable[Finding]) -> List[str]:
        """Baseline entries no finding matched — candidates for removal
        (the violation was fixed; keep the file honest)."""
        present = Counter(f.key() for f in findings)
        return sorted(k for k in self.budgets if not present.get(k))


def render(findings: Iterable[Finding]) -> str:
    """Serialize findings as a fresh baseline.toml body."""
    counts = Counter(f.key() for f in findings)
    lines = [
        "# dflint baseline — accepted pre-existing findings.",
        '# Key: "RULE:relpath:qualname" = <max findings with this key>.',
        "# Regenerate: python -m tools.dflint <paths> --write-baseline",
        "",
        "[accepted]",
    ]
    for key in sorted(counts):
        lines.append(f'"{key}" = {counts[key]}')
    return "\n".join(lines) + "\n"
