"""dflint — AST-based project invariant checker for dragonfly2_tpu.

Run: ``python -m tools.dflint dragonfly2_tpu/`` (exit 0 = no findings
beyond the checked-in baseline).  Tier-1 runs the same checks per file
via ``tests/test_lint.py``.

Rules:

- DF001 exception swallowing
- DF002 thread hygiene (daemon=/join, locked shared mutation)
- DF003 JAX trace purity
- DF004 fault-seam coverage (faultinject.fire adjacency)
- DF005 resource hygiene (open/socket lifetime)
- DF006 deadline propagation in rpc/
"""

from .baseline import Baseline
from .core import Finding, Module, load_module, run_checkers, run_paths

__all__ = [
    "Baseline",
    "Finding",
    "Module",
    "load_module",
    "run_checkers",
    "run_paths",
]
