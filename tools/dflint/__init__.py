"""dflint — AST-based project invariant checker for dragonfly2_tpu.

Run: ``python -m tools.dflint dragonfly2_tpu/`` (exit 0 = no findings
beyond the checked-in baseline).  Tier-1 runs the same checks per file
via ``tests/test_lint.py``, which also builds the whole-program analysis
once and attributes its findings back to files.

Per-file rules (``tools/dflint/checkers/``):

- DF001 exception swallowing
- DF002 thread hygiene (daemon=/join, locked shared mutation)
- DF003 JAX trace purity
- DF004 fault-seam coverage (faultinject.fire adjacency)
- DF005 resource hygiene (open/socket lifetime)
- DF006 deadline propagation in rpc/
- DF007 hot-path hygiene

Whole-program rules (``tools/dflint/program.py`` — project symbol
table, intra-project call graph, lock model; DESIGN.md §16):

- DF008 blocking-under-lock (transitively, no mutex across
  indefinitely-blocking operations)
- DF009 lock-order inversion (cycles in the global lock-ordering graph)

The static lock graph is runtime-validated by the dynamic lock witness
(``dragonfly2_tpu/utils/dflock.py`` + ``tests/test_zz_lockwitness.py``):
acquisition-order edges observed during the tier-1 run must all exist
statically, so resolver rot fails tests instead of hiding.
"""

from .baseline import Baseline
from .core import Finding, Module, load_module, run_checkers, run_paths
from .program import Program, witness_gaps

__all__ = [
    "Baseline",
    "Finding",
    "Module",
    "Program",
    "load_module",
    "run_checkers",
    "run_paths",
    "witness_gaps",
]
