"""Whole-program TPU trace-discipline analysis (DF010 / DF011 / DF012).

The concurrency pass (``program.py``) guards the threaded serving stack;
this module guards the JAX/XLA layer the ROADMAP's perf numbers live on.
A single silent retrace, host↔device sync, or float64 leak erases the
serving and trainer wins, and nothing before this PR watched the ~20
``jax.jit`` / ``pjit`` / ``pallas_call`` sites across trainer, ops,
parallel and scheduler.  Built on :class:`tools.dflint.program.Program`'s
symbol table and call graph:

**DF010 — retrace hazards.**  Jitted callables must be constructed once
and cached; per-call construction throws the compile cache away with the
object.  Flagged:

- ``jax.jit(f)(x)`` — construct-and-immediately-invoke inside a function
  (the compiled program is unreachable after the call returns);
- trace-wrapper construction inside a ``for``/``while`` body;
- trace-wrapper construction inside a ``# dflint: hotpath`` function or
  any function reachable from one (compilation on the serving path);
- a traced def capturing an array-valued module/closure variable — the
  array is constant-folded into EVERY compile instead of shipped as an
  operand (pass it as an argument);
- Python ``list``/``dict``/comprehension arguments at call sites of a
  known-jitted callable — shape varies with length, one compile per
  occupancy (go through the pad ladder, ``scheduler/microbatch.py``);
- a traced def branching (``if``/``while``/``range()``) on a parameter
  not declared in ``static_argnums``/``static_argnames`` (and not bound
  by ``functools.partial``): either a TracerBoolConversionError on real
  inputs or a retrace per Python value.

**DF011 — host-sync leaks in hot paths.**  Two scopes:

- functions *reachable from a traced body* through the project call
  graph (the traced def itself is DF003's beat): ``.item()`` /
  ``.tolist()``, ``np.asarray`` / ``np.array``, ``jax.device_get``,
  ``float()/int()/bool()`` on non-literals, ``.block_until_ready()`` —
  each forces the tracer to host or silently freezes a value at trace
  time;
- ``# dflint: hotpath`` functions (the DF007 serving inventory):
  ``.item()`` / ``.tolist()`` / ``jax.device_get`` /
  ``.block_until_ready()`` — a device sync on the announce path stalls
  every queued request behind one transfer.

**DF012 — columnar dtype/shape contracts.**  The registry
(``dragonfly2_tpu/records/contracts.py``, a pure literal this module
reads with ``ast.literal_eval`` — no import, stdlib-only) declares each
columnar surface once; producer/consumer seams are checked against it:
slot-column creation-site dtype pins, constructor/param defaults,
explicit non-contract float dtypes (float64 with x64 off is a silent
truncation under jit and a row-width lie on host), implicit-float64
constructors (``np.zeros(n)``), and float64 mentions inside any traced
def.  Findings name the contract key, so a widened column fails *by
column name*.

The static pass is cross-validated at runtime by the **compile witness**
(``dragonfly2_tpu/utils/dftrace.py`` + ``tests/test_zz_compilewitness.py``):
every ``jax.jit`` creation observed during the tier-1 run must map onto
this module's static jit-site index, and its per-creation compile count
must fit ``tools/dflint/compile_budget.toml`` (whose key set is
staleness-checked against the static index, like ``baseline.toml`` and
the §16 lock graph).  A static blind spot is a witness failure — a
resolver fix, never silent rot.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import Finding, dotted
from .program import FuncInfo, ModuleInfo, Program, _walk_skipping_defs

RULE_RETRACE = "DF010"
TITLE_RETRACE = "retrace hazard: per-call jit construction / non-static branch arg"
RULE_HOSTSYNC = "DF011"
TITLE_HOSTSYNC = "host-device sync leak in a hot path or trace-reachable function"
RULE_CONTRACT = "DF012"
TITLE_CONTRACT = "columnar dtype/shape contract violation"

CONTRACTS_RELPATH = "dragonfly2_tpu/records/contracts.py"

_JIT_CTORS = {"jit", "pjit"}
_TRACE_WRAPPERS = {"jit", "pjit", "shard_map", "pallas_call"}
_HOTPATH_MARK = re.compile(r"#\s*dflint:\s*hotpath\b")

_ARRAY_CTOR_LEAVES = {
    "zeros", "ones", "empty", "full", "asarray", "array", "arange",
    "linspace", "stack", "concatenate", "fromiter", "zeros_like",
    "ones_like", "full_like", "load", "frombuffer", "memmap",
}
_ARRAY_PREFIXES = {"np", "numpy", "jnp"}
# Constructors whose missing dtype defaults to float64 on numpy.
_F64_DEFAULT_CTORS = {"zeros", "ones", "empty", "full"}
_FLOAT_DTYPES = {"float16", "float32", "float64", "bfloat16", "half",
                 "double", "longdouble"}

_HOST_ESCAPES = {"item", "tolist"}
_HOST_ARRAY_CALLS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array"}
_SCALAR_CASTS = {"float", "int", "bool"}


def _leaf(name: Optional[str]) -> str:
    return name.split(".")[-1] if name else ""


def _is_trace_ctor(node: ast.AST, names: Iterable[str] = _TRACE_WRAPPERS) -> bool:
    """Is ``node`` an expression naming jax.jit / pjit / shard_map /
    pallas_call (or functools.partial over one)?"""
    name = dotted(node)
    if name and _leaf(name) in names:
        return True
    if isinstance(node, ast.Call):
        fname = dotted(node.func)
        if fname and _leaf(fname) == "partial" and node.args:
            return _is_trace_ctor(node.args[0], names)
    return False


def _partial_of(node: ast.AST) -> Optional[ast.Call]:
    if isinstance(node, ast.Call):
        fname = dotted(node.func)
        if fname and _leaf(fname) == "partial":
            return node
    return None


def _static_names_from_call(call: ast.Call, params: List[str]) -> Set[str]:
    """``static_argnames`` / ``static_argnums`` declared on a jit
    construction or decorator, mapped onto parameter names."""
    out: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                out.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                for elt in v.elts:
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                        out.add(elt.value)
        elif kw.arg == "static_argnums":
            v = kw.value
            nums: List[int] = []
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                nums = [v.value]
            elif isinstance(v, (ast.Tuple, ast.List)):
                nums = [
                    e.value for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, int)
                ]
            for n in nums:
                if 0 <= n < len(params):
                    out.add(params[n])
    return out


def _bound_kwargs(partial_call: Optional[ast.Call]) -> Set[str]:
    """Parameter names bound by ``functools.partial(f, hops=...)`` — no
    longer traced arguments at all."""
    if partial_call is None:
        return set()
    return {kw.arg for kw in partial_call.keywords if kw.arg}


def _param_names(fn: ast.FunctionDef) -> List[str]:
    a = fn.args
    return [p.arg for p in list(a.posonlyargs) + list(a.args)] + [
        p.arg for p in a.kwonlyargs
    ]


def _dtype_token(node: ast.AST) -> Optional[str]:
    """The dtype a call argument names: ``np.float64`` -> "float64",
    ``"float32"`` -> "float32", bare ``float`` -> "float64" (numpy
    semantics).  None when it isn't a recognizable dtype expression."""
    name = dotted(node)
    if name:
        leaf = _leaf(name)
        if leaf in _FLOAT_DTYPES or leaf in (
            "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32",
            "uint64", "intp", "bool_",
        ):
            return "float64" if leaf in ("double", "longdouble") else leaf
        if name == "float":
            return "float64"
        if name in ("int", "bool"):
            return name
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class TracedDef:
    """One function that runs under trace: a def wrapped by jit / pjit /
    shard_map / pallas_call (decorator or wrapping call), plus its
    statically-declared / partial-bound parameter names."""

    def __init__(self, fi: FuncInfo) -> None:
        self.fi = fi
        self.static: Set[str] = set()
        self.bound: Set[str] = set()
        self.wrap_sites: List[Tuple[str, int]] = []


class TraceAnalysis:
    """DF010-DF012 over a linked :class:`Program`."""

    def __init__(self, program: Program, root: Optional[Path] = None) -> None:
        self.program = program
        self.root = root
        self._findings: List[Finding] = []
        self.contracts = self._load_contracts()
        # traced defs + reachable closure, jitted-name tables, hotpaths
        self.traced: Dict[str, TracedDef] = {}           # FuncInfo.key -> TracedDef
        self._jitted_module_vars: Dict[str, Set[str]] = {}   # relpath -> names
        self._jitted_attrs: Dict[str, Set[str]] = {}         # relpath -> self attrs
        self._jit_sites: Dict[Tuple[str, int], str] = {}     # (relpath, line) -> key
        self._jit_site_keys: Set[str] = set()
        self._hotpath_funcs: Set[str] = set()            # FuncInfo.key
        self._collect_traced_defs()
        self._collect_jitted_names()
        self._collect_hotpaths()
        self._check_df010()
        self._check_df011()
        self._check_df012()
        self._findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    # ------------------------------------------------------------------
    # Shared plumbing
    # ------------------------------------------------------------------

    def findings(self) -> List[Finding]:
        return list(self._findings)

    def _emit(self, rule: str, mi: ModuleInfo, node: ast.AST, message: str) -> None:
        module = mi.module
        line = getattr(node, "lineno", 1)
        if module.suppressed(rule, line):
            return
        self._findings.append(
            Finding(
                rule=rule,
                path=mi.relpath,
                line=line,
                col=getattr(node, "col_offset", 0) + 1,
                message=message,
                qual=module.qualname(node),
            )
        )

    def _load_contracts(self) -> dict:
        mi = self.program.modules.get(CONTRACTS_RELPATH)
        tree = None
        if mi is not None:
            tree = mi.module.tree
        elif self.root is not None:
            path = self.root / CONTRACTS_RELPATH
            if path.exists():
                tree = ast.parse(path.read_text(encoding="utf-8"))
        if tree is None:
            return {}
        for stmt in tree.body:
            if (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id == "CONTRACTS"
            ):
                try:
                    return ast.literal_eval(stmt.value)
                except ValueError:
                    if mi is not None:
                        self._emit(
                            RULE_CONTRACT, mi, stmt,
                            "CONTRACTS must stay a pure literal "
                            "(ast.literal_eval failed — dflint reads it "
                            "without importing)",
                        )
                    return {}
        return {}

    # ------------------------------------------------------------------
    # Traced-def discovery (program-wide DF003 resolution + statics)
    # ------------------------------------------------------------------

    def _func_of_def(self, mi: ModuleInfo, fn: ast.AST) -> Optional[FuncInfo]:
        qual = mi.module.qualname(fn)
        return self.program.funcs.get(f"{mi.relpath}:{qual}")

    def _resolve_wrap_target(
        self, mi: ModuleInfo, enclosing: Optional[FuncInfo], arg: ast.AST
    ) -> Tuple[Optional[FuncInfo], Optional[ast.Call]]:
        """The FuncInfo a trace wrapper's first argument names, chasing
        ``partial(f, ...)``, local ``kernel = partial(f, ...)`` bindings,
        ``self._method``, bare names and imports.  Returns
        ``(target, partial_call)``."""
        partial_call = _partial_of(arg)
        if partial_call is not None and partial_call.args:
            target, _ = self._resolve_wrap_target(
                mi, enclosing, partial_call.args[0]
            )
            return target, partial_call
        if isinstance(arg, ast.Name):
            name = arg.id
            # Chase one local/module assignment: `kernel = partial(f, ...)`.
            assign = self._find_assignment(mi, enclosing, name)
            if assign is not None:
                inner_partial = _partial_of(assign)
                if inner_partial is not None and inner_partial.args:
                    target, _ = self._resolve_wrap_target(
                        mi, enclosing, inner_partial.args[0]
                    )
                    return target, inner_partial
            cur = enclosing
            while cur is not None:
                if name in cur.nested:
                    return cur.nested[name], None
                cur = self.program._parent_func(cur)
            if name in mi.functions:
                return mi.functions[name], None
            imp = mi.imports.get(name)
            if imp:
                return self.program._func_from_import(imp), None
            return None, None
        if isinstance(arg, ast.Attribute):
            # jax.jit(self._train_dispatch) / mod.fn
            base = dotted(arg.value)
            if base in ("self", "cls"):
                cls = enclosing.cls if enclosing is not None else None
                if cls is None:
                    # Module.qualname can find the class even without a
                    # FuncInfo (e.g. wrap at class body level) — skip.
                    return None, None
                hit = cls.find_method(arg.attr)
                if hit is not None:
                    return self.program._method_func(hit[0], hit[1]), None
                return None, None
            if base and base in mi.imports:
                target_mi = self.program._module_from_import(mi.imports[base])
                if target_mi is not None:
                    return target_mi.functions.get(arg.attr), None
        return None, None

    def _find_assignment(
        self, mi: ModuleInfo, enclosing: Optional[FuncInfo], name: str
    ) -> Optional[ast.AST]:
        scopes: List[ast.AST] = []
        cur = enclosing
        while cur is not None:
            scopes.append(cur.node)
            cur = self.program._parent_func(cur)
        scopes.append(mi.module.tree)
        for scope in scopes:
            for node in ast.walk(scope):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == name
                ):
                    return node.value
        return None

    def _collect_traced_defs(self) -> None:
        for mi in self.program.modules.values():
            tree = mi.module.tree
            # Decorated defs.
            for node in ast.walk(tree):
                if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                for dec in node.decorator_list:
                    if _is_trace_ctor(dec):
                        fi = self._func_of_def(mi, node)
                        if fi is None:
                            continue
                        td = self.traced.setdefault(fi.key, TracedDef(fi))
                        td.wrap_sites.append((mi.relpath, dec.lineno))
                        params = _param_names(node)
                        if isinstance(dec, ast.Call):
                            td.static |= _static_names_from_call(dec, params)
                            inner = _partial_of(dec)
                            if inner is not None:
                                td.static |= _static_names_from_call(inner, params)
                        # jit decorators are jit creations: index the
                        # decorator-through-signature line range so the
                        # runtime witness can map its creation frame.
                        if _is_trace_ctor(dec, _JIT_CTORS):
                            self._index_jit_site(
                                mi, dec.lineno,
                                (node.body[0].lineno if node.body else node.lineno),
                                mi.module.qualname(node),
                            )
                        break
            # Wrapping calls: jax.jit(f, ...) / pallas_call(kernel, ...).
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted(node.func)
                if not name or _leaf(name) not in _TRACE_WRAPPERS:
                    continue
                enclosing_fn = mi.module.enclosing_function(node)
                enclosing = (
                    self._func_of_def(mi, enclosing_fn)
                    if enclosing_fn is not None else None
                )
                if _leaf(name) in _JIT_CTORS:
                    self._index_jit_site(
                        mi, node.lineno,
                        getattr(node, "end_lineno", node.lineno),
                        mi.module.qualname(node),
                    )
                if not node.args:
                    continue
                target, partial_call = self._resolve_wrap_target(
                    mi, enclosing, node.args[0]
                )
                if target is None:
                    continue
                td = self.traced.setdefault(target.key, TracedDef(target))
                td.wrap_sites.append((mi.relpath, node.lineno))
                params = _param_names(target.node)
                td.static |= _static_names_from_call(node, params)
                td.bound |= _bound_kwargs(partial_call)

    def _index_jit_site(
        self, mi: ModuleInfo, start: int, end: int, qual: str
    ) -> None:
        key = f"{mi.relpath}:{qual}"
        self._jit_site_keys.add(key)
        for line in range(start, max(end, start) + 1):
            self._jit_sites.setdefault((mi.relpath, line), key)

    # -- public surface for the compile witness -------------------------

    def jit_site_index(self) -> Dict[Tuple[str, int], str]:
        """(relpath, lineno) covered by any static jax.jit/pjit
        construction → ``relpath:qual`` budget key.  The runtime compile
        witness maps each observed creation frame through this; an
        unknown frame is a resolver/static blind spot."""
        return dict(self._jit_sites)

    def jit_site_keys(self) -> Set[str]:
        """Every static jit-construction budget key — the compile
        budget's required key set (staleness contract)."""
        return set(self._jit_site_keys)

    # ------------------------------------------------------------------
    # Hot-path marks + jitted-name tables
    # ------------------------------------------------------------------

    def _collect_hotpaths(self) -> None:
        for mi in self.program.modules.values():
            marks = {
                i + 1
                for i, line in enumerate(mi.module.lines)
                if _HOTPATH_MARK.search(line)
            }
            if not marks:
                continue
            for node in ast.walk(mi.module.tree):
                if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                first_body = node.body[0].lineno if node.body else node.lineno
                if any(node.lineno - 1 <= m <= first_body for m in marks):
                    fi = self._func_of_def(mi, node)
                    if fi is not None:
                        self._hotpath_funcs.add(fi.key)

    def _collect_jitted_names(self) -> None:
        for mi in self.program.modules.values():
            mvars: Set[str] = set()
            attrs: Set[str] = set()
            for node in ast.walk(mi.module.tree):
                target = value = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    target, value = node.targets[0], node.value
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    target, value = node.target, node.value
                if value is None or not isinstance(value, ast.Call):
                    continue
                if not _is_trace_ctor(value.func, _JIT_CTORS):
                    continue
                if isinstance(target, ast.Name):
                    if mi.module.enclosing_function(node) is None:
                        mvars.add(target.id)
                elif (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                ):
                    attrs.add(target.attr)
            self._jitted_module_vars[mi.relpath] = mvars
            self._jitted_attrs[mi.relpath] = attrs

    # ------------------------------------------------------------------
    # DF010 — retrace hazards
    # ------------------------------------------------------------------

    def _hotpath_reachable(self) -> Set[str]:
        seen: Set[str] = set()
        stack = [
            self.program.funcs[k] for k in self._hotpath_funcs
            if k in self.program.funcs
        ]
        while stack:
            fi = stack.pop()
            if fi.key in seen:
                continue
            seen.add(fi.key)
            for _call, target in fi.calls:
                if target.key not in seen:
                    stack.append(target)
        return seen

    def _check_df010(self) -> None:
        hot = self._hotpath_reachable()
        for mi in self.program.modules.values():
            module = mi.module
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                # R1: jit(f)(x) — construct-and-invoke discards the cache.
                if isinstance(node.func, ast.Call) and _is_trace_ctor(
                    node.func.func, _JIT_CTORS
                ):
                    if module.enclosing_function(node) is not None:
                        self._emit(
                            RULE_RETRACE, mi, node,
                            "jit constructed and immediately invoked — the "
                            "compile cache dies with the call; construct "
                            "once (module scope / __init__) and reuse the "
                            "jitted callable",
                        )
                # R2/R3: trace-wrapper construction in a loop / hot path.
                if _is_trace_ctor(node.func, _TRACE_WRAPPERS):
                    wrapper = _leaf(dotted(node.func) or "")
                    if wrapper not in _TRACE_WRAPPERS:
                        continue
                    if self._inside_loop(module, node):
                        self._emit(
                            RULE_RETRACE, mi, node,
                            f"{wrapper} constructed inside a loop body — "
                            "one compile per iteration; hoist the "
                            "construction out of the loop",
                        )
                    fn = module.enclosing_function(node)
                    if fn is not None:
                        fi = self._func_of_def(mi, fn)
                        if fi is not None and fi.key in hot:
                            self._emit(
                                RULE_RETRACE, mi, node,
                                f"{wrapper} constructed on the serving hot "
                                "path (reachable from a '# dflint: hotpath' "
                                "function) — compilation stalls announces; "
                                "construct at load/refresh time",
                            )
                # R5: shape-varying Python containers into jitted callables.
                self._check_list_args(mi, node)
        # R4 + R6 run per traced def.
        for td in self.traced.values():
            self._check_closure_capture(td)
            self._check_nonstatic_branches(td)

    def _inside_loop(self, module, node: ast.AST) -> bool:
        fn = module.enclosing_function(node)
        cur = module.parent(node)
        while cur is not None and cur is not fn:
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                return True
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                return False
            cur = module.parent(cur)
        return False

    def _check_list_args(self, mi: ModuleInfo, call: ast.Call) -> None:
        name: Optional[str] = None
        func = call.func
        if isinstance(func, ast.Name):
            if func.id in self._jitted_module_vars.get(mi.relpath, ()):
                name = func.id
        elif (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"
            and func.attr in self._jitted_attrs.get(mi.relpath, ())
        ):
            name = func.attr
        if name is None:
            return
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            if isinstance(arg, (ast.List, ast.ListComp, ast.Dict, ast.Set,
                                ast.GeneratorExp)):
                self._emit(
                    RULE_RETRACE, mi, call,
                    f"Python container passed to jitted {name!r} — the "
                    "traced shape varies with length (one compile per "
                    "occupancy); convert to a fixed-shape array or pad "
                    "(scheduler/microbatch.py pad-ladder precedent)",
                )
                return

    def _check_closure_capture(self, td: TracedDef) -> None:
        fi = td.fi
        mi = fi.module
        fn = fi.node
        bound: Set[str] = set(_param_names(fn))
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)
            ):
                bound.add(node.id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node is not fn:
                    bound.add(node.name)
        reported: Set[str] = set()
        for node in ast.walk(fn):
            if not (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id not in bound
                and node.id not in reported
            ):
                continue
            origin = self._array_binding(fi, node.id)
            if origin is None:
                continue
            reported.add(node.id)
            self._emit(
                RULE_RETRACE, mi, node,
                f"traced {fn.name}() captures array {node.id!r} "
                f"({origin}) by closure — it is constant-folded into "
                "every compile; pass it as an argument so it ships as "
                "an operand",
            )

    def _array_binding(self, fi: FuncInfo, name: str) -> Optional[str]:
        """Where ``name`` (free in a traced def) binds to an
        array-constructor result: an enclosing function local or a
        module-level variable."""

        def is_array_ctor(value: ast.AST) -> bool:
            if not isinstance(value, ast.Call):
                return False
            callee = dotted(value.func)
            if not callee:
                return isinstance(value.func, ast.Attribute) and \
                    value.func.attr == "astype"
            parts = callee.split(".")
            return (
                parts[0] in _ARRAY_PREFIXES and parts[-1] in _ARRAY_CTOR_LEAVES
            ) or parts[-1] == "astype"

        cur = self.program._parent_func(fi)
        while cur is not None:
            for node in ast.walk(cur.node):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == name
                    and is_array_ctor(node.value)
                ):
                    return f"local of {cur.qual}"
            cur = self.program._parent_func(cur)
        for stmt in fi.module.module.tree.body:
            if (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id == name
                and is_array_ctor(stmt.value)
            ):
                return "module variable"
        return None

    def _check_nonstatic_branches(self, td: TracedDef) -> None:
        fi = td.fi
        params = set(_param_names(fi.node)) - td.static - td.bound
        params.discard("self")
        params.discard("cls")
        if not params:
            return
        # Params compared with `is None` anywhere are Python-level
        # optionals — their None-ness is fixed per trace, not traced.
        optional: Set[str] = set()
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
            ):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name) and sub.id in params:
                        optional.add(sub.id)
        suspects = params - optional
        if not suspects:
            return
        for node in ast.walk(fi.node):
            test = None
            if isinstance(node, (ast.If, ast.While, ast.IfExp)):
                test = node.test
            elif (
                isinstance(node, (ast.For, ast.AsyncFor))
                and isinstance(node.iter, ast.Call)
                and _leaf(dotted(node.iter.func) or "") == "range"
            ):
                test = node.iter
            if test is None:
                continue
            if isinstance(test, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
            ):
                continue
            for sub in ast.walk(test):
                if isinstance(sub, ast.Name) and sub.id in suspects:
                    self._emit(
                        RULE_RETRACE, fi.module, node,
                        f"traced {fi.node.name}() branches on arg "
                        f"{sub.id!r} which is not in static_argnums/"
                        "static_argnames — TracerBoolConversionError on "
                        "real inputs, or a silent retrace per Python "
                        "value; declare it static or rewrite with "
                        "jnp.where/lax.cond",
                    )
                    break

    # ------------------------------------------------------------------
    # DF011 — host-sync leaks
    # ------------------------------------------------------------------

    def _traced_closure(self) -> Dict[str, Tuple[str, ...]]:
        """FuncInfo.key -> call chain from a traced def, for every
        function reachable from a traced body (nested defs of a traced
        def trace too, so they seed the walk)."""
        out: Dict[str, Tuple[str, ...]] = {}
        stack: List[Tuple[FuncInfo, Tuple[str, ...]]] = []
        seeds: List[FuncInfo] = []
        for td in self.traced.values():
            seeds.append(td.fi)
            seeds.extend(self._all_nested(td.fi))
        for fi in seeds:
            out.setdefault(fi.key, (fi.qual,))
            stack.append((fi, (fi.qual,)))
        while stack:
            fi, chain = stack.pop()
            for _call, target in fi.calls:
                if target.key in out:
                    continue
                tchain = chain + (target.qual,)
                out[target.key] = tchain
                stack.append((target, tchain))
        return out

    def _all_nested(self, fi: FuncInfo) -> List[FuncInfo]:
        out: List[FuncInfo] = []
        stack = list(fi.nested.values())
        while stack:
            cur = stack.pop()
            out.append(cur)
            stack.extend(cur.nested.values())
        return out

    def _host_sync_op(self, call: ast.Call, *, hotpath: bool) -> Optional[str]:
        name = dotted(call.func) or ""
        leaf = call.func.attr if isinstance(call.func, ast.Attribute) else ""
        if leaf == "block_until_ready":
            return (
                ".block_until_ready() forces a device sync"
                if not hotpath else
                ".block_until_ready() stalls the serving path on a "
                "device sync"
            )
        if _leaf(name) == "device_get" or name == "jax.device_get":
            return "jax.device_get() copies device values to host"
        if leaf in _HOST_ESCAPES and not call.args:
            return (
                f".{leaf}() escapes the array to a Python value "
                "(host transfer + sync)"
            )
        if hotpath:
            return None
        if name in _HOST_ARRAY_CALLS:
            return f"{name}() forces the traced value to host memory"
        if (
            name in _SCALAR_CASTS
            and len(call.args) == 1
            and not isinstance(call.args[0], ast.Constant)
            and not call.keywords
        ):
            return (
                f"{name}() on a traced value is a concretization "
                "(ConcretizationTypeError / trace-frozen constant)"
            )
        return None

    def _check_df011(self) -> None:
        closure = self._traced_closure()
        traced_keys = {td.fi.key for td in self.traced.values()}
        for td in self.traced.values():
            traced_keys.update(n.key for n in self._all_nested(td.fi))
        for key, chain in closure.items():
            if key in traced_keys:
                continue  # directly-traced bodies are DF003's beat
            fi = self.program.funcs.get(key)
            if fi is None:
                continue
            self._scan_host_ops(fi, hotpath=False, chain=chain)
        for key in self._hotpath_funcs:
            fi = self.program.funcs.get(key)
            if fi is None:
                continue
            self._scan_host_ops(fi, hotpath=True, chain=(fi.qual,))

    def _scan_host_ops(
        self, fi: FuncInfo, *, hotpath: bool, chain: Tuple[str, ...]
    ) -> None:
        mi = fi.module
        seen_lines: Set[Tuple[int, str]] = set()
        # Nested defs are their own FuncInfos (scanned via the closure
        # walk when reachable), so skip their bodies here.
        for node in _walk_skipping_defs(fi.node):
            if not isinstance(node, ast.Call):
                continue
            msg = self._host_sync_op(node, hotpath=hotpath)
            if msg is None:
                continue
            dedupe = (node.lineno, msg)
            if dedupe in seen_lines:
                continue
            seen_lines.add(dedupe)
            where = (
                f"'# dflint: hotpath' function {fi.qual}"
                if hotpath
                else f"{fi.qual} (reachable from traced "
                     f"{' -> '.join(chain)})"
            )
            self._emit(
                RULE_HOSTSYNC, mi, node,
                f"{msg} — in {where}; keep host syncs out of hot paths "
                "(move to a build/export boundary or mark with "
                "'# dflint: disable=DF011' + justification)",
            )

    # ------------------------------------------------------------------
    # DF012 — columnar dtype contracts
    # ------------------------------------------------------------------

    def _check_df012(self) -> None:
        for key, spec in sorted(self.contracts.items()):
            relpath = spec.get("file")
            mi = self.program.modules.get(relpath) if relpath else None
            if relpath and mi is None:
                # The contract's module isn't in the analyzed path set
                # (e.g. a sub-tree lint run) — nothing to check against.
                continue
            if mi is not None:
                self._check_contract_attrs(key, spec, mi)
                self._check_contract_defaults(key, spec, mi)
                self._check_contract_functions(key, spec, mi)
        self._check_traced_float64()

    def _funcs_by_qual(self, mi: ModuleInfo) -> Dict[str, ast.AST]:
        out: Dict[str, ast.AST] = {}
        for node in ast.walk(mi.module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out[mi.module.qualname(node)] = node
        return out

    def _class_body(self, mi: ModuleInfo, cls_name: str) -> Optional[ast.ClassDef]:
        for node in ast.walk(mi.module.tree):
            if isinstance(node, ast.ClassDef) and node.name == cls_name:
                return node
        return None

    def _ctor_dtype(self, call: ast.Call) -> Tuple[Optional[str], bool]:
        """(dtype token, explicit?) of an array-constructor call.  The
        positional dtype slot per numpy signature: zeros/ones/empty
        (shape, dtype), full(shape, fill, dtype), asarray/array
        (obj, dtype), fromiter(it, dtype)."""
        for kw in call.keywords:
            if kw.arg == "dtype":
                return _dtype_token(kw.value), True
        callee = dotted(call.func) or ""
        leaf = _leaf(callee)
        pos = {
            "zeros": 1, "ones": 1, "empty": 1, "asarray": 1, "array": 1,
            "fromiter": 1, "full": 2, "frombuffer": 1, "arange": None,
        }.get(leaf)
        if pos is not None and len(call.args) > pos:
            tok = _dtype_token(call.args[pos])
            if tok is not None:
                return tok, True
        return None, False

    def _check_contract_attrs(self, key: str, spec: dict, mi: ModuleInfo) -> None:
        for attr_path, want in sorted(spec.get("attrs", {}).items()):
            cls_name, attr = attr_path.rsplit(".", 1)
            cls = self._class_body(mi, cls_name)
            if cls is None:
                self._emit(
                    RULE_CONTRACT, mi, mi.module.tree,
                    f"contract {key!r}: class {cls_name} missing from "
                    f"{mi.relpath} (registry: records/contracts.py)",
                )
                continue
            sites = []
            for node in ast.walk(cls):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Attribute)
                    and isinstance(node.targets[0].value, ast.Name)
                    and node.targets[0].value.id == "self"
                    and node.targets[0].attr == attr
                    and isinstance(node.value, ast.Call)
                ):
                    callee = dotted(node.value.func) or ""
                    if (
                        callee.split(".")[0] in _ARRAY_PREFIXES
                        and _leaf(callee) in _ARRAY_CTOR_LEAVES
                    ):
                        sites.append(node)
            if not sites:
                self._emit(
                    RULE_CONTRACT, mi, cls,
                    f"contract {key!r}: column {attr_path!r} has no "
                    f"array-constructor assignment in {cls_name} — the "
                    "slot column the registry pins is gone",
                )
                continue
            for node in sites:
                tok, explicit = self._ctor_dtype(node.value)
                if not explicit or tok != want:
                    got = tok if explicit else "implicit (float64)"
                    self._emit(
                        RULE_CONTRACT, mi, node,
                        f"contract {key!r}: column {attr_path!r} declared "
                        f"{want} but created as {got} — widen the registry "
                        "entry (reviewed) or fix the constructor",
                    )

    def _check_contract_defaults(self, key: str, spec: dict, mi: ModuleInfo) -> None:
        for path, want in sorted(spec.get("defaults", {}).items()):
            parts = path.split(".")
            found = False
            if len(parts) == 2:  # Class.field — dataclass/attr default
                cls = self._class_body(mi, parts[0])
                if cls is not None:
                    for stmt in cls.body:
                        if (
                            isinstance(stmt, ast.AnnAssign)
                            and isinstance(stmt.target, ast.Name)
                            and stmt.target.id == parts[1]
                            and isinstance(stmt.value, ast.Constant)
                        ):
                            found = True
                            if stmt.value.value != want:
                                self._emit(
                                    RULE_CONTRACT, mi, stmt,
                                    f"contract {key!r}: {path} defaults to "
                                    f"{stmt.value.value!r}, registry "
                                    f"declares {want!r}",
                                )
            elif len(parts) == 3:  # Class.fn.param default
                cls = self._class_body(mi, parts[0])
                fn = None
                if cls is not None:
                    for stmt in cls.body:
                        if (
                            isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
                            and stmt.name == parts[1]
                        ):
                            fn = stmt
                if fn is not None:
                    args = fn.args
                    names = [a.arg for a in args.args]
                    defaults = list(args.defaults)
                    pairs = list(zip(names[len(names) - len(defaults):], defaults))
                    pairs += [
                        (a.arg, d)
                        for a, d in zip(args.kwonlyargs, args.kw_defaults)
                        if d is not None
                    ]
                    for pname, default in pairs:
                        if pname == parts[2]:
                            found = True
                            if not (
                                isinstance(default, ast.Constant)
                                and default.value == want
                            ):
                                self._emit(
                                    RULE_CONTRACT, mi, default,
                                    f"contract {key!r}: {path} default "
                                    f"drifted from the declared {want!r}",
                                )
            if not found:
                self._emit(
                    RULE_CONTRACT, mi, mi.module.tree,
                    f"contract {key!r}: pinned default {path} not found "
                    f"in {mi.relpath} — registry and code drifted",
                )

    def _check_contract_functions(self, key: str, spec: dict, mi: ModuleInfo) -> None:
        wanted = spec.get("functions", [])
        if not wanted:
            return
        permitted = {spec.get("dtype", "float32")} | set(spec.get("allow", []))
        by_qual = self._funcs_by_qual(mi)
        for qual in wanted:
            fn = by_qual.get(qual)
            if fn is None:
                self._emit(
                    RULE_CONTRACT, mi, mi.module.tree,
                    f"contract {key!r}: producer/consumer {qual!r} missing "
                    f"from {mi.relpath} — update records/contracts.py with "
                    "the rename",
                )
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = dotted(node.func) or ""
                leaf = _leaf(callee)
                is_ctor = (
                    callee.split(".")[0] in _ARRAY_PREFIXES
                    and leaf in _ARRAY_CTOR_LEAVES
                )
                is_astype = (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype"
                )
                if not (is_ctor or is_astype):
                    continue
                tok, explicit = self._ctor_dtype(node)
                if is_astype and not explicit and node.args:
                    tok = _dtype_token(node.args[0])
                    explicit = tok is not None
                if explicit and tok in _FLOAT_DTYPES | {"float64"}:
                    if tok not in permitted:
                        self._emit(
                            RULE_CONTRACT, mi, node,
                            f"contract {key!r}: {qual} produces {tok} but "
                            f"the contract is "
                            f"{spec.get('dtype', 'float32')} (x64 is off — "
                            "float64 silently truncates under jit and "
                            "doubles host row width); allowed: "
                            f"{sorted(permitted)}",
                        )
                elif (
                    not explicit
                    and callee.split(".")[0] in ("np", "numpy")
                    and leaf in _F64_DEFAULT_CTORS
                ):
                    self._emit(
                        RULE_CONTRACT, mi, node,
                        f"contract {key!r}: {qual} calls {callee}() without "
                        "an explicit dtype — numpy defaults to float64; "
                        f"pass dtype=np.{spec.get('dtype', 'float32')}",
                    )

    def _check_traced_float64(self) -> None:
        """float64 anywhere inside a traced def: x64 is off, so the
        request silently truncates — the code lies about its dtype."""
        for td in self.traced.values():
            fi = td.fi
            for node in ast.walk(fi.node):
                tok = None
                if isinstance(node, ast.Attribute) and node.attr in (
                    "float64", "double",
                ):
                    base = dotted(node.value)
                    if base in ("np", "numpy", "jnp", "jax.numpy"):
                        tok = node.attr
                elif (
                    isinstance(node, ast.Constant)
                    and node.value == "float64"
                ):
                    tok = "float64"
                if tok is None:
                    continue
                self._emit(
                    RULE_CONTRACT, fi.module, node,
                    f"{tok} inside traced {fi.node.name}() — x64 is "
                    "disabled, the dtype silently truncates to float32 "
                    "under jit; say float32 (or enable x64 deliberately)",
                )


# ---------------------------------------------------------------------------
# Compile-budget file (tools/dflint/compile_budget.toml)
# ---------------------------------------------------------------------------

BUDGET_PATH = Path(__file__).with_name("compile_budget.toml")
DEFAULT_BUDGET = 4


def load_budget(path: Path = BUDGET_PATH) -> Dict[str, int]:
    from .baseline import parse_toml_subset

    if not path.exists():
        return {}
    data = parse_toml_subset(path.read_text(encoding="utf-8"))
    return {k: int(v) for k, v in data.get("budget", {}).items()}


def render_budget(keys: Iterable[str], existing: Dict[str, int]) -> str:
    lines = [
        "# dflint compile budget — max XLA compiles per jit construction",
        '# site "relpath:qual".  The underlying C++ pjit cache is shared per',
        "# WRAPPED FUNCTION: for bound methods / nested defs (fresh identity",
        "# per creation) the bound is effectively per creation; for",
        "# module-level functions wrapped repeatedly it accumulates one entry",
        "# per distinct signature the whole session drives — size those",
        "# bounds to test-suite shape variety (a per-call retrace is orders",
        "# of magnitude beyond any of them).  The key set is staleness-",
        "# checked against tools/dflint/tracerules.py's static jit-site index",
        "# (tests/test_zz_compilewitness.py), and the runtime compile witness",
        "# (dragonfly2_tpu/utils/dftrace.py) validates observed counts during",
        "# tier-1.  Calibrate: run tier-1 with DF_COMPILE_OBSERVED=<path>.",
        "# Regenerate keys: python -m tools.dflint --update-compile-budget",
        "# (existing bounds are preserved; new sites start at "
        f"{DEFAULT_BUDGET}).",
        "",
        "[budget]",
    ]
    for key in sorted(set(keys)):
        lines.append(f'"{key}" = {existing.get(key, DEFAULT_BUDGET)}')
    return "\n".join(lines) + "\n"


def budget_staleness(
    analysis: TraceAnalysis, budget: Dict[str, int]
) -> List[str]:
    """Key-set drift between the checked-in budget and the static jit-site
    index — same discipline as baseline.toml / the §16 lock graph."""
    static = analysis.jit_site_keys()
    out = []
    for key in sorted(set(budget) - static):
        out.append(
            f"stale budget entry {key!r}: no static jit construction "
            "there any more (site removed/moved — regenerate)"
        )
    for key in sorted(static - set(budget)):
        out.append(
            f"unbudgeted jit construction site {key!r}: add a budget "
            "entry (python -m tools.dflint --update-compile-budget)"
        )
    return out


def witness_compile_gaps(
    analysis: TraceAnalysis,
    observed: Dict[Tuple[str, int], dict],
    budget: Dict[str, int],
) -> List[str]:
    """Cross-validate runtime jit creations (from
    ``dragonfly2_tpu.utils.dftrace``) against the static site index and
    the compile budget.  ``observed`` maps creation site (relpath,
    lineno) -> {"creations", "calls", "max_compiles"}.

    Empty result == every runtime creation is statically known and
    within budget.  A gap is either a STATIC BLIND SPOT (unknown site —
    fix the tracerules site indexer / cache the construction) or a
    RETRACE (count over budget — a steady-state path is recompiling)."""
    index = analysis.jit_site_index()
    gaps: List[str] = []
    for (relpath, lineno), stats in sorted(observed.items()):
        key = index.get((relpath, lineno))
        if key is None:
            gaps.append(
                f"jit created at {relpath}:{lineno} "
                f"({stats.get('creations', '?')} creation(s), "
                f"{stats.get('calls', '?')} call(s)) is unknown to the "
                "static jit-site index — a per-call/uncached construction "
                "or a tracerules resolver blind spot"
            )
            continue
        limit = budget.get(key)
        if limit is None:
            gaps.append(
                f"jit creation at {key} ({relpath}:{lineno}) has no "
                "compile-budget entry — run "
                "python -m tools.dflint --update-compile-budget"
            )
            continue
        if stats.get("max_compiles", 0) > limit:
            gaps.append(
                f"{key} compiled {stats['max_compiles']}x (budget "
                f"{limit}) over {stats.get('calls', '?')} call(s) — a "
                "steady-state path is retracing; fix the shape/"
                "static-arg churn or raise the budget with a review"
            )
    return gaps


def trace_findings(program: Program, root: Optional[Path] = None) -> List[Finding]:
    return TraceAnalysis(program, root).findings()
