"""DF002 — thread hygiene.

Two invariants, both standing in for Go's ``-race`` + structured
goroutine shutdown:

1. **Explicit daemon flag.**  ``threading.Thread(...)`` must pass
   ``daemon=`` explicitly — ``daemon=False`` is fine when the starter
   also ``join()``s, but the choice has to be written down.  A
   non-daemon thread someone forgot about keeps the interpreter alive —
   test runs and CLI shutdown hang on stray threads instead of exiting —
   and an implicit default hides which behaviour the author intended.
   A ``join()``-only site additionally flags until the flag is spelled
   out, so deleting a ``daemon=`` kwarg anywhere is a lint regression.

2. **Lock shared mutations.**  Within a class that starts a thread with
   ``target=self._x``, an attribute assigned both inside the thread
   target and inside a public (externally-called) method is a data race
   unless at least the unguarded side sits under a ``with self.<lock>``
   block.  (Heuristic: any ``with`` over a ``self.*`` attribute counts
   as a lock scope; single-assignment handshakes belong under one.)
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..core import Finding, Module, has_kwarg, walk_calls

RULE = "DF002"
TITLE = "thread started without explicit daemon=, or unlocked shared mutation"


def _is_thread_ctor(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "Thread":
        return True
    if isinstance(f, ast.Name) and f.id == "Thread":
        return True
    return False


def _scope_has_join(module: Module, node: ast.AST) -> bool:
    scope = module.enclosing_function(node) or module.tree
    for call in walk_calls(scope):
        if isinstance(call.func, ast.Attribute) and call.func.attr == "join":
            return True
    return False


# -- invariant 2: shared-attribute mutations --------------------------------


def _thread_target_methods(cls: ast.ClassDef) -> Set[str]:
    """Names of ``self._x`` methods used as ``Thread(target=self._x)``."""
    targets: Set[str] = set()
    for call in walk_calls(cls):
        if not _is_thread_ctor(call):
            continue
        for kw in call.keywords:
            if kw.arg == "target" and isinstance(kw.value, ast.Attribute):
                v = kw.value
                if isinstance(v.value, ast.Name) and v.value.id == "self":
                    targets.add(v.attr)
    return targets


def _under_self_with(module: Module, node: ast.AST) -> bool:
    """Is ``node`` lexically inside ``with self.<attr>`` (a lock scope)?"""
    cur: Optional[ast.AST] = node
    while cur is not None:
        if isinstance(cur, ast.With):
            for item in cur.items:
                expr = item.context_expr
                if isinstance(expr, ast.Call):
                    expr = expr.func
                if (
                    isinstance(expr, ast.Attribute)
                    and isinstance(expr.value, ast.Name)
                    and expr.value.id == "self"
                ):
                    return True
        cur = module.parent(cur)
    return False


def _self_attr_writes(
    module: Module, fn: ast.FunctionDef
) -> List[Tuple[str, ast.AST, bool]]:
    """(attr, node, guarded) for every ``self.attr`` assignment in ``fn``
    proper (nested defs are their own scope, not this thread's body)."""
    out: List[Tuple[str, ast.AST, bool]] = []

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            targets: List[ast.AST] = []
            if isinstance(child, ast.Assign):
                targets = child.targets
            elif isinstance(child, (ast.AugAssign, ast.AnnAssign)):
                targets = [child.target]
            for t in targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    out.append((t.attr, child, _under_self_with(module, child)))
            visit(child)

    visit(fn)
    return out


def check(module: Module) -> Iterator[Finding]:
    # 1. explicit-daemon discipline
    for call in walk_calls(module.tree):
        if not _is_thread_ctor(call):
            continue
        if has_kwarg(call, "daemon"):
            continue
        if _scope_has_join(module, call):
            yield module.finding(
                RULE,
                call,
                "Thread() join()ed here but daemon= left implicit — spell "
                "out daemon=True/False so the shutdown contract is explicit",
            )
        else:
            yield module.finding(
                RULE,
                call,
                "Thread() without daemon= and never join()ed here — a stray "
                "non-daemon thread blocks interpreter exit",
            )

    # 2. unlocked mutation shared between a thread target and a public method
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        targets = _thread_target_methods(node)
        if not targets:
            continue
        methods = {
            m.name: m
            for m in node.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        target_writes: Dict[str, List[Tuple[ast.AST, bool]]] = {}
        for name in targets & set(methods):
            for attr, site, guarded in _self_attr_writes(module, methods[name]):
                target_writes.setdefault(attr, []).append((site, guarded))
        if not target_writes:
            continue
        for name, m in methods.items():
            if name.startswith("_") or name in targets:
                continue
            for attr, site, guarded in _self_attr_writes(module, m):
                if attr not in target_writes or guarded:
                    continue
                # Even when the thread side always holds the lock, a
                # racing unguarded public write is still a race.
                yield module.finding(
                    RULE,
                    site,
                    f"self.{attr} is written by thread target(s) "
                    f"{sorted(targets & set(methods))} and by public "
                    f"{name}() without a lock held here",
                )
