"""DF021 — native exception containment.

An exception escaping an ``extern "C"`` function is undefined behavior
at the ABI boundary, and one escaping a ``std::thread`` entry calls
``std::terminate`` — either way the embedding daemon dies, which is
exactly the PR-17 review finding class (a throwing burst handler took
the whole fetch pool down).  This rule makes the containment discipline
machine-checked:

- every ``extern "C"`` function defined in native.cpp must be a
  function-try-block (``) try { ... } catch (...) { return kAbiTrap; }``)
  or carry a top-level (depth-1) ``try`` whose handlers include
  ``catch (...)``;
- every function handed to ``std::thread(...)`` / ``emplace_back(...)``
  must satisfy the same shape, with its completion accounting (error
  completions, counter decrements, socket closes) placed so it runs
  exactly once whether the body completed or threw.

The exactly-once part is a review property the rule's comment anchors —
statically we enforce the catch-all's presence and position.  Suppress a
reviewed exception with ``// dflint: disable=DF021`` on the function's
signature line in native.cpp (the C++ twin of the Python pragma; the
extractor honors it because Python-side line pragmas cannot reach a
.cpp file).

The shared declaration extractor lives in ``df020_abi`` (one grammar,
two rules); like DF020 this anchors on the bindings module so the sweep
runs it exactly once.
"""

from __future__ import annotations

from typing import Iterator

from ..core import Finding, Module
from .df020_abi import BINDINGS_RELPATH, NATIVE_RELPATH, _project_root, extract_cpp

RULE = "DF021"
TITLE = "native exception containment (extern \"C\" + thread-entry catch-alls)"


def findings_for_cpp(cpp) -> Iterator[str]:
    """Messages for uncontained functions (fixture tests drive this)."""
    for name, fn in sorted(cpp.exports.items()):
        if fn.suppressed or fn.contained:
            continue
        yield (
            f"extern \"C\" {name} (native.cpp:{fn.line}) has no catch-all: "
            f"an escaping exception is UB at the ABI boundary — make it a "
            f"function-try-block returning kAbiTrap (or suppress with "
            f"// dflint: disable=DF021 on the signature)"
        )
    for name, fn in sorted(cpp.thread_entries.items()):
        if fn.suppressed or fn.contained or (fn.extern_c and name in cpp.exports):
            continue
        yield (
            f"thread entry {name} (native.cpp:{fn.line}) has no top-level "
            f"catch-all: an escaping exception calls std::terminate — wrap "
            f"the body in try/catch (...) with exactly-once completion "
            f"accounting"
        )


def check(module: Module) -> Iterator[Finding]:
    if module.relpath != BINDINGS_RELPATH:
        return
    root = _project_root(module)
    if root is None:
        return
    native_path = root / NATIVE_RELPATH
    if not native_path.exists():
        return  # DF020 reports the missing source
    cpp = extract_cpp(native_path.read_text(encoding="utf-8"))
    for message in findings_for_cpp(cpp):
        yield module.finding(RULE, module.tree, message)
