"""DF004 — fault-seam coverage.

PR 1's chaos drills only prove what the seams cover: every raw network
operation on the P2P control/data planes must sit in a function that
also calls ``faultinject.fire(...)``, or the drills silently stop
exercising that path.  This rule is the enforcement the fault-injection
layer was missing — deleting a seam now fails tier-1 by name.

Two sub-rules:

1. **Adjacency** — raw network operations (socket ``send``/``sendall``/
   ``sendto`` / ``recv``/``recvfrom``/``recv_into``,
   ``urllib.request.urlopen``, ``http.client`` request/response calls)
   must share an enclosing function with a ``faultinject.fire(...)``
   call, matching how every existing seam is laid out.

2. **Inventory** — ``REQUIRED_SEAMS`` pins each seam-bearing module to
   the site names it must fire.  Some seams guard LOGICAL planes with
   no raw socket in the same function (the upload manager's
   ``daemon.upload.serve_piece``, the StateBackend's ``state.*``, the
   trainer's ``trainer.dispatch``); adjacency can't see those, so the
   inventory is what makes deleting ANY seam a named tier-1 failure.
   F-string sites are matched on their constant prefix
   (``rpc.client.*``).  New seams: add the site here when you add the
   ``fire`` call.

Modules on ``ALLOWLIST`` are exempt from adjacency: observability
exporters, liveness probes, CLIs, the chaos harness itself, and
pure-helper socket plumbing where a seam would fire on the injector's
own machinery.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from ..core import Finding, Module, dotted, walk_calls

RULE = "DF004"
TITLE = "raw network call with no faultinject.fire seam in scope"

# fnmatch-style module relpath globs exempt from the seam requirement.
ALLOWLIST = (
    "*/utils/ping.py",       # ICMP liveness probe — below the fault model
    "*/utils/hostinfo.py",   # route discovery, no payload moves
    "*/utils/tracing.py",    # OTLP export: observability, not the plane
    "*/security/ca.py",      # CSR bootstrap: one-shot, pre-plane
    "*/sim/*",               # the chaos harness itself
    "*/cli/*",               # one-shot CLI conveniences
    "*/manager/oauth.py",    # third-party IdP exchange, not the P2P plane
    "*/rpc/vsock.py",        # transport constructor plumbing; the seams
                             # live in the clients riding it
    "tools/*",
    "deploy/*",
    "tests/*",
)

_SOCKET_VERBS = {"sendall", "sendto", "recvfrom", "recv_into", "recv", "send"}
_HTTP_CALLS = {"urlopen", "getresponse"}

# relpath -> site names that module must fire (f-string sites as
# ``prefix.*``).  The chaos drills' coverage contract, checked in.
REQUIRED_SEAMS = {
    "dragonfly2_tpu/source/client.py": (
        "source.transport", "source.fetch", "source.fetch.body",
        "source.content_length", "source.read_range",
    ),
    "dragonfly2_tpu/daemon/upload.py": (
        "daemon.upload.serve_piece", "daemon.upload.body",
        "daemon.upload.sendfile",
        # Tenant QoS gate (DESIGN.md §26): the per-tenant bandwidth
        # throttle at the shared begin_upload accounting gate.
        "daemon.upload.throttle",
    ),
    "dragonfly2_tpu/daemon/piece_pipeline.py": (
        "daemon.report.batch", "daemon.piece.hedge",
        # Pass-through read plane (DESIGN.md §25): tee delivery (a drop
        # degrades consumers to the disk path) and the slow-reader spill
        # (where the mid-tee SIGKILL drill crashes).
        "daemon.stream.tee", "daemon.stream.spill",
    ),
    "dragonfly2_tpu/daemon/conductor.py": (
        # In-engine fetch dispatch (DESIGN.md §28): a raising fault here
        # forces the byte-identical Python arm; the crash kind is the
        # mid-native-window SIGKILL drill's deterministic kill point.
        "daemon.piece.native_fetch",
    ),
    "dragonfly2_tpu/trainer/online_graph.py": ("trainer.dispatch",),
    "dragonfly2_tpu/rpc/grpc_transport.py": (
        "grpc.client.*", "grpc.manager.*",
    ),
    "dragonfly2_tpu/rpc/piece_transport.py": (
        "piece.server.body", "piece.fetch", "piece.fetch.body",
        "piece.bitmap", "piece.bitmap.body", "piece.pool.connect",
    ),
    "dragonfly2_tpu/rpc/_server.py": ("rpc.server.*",),
    "dragonfly2_tpu/rpc/scheduler_client.py": ("rpc.client.*",),
    "dragonfly2_tpu/rpc/registry_client.py": (
        "rpc.registry.get", "rpc.registry.post",
    ),
    "dragonfly2_tpu/rollout/client.py": (
        "rollout.fetch", "rollout.report", "rollout.begin",
    ),
    "dragonfly2_tpu/lifecycle/daemon.py": (
        "lifecycle.register", "lifecycle.report",
    ),
    "dragonfly2_tpu/rpc/trainer_transport.py": (
        "trainer.rpc.post", "trainer.rpc.get",
    ),
    "dragonfly2_tpu/rpc/daemon_control.py": (
        "daemon.control.healthy", "daemon.control.download",
    ),
    "dragonfly2_tpu/manager/state.py": (
        "state.put.*", "state.get.*", "state.delete.*", "state.load_all.*",
    ),
    "dragonfly2_tpu/manager/replication.py": (
        "state.replicate.*", "manager.lease.*",
    ),
    "dragonfly2_tpu/daemon/pex_net.py": ("pex.send", "pex.recv"),
    "dragonfly2_tpu/daemon/relay.py": ("relay.pump",),
    "dragonfly2_tpu/daemon/proxy.py": (
        "proxy.tunnel", "proxy.direct", "proxy.direct.body",
    ),
    "dragonfly2_tpu/daemon/sni.py": ("sni.peek", "sni.hijack"),
    "dragonfly2_tpu/scheduler/topology_sync.py": ("scheduler.topology.sync",),
    # Sharded fleet (DESIGN.md §24): the membership-change handoff sweep
    # and the client-side ring routing are the cross-shard fault seams
    # the SIGKILL drill steers through.
    "dragonfly2_tpu/scheduler/sharding.py": (
        "shard.handoff",
        # Tenant-aware shedding (DESIGN.md §26): fired on every QoS
        # refusal (rate cap + priority-band shed) — the SIGKILL drill's
        # deterministic kill point.
        "scheduler.qos.shed",
    ),
    "dragonfly2_tpu/rpc/resolver.py": ("shard.route",),
    "dragonfly2_tpu/scheduler/microbatch.py": ("scheduler.eval.batch",),
    "dragonfly2_tpu/scheduler/seed_client.py": ("seed.trigger",),
    "dragonfly2_tpu/jobs/image.py": ("jobs.image.fetch",),
    "dragonfly2_tpu/jobs/remote.py": ("jobs.remote.call",),
    "dragonfly2_tpu/objectstorage/s3.py": ("objectstorage.request",),
    "dragonfly2_tpu/utils/metric_journal.py": ("metrics.journal.write",),
}


def _is_raw_net_call(call: ast.Call) -> Optional[str]:
    name = dotted(call.func)
    if name:
        leaf = name.split(".")[-1]
        if leaf == "urlopen":
            return name
        if leaf == "getresponse" or (
            leaf == "request" and ("conn" in name or "http" in name.lower())
        ):
            return name
    if isinstance(call.func, ast.Attribute) and call.func.attr in _SOCKET_VERBS:
        # Heuristic receiver filter: generator .send()/queue .send() false
        # positives are excluded by requiring a socket-ish receiver name.
        recv = dotted(call.func.value) or ""
        leaf = recv.split(".")[-1].lstrip("_")
        if call.func.attr in ("send", "recv") and not (
            "sock" in leaf or "conn" in leaf or leaf in ("s", "tls", "client")
        ):
            return None
        return f"{recv or '<expr>'}.{call.func.attr}"
    return None


def _scope_has_fire(module: Module, node: ast.AST) -> bool:
    scope = module.enclosing_function(node) or module.tree
    for call in walk_calls(scope):
        name = dotted(call.func)
        if name and name.split(".")[-1] == "fire" and "faultinject" in name:
            return True
        # `from ..utils.faultinject import fire` style
        if name == "fire":
            return True
    return False


def allowlisted(relpath: str) -> bool:
    import fnmatch

    return any(fnmatch.fnmatch(relpath, pat) for pat in ALLOWLIST)


def _is_fire(call: ast.Call) -> bool:
    name = dotted(call.func)
    return bool(
        name
        and name.split(".")[-1] == "fire"
        and ("faultinject" in name or name == "fire")
    )


def fire_sites(module: Module) -> Set[str]:
    """Site names fired in this module; f-string sites normalize to
    their constant prefix + ``*`` (``fire(f"rpc.client.{m}")`` →
    ``rpc.client.*``)."""
    sites: Set[str] = set()
    for call in walk_calls(module.tree):
        if not _is_fire(call) or not call.args:
            continue
        arg = call.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            sites.add(arg.value)
        elif isinstance(arg, ast.JoinedStr):
            prefix = ""
            for part in arg.values:
                if isinstance(part, ast.Constant):
                    prefix += str(part.value)
                else:
                    break
            sites.add(prefix + "*")
    return sites


def check(module: Module) -> Iterator[Finding]:
    # Sub-rule 2: seam inventory (runs even for allowlisted modules —
    # a module listed here owns its sites regardless).
    required = REQUIRED_SEAMS.get(module.relpath, ())
    if required:
        present = fire_sites(module)
        for site in required:
            if site not in present:
                yield module.finding(
                    RULE,
                    module.tree,
                    f"required fault seam {site!r} is missing — the chaos "
                    "drills lost coverage of this plane (REQUIRED_SEAMS in "
                    "tools/dflint/checkers/df004_fault_seams.py)",
                )

    # Sub-rule 1: adjacency.
    if allowlisted(module.relpath):
        return
    for call in walk_calls(module.tree):
        op = _is_raw_net_call(call)
        if op is None:
            continue
        if _scope_has_fire(module, call):
            continue
        yield module.finding(
            RULE,
            call,
            f"raw network call {op} has no faultinject.fire(...) seam in "
            "the same function — chaos drills cannot exercise this path",
        )
