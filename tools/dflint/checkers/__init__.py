"""Per-file checker registry.  Each module exposes ``RULE``, ``TITLE``
and ``check(module) -> Iterable[Finding]``; order here is report order.

The whole-program rules (DF008 blocking-under-lock, DF009 lock-order
inversion) do NOT live here — they need every module at once and run via
``tools.dflint.program.Program`` (see ``__main__.PROGRAM_RULES``)."""

from . import (
    df001_exceptions,
    df002_threads,
    df003_jax_purity,
    df004_fault_seams,
    df005_resources,
    df006_deadlines,
    df007_hotpath,
    df016_spans,
    df017_metrics,
    df020_abi,
    df021_nativeexc,
)

CHECKERS = (
    df001_exceptions,
    df002_threads,
    df003_jax_purity,
    df004_fault_seams,
    df005_resources,
    df006_deadlines,
    df007_hotpath,
    df016_spans,
    df017_metrics,
    df020_abi,
    df021_nativeexc,
)

RULES = {c.RULE: c for c in CHECKERS}
