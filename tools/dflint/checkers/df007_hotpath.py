"""DF007 — hot-path hygiene.

The scheduler serving engine (DESIGN.md §14) got its ≥5× announces/sec
by replacing per-parent Python work with vectorized numpy; this rule is
what keeps that true.  Functions carrying a ``# dflint: hotpath`` mark
(on the ``def`` line, inside the signature, or on the line directly
above) promise to be **per-item-loop-free**:

1. **No loop statements** — a ``for``/``while``/``async for`` inside a
   marked function is flagged.  A hot-path function operates on whole
   arrays; per-item iteration belongs in a build-side helper outside the
   mark.  Comprehensions/generators are exempt: they are the accepted
   gather idiom for attribute reads feeding ``np.fromiter``.  Reviewed
   constant-trip loops (an MLP's per-LAYER stack) carry an inline
   ``# dflint: disable=DF007`` with a justification.
2. **No per-call array concatenation** — ``np.concatenate`` /
   ``np.append`` / ``np.vstack`` / ``np.hstack`` in a marked function is
   flagged: each call reallocates; hot paths preallocate and fill (the
   old ``_featurize`` built N ``np.concatenate`` rows per announce).

3. **Inventory** — ``REQUIRED_HOTPATH`` pins the serving-path functions
   that MUST stay marked (seeded with ``evaluate_parents`` /
   ``_featurize`` / ``score``).  Un-marking (or renaming away) any of
   them fails tier-1 by name, so the hygiene contract cannot be dropped
   silently.  New hot paths: mark the function and add it here.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, Set

from ..core import Finding, Module, dotted

RULE = "DF007"
TITLE = "per-item Python loop / per-call concatenate in a hot-path function"

_MARK = re.compile(r"#\s*dflint:\s*hotpath\b")

_BANNED_NP_CALLS = {"concatenate", "append", "vstack", "hstack"}
_NP_PREFIXES = {"np", "numpy", "jnp"}

# relpath -> qualnames that must carry the hotpath mark.  The serving
# engine's contract, checked in.
REQUIRED_HOTPATH = {
    "dragonfly2_tpu/scheduler/evaluator.py": (
        "Evaluator.evaluate_parents",
        "Evaluator.evaluate_all",
        "Evaluator._evaluate_all_columnar",
        "NetworkTopologyEvaluator.evaluate_all",
        "MLEvaluator.evaluate_parents",
        "MLEvaluator._featurize",
        "MLEvaluator._featurize_slots",
    ),
    "dragonfly2_tpu/scheduler/featcache.py": (
        "HostFeatureCache.gather",
        "HostFeatureCache.rule_scores",
    ),
    "dragonfly2_tpu/scheduler/microbatch.py": ("ScorerBatcher.score",),
    # Lifecycle-gauge refresh rides every register/leave at fleet scale:
    # the rate-limit guard keeps it loop-free and lock-cheap (ISSUE 13 —
    # it must never become the per-announce bottleneck at 100k peers).
    "dragonfly2_tpu/scheduler/service.py": (
        "SchedulerService._refresh_gauges",
    ),
    "dragonfly2_tpu/records/features.py": ("edge_features_batch",),
    "dragonfly2_tpu/trainer/export.py": ("MLPScorer.score", "GNNScorer.score"),
    # Fused gather+score serving entry points (ops/pallas_score.py): the
    # one-dispatch-per-flush contract dies if these grow per-row python.
    "dragonfly2_tpu/ops/pallas_score.py": (
        "FusedMLPScorer.score",
        "rule_weighted_sum",
    ),
    # Piece data plane (PR 11): the per-piece serve/fetch entry points —
    # per-item Python iteration belongs in their unmarked helpers (the
    # readinto/sendfile loops), never in these inner functions.
    "dragonfly2_tpu/rpc/piece_transport.py": ("HTTPPieceFetcher.fetch",),
    "dragonfly2_tpu/daemon/upload.py": ("UploadManager.serve_piece",),
    # Pass-through read plane (DESIGN.md §25): tee publish runs on the
    # committer thread per piece, take on every stream read — per-item
    # Python belongs in the unmarked _offer/close helpers.
    "dragonfly2_tpu/daemon/piece_pipeline.py": (
        "CommitTee.publish",
        "TeeConsumer.take",
    ),
    # In-engine fetch loop bindings (DESIGN.md §28): the submit/complete
    # wrappers ride once per piece / once per drain on the conductor's
    # window — batch record decode lives in struct.iter_unpack, never a
    # per-record Python loop.
    "dragonfly2_tpu/native/__init__.py": (
        "NativePieceFetcher.submit",
        "NativePieceFetcher.complete",
    ),
}


def _mark_lines(module: Module) -> Set[int]:
    return {
        i + 1 for i, line in enumerate(module.lines) if _MARK.search(line)
    }


def _is_marked(func: ast.AST, marks: Set[int]) -> bool:
    """Marked when the hotpath comment sits on the line above the def,
    anywhere across the (possibly multi-line) signature, or on the first
    body statement's line."""
    first_body = func.body[0].lineno if func.body else func.lineno
    return any(
        func.lineno - 1 <= line <= first_body for line in marks
    )


def _banned_np_call(node: ast.Call) -> bool:
    name = dotted(node.func)
    if not name or "." not in name:
        return False
    parts = name.split(".")
    return parts[0] in _NP_PREFIXES and parts[-1] in _BANNED_NP_CALLS


def check(module: Module) -> Iterator[Finding]:
    marks = _mark_lines(module)
    funcs: Dict[str, ast.AST] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs[module.qualname(node)] = node

    # Sub-rule 3: the inventory — required hot paths must exist AND stay
    # marked (deleting the mark is a named tier-1 failure).
    for qual in REQUIRED_HOTPATH.get(module.relpath, ()):
        func = funcs.get(qual)
        if func is None:
            yield module.finding(
                RULE,
                module.tree,
                f"required hot-path function {qual!r} is missing — the "
                "serving-engine hygiene inventory names it "
                "(REQUIRED_HOTPATH in tools/dflint/checkers/df007_hotpath.py)",
            )
        elif not _is_marked(func, marks):
            yield module.finding(
                RULE,
                func,
                f"{qual} lost its '# dflint: hotpath' mark — the "
                "serving-engine hygiene inventory requires it "
                "(REQUIRED_HOTPATH in tools/dflint/checkers/df007_hotpath.py)",
            )

    # Sub-rules 1-2: hygiene inside every marked function.
    for qual, func in funcs.items():
        if not _is_marked(func, marks):
            continue
        for node in ast.walk(func):
            if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                yield module.finding(
                    RULE,
                    node,
                    f"per-item Python loop in hot-path function {qual} — "
                    "vectorize it or move the iteration to an unmarked "
                    "build-side helper",
                )
            elif isinstance(node, ast.Call) and _banned_np_call(node):
                yield module.finding(
                    RULE,
                    node,
                    f"{dotted(node.func)} in hot-path function {qual} "
                    "reallocates per call — preallocate and fill "
                    "(np.empty + slice assignment) or np.stack once",
                )
