"""DF016 — span coverage.

The flight recorder (utils/tracing.py DurableSpanExporter + the span
sites across every plane, DESIGN.md §21) is only as good as the spans
that feed it: delete one ``remote_span`` from an RPC server and every
cross-process trace silently loses that hop — nothing else fails.  This
rule is the static half of the coverage contract (the runtime half is
``utils/dfspan.py`` + ``tests/test_zz_spanwitness.py``, in the
lock/compile/crash-witness mould).

Two sub-rules:

1. **Inventory** — ``REQUIRED_SPANS`` pins each instrumented module to
   the span names it must open (``tracer.span("name")`` /
   ``tracer.remote_span(f"rpc/{m}")``; f-string sites match on their
   constant prefix as ``prefix*``).  Deleting ANY inventoried span site
   fails tier-1 by file name.  New spans: add the site here when you add
   the instrumentation.

2. **Server-entry adjacency** — every RPC server entry (a call to the
   shared ``adapter.dispatch(...)``) must have a ``remote_span`` opened
   in the same function, so the handler span exists on EVERY transport
   binding and carries the caller's traceparent.  An adapter dispatched
   outside a remote_span is an un-traced plane entry.

Inventory staleness (an entry naming a module that no longer exists) is
checked by ``stale_inventory_entries`` and wired into tier-1 like the
§16 lock graph (tests/test_dflint.py).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterator, List, Set, Tuple

from ..core import Finding, Module, dotted, walk_calls

RULE = "DF016"
TITLE = "span coverage lost (missing inventoried span / untraced server entry)"

# relpath -> span names that module must open.  F-string sites are
# matched on their constant prefix (``rpc/*``).  The flight recorder's
# coverage contract, checked in.
REQUIRED_SPANS = {
    "dragonfly2_tpu/rpc/scheduler_server.py": ("rpc/*",),
    "dragonfly2_tpu/rpc/grpc_transport.py": ("rpc/*",),
    "dragonfly2_tpu/daemon/conductor.py": (
        "daemon/download", "daemon/piece", "daemon/source.piece",
        # Pass-through serve (DESIGN.md §25): rides the download span's
        # traceparent so a proxy/gateway serve lands on the SAME trace
        # as the swarm transfer that fed it.
        "daemon/stream", "daemon/*",
    ),
    "dragonfly2_tpu/daemon/piece_pipeline.py": ("daemon/report.flush",),
    "dragonfly2_tpu/manager/rest.py": ("manager/GET", "manager/POST"),
    "dragonfly2_tpu/jobs/preheat.py": (
        "jobs/preheat", "jobs/preheat.execute",
    ),
    "dragonfly2_tpu/rollout/controller.py": ("rollout/transition",),
    "dragonfly2_tpu/trainer/online_graph.py": ("trainer/dispatch",),
    "dragonfly2_tpu/manager/replication.py": ("manager/replicate.commit",),
    "dragonfly2_tpu/scheduler/microbatch.py": ("scheduler/eval.flush",),
    # Cross-shard task migration (DESIGN.md §24): the handoff sweep is
    # the edge trace_assemble must show on the chaos drill's critical
    # path — losing the span loses the migration evidence.
    "dragonfly2_tpu/scheduler/sharding.py": ("scheduler/shard.handoff",),
    # SLO-autopilot adjustments (DESIGN.md §26): every shed-floor/cap
    # change closes one span — the flight recorder's answer to "why did
    # the autopilot shed at 12:03"; losing it loses the feedback-loop
    # evidence.
    "dragonfly2_tpu/qos/autopilot.py": ("scheduler/qos.autopilot",),
    # Lifecycle plane (DESIGN.md §29): every unattended train→export→
    # register epoch and every arbitration/promotion sweep closes one
    # span — the evidence trail for "who promoted this model at 12:03".
    "dragonfly2_tpu/lifecycle/daemon.py": (
        "lifecycle/epoch", "lifecycle/promote",
    ),
}


def _is_span_call(call: ast.Call) -> bool:
    """``<tracer>.span(...)`` / ``<tracer>.remote_span(...)`` — the
    receiver must look like a tracer so dict ``.span`` lookalikes don't
    count as coverage."""
    if not isinstance(call.func, ast.Attribute):
        return False
    if call.func.attr not in ("span", "remote_span"):
        return False
    recv = dotted(call.func.value) or ""
    leaf = recv.split(".")[-1]
    return "tracer" in leaf


def span_sites(module: Module) -> Set[str]:
    """Span names opened in this module; f-string sites normalize to
    their constant prefix + ``*`` (``remote_span(f"rpc/{m}")`` →
    ``rpc/*``).  Shared with the runtime span witness
    (tests/test_zz_spanwitness.py) as the static site index."""
    sites: Set[str] = set()
    for call in walk_calls(module.tree):
        if not _is_span_call(call) or not call.args:
            continue
        arg = call.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            sites.add(arg.value)
        elif isinstance(arg, ast.JoinedStr):
            prefix = ""
            for part in arg.values:
                if isinstance(part, ast.Constant):
                    prefix += str(part.value)
                else:
                    break
            sites.add(prefix + "*")
    return sites


def site_matches(site: str, name: str) -> bool:
    """Does a runtime span ``name`` satisfy inventory ``site``?"""
    if site.endswith("*"):
        return name.startswith(site[:-1])
    return name == site


def stale_inventory_entries(root: Path) -> List[str]:
    """Inventory entries whose module no longer exists — the staleness
    check tier-1 runs so the contract can't rot silently."""
    return [rel for rel in REQUIRED_SPANS if not (root / rel).is_file()]


def _is_adapter_dispatch(call: ast.Call) -> bool:
    name = dotted(call.func)
    if not name or not name.endswith(".dispatch"):
        return False
    recv = name[: -len(".dispatch")]
    return recv.split(".")[-1] == "adapter"


def _scope_has_remote_span(module: Module, node: ast.AST) -> bool:
    scope = module.enclosing_function(node) or module.tree
    for call in walk_calls(scope):
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "remote_span"
        ):
            return True
    return False


def check(module: Module) -> Iterator[Finding]:
    # Sub-rule 1: inventory.
    required: Tuple[str, ...] = REQUIRED_SPANS.get(module.relpath, ())
    if required:
        present = span_sites(module)
        for site in required:
            if site not in present:
                yield module.finding(
                    RULE,
                    module.tree,
                    f"required span site {site!r} is missing — the flight "
                    "recorder lost coverage of this plane (REQUIRED_SPANS "
                    "in tools/dflint/checkers/df016_spans.py)",
                )

    # Sub-rule 2: server-entry adjacency.
    for call in walk_calls(module.tree):
        if not _is_adapter_dispatch(call):
            continue
        if _scope_has_remote_span(module, call):
            continue
        yield module.finding(
            RULE,
            call,
            "RPC server entry dispatches without a remote_span in the "
            "same function — this transport's handler spans (and the "
            "caller's traceparent) are lost to the flight recorder",
        )
