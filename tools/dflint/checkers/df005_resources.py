"""DF005 — resource hygiene.

``open(...)`` / ``socket.socket(...)`` must not leak on the error path:
acquire under ``with``, close in ``finally``, or hand ownership away
explicitly.  A leaked fd per failed piece fetch is invisible locally and
an fd-exhaustion outage at daemon scale.

Accepted shapes (not flagged):

- ``with open(...) as f:`` / ``with socket.socket(...) as s:``
- ``open(path, "wb").close()`` — immediate chained close
- ``f = open(...)`` then ``f.close()`` in the same function (incl. a
  ``finally`` block)
- ``self._f = open(...)`` — object-owned; lifetime is the object's
  (pair with a ``close()``/``stop()`` method)
- ``return socket.socket(...)`` / ``return s`` — factory: caller owns
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..core import Finding, Module, dotted, walk_calls

RULE = "DF005"
TITLE = "open()/socket() without context manager, tracked close, or owner"


def _resource_kind(call: ast.Call) -> Optional[str]:
    name = dotted(call.func)
    if name == "open":
        return "open()"
    if name and name.split(".")[-1] == "socket" and (
        "." in name or name == "socket"
    ):
        root = name.split(".")[0]
        if root in ("socket", "_socket"):
            return f"{name}()"
    return None


def check(module: Module) -> Iterator[Finding]:
    # Index every call used as a `with` context or immediately closed.
    in_with = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.With):
            for item in node.items:
                for n in ast.walk(item.context_expr):
                    in_with.add(id(n))

    for call in walk_calls(module.tree):
        kind = _resource_kind(call)
        if kind is None or id(call) in in_with:
            continue
        parent = module.parent(call)
        # open(...).close() — immediate close; open(...).read() chains
        # are still leaks and stay flagged.
        if isinstance(parent, ast.Attribute) and parent.attr == "close":
            continue
        # `return open(...)` — factory, caller owns.
        if isinstance(parent, ast.Return):
            continue
        target: Optional[str] = None
        owned = False
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
            t = parent.targets[0]
            if isinstance(t, ast.Name):
                target = t.id
            elif isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                owned = True  # object-owned; its close()/stop() is the pair
        if owned:
            continue
        if target is not None:
            scope = module.enclosing_function(call) or module.tree
            for inner in walk_calls(scope):
                f = inner.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr == "close"
                    and isinstance(f.value, ast.Name)
                    and f.value.id == target
                ):
                    break
            else:
                # `return s` — ownership handed to the caller.
                returned = any(
                    isinstance(n, ast.Return)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == target
                    for n in ast.walk(scope)
                )
                if not returned:
                    yield module.finding(
                        RULE,
                        call,
                        f"{kind} result '{target}' is never closed in this "
                        "function — use `with`, close in `finally`, or "
                        "return ownership",
                    )
            continue
        yield module.finding(
            RULE,
            call,
            f"{kind} result is discarded without close() — use `with` or "
            "a tracked variable",
        )
