"""DF003 — JAX trace purity.

Functions handed to ``jax.jit`` / ``pjit`` / ``shard_map`` /
``pl.pallas_call`` run ONCE at trace time; side effects inside them
execute at trace, silently vanish on cache hits, and — for value
escapes like ``.item()`` / ``np.asarray`` on tracers — raise
``TracerArrayConversionError`` only on the first real input.  The
ROADMAP's TPU north-star leans on these staying pure; this rule takes
them off the honor system.

Flagged inside a traced function: ``time.*``, ``random.*`` /
``np.random.*`` (module-level RNG: trace-frozen randomness), ``print``,
file I/O (``open``), ``.item()`` / ``.tolist()``, ``np.asarray`` /
``np.array`` / ``float()`` / ``int()`` on non-literal values, and
``os.environ`` reads.  ``jax.random`` (keyed, functional) and
``jax.debug.*`` (trace-aware) are exempt.

Traced functions are found both by decorator (``@jax.jit``,
``@partial(jax.jit, ...)``) and by wrapping-call resolution:
``jax.jit(self._step)`` / ``jax.jit(fn)`` / ``pl.pallas_call(kernel,
...)`` resolve the named def in the same module/class.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from ..core import Finding, Module, dotted, walk_calls

RULE = "DF003"
TITLE = "impure operation inside a jit/pjit/shard_map/pallas function"

_TRACE_ENTRY = {"jit", "pjit", "shard_map", "pallas_call"}


def _is_trace_wrapper(node: ast.AST) -> bool:
    """Is this expression jax.jit / pjit / shard_map / pallas_call or a
    functools.partial over one of them?"""
    name = dotted(node)
    if name and name.split(".")[-1] in _TRACE_ENTRY:
        return True
    if isinstance(node, ast.Call):
        fname = dotted(node.func)
        if fname and fname.split(".")[-1] == "partial" and node.args:
            return _is_trace_wrapper(node.args[0])
        # Decorator factories like jax.jit(static_argnames=...) applied
        # via @jax.jit(...)(fn) shapes.
        return _is_trace_wrapper(node.func)
    return False


def _wrapped_function_names(module: Module) -> Set[str]:
    """Bare names / method names passed as first arg to a trace wrapper:
    ``jax.jit(step)`` -> {"step"}, ``jax.jit(self._step)`` -> {"_step"}."""
    out: Set[str] = set()
    for call in walk_calls(module.tree):
        if not _is_trace_wrapper(call.func):
            continue
        if not call.args:
            continue
        arg = call.args[0]
        # Unwrap partial(fn, ...)
        if isinstance(arg, ast.Call):
            fname = dotted(arg.func)
            if fname and fname.split(".")[-1] == "partial" and arg.args:
                arg = arg.args[0]
        if isinstance(arg, ast.Name):
            out.add(arg.id)
        elif isinstance(arg, ast.Attribute):
            out.add(arg.attr)
    return out


def _traced_defs(module: Module) -> List[ast.FunctionDef]:
    wrapped = _wrapped_function_names(module)
    defs: List[ast.FunctionDef] = []
    for node in ast.walk(module.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if any(_is_trace_wrapper(d) for d in node.decorator_list):
            defs.append(node)
        elif node.name in wrapped:
            defs.append(node)
    return defs


_IMPURE_ROOTS = {"time", "random"}
_IMPURE_DOTTED_PREFIXES = (
    "np.random.", "numpy.random.", "os.environ", "os.getenv",
)
_VALUE_ESCAPES = {"item", "tolist"}
_HOST_ARRAY = {"np.asarray", "numpy.asarray", "np.array", "numpy.array"}
_EXEMPT_PREFIXES = ("jax.random.", "jax.debug.", "jax.experimental.",
                    "random.PRNGKey")


def _impurity(call: ast.Call) -> Optional[str]:
    name = dotted(call.func)
    if name:
        if any(name.startswith(p) for p in _EXEMPT_PREFIXES):
            return None
        root = name.split(".")[0]
        if root in _IMPURE_ROOTS and "." in name:
            return f"{name}() is host-side (runs at trace time only)"
        if any(name.startswith(p) for p in _IMPURE_DOTTED_PREFIXES):
            return f"{name} is host-side (runs at trace time only)"
        if name == "print":
            return "print() inside a traced function (use jax.debug.print)"
        if name == "open":
            return "file I/O inside a traced function"
        if name in _HOST_ARRAY:
            return f"{name}() forces the tracer to host memory"
    if isinstance(call.func, ast.Attribute) and call.func.attr in _VALUE_ESCAPES:
        return (
            f".{call.func.attr}() escapes the tracer to a Python value "
            "(TracerArrayConversionError on real inputs)"
        )
    return None


def check(module: Module) -> Iterator[Finding]:
    seen: Set[int] = set()
    for fn in _traced_defs(module):
        for call in walk_calls(fn):
            if id(call) in seen:
                continue
            seen.add(id(call))
            msg = _impurity(call)
            if msg:
                yield module.finding(
                    RULE, call, f"in traced {fn.name}(): {msg}"
                )
