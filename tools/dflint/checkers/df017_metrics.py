"""DF017 — metric hygiene.

The fleet telemetry plane (utils/metric_journal.py +
tools/fleet_assemble.py, DESIGN.md §23) is only as trustworthy as the
metric definitions feeding it: a metric registered inside a request
handler allocates per call and may race its own re-registration; an
unbounded label value (a raw peer id) explodes series cardinality until
the scrape — and every journal frame — is megabytes; a misnamed metric
breaks every dashboard that greps by convention; and deleting a
hot-path metric silently blinds the fleet view — nothing else fails.

Four sub-rules over literal-name registration sites (``_reg.counter(
"name", ...)`` / ``Counter("name", ...)`` and the gauge/histogram/
sketch twins):

1. **Module scope, exactly once** — registration calls must sit at
   module scope (constants, like the reference's metrics.go:44-180),
   and a literal name must not be registered twice in one module.

2. **Label-cardinality bound** — declared label names must not come
   from the unbounded-identifier family (``peer_id``, ``task_id``,
   ``url``, ``ip``, ...): those take one series per entity and a label
   value per request.  Bounded enums (``result``, ``outcome``,
   ``algorithm``) are the accepted shape.

3. **Naming convention** — ``<subsystem>_<name>[_<unit>]``: the first
   token must be a known subsystem, counters must end ``_total``, and
   histograms/sketches must end in a declared unit suffix
   (``_seconds``, ``_bytes``, ...).

4. **Inventory** — ``REQUIRED_METRICS`` pins each instrumented module
   to the metric names it must register; deleting an inventoried
   hot-path metric fails tier-1 by name (the DF004/DF016 discipline).
   Staleness is checked by ``stale_inventory_entries`` in tier-1.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Iterator, List, Optional, Tuple

from ..core import Finding, Module, dotted, walk_calls

RULE = "DF017"
TITLE = "metric hygiene (module-scope registration, labels, naming, inventory)"

REGISTER_METHODS = ("counter", "gauge", "histogram", "sketch")
CONSTRUCTOR_KINDS = {
    "Counter": "counter",
    "Gauge": "gauge",
    "Histogram": "histogram",
    "Sketch": "sketch",
}

# The metric classes' own definition/registration plumbing.
SELF_MODULE = "dragonfly2_tpu/utils/metrics.py"

SUBSYSTEMS = (
    "daemon", "scheduler", "manager", "rpc", "trainer", "rollout",
    "jobs", "source", "slo", "fleet", "sim", "lifecycle",
)

# Counter names must end _total; histogram/sketch names must end in one
# of these unit tokens.  Gauges carry state (roles, counts-in-flight),
# so they are exempt from the unit suffix but not from the subsystem
# prefix.
UNIT_SUFFIXES = (
    "seconds", "bytes", "total", "ratio", "percent", "retries", "size",
    "ms", "ns",
)

# Unbounded-identifier label names: one series per peer/task/host is a
# cardinality explosion on a million-peer fleet.  Raw tenant ids join
# the family (DESIGN.md §26): tenant-shaped series must carry the
# BOUNDED ``tenant_class`` label ("gold".."background"), never one
# series per tenant on a million-user fleet.
FORBIDDEN_LABELS = (
    "peer_id", "host_id", "task_id", "trace_id", "span_id", "run_id",
    "url", "ip", "addr", "address", "peer", "hostname",
    "tenant", "tenant_id", "user", "user_id",
)

_NAME_RE = re.compile(r"^[a-z][a-z0-9]*(_[a-z0-9]+)+$")

# relpath -> metric names that module must register.  The telemetry
# plane's coverage contract, checked in: deleting an inventoried
# hot-path metric fails tier-1 by name.
REQUIRED_METRICS = {
    "dragonfly2_tpu/daemon/piece_pipeline.py": (
        "daemon_piece_hedge_total",
        "daemon_piece_report_batches_total",
        "daemon_piece_fetch_seconds",
        "daemon_report_linger_seconds",
    ),
    "dragonfly2_tpu/lifecycle/metrics.py": (
        "lifecycle_epochs_total",
        "lifecycle_promotions_total",
        "lifecycle_rollbacks_total",
        "lifecycle_dropped_records_total",
        "lifecycle_epoch_seconds",
    ),
    "dragonfly2_tpu/rpc/piece_transport.py": (
        "rpc_piece_fetch_seconds",
    ),
    "dragonfly2_tpu/scheduler/metrics.py": (
        "scheduler_eval_seconds",
        "scheduler_announce_seconds",
        "scheduler_eval_flush_seconds",
    ),
    "dragonfly2_tpu/rpc/metrics.py": (
        "manager_replication_lag_seconds",
        "manager_replication_commit_seconds",
    ),
    "dragonfly2_tpu/utils/slo.py": (
        "slo_burn_rate",
        "slo_breached",
    ),
    # Tenant QoS plane (DESIGN.md §26) — every tenant-shaped series
    # carries the bounded tenant_class label, never raw tenant ids.
    "dragonfly2_tpu/qos/metrics.py": (
        "scheduler_qos_shed_total",
        "scheduler_qos_rate_capped_total",
        "scheduler_qos_autopilot_level",
        "scheduler_qos_autopilot_adjustments_total",
    ),
    "dragonfly2_tpu/daemon/upload.py": (
        "daemon_upload_throttled_total",
        "daemon_upload_tenant_bytes_total",
    ),
}


def _registration_of(call: ast.Call) -> Optional[str]:
    """The metric KIND registered by this call, or None.

    Matches ``<receiver>.counter|gauge|histogram|sketch("literal", ...)``
    where the receiver looks like a registry, and direct
    ``Counter("literal", ...)``-family constructors."""
    if not call.args:
        return None
    first = call.args[0]
    if not (isinstance(first, ast.Constant) and isinstance(first.value, str)):
        return None
    if isinstance(call.func, ast.Attribute):
        if call.func.attr not in REGISTER_METHODS:
            return None
        recv = dotted(call.func.value) or ""
        leaf = recv.split(".")[-1].lower()
        if "reg" not in leaf:
            return None
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return CONSTRUCTOR_KINDS.get(call.func.id)
    return None


def _label_names(call: ast.Call) -> List[Tuple[ast.AST, str]]:
    """Literal label names declared at the registration site (the third
    positional arg / ``label_names=``)."""
    node: Optional[ast.AST] = None
    if len(call.args) >= 3:
        node = call.args[2]
    for kw in call.keywords:
        if kw.arg == "label_names":
            node = kw.value
    if not isinstance(node, (ast.List, ast.Tuple)):
        return []
    out = []
    for elt in node.elts:
        if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
            out.append((elt, elt.value))
    return out


def metric_sites(module: Module) -> List[Tuple[ast.Call, str, str]]:
    """(call, kind, name) for every literal-name registration in the
    module — shared with the inventory check and the tests."""
    out = []
    for call in walk_calls(module.tree):
        kind = _registration_of(call)
        if kind is not None:
            out.append((call, kind, call.args[0].value))
    return out


def stale_inventory_entries(root: Path) -> List[str]:
    """Inventory entries whose module no longer exists (tier-1 staleness
    check, the DF004/DF016 discipline)."""
    return [rel for rel in REQUIRED_METRICS if not (root / rel).is_file()]


def _check_name(kind: str, name: str) -> Optional[str]:
    if not _NAME_RE.match(name):
        return (
            f"metric name {name!r} breaks the <subsystem>_<name>_<unit> "
            "convention (lowercase tokens joined by underscores)"
        )
    first = name.split("_", 1)[0]
    if first not in SUBSYSTEMS:
        return (
            f"metric {name!r}: unknown subsystem prefix {first!r} "
            f"(known: {', '.join(SUBSYSTEMS)})"
        )
    if kind == "counter" and not name.endswith("_total"):
        return f"counter {name!r} must end in _total"
    if kind in ("histogram", "sketch"):
        unit = name.rsplit("_", 1)[-1]
        if unit not in UNIT_SUFFIXES:
            return (
                f"{kind} {name!r} must end in a unit suffix "
                f"({', '.join('_' + u for u in UNIT_SUFFIXES)})"
            )
    return None


def check(module: Module) -> Iterator[Finding]:
    if module.relpath == SELF_MODULE:
        return

    sites = metric_sites(module)
    seen: dict = {}
    for call, kind, name in sites:
        # Sub-rule 1: module scope, exactly once.
        if module.enclosing_function(call) is not None:
            yield module.finding(
                RULE,
                call,
                f"metric {name!r} registered inside a function — metrics "
                "are module-scope constants (one registration per "
                "process, like the reference's metrics.go)",
            )
        prev = seen.get(name)
        if prev is not None:
            yield module.finding(
                RULE,
                call,
                f"metric {name!r} registered twice in this module "
                f"(first at line {prev})",
            )
        else:
            seen[name] = call.lineno

        # Sub-rule 2: label-cardinality bound.
        for node, label in _label_names(call):
            if label in FORBIDDEN_LABELS:
                yield module.finding(
                    RULE,
                    node,
                    f"metric {name!r} declares unbounded label "
                    f"{label!r} — one series per entity explodes "
                    "cardinality on a fleet; aggregate or drop the "
                    "label (sketches carry the distribution)",
                )

        # Sub-rule 3: naming convention.
        msg = _check_name(kind, name)
        if msg is not None:
            yield module.finding(RULE, call, msg)

    # Sub-rule 4: inventory.
    required = REQUIRED_METRICS.get(module.relpath, ())
    if required:
        present = {name for _call, _kind, name in sites}
        for name in required:
            if name not in present:
                yield module.finding(
                    RULE,
                    module.tree,
                    f"required metric {name!r} is missing — the fleet "
                    "telemetry plane lost this hot-path signal "
                    "(REQUIRED_METRICS in "
                    "tools/dflint/checkers/df017_metrics.py)",
                )
