"""DF006 — deadline propagation in rpc/.

``rpc/retry.py`` implements deadline propagation: ``retry_call``'s
``deadline_s`` bounds the WHOLE call and is forwarded to deadline-aware
callables so the transport clamps its own timeout to the remaining
budget.  That only works if every retry site in the RPC layer actually
threads the parameter — an rpc/ function that calls ``retry_call``
without ``deadline_s=`` silently caps nothing, and an ``urlopen``
without ``timeout=`` can hang a worker forever.

Two sub-rules, scoped to ``rpc/`` modules:

1. every ``retry_call(...)`` passes ``deadline_s=`` (``None`` is fine —
   the plumbing must exist so callers CAN bound the call), and the
   enclosing function accepts a ``deadline_s`` parameter to forward;
2. every ``urlopen(...)`` passes ``timeout=``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import Finding, Module, dotted, has_kwarg, walk_calls

RULE = "DF006"
TITLE = "rpc/ call without deadline/timeout propagation"


def _in_rpc(module: Module) -> bool:
    return "/rpc/" in f"/{module.relpath}"


def _accepts_deadline(fn) -> bool:
    args = fn.args
    names = [a.arg for a in args.args + args.kwonlyargs + args.posonlyargs]
    return "deadline_s" in names or args.kwarg is not None


def check(module: Module) -> Iterator[Finding]:
    if not _in_rpc(module):
        return
    for call in walk_calls(module.tree):
        name = dotted(call.func)
        if not name:
            continue
        leaf = name.split(".")[-1]
        if leaf == "retry_call":
            if not has_kwarg(call, "deadline_s"):
                yield module.finding(
                    RULE,
                    call,
                    "retry_call(...) without deadline_s= — the overall "
                    "budget cannot be bounded by callers",
                )
                continue
            fn = module.enclosing_function(call)
            if fn is not None and not _accepts_deadline(fn):
                # The seam passes a deadline but callers can't set it:
                # the budget is hardcoded where policy belongs upstream.
                yield module.finding(
                    RULE,
                    call,
                    f"{fn.name}() calls retry_call(deadline_s=...) but "
                    "takes no deadline_s parameter to forward",
                )
        elif leaf == "urlopen":
            if not has_kwarg(call, "timeout"):
                yield module.finding(
                    RULE,
                    call,
                    "urlopen(...) without timeout= — an unresponsive peer "
                    "hangs this worker forever",
                )
