"""DF001 — exception swallowing.

A broad handler (bare ``except:``, ``except BaseException``,
``except Exception``) that discards the error — no re-raise, no call
(logging, metric, cleanup, error response), no use of the bound
exception — hides real failures.  PR 1's chaos drills inject typed
errors precisely so they surface; a silent ``except Exception: pass``
at a seam turns an injected fault into a wrong answer.

Fix by logging (``log.warning("...: %s", exc)``) and continuing, or by
narrowing the except type, or by re-raising.  A site where silence IS
the contract gets ``# dflint: disable=DF001`` with a justifying comment.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import Finding, Module

RULE = "DF001"
TITLE = "broad except swallows the error (no log / re-raise / use)"

_BROAD = {"Exception", "BaseException"}


def _is_broad(type_node) -> bool:
    if type_node is None:
        return True  # bare except
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(elt) for elt in type_node.elts)
    if isinstance(type_node, ast.Name):
        return type_node.id in _BROAD
    if isinstance(type_node, ast.Attribute):
        return type_node.attr in _BROAD
    return False


def _handles(handler: ast.ExceptHandler) -> bool:
    """Does the body do ANYTHING with the failure?  A raise, any call
    (logging / metric / fallback work), or a read of the bound name all
    count — the goal is catching pure discards, not auditing style."""
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.Call)):
            return True
        if (
            handler.name
            and isinstance(node, ast.Name)
            and node.id == handler.name
            and isinstance(node.ctx, ast.Load)
        ):
            return True
    return False


def check(module: Module) -> Iterator[Finding]:
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad(node.type):
            continue
        if _handles(node):
            continue
        shape = (
            "bare except" if node.type is None
            else f"except {ast.unparse(node.type)}"
        )
        yield module.finding(
            RULE,
            node,
            f"{shape} discards the error silently — log it, use it, or re-raise",
        )
