"""DF020 — native ABI contract parity (DESIGN.md §30).

The native data plane crosses a C ABI: ``native/src/native.cpp`` exports
~40 ``extern "C"`` symbols that the hand-maintained ctypes table in
``native/__init__.py`` binds, plus packed records and shared constants
both sides restate.  Drift on either side compiles clean and corrupts
memory at runtime — a widened parameter, a reordered field in the packed
24-byte FetchDone completion, a constant changed on one side.

``records/abi_contracts.py`` (read with ``ast.literal_eval`` — dflint
never imports project code) is the single declaration.  This checker
anchors on the bindings module and cross-checks THREE views of the
boundary against each other, by name:

1. **C side** — a declaration extractor over native.cpp: ``extern "C"``
   block function definitions (prototypes canonicalized into the shared
   type vocabulary, ``const`` dropped), ``constexpr`` ``k``-prefixed
   constants (tiny int-expression evaluator: ``512 * 1024`` and LL/u
   suffixes fold), ``#pragma pack(push, 1)`` struct layouts, and the
   ``std::map<int64_t, T> g_*`` handle registries.
2. **Python side** — an AST pass over the ctypes bindings: per-symbol
   ``restype``/``argtypes`` (local aliases like ``i64 = ctypes.c_int64``
   resolve), the registry-derived struct format attributes, the stats
   dict builders, and every declared constant mirror (which must read
   through ``abi_contracts.constant()``, not restate a literal).
3. **The registry itself** — entries naming symbols/constants/records/
   maps that no longer exist on either side fail as stale, the
   baseline.toml discipline.

Exported-but-unbound, bound-but-unexported, and any prototype/layout/
value mismatch all fail tier-1 naming the symbol/field/constant.  The
extractor grammar is deliberately small (see DESIGN.md §30 for its
limits); the runtime witness (``utils/dfabi.py`` + the compiled-in
``df_abi_manifest()``) covers what a text extractor cannot — the
compiler's actual sizeof/offsetof and the built .so's symbol table.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Tuple

from ..core import Finding, Module, dotted

RULE = "DF020"
TITLE = "native ABI contract parity (registry <-> C++ exports <-> ctypes)"

BINDINGS_RELPATH = "dragonfly2_tpu/native/__init__.py"
CONTRACTS_RELPATH = "dragonfly2_tpu/records/abi_contracts.py"
NATIVE_RELPATH = "dragonfly2_tpu/native/src/native.cpp"

# ---------------------------------------------------------------------------
# Canonical type vocabulary (mirrors the table in records/abi_contracts.py
# and the using-aliases in native.cpp's manifest section).
# ---------------------------------------------------------------------------

_CPP_SCALARS = {
    "void": "void",
    "int": "i32",
    "int32_t": "i32",
    "int64_t": "i64",
    "uint16_t": "u16",
    "uint32_t": "u32",
    "uint64_t": "u64",
    "double": "f64",
    "float": "f32",
    "char": "char",
    "uint8_t": "u8",
    "size_t": "u64",
}

_POINTER_CANON = {
    "char": "cstr",
    "u8": "u8p",
    "f32": "f32p",
    "i32": "i32p",
    "i64": "i64p",
    "f64": "f64p",
}

_CTYPES_SCALARS = {
    "c_int": "i32",
    "c_int32": "i32",
    "c_int64": "i64",
    "c_uint16": "u16",
    "c_uint32": "u32",
    "c_uint64": "u64",
    "c_uint8": "u8",
    "c_float": "f32",
    "c_double": "f64",
    "c_char_p": "cstr",
}


def canon_cpp_type(text: str) -> str:
    """``const char*`` / ``uint8_t *`` / ``int32_t`` -> canonical name.
    Unknown shapes come back verbatim so the mismatch message shows them.
    """
    t = text.replace("*", " * ").split()
    t = [w for w in t if w != "const"]
    stars = t.count("*")
    t = [w for w in t if w != "*"]
    base = " ".join(t)
    scalar = _CPP_SCALARS.get(base, base)
    if stars == 0:
        return scalar
    if stars == 1 and scalar in _POINTER_CANON:
        return _POINTER_CANON[scalar]
    return text.strip()


# ---------------------------------------------------------------------------
# C++ declaration extractor
# ---------------------------------------------------------------------------


@dataclass
class CppFunction:
    name: str
    ret: str                  # canonical
    params: List[str]         # canonical
    line: int
    extern_c: bool = False
    static: bool = False
    function_try: bool = False
    contained: bool = False   # function-try-block OR depth-1 try/catch(...)
    suppressed: bool = False  # `// dflint: disable=DF021` on the signature


@dataclass
class CppDecls:
    exports: Dict[str, CppFunction] = field(default_factory=dict)
    constants: Dict[str, object] = field(default_factory=dict)  # int or str
    records: Dict[str, List[Tuple[str, str]]] = field(default_factory=dict)
    record_lines: Dict[str, int] = field(default_factory=dict)
    handle_maps: Dict[str, str] = field(default_factory=dict)   # g_x -> T
    thread_entries: Dict[str, CppFunction] = field(default_factory=dict)
    parse_errors: List[str] = field(default_factory=list)


def _mask_literals(s: str) -> str:
    """Blank out comment and string/char-literal BODIES (delimiters and
    length preserved) so brace/paren scans can't be fooled.  Records
    DF021 pragma lines first — they live inside comments."""
    out = list(s)
    i, n = 0, len(s)
    while i < n:
        c = s[i]
        if c == "/" and i + 1 < n and s[i + 1] == "/":
            j = s.find("\n", i)
            j = n if j < 0 else j
            for k in range(i, j):
                out[k] = " "
            i = j
        elif c == "/" and i + 1 < n and s[i + 1] == "*":
            j = s.find("*/", i + 2)
            j = n - 2 if j < 0 else j
            for k in range(i, j + 2):
                out[k] = " "
            i = j + 2
        elif c in ('"', "'"):
            q = c
            j = i + 1
            while j < n:
                if s[j] == "\\":
                    j += 2
                    continue
                if s[j] == q:
                    break
                j += 1
            for k in range(i + 1, min(j, n)):
                out[k] = " "
            i = j + 1
        else:
            i += 1
    return "".join(out)


_DF021_PRAGMA = re.compile(r"//\s*dflint:\s*disable\s*=\s*DF021")

_INT_SUFFIX = re.compile(r"(?<=\d)(?:[uU]|[lL]{1,2})+")

_ALLOWED_OPS = (ast.Add, ast.Sub, ast.Mult, ast.LShift, ast.FloorDiv)


def _eval_int_expr(expr: str) -> Optional[int]:
    """Fold a constexpr initializer: integer literals (LL/u suffixes
    stripped), + - * << and unary minus.  None when outside the grammar."""
    text = _INT_SUFFIX.sub("", expr.strip())
    try:
        node = ast.parse(text, mode="eval").body
    except SyntaxError:
        return None

    def ev(n: ast.AST) -> Optional[int]:
        if isinstance(n, ast.Constant) and isinstance(n.value, int):
            return n.value
        if isinstance(n, ast.UnaryOp) and isinstance(n.op, (ast.USub, ast.UAdd)):
            v = ev(n.operand)
            if v is None:
                return None
            return -v if isinstance(n.op, ast.USub) else v
        if isinstance(n, ast.BinOp) and isinstance(n.op, _ALLOWED_OPS):
            a, b = ev(n.left), ev(n.right)
            if a is None or b is None:
                return None
            if isinstance(n.op, ast.Add):
                return a + b
            if isinstance(n.op, ast.Sub):
                return a - b
            if isinstance(n.op, ast.Mult):
                return a * b
            if isinstance(n.op, ast.LShift):
                return a << b
            return a // b
        return None

    return ev(node)


_CONST_INT = re.compile(
    r"constexpr\s+(?:unsigned\s+)?[A-Za-z_]\w*\s+(k[A-Z]\w*)\s*=\s*([^;]+);"
)
_CONST_STR = re.compile(r'constexpr\s+char\s+(k[A-Z]\w*)\s*\[\]\s*=\s*"([^"]*)"\s*;')
_HANDLE_MAP = re.compile(r"std::map<\s*int64_t\s*,\s*([\w:]+\s*\*?)\s*>\s+(g_\w+)\s*;")
_PACK_REGION = re.compile(
    r"#pragma\s+pack\(push,\s*1\)(.*?)#pragma\s+pack\(pop\)", re.S
)
_STRUCT = re.compile(r"struct\s+(\w+)\s*\{([^}]*)\}\s*;", re.S)
_STRUCT_FIELD = re.compile(
    r"^\s*([A-Za-z_][\w:]*)\s+(\w+)(\[(\d+)\])?\s*;", re.M
)
_FN_SIG = re.compile(
    r"^[ \t]*(static\s+)?"
    r"(void|int|int32_t|int64_t|uint32_t|uint64_t|uint16_t|double|float|"
    r"const\s+char\s*\*|char\s*\*)\s+"
    r"(\w+)\s*\(([^()]*)\)",
    re.M,
)
_THREAD_REF = re.compile(r"(?:std::thread\s*\(|emplace_back\s*\()\s*(\w+)\s*[,)]")


def _line_of(text: str, pos: int) -> int:
    return text.count("\n", 0, pos) + 1


def _params_of(raw: str) -> List[str]:
    raw = raw.strip()
    if not raw or raw == "void":
        return []
    out = []
    for part in raw.split(","):
        part = part.strip()
        # drop the trailing identifier (the parameter name), if any
        m = re.match(r"^(.*?[\s*&])([A-Za-z_]\w*)$", part)
        ty = m.group(1).strip() if m else part
        out.append(canon_cpp_type(ty))
    return out


def _containment(masked: str, body_start: int, body_end: int) -> bool:
    """True when the body [start, end) carries a depth-1 ``try`` whose
    handlers include ``catch (...)``."""
    depth = 0
    i = body_start
    while i < body_end:
        c = masked[i]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
        elif depth == 0 and masked.startswith("try", i) and (
            i == 0 or not (masked[i - 1].isalnum() or masked[i - 1] == "_")
        ) and not (
            i + 3 < len(masked)
            and (masked[i + 3].isalnum() or masked[i + 3] == "_")
        ):
            # scan this try's block + handlers for catch (...)
            j = masked.find("{", i)
            if j < 0 or j >= body_end:
                return False
            d = 1
            j += 1
            while j < body_end and d:
                if masked[j] == "{":
                    d += 1
                elif masked[j] == "}":
                    d -= 1
                j += 1
            rest = masked[j:body_end]
            if re.match(r"\s*catch\s*\(\s*\.\.\.\s*\)", rest):
                return True
            # walk catch chains: catch (X&) {...} catch (...) {...}
            while True:
                m = re.match(r"\s*catch\s*\(([^)]*)\)\s*\{", rest)
                if not m:
                    break
                if m.group(1).strip() == "...":
                    return True
                d = 1
                k = m.end()
                while k < len(rest) and d:
                    if rest[k] == "{":
                        d += 1
                    elif rest[k] == "}":
                        d -= 1
                    k += 1
                rest = rest[k:]
            i = j
            continue
        i += 1
    return False


def _match_brace(masked: str, open_pos: int) -> int:
    """Index just past the brace matching ``masked[open_pos] == '{'``."""
    depth = 1
    i = open_pos + 1
    while i < len(masked) and depth:
        if masked[i] == "{":
            depth += 1
        elif masked[i] == "}":
            depth -= 1
        i += 1
    return i


def _parse_function_at(
    src: str, masked: str, m: "re.Match", extern_c: bool
) -> Optional[CppFunction]:
    """One ``_FN_SIG`` match -> a CppFunction, or None for declarations."""
    sig_line = _line_of(src, m.start())
    after = m.end()
    j = after
    while j < len(masked) and masked[j] in " \t\n":
        j += 1
    function_try = masked.startswith("try", j)
    if function_try:
        j += 3
        while j < len(masked) and masked[j] in " \t\n":
            j += 1
    if j >= len(masked) or masked[j] != "{":
        return None  # declaration (`;`) or something the grammar skips
    body_end = _match_brace(masked, j)
    if function_try:
        # the handlers sit after the body close; require catch (...)
        contained = bool(
            re.match(r"\s*catch\s*\(\s*\.\.\.\s*\)", masked[body_end:])
        )
    else:
        contained = _containment(masked, j + 1, body_end - 1)
    lines = src.splitlines()
    line_text = lines[sig_line - 1] if sig_line - 1 < len(lines) else ""
    return CppFunction(
        name=m.group(3),
        ret=canon_cpp_type(m.group(2)),
        params=_params_of(m.group(4)),
        line=sig_line,
        extern_c=extern_c,
        static=bool(m.group(1)),
        function_try=function_try,
        contained=contained,
        suppressed=bool(_DF021_PRAGMA.search(line_text)),
    )


def extract_cpp(src: str) -> CppDecls:
    """Parse native.cpp's declaration surface (grammar per DESIGN.md §30)."""
    decls = CppDecls()
    masked = _mask_literals(src)

    # extern "C" block spans (found on the RAW text — the literal is a
    # string; masking blanks it).
    spans: List[Tuple[int, int]] = []
    for m in re.finditer(r'extern\s+"C"\s*\{', src):
        end = _match_brace(masked, m.end() - 1)
        spans.append((m.end(), end - 1))

    def in_extern_c(pos: int) -> bool:
        return any(a <= pos < b for a, b in spans)

    for m in _FN_SIG.finditer(masked):
        if not in_extern_c(m.start()):
            continue
        fn = _parse_function_at(src, masked, m, extern_c=True)
        if fn is None or fn.static:
            continue
        if fn.name in decls.exports:
            decls.parse_errors.append(
                f"duplicate extern \"C\" definition of {fn.name}"
            )
        decls.exports[fn.name] = fn

    # constants (comment-stripped text so commented-out declarations
    # don't count; string constants need the RAW text for their value)
    for m in _CONST_INT.finditer(masked):
        if m.group(1) in decls.constants:
            continue
        value = _eval_int_expr(m.group(2))
        if value is None:
            decls.parse_errors.append(
                f"constexpr {m.group(1)}: initializer "
                f"{m.group(2).strip()!r} outside the DF020 int-expression "
                "grammar"
            )
        else:
            decls.constants[m.group(1)] = value
    for m in _CONST_STR.finditer(src):
        # raw-text match (masking blanks the value); skip commented-out
        # declarations by requiring the keyword to survive masking
        if masked[m.start():m.start() + 9] == "constexpr":
            decls.constants.setdefault(m.group(1), m.group(2))

    # packed records
    for region in _PACK_REGION.finditer(masked):
        for sm in _STRUCT.finditer(region.group(1)):
            fields: List[Tuple[str, str]] = []
            for fm in _STRUCT_FIELD.finditer(sm.group(2)):
                base = _CPP_SCALARS.get(fm.group(1), fm.group(1))
                if fm.group(4):  # array field
                    base = f"{base}{fm.group(4)}"
                fields.append((fm.group(2), base))
            decls.records[sm.group(1)] = fields
            decls.record_lines[sm.group(1)] = _line_of(
                src, region.start(1) + sm.start()
            )

    # handle registries
    for m in _HANDLE_MAP.finditer(masked):
        decls.handle_maps[m.group(2)] = m.group(1).replace(" ", "")

    # thread entries: every function handed to std::thread/emplace_back
    entry_names = {m.group(1) for m in _THREAD_REF.finditer(masked)}
    for m in _FN_SIG.finditer(masked):
        if m.group(3) in entry_names and m.group(3) not in decls.thread_entries:
            fn = _parse_function_at(src, masked, m, extern_c=in_extern_c(m.start()))
            if fn is not None:
                decls.thread_entries[fn.name] = fn

    return decls


# ---------------------------------------------------------------------------
# Registry loading (ast.literal_eval — never imported)
# ---------------------------------------------------------------------------


def load_contracts_text(text: str) -> Optional[dict]:
    tree = ast.parse(text)
    for stmt in tree.body:
        if (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
            and stmt.targets[0].id == "ABI_CONTRACTS"
        ):
            try:
                return ast.literal_eval(stmt.value)
            except ValueError:
                return None
    return None


def record_layout(spec: dict) -> List[Tuple[str, str, int, int]]:
    """[(field, ctype, offset, size)] with cumulative pack(1) offsets."""
    sizes = {
        "u8": 1, "i8": 1, "u16": 2, "i16": 2, "u32": 4, "i32": 4,
        "u64": 8, "i64": 8, "f32": 4, "f64": 8, "char4": 4,
    }
    out = []
    offset = 0
    for fname, ctype in spec["fields"]:
        size = sizes.get(ctype, 0)
        out.append((fname, ctype, offset, size))
        offset += size
    return out


_STRUCT_FMT = {
    "u8": "B", "i8": "b", "u16": "H", "i16": "h", "u32": "I", "i32": "i",
    "u64": "Q", "i64": "q", "f32": "f", "f64": "d", "char4": "4s",
}


def record_struct_format(spec: dict) -> str:
    return "<" + "".join(_STRUCT_FMT.get(t, "?") for _, t in spec["fields"])


def expected_manifest(contracts: dict) -> dict:
    """The manifest ``df_abi_manifest()`` must emit (same shape as
    ``records.abi_contracts.expected_manifest`` — a tier-1 test pins the
    two renderings to each other)."""
    records = {}
    for rname, spec in contracts.get("records", {}).items():
        records[rname] = {
            "fields": [
                [f, off, size] for f, _t, off, size in record_layout(spec)
            ],
            "size": spec["size"],
        }
    return {
        "constants": dict(contracts.get("constants", {})),
        "exports": {k: list(v) for k, v in contracts.get("exports", {}).items()},
        "records": records,
        "version": 1,
    }


def manifest_json(contracts: dict) -> str:
    import json

    return json.dumps(
        expected_manifest(contracts), sort_keys=True, separators=(",", ":")
    )


# ---------------------------------------------------------------------------
# Python bindings extraction
# ---------------------------------------------------------------------------


@dataclass
class PyBindings:
    # symbol -> ("restype"/"argtypes", canonical or list, AST node)
    restypes: Dict[str, Tuple[str, ast.AST]] = field(default_factory=dict)
    argtypes: Dict[str, Tuple[List[str], ast.AST]] = field(default_factory=dict)


def _canon_ctypes(node: ast.AST, aliases: Dict[str, str]) -> str:
    if isinstance(node, ast.Constant) and node.value is None:
        return "void"
    if isinstance(node, ast.Name):
        return aliases.get(node.id, node.id)
    d = dotted(node)
    if d is not None:
        leaf = d.rsplit(".", 1)[-1]
        return _CTYPES_SCALARS.get(leaf, d)
    if isinstance(node, ast.Call):
        fn = dotted(node.func)
        if fn is not None and fn.rsplit(".", 1)[-1] == "POINTER" and node.args:
            inner = _canon_ctypes(node.args[0], aliases)
            return _POINTER_CANON.get(inner, f"{inner}p")
    return "<unresolved>"


def extract_bindings(tree: ast.AST) -> PyBindings:
    """Collect every ``<lib>.<sym>.restype/argtypes = ...`` assignment,
    resolving single-name aliases assigned in the same module."""
    out = PyBindings()
    aliases: Dict[str, str] = {}
    # pass 1: aliases (`i64 = ctypes.c_int64`, tuple unpacks included)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        targets = node.targets[0]
        if isinstance(targets, ast.Tuple) and isinstance(node.value, ast.Tuple):
            pairs = list(zip(targets.elts, node.value.elts))
        else:
            pairs = [(node.targets[0], node.value)]
        for tgt, val in pairs:
            if isinstance(tgt, ast.Name):
                canon = _canon_ctypes(val, {})
                if canon != "<unresolved>" and (
                    canon in _CTYPES_SCALARS.values()
                    or canon in _POINTER_CANON.values()
                ):
                    aliases[tgt.id] = canon
    # pass 2: bindings
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not (
            isinstance(tgt, ast.Attribute)
            and isinstance(tgt.value, ast.Attribute)
            and isinstance(tgt.value.value, ast.Name)
        ):
            continue
        sym, what = tgt.value.attr, tgt.attr
        if what == "restype":
            out.restypes[sym] = (_canon_ctypes(node.value, aliases), node)
        elif what == "argtypes":
            if isinstance(node.value, (ast.List, ast.Tuple)):
                out.argtypes[sym] = (
                    [_canon_ctypes(e, aliases) for e in node.value.elts],
                    node,
                )
            else:
                out.argtypes[sym] = ([], node)
    return out


def _is_accessor_call(node: ast.AST, accessor: str, arg: str) -> bool:
    """``<mod>.accessor("arg")`` (optionally wrapped in ``.encode(...)``)."""
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "encode"
    ):
        return _is_accessor_call(node.func.value, accessor, arg)
    if not isinstance(node, ast.Call) or not node.args:
        return False
    fn = dotted(node.func)
    if fn is None or fn.rsplit(".", 1)[-1] != accessor:
        return False
    a0 = node.args[0]
    return isinstance(a0, ast.Constant) and a0.value == arg


# ---------------------------------------------------------------------------
# Cross-checks
# ---------------------------------------------------------------------------


def compare_exports(
    contracts: dict, cpp: CppDecls, py: PyBindings
) -> List[Tuple[Optional[ast.AST], str]]:
    out: List[Tuple[Optional[ast.AST], str]] = []
    declared = contracts.get("exports", {})

    for name, proto in declared.items():
        ret, args = proto[0], list(proto[1:])
        fn = cpp.exports.get(name)
        if fn is None:
            out.append((None, f"stale registry export: {name} is not "
                              f"defined in an extern \"C\" block of native.cpp"))
        else:
            if fn.ret != ret:
                out.append((None, f"{name}: C return type {fn.ret} != "
                                  f"declared {ret} (native.cpp:{fn.line})"))
            if fn.params != args:
                out.append((None, f"{name}: C parameters {fn.params} != "
                                  f"declared {args} (native.cpp:{fn.line})"))
        rt = py.restypes.get(name)
        at = py.argtypes.get(name)
        if rt is None and at is None:
            out.append((None, f"exported-but-unbound: {name} has no ctypes "
                              f"restype/argtypes declaration"))
            continue
        if rt is not None and rt[0] != ret:
            out.append((rt[1], f"{name}: ctypes restype {rt[0]} != "
                               f"declared {ret}"))
        if rt is None:
            out.append((None, f"{name}: argtypes declared but restype missing"))
        if at is not None and at[0] != args:
            out.append((at[1], f"{name}: ctypes argtypes {at[0]} != "
                               f"declared {args}"))
        if at is None and args:
            out.append((None, f"{name}: restype declared but argtypes missing"))

    for name, fn in cpp.exports.items():
        if name not in declared:
            out.append((None, f"exported-but-undeclared: {name} "
                              f"(native.cpp:{fn.line}) is missing from "
                              f"records/abi_contracts.py exports"))
    for name in set(py.restypes) | set(py.argtypes):
        if name not in declared:
            node = (py.restypes.get(name) or py.argtypes.get(name))[1]
            out.append((node, f"bound-but-undeclared: ctypes declares {name} "
                              f"but records/abi_contracts.py does not"))
    return out


def compare_constants(
    contracts: dict, cpp: CppDecls
) -> List[Tuple[Optional[ast.AST], str]]:
    out: List[Tuple[Optional[ast.AST], str]] = []
    declared = contracts.get("constants", {})
    for name, value in declared.items():
        got = cpp.constants.get(name)
        if got is None:
            out.append((None, f"stale registry constant: {name} has no "
                              f"constexpr declaration in native.cpp"))
        elif got != value:
            out.append((None, f"constant {name}: native.cpp value {got!r} != "
                              f"declared {value!r}"))
    for name, got in cpp.constants.items():
        if name not in declared:
            out.append((None, f"undeclared shared constant: constexpr {name} "
                              f"= {got!r} in native.cpp is missing from "
                              f"records/abi_contracts.py constants"))
    return out


def compare_records(
    contracts: dict, cpp: CppDecls
) -> List[Tuple[Optional[ast.AST], str]]:
    out: List[Tuple[Optional[ast.AST], str]] = []
    declared = contracts.get("records", {})
    for name, spec in declared.items():
        got = cpp.records.get(name)
        if got is None:
            out.append((None, f"stale registry record: {name} has no "
                              f"pack(1) struct in native.cpp"))
            continue
        want = [(f, t) for f, t in (tuple(x) for x in spec["fields"])]
        if got != want:
            out.append((None, f"record {name}: native.cpp layout {got} != "
                              f"declared {want} "
                              f"(native.cpp:{cpp.record_lines.get(name, '?')})"))
        total = sum(s for _f, _t, _o, s in record_layout(spec))
        if total != spec["size"]:
            out.append((None, f"record {name}: declared size {spec['size']} "
                              f"!= sum of field sizes {total}"))
    for name in cpp.records:
        if name not in declared:
            out.append((None, f"undeclared packed record: struct {name} sits "
                              f"in a pack(1) region of native.cpp but is "
                              f"missing from records/abi_contracts.py"))
    return out


def compare_handles(
    contracts: dict, cpp: CppDecls
) -> List[Tuple[Optional[ast.AST], str]]:
    out: List[Tuple[Optional[ast.AST], str]] = []
    for prefix, spec in contracts.get("handle_families", {}).items():
        reg = spec.get("registry")
        if reg is None:
            continue
        vt = cpp.handle_maps.get(reg)
        if vt is None:
            out.append((None, f"handle family {prefix}: registry map {reg} "
                              f"not found in native.cpp"))
            continue
        raw = vt.endswith("*")
        want_raw = spec.get("lifetime") == "raw"
        if raw != want_raw:
            out.append((None, f"handle family {prefix}: {reg} holds "
                              f"{vt} but the registry declares lifetime "
                              f"{spec.get('lifetime')!r}"))
    return out


def compare_stats(
    contracts: dict, tree: Optional[ast.AST]
) -> List[Tuple[Optional[ast.AST], str]]:
    out: List[Tuple[Optional[ast.AST], str]] = []
    declared = contracts.get("stats_fields", {})
    exports = contracts.get("exports", {})
    for sym, spec in declared.items():
        fields = list(spec.get("fields", []))
        proto = exports.get(sym)
        if proto is None:
            out.append((None, f"stats_fields {sym}: not a declared export"))
            continue
        outptrs = [a for a in proto[1:] if a == "i64p"]
        if len(outptrs) != len(fields):
            out.append((None, f"stats_fields {sym}: {len(fields)} field "
                              f"name(s) vs {len(outptrs)} i64p out-pointer "
                              f"parameter(s) in the declared prototype"))
        builder = spec.get("py_builder")
        if builder is None or tree is None:
            continue
        cls_name, meth_name = builder.split(".", 1)
        meth = None
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and node.name == cls_name:
                for sub in node.body:
                    if (
                        isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and sub.name == meth_name
                    ):
                        meth = sub
        if meth is None:
            out.append((None, f"stats_fields {sym}: py_builder {builder} "
                              f"not found in the bindings module"))
            continue
        for node in ast.walk(meth):
            if isinstance(node, ast.Return) and isinstance(node.value, ast.Dict):
                keys = [
                    k.value for k in node.value.keys
                    if isinstance(k, ast.Constant)
                ]
                if keys != fields:
                    out.append((node, f"stats_fields {sym}: {builder} returns "
                                      f"dict keys {keys} != declared field "
                                      f"order {fields}"))
    return out


def compare_mirrors(
    contracts: dict,
    module_relpath: str,
    module_tree: ast.AST,
    read_tree,  # (relpath) -> Optional[ast.AST]
) -> List[Tuple[Optional[ast.AST], str]]:
    out: List[Tuple[Optional[ast.AST], str]] = []
    constants = contracts.get("constants", {})
    for spec in contracts.get("constant_mirrors", []):
        cname, relpath, attr = spec["constant"], spec["file"], spec["attr"]
        if cname not in constants:
            out.append((None, f"constant mirror {attr}: mirrored constant "
                              f"{cname} is not declared"))
            continue
        tree = module_tree if relpath == module_relpath else read_tree(relpath)
        if tree is None:
            out.append((None, f"stale constant mirror: {relpath} "
                              f"missing/unparseable (mirror for {cname})"))
            continue
        assign = None
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == attr:
                        assign = node
        if assign is None:
            out.append((None, f"stale constant mirror: {relpath} no longer "
                              f"assigns {attr} (mirror for {cname})"))
            continue
        node = assign if relpath == module_relpath else None
        if not _is_accessor_call(assign.value, "constant", cname):
            if isinstance(assign.value, ast.Constant):
                out.append((node, f"{relpath}:{assign.lineno}: {attr} "
                                  f"restates shared constant {cname} as a "
                                  f"literal — read it through "
                                  f"records/abi_contracts.constant()"))
            else:
                out.append((node, f"{relpath}:{assign.lineno}: {attr} "
                                  f"(mirror for {cname}) is not derived via "
                                  f"records/abi_contracts.constant()"))
    return out


def compare_py_structs(
    contracts: dict, tree: ast.AST
) -> List[Tuple[Optional[ast.AST], str]]:
    out: List[Tuple[Optional[ast.AST], str]] = []
    for rname, spec in contracts.get("records", {}).items():
        py = spec.get("py_struct")
        if py is None:
            continue
        cls = None
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and node.name == py["qual"]:
                cls = node
        if cls is None:
            out.append((None, f"record {rname}: py_struct class "
                              f"{py['qual']} not found in bindings"))
            continue
        for attr, accessor in (
            (py["fmt_attr"], "record_format"),
            (py["size_attr"], "record_size"),
        ):
            assign = None
            for sub in cls.body:
                if isinstance(sub, ast.Assign):
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Name) and tgt.id == attr:
                            assign = sub
            if assign is None:
                out.append((None, f"record {rname}: {py['qual']}.{attr} "
                                  f"missing from the bindings module"))
                continue
            if not _is_accessor_call(assign.value, accessor, rname):
                out.append((assign, f"record {rname}: {py['qual']}.{attr} "
                                    f"must be derived via records/"
                                    f"abi_contracts.{accessor}({rname!r}), "
                                    f"not restated"))
    return out


def compare_all(
    contracts: dict,
    cpp: CppDecls,
    py: PyBindings,
    tree: Optional[ast.AST] = None,
    module_relpath: str = BINDINGS_RELPATH,
    read_tree=lambda relpath: None,
) -> List[Tuple[Optional[ast.AST], str]]:
    """Every DF020 cross-check; fixture tests drive this directly."""
    out = []
    out.extend(compare_exports(contracts, cpp, py))
    out.extend(compare_constants(contracts, cpp))
    out.extend(compare_records(contracts, cpp))
    out.extend(compare_handles(contracts, cpp))
    out.extend(compare_stats(contracts, tree))
    if tree is not None:
        out.extend(compare_py_structs(contracts, tree))
        out.extend(
            compare_mirrors(contracts, module_relpath, tree, read_tree)
        )
    for err in cpp.parse_errors:
        out.append((None, f"extractor: {err}"))
    return out


# ---------------------------------------------------------------------------
# Checker entry point
# ---------------------------------------------------------------------------


def _project_root(module: Module) -> Optional[Path]:
    # module.path ends with dragonfly2_tpu/native/__init__.py
    p = module.path.resolve()
    if len(p.parents) < 3:
        return None
    return p.parents[2]


def check(module: Module) -> Iterator[Finding]:
    if module.relpath != BINDINGS_RELPATH:
        return
    root = _project_root(module)
    if root is None:
        return
    contracts_path = root / CONTRACTS_RELPATH
    native_path = root / NATIVE_RELPATH
    if not contracts_path.exists() or not native_path.exists():
        yield module.finding(
            RULE,
            module.tree,
            f"ABI registry or native source missing "
            f"({CONTRACTS_RELPATH} / {NATIVE_RELPATH}) — the bindings "
            f"module cannot be checked",
        )
        return
    contracts = load_contracts_text(
        contracts_path.read_text(encoding="utf-8")
    )
    if contracts is None:
        yield module.finding(
            RULE,
            module.tree,
            "ABI_CONTRACTS must stay a pure literal (ast.literal_eval "
            "failed — dflint reads it without importing)",
        )
        return
    cpp = extract_cpp(native_path.read_text(encoding="utf-8"))
    py = extract_bindings(module.tree)

    _tree_cache: Dict[str, Optional[ast.AST]] = {}

    def read_tree(relpath: str) -> Optional[ast.AST]:
        if relpath not in _tree_cache:
            p = root / relpath
            try:
                _tree_cache[relpath] = ast.parse(
                    p.read_text(encoding="utf-8")
                )
            except (OSError, SyntaxError, UnicodeDecodeError):
                _tree_cache[relpath] = None
        return _tree_cache[relpath]

    for node, message in compare_all(
        contracts, cpp, py, module.tree, module.relpath, read_tree
    ):
        yield module.finding(RULE, node if node is not None else module.tree,
                             message)
